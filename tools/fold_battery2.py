"""Fold /tmp battery2 results into committed artifacts.

Run after tools/tpu_battery2_r3.sh completes (the tpu_watch.sh arm):

    python tools/fold_battery2.py /tmp/tpu_battery2_r3

Copies every parseable one-line JSON into BENCH_SERVE_r03.json (one
object per entry) and prints a PROFILE.md-ready markdown section to
stdout — paste/append, review, commit.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

ENTRIES = [
    ("default", "headline: raw engine loop, default config"),
    ("serve_safe", "serving path, 64 streams, b256, "
                   "EVAM_SERIALIZE_COMPILE wedge-proof mode"),
    ("serve", "serving path, 64 streams, b256, seed ingest"),
    ("serve_b128", "serving path, 64 streams, b128"),
    ("serve_file_32", "serving path, 32 streams, file publish"),
    ("serve_ir", "serving path, 64 streams, manifest IR models"),
    ("serve_rtsp_8", "serving path, 8 LIVE RTSP streams via the "
                     "async demux (tunnel-bound pixels)"),
    ("detect_ir", "detect bench, manifest IR person_vehicle_bike"),
    ("detect_int8", "detect bench, int8 quantized modules"),
    ("sweep40", "operating-point sweep @ p99<40ms"),
    ("blocking", "block_until_ready probe (action/audio programs)"),
    ("action", "action streams (enc+dec combined metric)"),
    ("audio", "audio streams (window-rate/5 metric)"),
    ("ir_layout", "NCHW-vs-NHWC IR executor gap"),
    ("budget", "on-device step time + 40ms budget table"),
    ("accuracy", "accuracy harness forward on the real chip"),
    ("host", "host-ingest point (tunnel-bound here)"),
    ("wedge_repro", "deliberate compile-racing-dispatch repro "
                    "(LAST: may wedge — that outcome is the datum)"),
    ("wedge_repro_locked", "same repro under the global devlock"),
]


def main() -> int:
    out_dir = Path(sys.argv[1] if len(sys.argv) > 1
                   else "/tmp/tpu_battery2_r3")
    folded: dict[str, object] = {}
    lines = ["", f"## Battery fold: {out_dir.name} (real chip)", ""]
    for name, desc in ENTRIES:
        path = out_dir / f"{name}.json"
        if not path.exists():
            lines.append(f"- `{name}`: (not run)")
            continue
        text = path.read_text().strip()
        last = text.splitlines()[-1] if text else ""
        try:
            folded[name] = json.loads(last)
            lines.append(f"- `{name}` ({desc}):")
            lines.append(f"  `{last}`")
        except json.JSONDecodeError:
            folded[name] = {"unparsed": last[-300:]}
            lines.append(f"- `{name}`: UNPARSED tail: `{last[-120:]}`")
    if not folded:
        print(f"refusing to fold: nothing parseable in {out_dir} "
              "(wrong path, or the battery never ran)", file=sys.stderr)
        return 1
    repo = Path(__file__).resolve().parent.parent
    dest = repo / (sys.argv[2] if len(sys.argv) > 2
                   else "BENCH_SERVE_r03.json")
    # MERGE into the existing artifact: a re-armed battery whose first
    # entry crashes must not clobber an earlier good record (e.g. the
    # committed headline) — and an unparsed tail never overwrites a
    # previously parsed entry for the same name.
    merged: dict[str, object] = {}
    if dest.exists():
        try:
            merged = json.loads(dest.read_text())
        except json.JSONDecodeError:
            merged = {}
    def _bad(v) -> bool:
        # unparsed tail OR a parsed failure line (bench emits
        # {"value": 0.0, "error": ...} on wedge/fit failures)
        return isinstance(v, dict) and ("unparsed" in v or "error" in v)

    for name, val in folded.items():
        prior = merged.get(name)
        if _bad(val) and prior is not None and not _bad(prior):
            continue  # a failed re-arm never clobbers a good record
        merged[name] = val
    dest.write_text(json.dumps(merged, indent=1) + "\n")
    print("\n".join(lines))
    print(f"\n[folded {len(folded)} entries -> {dest}]", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
