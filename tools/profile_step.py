"""Cumulative-program timing of the fused detect+classify step.

Measures P1..P6 where each program adds one pipeline phase, all
consuming a seed-synthesized on-device input (like bench.py
--ingest device) so host transfer and any same-input caching in the
tunnel is out of the measured path, and all reducing to a small
output so readback cost is constant. The phase cost is the delta
between consecutive rows. Produces the PROFILE.md table.
"""

from __future__ import annotations

import os as _os
_os.environ.setdefault("EVAM_ALLOW_RANDOM_WEIGHTS", "1")  # hermetic profiling tool

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def bench_fn(fn, seeds, iters=20, warmup=3):
    import jax

    for i in range(warmup):
        jax.block_until_ready(fn(np.int32(i)))
    t0 = time.perf_counter()
    for i in range(iters):
        out = fn(np.int32(100 + i))
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters * 1e3  # ms


def main() -> int:
    import jax
    import jax.numpy as jnp

    from evam_tpu.engine import steps as step_builders
    from evam_tpu.models.registry import ModelRegistry
    from evam_tpu.ops.boxes import decode_boxes
    from evam_tpu.ops.preprocess import decode_wire, preprocess_bgr

    b, h, w = 32, 1080, 1920
    dev = jax.devices()[0]
    print(f"device: {dev.platform} batch={b} {h}x{w} wire=i420", flush=True)

    registry = ModelRegistry()
    det = registry.get("object_detection/person_vehicle_bike")
    cls = registry.get("object_classification/vehicle_attributes")
    anchors = jnp.asarray(det.anchors)
    det_params = jax.device_put(det.params)
    cls_params = jax.device_put(cls.params)

    wire_shape = (b, h * 3 // 2, w)
    n_elems = int(np.prod(wire_shape))

    def synth(seed):
        i = jax.lax.iota(jnp.uint32, n_elems)
        bits = i * jnp.uint32(2654435761) + seed.astype(jnp.uint32)
        return (bits >> 13).astype(jnp.uint8).reshape(wire_shape)

    rows = []

    def add(name, ms):
        prev = rows[-1][1] if rows else 0.0
        rows.append((name, ms))
        print(f"{name:44s} {ms:8.2f} ms  (+{ms - prev:6.2f})", flush=True)

    # P1 synth + decode_wire
    @jax.jit
    def p1(seed):
        return decode_wire(synth(seed), "i420").sum()

    add("P1 synth+decode_wire", bench_fn(p1, None))

    # P2 + preprocess (resize to 512)
    @jax.jit
    def p2(seed):
        x = preprocess_bgr(decode_wire(synth(seed), "i420"), det.preprocess)
        return x.astype(jnp.float32).sum()

    add("P2 +preprocess(512)", bench_fn(p2, None))

    # P3 + SSD forward
    @jax.jit
    def p3(seed):
        x = preprocess_bgr(decode_wire(synth(seed), "i420"), det.preprocess)
        out = det.forward(det_params, x)
        return out["loc"].astype(jnp.float32).sum() + out["conf"].astype(jnp.float32).sum()

    add("P3 +SSD forward", bench_fn(p3, None))

    # P4 + box decode + softmax + top_k
    @jax.jit
    def p4(seed):
        x = preprocess_bgr(decode_wire(synth(seed), "i420"), det.preprocess)
        out = det.forward(det_params, x)
        boxes = decode_boxes(out["loc"].astype(jnp.float32), anchors)
        scores = jax.nn.softmax(out["conf"].astype(jnp.float32), axis=-1)
        fg = scores[..., 1:]
        best = jnp.max(fg, axis=-1)
        top, idx = jax.lax.top_k(best, 32)
        return top.sum() + boxes.sum()

    add("P4 +decode+softmax+topk", bench_fn(p4, None))

    # P5 + NMS (full detect)
    det_step = step_builders.build_detect_step(det, wire_format="i420")

    @jax.jit
    def p5(seed):
        return det_step(det_params, synth(seed)).sum()

    add("P5 +NMS = full detect", bench_fn(p5, None))

    # P6 full fused detect+classify
    full_step = step_builders.build_detect_classify_step(
        det, cls, wire_format="i420")
    params = {"det": det_params, "cls": cls_params}

    @jax.jit
    def p6(seed):
        return full_step(params, synth(seed)).sum()

    add("P6 +crop+classify = full fused", bench_fn(p6, None))

    full_ms = rows[-1][1]
    print(f"\nper-frame: {full_ms / b:.3f} ms -> "
          f"{b / (full_ms / 1e3):.0f} FPS = "
          f"{b / (full_ms / 1e3) / 30:.1f} streams", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
