"""Why does preprocess+SSD fuse to 33 ms when SSD alone is 7 ms?

Variants of profile_step.py's P3 program on the real chip:
  A. verbatim: synth 1080p i420 -> decode -> resize 512 -> SSD
  B. same with lax.optimization_barrier between preprocess and net
     (keeps one jit, forbids cross-phase fusion/layout coupling)
  C. wire=bgr instead of i420
  D. net on directly synthesized 512^2 input (no resize) [control]
"""

from __future__ import annotations

import os as _os
_os.environ.setdefault("EVAM_ALLOW_RANDOM_WEIGHTS", "1")  # hermetic profiling tool

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def bench_fn(fn, iters=20, warmup=3):
    import jax

    for i in range(warmup):
        jax.block_until_ready(fn(np.int32(i)))
    t0 = time.perf_counter()
    for i in range(iters):
        out = fn(np.int32(100 + i))
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters * 1e3


def main() -> int:
    import jax
    import jax.numpy as jnp

    from evam_tpu.models.registry import ModelRegistry
    from evam_tpu.ops.preprocess import decode_wire, preprocess_bgr

    b, h, w = 32, 1080, 1920
    print(f"device: {jax.devices()[0].platform} batch={b}", flush=True)

    registry = ModelRegistry()
    det = registry.get("object_detection/person_vehicle_bike")
    params = jax.device_put(det.params)

    def synth(seed, shape):
        nn = int(np.prod(shape))
        i = jax.lax.iota(jnp.uint32, nn)
        bits = i * jnp.uint32(2654435761) + seed.astype(jnp.uint32)
        return (bits >> 13).astype(jnp.uint8).reshape(shape)

    def run(label, fn):
        print(f"{label}: {bench_fn(jax.jit(fn)):7.2f} ms", flush=True)

    def net_sum(x):
        out = det.forward(params, x)
        return (out["loc"].astype(jnp.float32).sum()
                + out["conf"].astype(jnp.float32).sum())

    # A. verbatim P3
    def pA(seed):
        x = preprocess_bgr(
            decode_wire(synth(seed, (b, h * 3 // 2, w)), "i420"),
            det.preprocess)
        return net_sum(x)

    # B. optimization barrier between phases
    def pB(seed):
        x = preprocess_bgr(
            decode_wire(synth(seed, (b, h * 3 // 2, w)), "i420"),
            det.preprocess)
        x = jax.lax.optimization_barrier(x)
        return net_sum(x)

    # C. bgr wire
    def pC(seed):
        x = preprocess_bgr(
            decode_wire(synth(seed, (b, h, w, 3)), "bgr"), det.preprocess)
        return net_sum(x)

    # D. control: net on 512^2 synth
    def pD(seed):
        x = synth(seed, (b, 512, 512, 3)).astype(jnp.float32)
        return net_sum(x.astype(jnp.bfloat16))

    run("A i420+resize+ssd (P3 verbatim)", pA)
    run("B  + optimization_barrier     ", pB)
    run("C bgr wire + resize + ssd     ", pC)
    run("D ssd on 512^2 direct [ctrl]  ", pD)
    return 0


if __name__ == "__main__":
    sys.exit(main())
