#!/usr/bin/env python
"""Ragged-batching microbench: EVAM_RAGGED packed vs off (the pad tax).

CPU-only A/B through the REAL EngineHub + BatchEngine + classify
steps (engine/ragged.py, steps.build_classify_step[_ragged]): a
deliberately heterogeneous stream mix — two classify engines at
MIXED ingest resolutions (the bucket-fragmentation half of the pad
tax) fed items with RAGGED per-frame region counts drawn from a
skewed surveillance-like distribution, zero-region frames included
(the interior-padding half). The same frames and boxes run twice:
once packed (masked region packing + consolidated bucket ladder) and
once through today's dense bucketed path.

Four assertions, all gating (full mode):

* **bit-identical outputs** — every item's packed result rows equal
  the dense path's first ``k`` rows, byte for byte ("equal accuracy"
  is checked, not assumed: packing moves rows, it must never change
  a number);
* **occupancy-weighted throughput ≥ --min-ratio (1.0)** — real unit
  rows (regions) classified per second, packed / off, as the MEDIAN
  of per-pair ratios over --windows order-alternated window pairs
  (the bench_transfer pairing discipline). Pad rows are not useful
  work, so units/s is the honest rate; the CPU gate is parity-plus —
  the masking overhead (per-unit frame gather + seg mask) must not
  eat the computed-rows saving. The full win is device-bound (fewer
  unit rows = fewer FLOPs AND fewer programs);
* **mean unit occupancy strictly higher** — EngineStats'
  units/unit_slots (the honest fill the dense n/bucket number
  hides) must rise under packing;
* **compiled-program count strictly lower** — after warming every
  bucket on both engines, the consolidated ladder must have compiled
  fewer programs than the dense ladder (the "compile-cache entries
  drop" claim, measured via EngineStats.compiled_programs).

``--smoke`` (CI): short run; identity + occupancy + program-count
gate, the throughput ratio prints but does not gate.

Prints ONE JSON line on stdout; diagnostics go to stderr.
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


#: skewed per-frame region counts: mostly 1-3 of the 8-slot budget,
#: the occasional empty and the occasional full frame — the already-
#: ragged shape the classifier sees behind a detector
REGION_MIX = (1, 2, 0, 3, 1, 2, 8, 1, 4, 2, 1, 0, 2, 5, 1, 3)

MODEL_A = "object_classification/vehicle_attributes"
MODEL_B = "emotion_recognition/1"


def _build_hub(ragged: str, sizes: dict[str, tuple[int, int]],
               max_batch: int):
    from evam_tpu.engine.hub import EngineHub
    from evam_tpu.models import ModelRegistry, ZOO_SPECS

    overrides = {k: (64, 64) for k in ZOO_SPECS}
    overrides["audio_detection/environment"] = (1, 1600)
    overrides.update(sizes)
    registry = ModelRegistry(
        dtype="float32", input_overrides=overrides,
        width_overrides={k: 8 for k in ZOO_SPECS})
    return EngineHub(
        registry, plan=None, max_batch=max_batch, deadline_ms=2.0,
        supervise=False, stall_timeout_s=0, ragged=ragged)


def _engines(hub):
    """The heterogeneous pair: two classify engines at different wire
    resolutions (mixed-resolution fleets fragment buckets — each
    engine pays its own ladder)."""
    a = hub.engine("classify", MODEL_A, roi_budget=8)
    b = hub.engine("classify", MODEL_B, roi_budget=8)
    return a, b


def make_items(n: int, hw: tuple[int, int], seed: int = 7):
    """Deterministic (wire_frame, boxes[k,4], k) items, reused across
    both sides so the A/B hashes the exact same pixels and boxes."""
    from evam_tpu.ops.color import wire_shape

    rng = np.random.default_rng(seed)
    ws = tuple(wire_shape("i420", *hw))
    items = []
    for i in range(n):
        k = REGION_MIX[i % len(REGION_MIX)]
        frame = rng.integers(0, 255, ws, np.uint8)
        boxes = np.sort(
            rng.random((k, 2, 2)).astype(np.float32), axis=1
        ).reshape(k, 4)
        items.append((frame, boxes, k))
    return items


def _submit_all(eng, items, packed: bool, budget: int = 8):
    futs = []
    for frame, boxes, k in items:
        if packed:
            bx = boxes
        else:
            bx = np.zeros((budget, 4), np.float32)
            bx[:k] = boxes
        futs.append(eng.submit(units=k, frames=frame, boxes=bx))
    return [f.result(timeout=120) for f in futs]


def _identical(out_off, out_pk, items) -> bool:
    for (_, _, k), od, op in zip(items, out_off, out_pk):
        if op.shape[0] != k:
            log(f"packed row count {op.shape[0]} != {k}")
            return False
        if not np.array_equal(od[:k], op):
            log(f"output mismatch at k={k}: "
                f"max|Δ|={np.max(np.abs(od[:k] - op))}")
            return False
    return True


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--items", type=int, default=192,
                   help="items per timed window")
    p.add_argument("--windows", type=int, default=5,
                   help="paired (off, packed) windows")
    p.add_argument("--max-batch", type=int, default=16)
    p.add_argument("--min-ratio", type=float, default=1.0,
                   help="gate: median packed/off units-per-second")
    p.add_argument("--smoke", action="store_true",
                   help="CI mode: short run, throughput ratio "
                        "informational only")
    args = p.parse_args()
    if args.smoke:
        args.items = min(args.items, 96)
        args.windows = 2

    import os

    os.environ.setdefault("EVAM_ALLOW_RANDOM_WEIGHTS", "1")

    sizes = {MODEL_A: (64, 64), MODEL_B: (96, 96)}
    log("building dense (off) and packed hubs ...")
    hub_off = _build_hub("off", sizes, args.max_batch)
    hub_pk = _build_hub("packed", sizes, args.max_batch)
    try:
        eng_off, eng_off_b = _engines(hub_off)
        eng_pk, eng_pk_b = _engines(hub_pk)
        items = make_items(args.items, sizes[MODEL_A])
        items_b = make_items(max(16, args.items // 4), sizes[MODEL_B],
                             seed=11)

        # warm every bucket on every engine: the compile bill is the
        # consolidation claim, and nothing below should time a compile
        for eng, ex in ((eng_off, items[0]), (eng_pk, items[0]),
                        (eng_off_b, items_b[0]), (eng_pk_b, items_b[0])):
            frame, boxes, _ = ex
            eng.set_example(frames=frame,
                            boxes=np.zeros((8, 4), np.float32))
            t0 = time.perf_counter()
            eng.warmup()
            log(f"warmed {eng.name} ragged={eng.ragged} "
                f"buckets={eng.buckets} in "
                f"{time.perf_counter() - t0:.1f}s")
        programs_off = (eng_off.stats.compiled_programs
                        + eng_off_b.stats.compiled_programs)
        programs_pk = (eng_pk.stats.compiled_programs
                       + eng_pk_b.stats.compiled_programs)

        log("identity pass (packed rows == dense rows, bit for bit)")
        out_off = _submit_all(eng_off, items, packed=False)
        out_pk = _submit_all(eng_pk, items, packed=True)
        identical = _identical(out_off, out_pk, items)
        # the mixed-resolution engine too (smaller set)
        out_off_b = _submit_all(eng_off_b, items_b, packed=False)
        out_pk_b = _submit_all(eng_pk_b, items_b, packed=True)
        identical = identical and _identical(out_off_b, out_pk_b,
                                             items_b)

        units = sum(k for _, _, k in items)
        ratios = []
        sides = {"off": 0.0, "packed": 0.0}
        for w in range(args.windows):
            order = (("off", "packed") if w % 2 == 0
                     else ("packed", "off"))
            pair = {}
            for side in order:
                eng = eng_off if side == "off" else eng_pk
                t0 = time.perf_counter()
                _submit_all(eng, items, packed=(side == "packed"))
                dt = time.perf_counter() - t0
                pair[side] = units / dt
                sides[side] += units / dt
            ratios.append(pair["packed"] / pair["off"])
            log(f"window {w}: off={pair['off']:.0f} u/s "
                f"packed={pair['packed']:.0f} u/s "
                f"ratio={ratios[-1]:.3f}")
        ratio = statistics.median(ratios)
        occ_off = eng_off.stats.unit_occupancy
        occ_pk = eng_pk.stats.unit_occupancy
    finally:
        hub_off.stop()
        hub_pk.stop()

    perf_gate = 0.0 if args.smoke else args.min_ratio
    ok_perf = ratio >= perf_gate
    ok_occ = occ_pk > occ_off
    ok_programs = programs_pk < programs_off
    ok = identical and ok_perf and ok_occ and ok_programs
    print(json.dumps({
        "metric": "ragged_units_per_s_ratio",
        "value": round(ratio, 3),
        "unit": "x (packed/off, median of paired windows)",
        "vs_baseline": round(ratio, 3),
        "identical_outputs": identical,
        "unit_occupancy_off": round(occ_off, 4),
        "unit_occupancy_packed": round(occ_pk, 4),
        "compiled_programs_off": programs_off,
        "compiled_programs_packed": programs_pk,
        "units_per_s_off": round(sides["off"] / args.windows, 1),
        "units_per_s_packed": round(sides["packed"] / args.windows, 1),
        "items_per_window": args.items,
        "windows": args.windows,
        "min_ratio": args.min_ratio,
        "smoke": bool(args.smoke),
        "ok": ok,
    }))
    if not identical:
        log("FAIL: packed outputs differ from the dense path")
    if not ok_occ:
        log(f"FAIL: packed unit occupancy {occ_pk:.3f} not above "
            f"dense {occ_off:.3f}")
    if not ok_programs:
        log(f"FAIL: packed compiled {programs_pk} programs, dense "
            f"{programs_off} — consolidation didn't shrink the cache")
    if not ok_perf:
        log(f"FAIL: packed/off units-per-second ratio {ratio:.3f} "
            f"below {perf_gate}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
