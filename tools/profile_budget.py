"""Latency-budget terms, measured (round-2 VERDICT item 2).

BASELINE.md's north star is end-to-end p99 < 40 ms. Through this
environment's axon tunnel every dispatch pays ~66 ms, so wall-clock
can never show the budget closing; this tool measures the ON-DEVICE
step time instead, tunnel-independent, by chaining K step iterations
inside ONE XLA program (one dispatch) and taking the slope:

    wall(K) = dispatch_overhead + K * t_step
    t_step  = (wall(K2) - wall(K1)) / (K2 - K1)

The chained iterations are data-dependent (each iteration's synth seed
mixes in the previous packed output), so XLA cannot parallelize or
elide them — and the whole fused program (wire-decode, preprocess,
SSD, NMS, classify) is consumed per iteration, avoiding the
`.sum()`-ladder simplifier trap documented in PROFILE.md.

Output: one JSON line per batch size with t_step_ms, per-frame µs, and
the production budget check: fill deadline (8 ms serving default) +
t_step + PCIe readback estimate vs the 40 ms target.
"""

from __future__ import annotations

import os as _os
_os.environ.setdefault("EVAM_ALLOW_RANDOM_WEIGHTS", "1")  # hermetic profiling tool

import json
import sys
import time

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main() -> int:
    import jax
    import jax.numpy as jnp
    from jax import lax

    from evam_tpu.engine import steps as step_builders
    from evam_tpu.models.registry import ModelRegistry

    dev = jax.devices()[0]
    log(f"device: {dev.platform} {getattr(dev, 'device_kind', '')}")

    registry = ModelRegistry(dtype="bfloat16")
    det = registry.get("object_detection/person_vehicle_bike")
    cls = registry.get("object_classification/vehicle_attributes")
    step = step_builders.build_detect_classify_step(
        det, cls, wire_format="i420")
    params = jax.device_put({"det": det.params, "cls": cls.params})

    h, w = 1080, 1920
    wire = (h * 3 // 2, w)
    rows = []
    for b in (32, 64, 128, 256, 512):
        n_elems = int(b * np.prod(wire))

        # packed output shape: the fori_loop carry needs it up front
        probe = jax.eval_shape(
            lambda p, f: step(p, frames=f),
            params,
            jax.ShapeDtypeStruct((b,) + wire, jnp.uint8),
        )

        def chained(params, seed0, k, out_sd=probe):
            def body(_, carry):
                seed, _prev = carry
                bits = step_builders.weyl_bits(seed, n_elems)
                frames = (bits >> jnp.uint32(13)).astype(jnp.uint8)
                packed = step(
                    params, frames=frames.reshape((b,) + wire))
                nxt = (
                    seed
                    + jnp.max(packed).astype(jnp.float32)
                    .view(jnp.uint32) % jnp.uint32(97)
                )
                return (nxt, packed)
            dummy = jnp.zeros(out_sd.shape, out_sd.dtype)
            return lax.fori_loop(0, k, body, (jnp.uint32(seed0), dummy))[1]

        times = {}
        for k in (1, 9):
            fn = jax.jit(chained, static_argnums=2)
            out = fn(params, np.uint32(1), k)
            jax.block_until_ready(out)  # compile + warm
            best = np.inf
            for rep in range(3):
                t0 = time.perf_counter()
                jax.block_until_ready(fn(params, np.uint32(2 + rep), k))
                best = min(best, time.perf_counter() - t0)
            times[k] = best
        t_step = (times[9] - times[1]) / 8.0
        per_frame_us = t_step * 1e6 / b
        # production budget: serving fill deadline + step + PCIe
        # readback (packed output over ~16 GB/s; the tunnel's 18 MB/s
        # is an environment artifact, not the deployment fabric)
        out_bytes = int(np.prod(probe.shape)) * 4
        readback_ms = out_bytes / 16e9 * 1e3
        budget_ms = 8.0 + t_step * 1e3 + readback_ms
        rows.append({
            "batch": b,
            "t_step_ms": round(t_step * 1e3, 2),
            "per_frame_us": round(per_frame_us, 1),
            "wall_k1_ms": round(times[1] * 1e3, 1),
            "wall_k9_ms": round(times[9] * 1e3, 1),
            "readback_est_ms": round(readback_ms, 3),
            "budget_fill8_step_readback_ms": round(budget_ms, 1),
            "meets_40ms": budget_ms < 40.0,
        })
        log(f"b={b}: t_step={t_step*1e3:.2f} ms "
            f"({per_frame_us:.0f} µs/frame), budget "
            f"{budget_ms:.1f} ms vs 40 -> "
            f"{'OK' if budget_ms < 40 else 'over'}")
    print(json.dumps({"budget_rows": rows}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
