"""Capture a jax.profiler trace of the fused detect+classify step.

Produces a TensorBoard-loadable trace directory (top ops, fusion
boundaries, HBM traffic) — the artifact PROFILE.md's before/after
tables are built from. Run on the real chip:

    python tools/capture_trace.py --outdir /tmp/evam_trace

The trace directory is also summarized to stdout when
tensorflow/tensorboard parsing is available; otherwise inspect with
`tensorboard --logdir <outdir>` elsewhere.

``--from-flight-recorder <flight.jsonl>`` replays the batch shape a
quarantine dump recorded (evam_tpu/obs/trace.py flight_dump): the
wedged batch's bucket size parameterizes the capture, so the device
timeline profiles exactly the batch geometry that wedged. Prefers the
pending (in-flight at quarantine) batch row; every dump's header also
says whether the profiler server was up (``profiler_running``) at the
moment of the wedge.
"""

from __future__ import annotations

import os as _os
_os.environ.setdefault("EVAM_ALLOW_RANDOM_WEIGHTS", "1")  # hermetic profiling tool

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pick_flight_batch(path: str) -> dict | None:
    """The batch row to replay from a flight-recorder JSONL: the
    in-flight (wedged) batch when there is one, else the last
    completed batch."""
    import json

    pending, done = [], []
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            if not line.strip():
                continue
            row = json.loads(line)
            if row.get("type") != "batch":
                continue
            (pending if row.get("pending") else done).append(row)
    if pending:
        return pending[-1]
    return done[-1] if done else None


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--outdir", default="/tmp/evam_trace")
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--from-flight-recorder", metavar="FLIGHT_JSONL",
                   help="replay the batch shape recorded by a "
                        "quarantine flight dump (bucket size of the "
                        "wedged batch parameterizes the capture)")
    args = p.parse_args()

    if args.from_flight_recorder:
        row = pick_flight_batch(args.from_flight_recorder)
        if row is None:
            print("no batch rows in flight dump; nothing to replay",
                  file=sys.stderr)
            return 1
        args.batch = int(row.get("bucket") or row.get("n") or args.batch)
        print(
            f"replaying flight batch: engine={row.get('engine')} "
            f"bid={row.get('bid')} bucket={args.batch} "
            f"pending={row.get('pending')} "
            f"last_stage={row.get('last_stage')}",
            file=sys.stderr)

    import jax
    import jax.numpy as jnp

    from evam_tpu.engine import steps as step_builders
    from evam_tpu.models.registry import ModelRegistry

    registry = ModelRegistry()
    det = registry.get("object_detection/person_vehicle_bike")
    cls = registry.get("object_classification/vehicle_attributes")
    step = step_builders.build_detect_classify_step(
        det, cls, wire_format="i420")
    params = jax.device_put({"det": det.params, "cls": cls.params})

    b, h, w = args.batch, 1080, 1920
    wire_shape = (b, h * 3 // 2, w)
    n_elems = int(np.prod(wire_shape))

    @jax.jit
    def seeded(params, seed):
        i = jax.lax.iota(jnp.uint32, n_elems)
        bits = i * jnp.uint32(2654435761) + seed.astype(jnp.uint32)
        frames = (bits >> 13).astype(jnp.uint8).reshape(wire_shape)
        return step(params, frames=frames)

    t0 = time.perf_counter()
    jax.block_until_ready(seeded(params, np.int32(0)))
    print(f"compile: {time.perf_counter() - t0:.1f}s", file=sys.stderr)

    with jax.profiler.trace(args.outdir):
        for i in range(args.iters):
            out = seeded(params, np.int32(i))
        jax.block_until_ready(out)
    print(f"trace written to {args.outdir} ({args.iters} steps)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
