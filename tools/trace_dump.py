#!/usr/bin/env python
"""Render a /traces capture (or a flight-recorder JSONL) to Chrome
trace-event JSON.

The serving side exposes the tail-sampled trace ring three ways
(evam_tpu/obs/trace.py); this tool is the consumer: pull ``GET
/traces`` from a running service (or read a saved payload / flight
JSONL), write a ``chrome://tracing`` / Perfetto-loadable file, and
assert the linkage property the tracing layer exists for — batch spans
that name >= 2 member frame trace ids and carry the full
h2d_issue/h2d_wait/launch/readback stage clock.

    python tools/trace_dump.py --url http://localhost:8080/traces \
        --out /tmp/evam_traces.json --require-linked 1

Stdlib only (urllib), importable by tests: ``convert``,
``events_from_flight``, ``linked_batches``.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from evam_tpu.obs.trace import STAGE_ORDER, last_stage  # noqa: E402

#: the transfer/compute stages a linked batch span must clock for the
#: acceptance check (readback rides completion, so it proves the batch
#: made the full round trip)
LINK_STAGES = ("h2d_issue", "h2d_wait", "launch", "readback")


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def convert(payload: dict) -> dict:
    """A /traces payload -> a Chrome trace-event file body. The route
    already serves ready-made events; this validates the shape and
    wraps them with the displayTimeUnit header."""
    events = payload.get("traceEvents", [])
    if not isinstance(events, list):
        raise ValueError("payload.traceEvents must be a list")
    return {"displayTimeUnit": "ms", "traceEvents": events}


def events_from_flight(rows: list[dict]) -> list[dict]:
    """Flight-recorder JSONL rows -> Chrome trace events (same layout
    as the live route: frame spans per stream track, one batch span
    per record plus sequential per-stage slices)."""
    events: list[dict] = []
    for row in rows:
        kind = row.get("type")
        if kind == "frame":
            for span in row.get("spans", ()):
                args = {"trace_id": row.get("trace_id"),
                        "seq": row.get("seq"), "class": row.get("class"),
                        "status": row.get("status")}
                args.update(span.get("attrs", {}))
                events.append({
                    "name": span["name"], "ph": "X", "cat": "frame",
                    "ts": round(span["t0"] * 1e6, 1),
                    "dur": round(span["dur_s"] * 1e6, 1),
                    "pid": "frames", "tid": row.get("stream", ""),
                    "args": args,
                })
        elif kind == "batch":
            stages = row.get("stages") or {}
            total = row.get("dur_s")
            if total is None:
                total = sum(stages.values())
            events.append({
                "name": f"batch {row['engine']}#{row['bid']}", "ph": "X",
                "cat": "batch", "ts": round(row["t0"] * 1e6, 1),
                "dur": round(total * 1e6, 1),
                "pid": f"engine {row['engine']}",
                "tid": row.get("device", ""),
                "args": {
                    "bid": row["bid"],
                    "frames": list(row.get("frames", ())),
                    "bucket": row.get("bucket"), "n": row.get("n"),
                    "device": row.get("device", ""),
                    "status": row.get("status", ""),
                    "pending": row.get("pending", False),
                    "stages": stages,
                    "last_stage": row.get("last_stage") or last_stage(stages),
                },
            })
            t = row["t0"]
            for s in STAGE_ORDER:
                if s not in stages:
                    continue
                events.append({
                    "name": s, "ph": "X", "cat": "batch-stage",
                    "ts": round(t * 1e6, 1),
                    "dur": round(stages[s] * 1e6, 1),
                    "pid": f"engine {row['engine']}",
                    "tid": f"{row.get('device', '')}/stages",
                    "args": {"bid": row["bid"]},
                })
                t += stages[s]
    return events


def linked_batches(events: list[dict]) -> int:
    """How many batch spans link >= 2 member frame spans AND carry the
    full transfer/compute stage clock — the acceptance property."""
    count = 0
    for ev in events:
        if ev.get("cat") != "batch":
            continue
        args = ev.get("args", {})
        if len(args.get("frames", ())) >= 2 \
                and all(s in args.get("stages", {}) for s in LINK_STAGES):
            count += 1
    return count


def _fetch(url: str) -> dict:
    from urllib.request import urlopen

    with urlopen(url, timeout=10) as resp:
        return json.loads(resp.read().decode("utf-8"))


def main() -> int:
    p = argparse.ArgumentParser()
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument("--url", help="GET this /traces endpoint")
    src.add_argument("--input", help="saved /traces JSON payload file")
    src.add_argument("--flight", help="flight-recorder JSONL artifact")
    p.add_argument("--out", default="/tmp/evam_traces.json",
                   help="Chrome trace-event output path")
    p.add_argument("--require-linked", type=int, default=0,
                   help="exit 1 unless >= N batch spans link >= 2 "
                        "frame spans with the full h2d/launch/readback "
                        "stage clock")
    args = p.parse_args()

    if args.flight:
        rows = [json.loads(line) for line in
                Path(args.flight).read_text(encoding="utf-8").splitlines()
                if line.strip()]
        header = next((r for r in rows if r.get("type") == "flight"), {})
        if header:
            log(f"flight dump: engine={header.get('engine')} "
                f"reason={header.get('reason')!r} "
                f"profiler_running={header.get('profiler_running')}")
        body = {"displayTimeUnit": "ms",
                "traceEvents": events_from_flight(rows)}
    else:
        payload = _fetch(args.url) if args.url else json.loads(
            Path(args.input).read_text(encoding="utf-8"))
        log(f"payload: enabled={payload.get('enabled')} "
            f"retained={payload.get('retained')} "
            f"frames={payload.get('frames')} "
            f"batches={payload.get('batches')} "
            f"pending={payload.get('pending')}")
        body = convert(payload)

    linked = linked_batches(body["traceEvents"])
    Path(args.out).write_text(json.dumps(body), encoding="utf-8")
    print(json.dumps({
        "out": args.out,
        "events": len(body["traceEvents"]),
        "linked_batches": linked,
        "ok": linked >= args.require_linked,
    }))
    if linked < args.require_linked:
        log(f"FAIL: {linked} linked batch span(s) < "
            f"required {args.require_linked}")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
