#!/usr/bin/env python
"""Motion-gate microbench: content-adaptive inference gating A/B.

CPU-only, through the REAL DetectStage + BatchEngine path
(stages/infer.py → stages/gate.py → engine/batcher.py): a
deterministic synthetic workload alternates MOVING segments (a square
relocating every frame) with STATIC segments (frozen frame), with a
majority-static mix — the temporal shape of surveillance video. The
same frames run twice: once with ``inference-interval=adaptive`` (the
motion gate) and once ungated.

Three assertions, all gating (full mode):

* **throughput uplift ≥ --min-uplift** — wall-clock frames/s through
  the stage chain, gated / ungated, as the MEDIAN of per-pair ratios
  over --windows order-alternated window pairs (same pairing
  discipline as tools/bench_transfer.py). The gate removes whole
  engine round-trips, so unlike the transfer pipeline this win IS
  expected on CPU;
* **bounded detection staleness** — the gate never skipped more than
  ``gate-max-skip`` consecutive frames (every object re-validated
  within that bound), and every skipped frame still carried coasted
  detections;
* **EVAM_GATE=off identity** — with the kill switch set, a stage built
  WITH gate properties produces byte-identical per-frame regions to a
  stage built with none (the A/B the serving default relies on).

``--smoke`` (CI): short run, identity + staleness gate only; the
uplift still prints but does not gate.

Prints ONE JSON line on stdout; diagnostics go to stderr.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def make_frames(n: int, static_frac: float, h: int = 96, w: int = 96,
                cycle: int = 50) -> list[np.ndarray]:
    """Deterministic majority-static workload: each ``cycle`` frames
    start with a moving burst (square relocating every frame) and then
    freeze. Returned frames are reused across runs so both A/B sides
    hash the exact same pixels."""
    moving_len = max(1, int(round(cycle * (1.0 - static_frac))))
    frames = []
    base = np.full((h, w, 3), 18, np.uint8)
    sq = 24
    x = y = 0
    for i in range(n):
        if i % cycle < moving_len:
            x = (x + 17) % (w - sq)
            y = (y + 11) % (h - sq)
        f = base.copy()
        f[y:y + sq, x:x + sq] = (64, 160, 240)
        frames.append(f)
    return frames


def build_hub():
    from evam_tpu.engine import EngineHub
    from evam_tpu.models import ModelRegistry, ZOO_SPECS
    from evam_tpu.parallel import build_mesh

    small = {k: (64, 64) for k in ZOO_SPECS}
    small["audio_detection/environment"] = (1, 1600)
    narrow = {k: 8 for k in ZOO_SPECS}
    registry = ModelRegistry(dtype="float32", input_overrides=small,
                             width_overrides=narrow)
    return EngineHub(registry, plan=build_mesh(), max_batch=16,
                     deadline_ms=2.0)


MODEL = "object_detection/person_vehicle_bike"


def run_stream(hub, frames, props, collect=False):
    """Drive the frames through a fresh DetectStage (shared warm
    engine) on a StreamRunner; return (elapsed_s, stage, outputs).
    ``outputs`` is the per-frame serialized region payload when
    ``collect`` (identity/staleness checks), else None."""
    from evam_tpu.media.source import FrameEvent
    from evam_tpu.stages.base import Stage
    from evam_tpu.stages.infer import DetectStage
    from evam_tpu.stages.runner import StreamRunner

    stage = DetectStage("detection", MODEL, dict(props), hub)
    outs: list[bytes] = []

    class Collect(Stage):
        name = "collect"

        def process(self, ctx):
            rows = np.asarray(
                [[r.x0, r.y0, r.x1, r.y1, r.confidence, r.label_id]
                 for r in ctx.regions], np.float32)
            outs.append(rows.tobytes())
            return [ctx]

    stages = [stage] + ([Collect()] if collect else [])
    runner = StreamRunner("bench-gate", stages)
    events = (FrameEvent(frame=f, pts_ns=i, seq=i)
              for i, f in enumerate(frames))
    t0 = time.perf_counter()
    runner.run(events)
    elapsed = time.perf_counter() - t0
    assert runner.frames_out == len(frames), (
        runner.frames_out, runner.errors)
    return elapsed, stage, outs if collect else None


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--frames", type=int, default=400,
                   help="frames per measured window")
    p.add_argument("--static-frac", type=float, default=0.8,
                   help="fraction of each segment cycle that is static "
                        "(the majority-static surveillance shape)")
    p.add_argument("--max-skip", type=int, default=8,
                   help="gate-max-skip: the detection staleness bound")
    p.add_argument("--min-uplift", type=float, default=1.5,
                   help="fail when the median gated/ungated throughput "
                        "ratio drops below this (full mode)")
    p.add_argument("--windows", type=int, default=3,
                   help="order-alternated A/B window pairs; median "
                        "per-pair ratio gates")
    p.add_argument("--smoke", action="store_true",
                   help="CI shape: short run, identity + staleness "
                        "gates only; uplift prints but does not gate")
    args = p.parse_args()
    if args.smoke:
        args.frames = min(args.frames, 150)
        args.windows = 1

    os.environ.setdefault("EVAM_ALLOW_RANDOM_WEIGHTS", "1")
    os.environ.pop("EVAM_GATE", None)  # props drive the A/B below

    import jax

    # the image's .axon_site hook rewrites JAX_PLATFORMS at jax
    # import; this tool is the CPU A/B by definition
    jax.config.update("jax_platforms", "cpu")

    frames = make_frames(args.frames, args.static_frac)
    log(f"{args.frames} frames, static fraction {args.static_frac}, "
        f"max_skip {args.max_skip}")

    gated_props = {"threshold": 0.2, "inference-interval": "adaptive",
                   "gate-max-skip": args.max_skip}
    plain_props = {"threshold": 0.2}

    hub = build_hub()
    try:
        t0 = time.perf_counter()
        _, warm_stage, _ = run_stream(hub, frames[:8], plain_props)
        warm_stage.engine.warmup()  # compile every bucket pre-timing
        log(f"engine warmed in {time.perf_counter() - t0:.1f}s")

        # ---- correctness: staleness bound + coasted detections on a
        # collected gated run
        _, gstage, gouts = run_stream(hub, frames, gated_props,
                                      collect=True)
        snap = gstage.gate.snapshot()
        log(f"gated run: {snap}")
        stale_ok = snap["max_consecutive_skips"] <= args.max_skip
        # every frame after the first inference must carry detections
        # (real or coasted) — a skip must never publish an empty frame
        # while an object is in scene
        coasted_ok = all(len(o) > 0 for o in gouts[1:])
        skip_rate = snap["skip_rate"]

        # ---- identity: EVAM_GATE=off + gate props == no gate props
        os.environ["EVAM_GATE"] = "off"
        try:
            _, _, off_outs = run_stream(hub, frames, gated_props,
                                        collect=True)
            _, _, plain_outs = run_stream(hub, frames, plain_props,
                                          collect=True)
        finally:
            os.environ.pop("EVAM_GATE", None)
        identical = off_outs == plain_outs
        log(f"EVAM_GATE=off identity: {identical}")

        # ---- throughput: paired, order-alternated windows
        ratios = []
        best = {"gated": 0.0, "ungated": 0.0}
        for k in range(max(1, args.windows)):
            order = (("ungated", "gated") if k % 2 == 0
                     else ("gated", "ungated"))
            pair = {}
            for mode in order:
                props = gated_props if mode == "gated" else plain_props
                dt, _, _ = run_stream(hub, frames, props)
                fps = len(frames) / dt
                pair[mode] = fps
                best[mode] = max(best[mode], fps)
                log(f"[{mode}] {fps:.0f} frames/s")
            ratios.append(pair["gated"] / max(pair["ungated"], 1e-9))
    finally:
        hub.stop()

    uplift = float(np.median(ratios))
    log(f"per-pair ratios {[round(r, 3) for r in ratios]} "
        f"→ median {uplift:.2f}x")

    perf_gate = 0.0 if args.smoke else args.min_uplift
    ok = bool(identical and stale_ok and coasted_ok
              and skip_rate > 0.3 and uplift >= perf_gate)
    print(json.dumps({
        "metric": "gate_engine_uplift",
        "value": round(uplift, 2),
        "unit": "x",
        "identical": identical,
        "skip_rate": skip_rate,
        "max_consecutive_skips": snap["max_consecutive_skips"],
        "max_skip": args.max_skip,
        "staleness_bounded": stale_ok,
        "coasted_frames_nonempty": coasted_ok,
        "ratios": [round(r, 3) for r in ratios],
        "gated_fps": round(best["gated"], 1),
        "ungated_fps": round(best["ungated"], 1),
        "frames": args.frames,
        "static_frac": args.static_frac,
        "smoke": bool(args.smoke),
        "ok": ok,
    }))
    if not identical:
        log("FAIL: EVAM_GATE=off does not reproduce the ungated outputs")
    if not stale_ok:
        log(f"FAIL: staleness bound violated "
            f"({snap['max_consecutive_skips']} > {args.max_skip})")
    if not coasted_ok:
        log("FAIL: a skipped frame published no detections")
    if skip_rate <= 0.3:
        log(f"FAIL: gate barely engaged (skip rate {skip_rate})")
    if uplift < perf_gate:
        log(f"FAIL: uplift {uplift:.2f}x < {perf_gate:.2f}x")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
