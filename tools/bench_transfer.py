#!/usr/bin/env python
"""Device-transfer pipeline microbench: EVAM_TRANSFER pipelined vs inline.

CPU-only A/B through the REAL BatchEngine (engine/batcher.py): the
same deterministic elementwise step, the same wire-shaped uint8 rows,
once with the pipelined transfer (H2D issued on the dispatcher,
launch on the launcher thread, D2H put in flight at launch) and once
with the inline serial path (H2D + launch back-to-back on the
dispatcher — the pre-pipeline behavior `EVAM_TRANSFER=inline`
preserves byte-identically).

Two assertions, both gating:

* **bit-identical outputs** — every item's result through the
  pipelined engine equals the inline engine's, byte for byte (the
  pipeline moves copies around; it must never change a number);
* **throughput parity ≥ --min-speedup** — sustained items/s,
  pipelined / inline, as the MEDIAN of per-pair ratios over
  --windows adjacent window pairs (paired + order-alternated because
  a shared-vCPU host swings single windows by ±30%; the ratio within
  a pair cancels most of that). On CPU the two modes do the same
  total host work — the pipeline overlaps DEVICE time, it does not
  remove host work — so the truthful CPU expectation is parity
  (measured 0.95-1.1x across runs on the 1-vCPU dev box, median ~1.0)
  and the gate asserts the pipeline never costs meaningful
  throughput. The overlap win itself is device-bound — the axon
  tunnel's ~66 ms dispatch floor (PROFILE.md Finding 3) — which the
  per-stage attribution in the JSON line (h2d_issue / h2d_wait /
  launch / readback residual) exists to isolate on the next TPU
  window.

Prints ONE JSON line on stdout; diagnostics go to stderr.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from collections import deque
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _step(params, frames):
    # deterministic elementwise uint8 math: per-row results are
    # independent of batch composition/bucket, so the bit-identical
    # A/B holds regardless of how the two runs happened to batch
    return frames * 3 + 1


def _build_engine(mode: str, bucket: int, example: np.ndarray):
    from evam_tpu.engine.batcher import BatchEngine

    eng = BatchEngine(
        f"xfer-{mode}", _step, params=None, max_batch=bucket,
        deadline_ms=2.0, input_names=("frames",),
        stall_timeout_s=0, transfer=mode,
    )
    eng.set_example(frames=example)
    eng.warmup()  # compile every bucket before anything is timed
    return eng


def _identical(eng_a, eng_b, rows: list[np.ndarray]) -> bool:
    outs = []
    for eng in (eng_a, eng_b):
        futs = [eng.submit(frames=r) for r in rows]
        outs.append([f.result(timeout=120) for f in futs])
    return all(
        a.tobytes() == b.tobytes() for a, b in zip(outs[0], outs[1])
    )


def _drive(eng, rows: list[np.ndarray], items: int,
           feeders: int = 2) -> dict:
    """Fixed-work window: push exactly ``items`` rows through the
    engine from ``feeders`` threads (each pipelining up to 64
    in-flight futures) and clock the wall time to complete ALL of
    them; return items/s plus the per-batch stage means accumulated
    during the window (warmup batches subtracted out). Fixed work —
    rather than fixed time — keeps the two modes' windows exactly
    comparable on a noisy shared-vCPU host."""
    base_batches = eng.stats.batches
    base_stages = dict(eng.stats.stage_seconds)
    quota = [items // feeders + (1 if k < items % feeders else 0)
             for k in range(feeders)]

    def feeder(k: int):
        inflight: deque = deque()
        for j in range(quota[k]):
            inflight.append(eng.submit(frames=rows[(k + j) % len(rows)]))
            if len(inflight) > 64:
                inflight.popleft().result(timeout=120)
        while inflight:
            inflight.popleft().result(timeout=120)

    threads = [threading.Thread(target=feeder, args=(k,), daemon=True)
               for k in range(feeders)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    elapsed = time.perf_counter() - t0

    batches = eng.stats.batches - base_batches
    stage_ms = {
        s: round(1e3 * (eng.stats.stage_seconds.get(s, 0.0)
                        - base_stages.get(s, 0.0)) / max(batches, 1), 3)
        for s in ("h2d_issue", "h2d_wait", "launch", "readback")
    }
    return {
        "items_per_s": round(items / elapsed, 1),
        "batches": batches,
        "occupancy": round(
            (eng.stats.items / eng.stats.batches) if eng.stats.batches
            else 0.0, 1),
        "stage_ms": stage_ms,
    }


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--bucket", type=int, default=128,
                   help="top batch bucket (the hub's serving default)")
    p.add_argument("--height", type=int, default=324,
                   help="wire row height (default: a quarter-area "
                        "432x768 I420 wire row — full serving rows "
                        "make the CPU A/B take minutes, same code "
                        "path)")
    p.add_argument("--width", type=int, default=384)
    p.add_argument("--items", type=int, default=4096,
                   help="rows pushed through each engine per window "
                        "(fixed work, default 32 full serving "
                        "buckets)")
    p.add_argument("--min-speedup", type=float, default=0.9,
                   help="fail when the median pipelined/inline "
                        "throughput ratio drops below this — the "
                        "shared-vCPU noise floor under parity (the "
                        "pipeline must never meaningfully cost "
                        "throughput; the overlap WIN is device-bound "
                        "and measured on hardware)")
    p.add_argument("--windows", type=int, default=4,
                   help="adjacent window pairs; the median per-pair "
                        "ratio gates")
    p.add_argument("--smoke", action="store_true",
                   help="CI shape: short windows, correctness gates "
                        "only (bit-identical outputs + both modes "
                        "serve); the speedup still prints but does "
                        "not gate")
    args = p.parse_args()
    if args.smoke:
        args.items = min(args.items, 1024)

    import jax

    # the image's .axon_site hook rewrites JAX_PLATFORMS at jax
    # import; this tool is the CPU A/B by definition
    jax.config.update("jax_platforms", "cpu")

    rng = np.random.default_rng(0)
    rows = [rng.integers(0, 255, (args.height, args.width), np.uint8)
            for _ in range(16)]
    row_mb = rows[0].nbytes / 1e6
    log(f"bucket {args.bucket}, rows {args.height}x{args.width} uint8 "
        f"({row_mb:.2f} MB each), {args.items} rows per window")

    t0 = time.perf_counter()
    eng_pipe = _build_engine("pipelined", args.bucket, rows[0])
    eng_inline = _build_engine("inline", args.bucket, rows[0])
    log(f"engines warmed in {time.perf_counter() - t0:.1f}s")

    ident_rows = [rng.integers(0, 255, (args.height, args.width),
                               np.uint8) for _ in range(48)]
    identical = _identical(eng_pipe, eng_inline, ident_rows)
    log(f"bit-identical outputs: {identical}")

    # paired windows, order alternating pair to pair, so machine
    # noise (CPU steal, GC) hits both modes of a pair alike and the
    # per-pair ratio stays comparable
    windows = max(1, args.windows) if not args.smoke else 1
    engines = {"inline": eng_inline, "pipelined": eng_pipe}
    results = {"inline": None, "pipelined": None}
    ratios = []
    for k in range(windows):
        order = (("inline", "pipelined") if k % 2 == 0
                 else ("pipelined", "inline"))
        pair = {}
        for mode in order:
            r = _drive(engines[mode], rows, args.items)
            pair[mode] = r
            prev = results[mode]
            if prev is None or r["items_per_s"] > prev["items_per_s"]:
                results[mode] = r
            log(f"[{mode}] {r['items_per_s']:.0f} items/s, "
                f"{r['batches']} batches, stages {r['stage_ms']}")
        ratios.append(pair["pipelined"]["items_per_s"]
                      / max(pair["inline"]["items_per_s"], 1e-9))
    eng_pipe.stop()
    eng_inline.stop()

    speedup = float(np.median(ratios))
    log(f"per-pair ratios {[round(r, 3) for r in ratios]} "
        f"→ median {speedup:.2f}x (best windows: inline "
        f"{results['inline']['items_per_s']:.0f}, pipelined "
        f"{results['pipelined']['items_per_s']:.0f} items/s)")

    gate = 0.0 if args.smoke else args.min_speedup
    ok = bool(identical and speedup >= gate)
    print(json.dumps({
        "metric": "transfer_pipeline_speedup",
        "value": round(speedup, 2),
        "unit": "x",
        "identical": identical,
        "ratios": [round(r, 3) for r in ratios],
        "inline": results["inline"],
        "pipelined": results["pipelined"],
        "bucket": args.bucket,
        "row_shape": [args.height, args.width],
        "smoke": bool(args.smoke),
        "ok": ok,
    }))
    if not identical:
        log("FAIL: pipelined and inline outputs differ")
    if speedup < gate:
        log(f"FAIL: pipelined throughput below inline "
            f"({speedup:.2f}x < {gate:.2f}x)")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
