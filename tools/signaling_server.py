"""Reference WebRTC signaling server for evam-tpu streams.

The reference points EVAM at an external signaling stack
(WEBRTC_SIGNALING_SERVER, reference docker-compose.yml:51-52); this
is the matching in-repo implementation of that role: a tiny ws relay
between publishing services and viewers.

Protocol (JSON text frames):
  service -> {"type": "register", "stream": s}
  viewer  -> {"type": "watch", "stream": s, "sdp": <offer>}
  relay   -> service: {"type": "offer", "stream": s, "peer": id,
                        "sdp": <offer>}
  service -> {"type": "answer", "stream": s, "peer": id,
               "sdp": <answer>}
  relay   -> viewer: {"type": "answer", "sdp": <answer>}
  (media then flows service→viewer directly over SRTP/UDP)

Run: python tools/signaling_server.py [--port 8443]
Viewer page: deploy/webrtc_viewer.html
"""

from __future__ import annotations

import argparse
import asyncio
import itertools
import json


async def main() -> None:
    import websockets

    p = argparse.ArgumentParser()
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8443)
    args = p.parse_args()

    services: dict[str, object] = {}      # stream -> service ws
    viewers: dict[str, object] = {}       # peer id -> viewer ws
    peer_ids = itertools.count(1)

    async def handler(ws):
        role, stream, peer = None, None, None
        try:
            async for raw in ws:
                if isinstance(raw, (bytes, bytearray)):
                    continue  # MJPEG fallback frames: not relayed here
                msg = json.loads(raw)
                t = msg.get("type")
                if t == "register":
                    role, stream = "service", msg["stream"]
                    services[stream] = ws
                    print(f"service registered: {stream}")
                elif t == "watch":
                    role, stream = "viewer", msg["stream"]
                    peer = str(next(peer_ids))
                    viewers[peer] = ws
                    svc = services.get(stream)
                    if svc is None:
                        await ws.send(json.dumps(
                            {"type": "error",
                             "message": f"no such stream {stream}"}))
                        continue
                    await svc.send(json.dumps({
                        "type": "offer", "stream": stream,
                        "peer": peer, "sdp": msg["sdp"],
                    }))
                elif t == "answer":
                    viewer = viewers.get(str(msg.get("peer")))
                    if viewer is not None:
                        await viewer.send(json.dumps(
                            {"type": "answer", "sdp": msg["sdp"]}))
        finally:
            if role == "service" and services.get(stream) is ws:
                del services[stream]
            if peer is not None:
                viewers.pop(peer, None)
                svc = services.get(stream)
                if svc is not None:
                    try:
                        await svc.send(json.dumps(
                            {"type": "bye", "stream": stream,
                             "peer": peer}))
                    except Exception:  # noqa: BLE001
                        pass

    async with websockets.serve(handler, args.host, args.port) as server:
        port = server.sockets[0].getsockname()[1]
        print(f"signaling on ws://{args.host}:{port}", flush=True)
        await asyncio.Future()


if __name__ == "__main__":
    asyncio.run(main())
