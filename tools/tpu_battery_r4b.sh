#!/bin/bash
# Round-4 battery, REORDERED after the first serve attempt wedged the
# tunnel (04:06 stall log in /tmp/tpu_battery_r4): every quick
# measurement-debt entry runs BEFORE the serve family, so a serve-
# induced wedge can no longer take the whole round's record with it.
# The serve entries now preload+warm engines before streams start
# (bench.py change) — the prime wedge suspect was bucket-warmup
# compiles racing steady-state dispatch on the tunnel.
# Arm with:
#   bash tools/tpu_watch.sh tools/tpu_battery_r4b.sh /tmp/tpu_battery_r4b 43200 BENCH_SERVE_r04.json
set -u
OUT=${1:-/tmp/tpu_battery_r4b}
mkdir -p "$OUT"
cd "$(dirname "$0")/.."

FAILED=0
run() {
    name=$1; hard_timeout=$2; shift 2
    echo "=== $name: $* ===" | tee -a "$OUT/battery.log"
    timeout "$hard_timeout" "$@" >"$OUT/$name.json" 2>"$OUT/$name.err"
    local rc=$?
    echo "rc=$rc $(tail -1 "$OUT/$name.json" 2>/dev/null)" | tee -a "$OUT/battery.log"
    [ $rc -ne 0 ] && FAILED=$((FAILED + 1))
    python tools/fold_battery2.py "$OUT" BENCH_SERVE_r04.json \
        > "$OUT/folded.md" 2>>"$OUT/watch.log" || true
    return $rc
}

# after a wedge, re-establish the headline cheaply first
run default 600 python bench.py --seconds 12

# ---- the small-program debt (r3 item 2): these are minutes, not tens
run blocking 600 python tools/verify_blocking.py
run action 600 python bench.py --config action --seconds 8
run audio 600 python bench.py --config audio --seconds 8

# ---- layout + budget instruments (r3 items 1/weak-2, weak-4)
run ir_layout 900 python tools/profile_ir_layout.py
run budget 900 python tools/profile_budget.py

# ---- accuracy harness forward pass on the real chip (r4 item 3)
if [ -e tools/accuracy_device.py ]; then
    run accuracy 900 python tools/accuracy_device.py
fi

# ---- 40 ms p99 sweep (r3 item 1): still pre-serve, raw engine path
run sweep40 900 python bench.py --sweep --seconds 40 --p99-target-ms 40

# ---- IR-backed detect (models synthesized once, reused)
IRDIR=$OUT/omz_models
if [ ! -d "$IRDIR" ]; then
    # synthesize into a tmp dir and move atomically: a timeout-killed
    # partial tree must not satisfy the -d guard on the next re-arm
    rm -rf "$IRDIR.tmp"
    if timeout 900 python -m evam_tpu.cli.main fetch-models \
        --synthesize-omz all --topology manifest --output "$IRDIR.tmp" \
        >"$OUT/fetch.log" 2>&1; then
        mv "$IRDIR.tmp" "$IRDIR"
    fi
fi
run detect_ir 600 python bench.py --config detect --models-dir "$IRDIR" --seconds 8

# ---- host-ingest point
run host 600 python bench.py --ingest host --batch 8 --depth 2 --seconds 6

# ---- int8 quantized path (same checkpoint family, quant modules):
# if the MXU int8 path beats bf16, this is a headline lever
run detect_int8 600 python bench.py --config detect --precision int8 --seconds 8

# ---- THE serve family, LAST (r3 item 1). Shorter wrapper timeouts:
# a wedge here costs <=15 min per entry and nothing upstream.
run serve 900 python bench.py --config serve --streams 64 --seconds 24 --batch 256 --stall-timeout 180
run serve_b128 700 python bench.py --config serve --streams 64 --seconds 16 --batch 128 --stall-timeout 180
run serve_file_32 700 python bench.py --config serve --streams 32 --seconds 12 --batch 256 --serve-publish file --stall-timeout 180
run serve_ir 700 python bench.py --config serve --streams 64 --seconds 16 --batch 256 --models-dir "$IRDIR" --stall-timeout 180

echo "battery r4b complete -> $OUT ($FAILED failed)" | tee -a "$OUT/battery.log"
exit $((FAILED > 0))
