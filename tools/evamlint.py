#!/usr/bin/env python3
"""evamlint wrapper — the pre-commit entry point.

    tools/evamlint.py            # whole repo, like CI
    tools/evamlint.py --diff     # only files changed vs main
    tools/evamlint.py --json report.json

Thin shim over ``python -m evam_tpu.analysis`` so it works without an
installed package (adds the repo root to sys.path first).
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from evam_tpu.analysis.__main__ import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
