#!/bin/bash
# Round-5 battery — the evidence round (VERDICT r4 items 1+2).
# Ordering doctrine (PROFILE.md r3/r4 wedge history):
#   1. cheapest headline first (re-establish the record),
#   2. every quick measurement-debt entry next,
#   3. the serve family: serve_safe FIRST with --serialize-compile
#      (wedge-proof mode: global compile/execute lock + preload-first
#      — banks the first-ever serve-path TPU number even if later
#      entries wedge), then the unserialized serve entries (tests
#      whether preload-first alone holds),
#   4. tools/wedge_repro.py DEAD LAST: it deliberately recreates the
#      suspected wedge condition (background compiles racing steady
#      dispatch). If it wedges after serve survived, the hypothesis
#      is confirmed and the defense validated; nothing is lost.
# Arm with:
#   bash tools/tpu_watch.sh tools/tpu_battery_r5.sh /tmp/tpu_battery_r5 43200 BENCH_SERVE_r05.json
set -u
OUT=${1:-/tmp/tpu_battery_r5}
mkdir -p "$OUT"
cd "$(dirname "$0")/.."

FAILED=0
run() {
    name=$1; hard_timeout=$2; shift 2
    echo "=== $name: $* ===" | tee -a "$OUT/battery.log"
    timeout "$hard_timeout" "$@" >"$OUT/$name.json" 2>"$OUT/$name.err"
    local rc=$?
    echo "rc=$rc $(tail -1 "$OUT/$name.json" 2>/dev/null)" | tee -a "$OUT/battery.log"
    [ $rc -ne 0 ] && FAILED=$((FAILED + 1))
    python tools/fold_battery2.py "$OUT" BENCH_SERVE_r05.json \
        > "$OUT/folded.md" 2>>"$OUT/watch.log" || true
    return $rc
}

# 1 ---- re-establish the headline cheaply
run default 600 python bench.py --seconds 12

# 2 ---- the measurement debt (r3 item 2, third ask): minutes each
run blocking 600 python tools/verify_blocking.py
run action 600 python bench.py --config action --seconds 8
run audio 600 python bench.py --config audio --seconds 8
run budget 900 python tools/profile_budget.py
run sweep40 900 python bench.py --sweep --seconds 40 --p99-target-ms 40
if [ -e tools/accuracy_device.py ]; then
    run accuracy 900 python tools/accuracy_device.py
fi

# ---- IR-backed detect (models synthesized once, reused)
IRDIR=$OUT/omz_models
if [ ! -d "$IRDIR" ]; then
    rm -rf "$IRDIR.tmp"
    if timeout 900 python -m evam_tpu.cli.main fetch-models \
        --synthesize-omz all --topology manifest --output "$IRDIR.tmp" \
        >"$OUT/fetch.log" 2>&1; then
        mv "$IRDIR.tmp" "$IRDIR"
    fi
fi
run detect_ir 600 python bench.py --config detect --models-dir "$IRDIR" --seconds 8

# ---- host-ingest point
run host 600 python bench.py --ingest host --batch 8 --depth 2 --seconds 6

# 3 ---- THE serve family (r4 item 1, final ask). serve_safe first:
# both defenses on, banks the number; plain serve second: preload-
# first only (the r4 mitigation hypothesis under test).
run serve_safe 900 python bench.py --config serve --streams 64 --seconds 24 \
    --batch 256 --stall-timeout 180 --serialize-compile
run serve 900 python bench.py --config serve --streams 64 --seconds 24 \
    --batch 256 --stall-timeout 180
run serve_b128 700 python bench.py --config serve --streams 64 --seconds 16 \
    --batch 128 --stall-timeout 180 --serialize-compile
run serve_file_32 700 python bench.py --config serve --streams 32 --seconds 12 \
    --batch 256 --serve-publish file --stall-timeout 180 --serialize-compile
run serve_ir 700 python bench.py --config serve --streams 64 --seconds 16 \
    --batch 256 --models-dir "$IRDIR" --stall-timeout 180 --serialize-compile
# live-RTSP ingest through the async demux: tunnel-bound here (real
# pixels ride the ~18 MB/s link) but the first ever live-path number
run serve_rtsp_8 700 python bench.py --config serve --serve-ingest rtsp \
    --streams 8 --seconds 12 --batch 32 --width 640 --height 480 \
    --stall-timeout 180 --serialize-compile

# 4 ---- the deliberate wedge repro, DEAD LAST (may take the tunnel
# down — that outcome IS the datum). Unserialized on purpose.
run wedge_repro 600 python tools/wedge_repro.py --seconds 8
# control: same structure under the lock — if the first repro wedged,
# this one never runs (the wrapper timeout + wedged tunnel), which the
# log records; if both run, compare overlap_max.
run wedge_repro_locked 600 python tools/wedge_repro.py --seconds 8 --serialize

echo "battery r5 complete -> $OUT ($FAILED failed)" | tee -a "$OUT/battery.log"
exit $((FAILED > 0))
