#!/usr/bin/env python
"""Live-soak drop ATTRIBUTION: where does the <10% budget actually go?

VERDICT item 5: the 64-stream live soak asserted a blanket drop rate;
a framework regression could hide inside it. This tool runs the same
live-paced loopback shape (RTSP feeders → shared async demux → stage
chain → publish) and reports EVERY loss layer separately:

* ``demux.dropped_decode``      — shared decode workers behind
  (decode-bound; the ingest layer's own ceiling);
* ``demux.dropped_downstream``  — the per-stream emit queue was full
  (runner/engine behind — backpressure working as designed);
* ``engine shed``               — QoS staleness shedding
  (evam_sched_shed_total, only with EVAM_SCHED on);
* ``publish dropped``           — destination backpressure
  (evam_publish_dropped{dest});
* ``runner errors``             — per-frame faults (injected or real).

``--null-engine`` runs the identical ingest load through the
``video_decode/app_dst`` pipeline (decode → sink, NO inference), the
decode-bound control: any drops there are pure framework/ingest
overhead, so the engine's contribution in the full run is separable
by subtraction. INGEST.md records the attribution from both modes.

The accounting gate: total demux drops must equal the sum of the two
demux layers (no unattributed loss), and with instant-decode frames
on this box the control run is expected lossless.

Prints ONE JSON line on stdout; diagnostics go to stderr.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--streams", type=int, default=16)
    p.add_argument("--fps", type=float, default=4.0)
    p.add_argument("--seconds", type=float, default=10.0,
                   help="steady-state measurement window")
    p.add_argument("--null-engine", action="store_true",
                   help="decode-bound control: video_decode/app_dst "
                        "(no inference stage) under the same load")
    p.add_argument("--max-drop-frac", type=float, default=0.10,
                   help="steady-state demux drop budget (gate)")
    args = p.parse_args()

    import os

    os.environ.setdefault("EVAM_ALLOW_RANDOM_WEIGHTS", "1")

    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from evam_tpu.config import Settings
    from evam_tpu.engine import EngineHub
    from evam_tpu.models import ModelRegistry, ZOO_SPECS
    from evam_tpu.obs.metrics import metrics
    from evam_tpu.parallel import build_mesh
    from evam_tpu.publish.rtsp import RtspServer
    from evam_tpu.server.registry import PipelineRegistry

    small = {k: (64, 64) for k in ZOO_SPECS}
    small["audio_detection/environment"] = (1, 1600)
    narrow = {k: 8 for k in ZOO_SPECS}
    hub = EngineHub(
        ModelRegistry(dtype="float32", input_overrides=small,
                      width_overrides=narrow),
        plan=build_mesh(), max_batch=16, deadline_ms=4.0,
    )
    settings = Settings(pipelines_dir=str(REPO / "pipelines"),
                        rtsp_demux_workers=2)
    reg = PipelineRegistry(settings, hub=hub)

    pipeline = (("video_decode", "app_dst") if args.null_engine
                else ("object_tracking", "person_vehicle_bike"))
    log(f"mode: {'null-engine control' if args.null_engine else 'full'} "
        f"({'/'.join(pipeline)}), {args.streams} streams @ {args.fps} f/s")

    srv = RtspServer(port=0, host="127.0.0.1")
    srv.start()
    stop_feed = threading.Event()

    def feeder(relay, i):
        k = 0
        f = np.zeros((96, 96, 3), np.uint8)
        f[:, :, 2] = (3 * i) % 256
        while not stop_feed.is_set():
            f[:, :, 1] = (k * 5) % 256
            relay.push_bgr(f)
            k += 1
            time.sleep(1 / args.fps)

    for i in range(args.streams):
        threading.Thread(target=feeder, args=(srv.mount(f"cam{i}"), i),
                         daemon=True).start()

    def publish_drops() -> float:
        return metrics.counter_total("evam_publish_dropped")

    try:
        if not args.null_engine:
            reg.preload("object_tracking")
            for _, e in reg.hub._engines.items():
                e.warmed.wait(timeout=120)
        insts = [
            reg.start_instance(*pipeline, {
                "source": {"uri": f"rtsp://127.0.0.1:{srv.port}/cam{i}",
                           "type": "uri"},
                "destination": {"metadata": {"type": "null"}},
            })
            for i in range(args.streams)
        ]
        time.sleep(4.0)  # past the handshake storm
        demux = reg.rtsp_demux
        base = demux.stats()
        base_shed = reg.hub.shed_totals()
        base_pub = publish_drops()
        base_err = sum(i._runner.errors for i in insts if i._runner)
        t0 = time.perf_counter()
        time.sleep(args.seconds)
        elapsed = time.perf_counter() - t0
        stats = demux.stats()
        shed = reg.hub.shed_totals()

        win = {
            "decoded": stats["decoded"] - base["decoded"],
            "demux_dropped_decode":
                stats["dropped_decode"] - base["dropped_decode"],
            "demux_dropped_downstream":
                stats["dropped_downstream"] - base["dropped_downstream"],
            "engine_shed": {
                c: shed.get(c, 0) - base_shed.get(c, 0) for c in shed},
            "publish_dropped": publish_drops() - base_pub,
            "runner_errors": sum(
                i._runner.errors for i in insts if i._runner) - base_err,
        }
        states = [i.state.value for i in insts]
    finally:
        stop_feed.set()
        reg.stop_all()
        srv.stop()

    win_dropped = (win["demux_dropped_decode"]
                   + win["demux_dropped_downstream"])
    total_demux = stats["dropped_decode"] + stats["dropped_downstream"]
    accounted = stats["dropped"] == total_demux
    drop_frac = win_dropped / max(1, win["decoded"])
    alive = all(s in ("RUNNING", "QUEUED") for s in states)
    ok = bool(accounted and alive
              and drop_frac <= args.max_drop_frac
              and win["decoded"] > 0)
    log(f"window {elapsed:.1f}s: {win}")
    print(json.dumps({
        "metric": "soak_drop_attribution",
        "mode": "null_engine" if args.null_engine else "full",
        "streams": args.streams,
        "fps": args.fps,
        "window_s": round(elapsed, 1),
        **win,
        "drop_frac": round(drop_frac, 4),
        "drops_accounted": accounted,
        "all_alive": alive,
        "ok": ok,
    }))
    if not accounted:
        log("FAIL: demux total != decode-side + downstream-side drops")
    if drop_frac > args.max_drop_frac:
        log(f"FAIL: drop fraction {drop_frac:.3f} > {args.max_drop_frac}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
