"""Ground-truth accuracy on the REAL chip (battery entry `accuracy`).

Two phases:

1. **fit** (CPU subprocess, ~3 min, cached): trains the zoo SSD on
   synthetic ground-truth scenes via evam_tpu.models.accuracy and
   saves weights to a /tmp cache keyed on the fit config — rerun the
   battery and the fit is reused.
2. **eval** (this process, default backend = the TPU): loads the
   fitted weights, renders the same held-out 1080p scenes as
   ``tests/test_accuracy.py`` (seed 99), runs the fused i420 detect
   step on the device, and reports recall/precision plus the max
   divergence of the packed rows vs the CPU reference — device
   numerics AND geometry in one line.

Prints ONE JSON line (battery/fold contract).
"""

from __future__ import annotations

import os as _os
_os.environ.setdefault("EVAM_ALLOW_RANDOM_WEIGHTS", "1")  # hermetic tool

import json
import os
import subprocess
import sys
import time
from pathlib import Path

KEY = "object_detection/person_vehicle_bike"
INPUT = (96, 96)
WIDTH = 16
SEED = 99
CLS_KEY = "object_classification/vehicle_attributes"
CLS_INPUT = (48, 48)
CLS_WIDTH = 16
ENC_KEY = "action_recognition/encoder"
DEC_KEY = "action_recognition/decoder"
AUD_KEY = "audio_detection/environment"
ENC_INPUT = (48, 48)
TEMPORAL_WIDTH = 8
#: cache keyed on the fit config — stale weights from an older
#: KEY/INPUT/WIDTH can't poison a new run
FIT_PATH = Path(
    f"/tmp/evam_acc_fit_{KEY.replace('/', '_')}"
    f"_{INPUT[0]}x{INPUT[1]}_w{WIDTH}.msgpack")
#: color-attr variant (detector refit on attr scenes) + classifier —
#: the fused detect+classify / wire-plane-ROI-crop assertion
FIT_ATTR_PATH = FIT_PATH.with_suffix(".attr.msgpack")
CLS_FIT_PATH = Path(
    f"/tmp/evam_acc_fit_{CLS_KEY.replace('/', '_')}"
    f"_{CLS_INPUT[0]}x{CLS_INPUT[1]}_w{CLS_WIDTH}.msgpack")
#: temporal families (action enc+dec, aclnet) — one cache file each
ENC_FIT_PATH = Path(
    f"/tmp/evam_acc_fit_action_enc_{ENC_INPUT[0]}x{ENC_INPUT[1]}"
    f"_w{TEMPORAL_WIDTH}.msgpack")
DEC_FIT_PATH = ENC_FIT_PATH.with_suffix(".dec.msgpack")
AUD_FIT_PATH = Path(
    f"/tmp/evam_acc_fit_aclnet_w{TEMPORAL_WIDTH}.msgpack")


def _build():
    from evam_tpu.models.registry import ModelRegistry

    reg = ModelRegistry(dtype="float32", input_overrides={KEY: INPUT},
                        width_overrides={KEY: WIDTH},
                        allow_random_weights=True)
    return reg.get(KEY)


def _build_cls():
    from evam_tpu.models.registry import ModelRegistry

    reg = ModelRegistry(
        dtype="float32", input_overrides={CLS_KEY: CLS_INPUT},
        width_overrides={CLS_KEY: CLS_WIDTH},
        allow_random_weights=True)
    return reg.get(CLS_KEY)


def _build_temporal():
    from evam_tpu.models.registry import ModelRegistry

    reg = ModelRegistry(
        dtype="float32", input_overrides={ENC_KEY: ENC_INPUT},
        width_overrides={ENC_KEY: TEMPORAL_WIDTH,
                         DEC_KEY: TEMPORAL_WIDTH,
                         AUD_KEY: TEMPORAL_WIDTH},
        allow_random_weights=True)
    return reg.get(ENC_KEY), reg.get(DEC_KEY), reg.get(AUD_KEY)


def run_fit() -> int:
    """CPU-pinned subprocess body: fit + save."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from flax import serialization

    from evam_tpu.models import accuracy as acc

    model = _build()
    params, history = acc.fit_detector(model, steps=1200, n_scenes=128)
    print(json.dumps({"fit_final_loss": history[-1]}), file=sys.stderr)
    if history[-1] >= 0.5:
        # never cache a diverged fit — the next run must retry
        print("fit did not converge; not caching", file=sys.stderr)
        return 3
    FIT_PATH.write_bytes(serialization.to_bytes(
        jax.tree.map(lambda a: __import__("numpy").asarray(a), params)))
    return 0


def run_fit_classify() -> int:
    """CPU-pinned subprocess: color-attr detector + classifier fits."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from flax import serialization

    from evam_tpu.models import accuracy as acc

    model = _build()
    params, history = acc.fit_detector(
        model, steps=1200, n_scenes=128, color_attr=True)
    cls_model = _build_cls()
    cls_params, chist = acc.fit_classifier(
        cls_model, steps=900, n_crops=768)
    print(json.dumps({"det_attr_loss": history[-1],
                      "cls_loss": chist[-1]}), file=sys.stderr)
    if history[-1] >= 0.6 or chist[-1] >= 0.2:
        print("classify fits did not converge; not caching",
              file=sys.stderr)
        return 3
    FIT_ATTR_PATH.write_bytes(serialization.to_bytes(
        jax.tree.map(np.asarray, params)))
    CLS_FIT_PATH.write_bytes(serialization.to_bytes(
        jax.tree.map(np.asarray, cls_params)))
    return 0


def run_fit_temporal() -> int:
    """CPU-pinned subprocess: action enc+dec and aclnet fits."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from flax import serialization

    from evam_tpu.models import accuracy as acc

    enc, dec, aud = _build_temporal()
    (ep, dp), hist = acc.fit_action(enc, dec)
    ap, ahist = acc.fit_audio(aud)
    print(json.dumps({"action_loss": hist[-1],
                      "audio_loss": ahist[-1]}), file=sys.stderr)
    if hist[-1] >= 0.6 or ahist[-1] >= 0.3:
        print("temporal fits did not converge; not caching",
              file=sys.stderr)
        return 3
    ENC_FIT_PATH.write_bytes(serialization.to_bytes(
        jax.tree.map(np.asarray, ep)))
    DEC_FIT_PATH.write_bytes(serialization.to_bytes(
        jax.tree.map(np.asarray, dp)))
    AUD_FIT_PATH.write_bytes(serialization.to_bytes(
        jax.tree.map(np.asarray, ap)))
    return 0


def main() -> int:
    if "--fit" in sys.argv:
        return run_fit()
    if "--fit-classify" in sys.argv:
        return run_fit_classify()
    if "--fit-temporal" in sys.argv:
        return run_fit_temporal()

    if not FIT_PATH.exists():
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        try:
            rc = subprocess.run(
                [sys.executable, __file__, "--fit"], env=env,
                timeout=900).returncode
        except subprocess.TimeoutExpired:
            rc = -9
        if rc != 0 or not FIT_PATH.exists():
            print(json.dumps({"metric": "accuracy_recall_1080p_i420",
                              "value": 0.0, "unit": "recall",
                              "error": f"fit failed rc={rc}"}))
            return 1
    attr_error = None
    if not (FIT_ATTR_PATH.exists() and CLS_FIT_PATH.exists()):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        try:
            crc = subprocess.run(
                [sys.executable, __file__, "--fit-classify"], env=env,
                timeout=900).returncode
        except subprocess.TimeoutExpired:
            crc = -9
        if crc != 0 or not (FIT_ATTR_PATH.exists()
                            and CLS_FIT_PATH.exists()):
            # classify phase is additive (detect still reports), but
            # an attempted-and-failed fit must be visible in the line
            attr_error = f"fit-classify failed rc={crc}"
    temporal_error = None
    if not (ENC_FIT_PATH.exists() and DEC_FIT_PATH.exists()
            and AUD_FIT_PATH.exists()):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        try:
            trc = subprocess.run(
                [sys.executable, __file__, "--fit-temporal"], env=env,
                timeout=900).returncode
        except subprocess.TimeoutExpired:
            trc = -9
        if trc != 0 or not (ENC_FIT_PATH.exists()
                            and DEC_FIT_PATH.exists()
                            and AUD_FIT_PATH.exists()):
            temporal_error = f"fit-temporal failed rc={trc}"

    import jax

    # the image's .axon_site hook rewrites JAX_PLATFORMS at jax
    # import; EVAM_PLATFORM=cpu pins the config back (same knob as
    # cli.main) for CPU smoke runs
    if os.environ.get("EVAM_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["EVAM_PLATFORM"])
    import numpy as np
    from flax import serialization

    from evam_tpu.engine.steps import build_detect_step
    from evam_tpu.models import accuracy as acc
    from evam_tpu.ops.color import bgr_to_i420_host

    model = _build()
    params = serialization.from_bytes(model.params, FIT_PATH.read_bytes())

    rng = np.random.default_rng(SEED)
    scenes = [acc.render_scene(rng, hw=(1080, 1920)) for _ in range(8)]
    wire = np.stack([bgr_to_i420_host(s.frame) for s in scenes])
    step = build_detect_step(model, max_detections=16,
                             score_threshold=0.3, wire_format="i420")

    dev = jax.devices()[0]
    fn = jax.jit(step)
    t0 = time.time()
    packed_dev = np.asarray(jax.block_until_ready(fn(
        jax.device_put(params, dev), jax.device_put(wire, dev))))
    dt = time.time() - t0
    report = acc.evaluate_packed(packed_dev, scenes)

    # CPU reference for numeric divergence (committed inputs pick the
    # backend; same jitted fn recompiles for the cpu placement)
    cpu = jax.devices("cpu")[0]
    packed_cpu = np.asarray(fn(
        jax.device_put(params, cpu), jax.device_put(wire, cpu)))
    raw_div = np.abs(packed_dev[..., :5] - packed_cpu[..., :5]).max()
    # non-finite divergence IS the finding — keep the line valid JSON
    max_div = float(raw_div) if np.isfinite(raw_div) else str(raw_div)

    line = {
        "metric": "accuracy_recall_1080p_i420",
        "value": round(report["recall"], 4),
        "unit": "recall@iou0.5",
        "precision": round(report["precision"], 4),
        "gt": report["gt"],
        "device": str(dev.platform),
        "first_call_s": round(dt, 2),
        "max_divergence_vs_cpu": max_div,
    }

    # fused detect+classify on device: exercises the wire-plane ROI
    # crop (crop_rois_i420) geometry + classifier numerics on chip
    if FIT_ATTR_PATH.exists() and CLS_FIT_PATH.exists():
        from evam_tpu.engine.steps import build_detect_classify_step

        det_attr = serialization.from_bytes(
            model.params, FIT_ATTR_PATH.read_bytes())
        cls_model = _build_cls()
        cls_params = serialization.from_bytes(
            cls_model.params, CLS_FIT_PATH.read_bytes())
        rng2 = np.random.default_rng(123)
        cscenes = [acc.render_scene(rng2, hw=(1080, 1920),
                                    color_attr=True)
                   for _ in range(12)]
        cwire = np.stack(
            [bgr_to_i420_host(s.frame) for s in cscenes])
        cstep = jax.jit(build_detect_classify_step(
            model, cls_model, max_detections=16, roi_budget=8,
            score_threshold=0.3, wire_format="i420",
            allowed_label_ids=(2,)))
        cparams = {"det": det_attr, "cls": cls_params}
        cp = np.asarray(jax.block_until_ready(cstep(
            jax.device_put(cparams, dev),
            jax.device_put(cwire, dev))))
        attr_report = acc.evaluate_attrs(cp, cscenes)
        line["attr_recall"] = round(attr_report["attr_recall"], 4)
        line["attr_gt"] = attr_report["gt"]
    elif attr_error is not None:
        line["attr_error"] = attr_error

    # temporal families on device: action clip classes + audio tones
    if (ENC_FIT_PATH.exists() and DEC_FIT_PATH.exists()
            and AUD_FIT_PATH.exists()):
        from evam_tpu.engine.steps import (
            build_action_decode_step,
            build_action_encode_step,
            build_audio_step,
        )

        enc, dec_m, aud = _build_temporal()
        ep = serialization.from_bytes(
            enc.params, ENC_FIT_PATH.read_bytes())
        dp = serialization.from_bytes(
            dec_m.params, DEC_FIT_PATH.read_bytes())
        ap = serialization.from_bytes(
            aud.params, AUD_FIT_PATH.read_bytes())
        enc_step = jax.jit(build_action_encode_step(
            enc, wire_format="bgr"))
        dec_step = jax.jit(build_action_decode_step(dec_m))
        rng3 = np.random.default_rng(21)
        classes = [i % 4 for i in range(8)]
        clips = np.stack([
            acc.render_temporal_clip(rng3, c, ENC_INPUT, 16)
            for c in classes])                    # [8, 16, H, W, 3]
        ep_d = jax.device_put(ep, dev)
        dp_d = jax.device_put(dp, dev)
        flat = clips.reshape((-1,) + clips.shape[2:])
        emb = enc_step(ep_d, jax.device_put(flat, dev))
        emb = np.asarray(emb).reshape(8, 16, -1)
        aprobs = np.asarray(dec_step(dp_d, jax.device_put(emb, dev)))
        line["action_acc"] = float(
            (aprobs.argmax(axis=1) == np.asarray(classes)).mean())

        audio_step = jax.jit(build_audio_step(aud))
        rng4 = np.random.default_rng(22)
        n_samples = aud.spec.input_size[1]  # aclnet window (matches
        # fit_audio's sizing — no duplicated constant)
        wins = []
        tones = []
        for i in range(8):
            t = i % 4
            tones.append(t)
            wins.append(acc.render_tone_window(rng4, t, n_samples))
        probs = np.asarray(audio_step(
            jax.device_put(ap, dev),
            jax.device_put(np.stack(wins), dev)))
        line["audio_acc"] = float(
            (probs.argmax(axis=1) == np.asarray(tones)).mean())
    elif temporal_error is not None:
        line["temporal_error"] = temporal_error

    print(json.dumps(line))
    return 0 if report["recall"] >= 0.75 else 1


if __name__ == "__main__":
    sys.exit(main())
