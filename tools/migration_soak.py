#!/usr/bin/env python
"""Migration soak: crash-consistent stream state end to end.

The acceptance drill for the checkpointed-state PR (evam_tpu/state/):
three phases, each a state-loss path the StreamCheckpoint must cover,
asserting the contract on a CPU host fleet
(``--xla_force_host_platform_device_count``):

A. **Live migration** — a sharded fleet (EVAM_FLEET=sharded) serves
   realtime tracking streams (gate + IouTracker + coaster state live)
   with EVAM_CKPT=on when a deliberate ``scale_down()`` retires one
   chip mid-traffic. Every moved stream is checkpointed at the
   pre-rebalance barrier and counted on
   ``evam_stream_migrations_total{reason="scale_down"}``; zero
   realtime streams fail; every held blob decodes (CRC + schema) and
   is within the gate's max-skip staleness bound.

B. **Crash-consistent restart** — streams are stopped via the drain
   path (``stop_all``), which banks a drain-barrier checkpoint into
   streams.json; a fresh registry ``resume()``s them and the restored
   instances report ``restored_from`` with the tracker id high-water
   mark preserved (identities never reset across a restart).

C. **Corruption drill** — ``EVAM_FAULT_INJECT=ckpt_corrupt=1`` flips
   the CRC on the banked checkpoint; the resume is a LOUD COLD START:
   ``evam_ckpt_restore_failures_total{reason="crc"}`` increments, the
   stream still starts and serves, and the engine restart budget is
   untouched (no wedge, no supervisor burn).

Exit 0 iff every phase holds. Prints ONE JSON line on stdout;
diagnostics on stderr. ``--smoke`` is the CI shape (~small streams /
short windows); the default shape is the soak-battery one.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time
from pathlib import Path

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("EVAM_ALLOW_RANDOM_WEIGHTS", "1")
os.environ.setdefault("EVAM_LOG_LEVEL", "warning")
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

PIPELINE = ("object_tracking", "person_vehicle_bike")


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _model_registry():
    from evam_tpu.models import ModelRegistry, ZOO_SPECS

    small = {k: (64, 64) for k in ZOO_SPECS}
    small["audio_detection/environment"] = (1, 1600)
    return ModelRegistry(dtype="float32", input_overrides=small,
                         width_overrides={k: 8 for k in ZOO_SPECS})


def _build_registry(state_dir: str | None = None, shards: int = 0):
    """A PipelineRegistry over a fresh hub: sharded fleet when
    ``shards`` > 1, single-chip otherwise."""
    import jax

    from evam_tpu.config import Settings
    from evam_tpu.engine import EngineHub
    from evam_tpu.parallel import build_mesh
    from evam_tpu.server.registry import PipelineRegistry

    plan = (build_mesh(devices=list(jax.devices())[:shards])
            if shards > 1 else build_mesh())
    hub = EngineHub(
        _model_registry(), plan=plan, max_batch=16, deadline_ms=4.0,
        warmup=True, supervise=True, max_restarts=3,
        restart_backoff_s=0.1,
        fleet="sharded" if shards > 1 else "off",
    )
    settings = Settings(pipelines_dir=str(REPO / "pipelines"),
                        state_dir=state_dir or "")
    registry = PipelineRegistry(settings, hub=hub)
    registry.preload(f"{PIPELINE[0]}/{PIPELINE[1]}")
    deadline = time.time() + 180
    while time.time() < deadline:
        ready = hub.readiness()
        if ready["engines"] and not ready["warming"]:
            return registry
        time.sleep(0.1)
    registry.stop_all()
    raise RuntimeError("engines never warmed")


def _start_streams(registry, n: int, frames: int, seed0: int = 0):
    return [
        registry.start_instance(
            *PIPELINE,
            {
                "source": {
                    "uri": f"synthetic://96x96@30?count={frames}"
                           f"&seed={seed0 + i}",
                    "type": "uri",
                    "realtime": True,
                },
                "destination": {"metadata": {"type": "null"}},
                "priority": "realtime",
            },
        )
        for i in range(n)
    ]


def _tracker_next_ids(insts) -> dict[str, int]:
    out = {}
    for inst in insts:
        for st in (inst.stage_state() or {}).values():
            if isinstance(st, dict) and "next_id" in st:
                out[inst.id] = int(st["next_id"])
    return out


def phase_live_migration(streams: int, frames: int, shards: int) -> dict:
    """Phase A: scale_down() under live traffic."""
    from evam_tpu import state as stream_state
    from evam_tpu.obs.metrics import metrics
    from evam_tpu.state import decode

    registry = _build_registry(shards=shards)
    store = stream_state.active()
    mig0 = metrics.get_counter(
        "evam_stream_migrations", labels={"reason": "scale_down"})
    t0 = time.time()
    try:
        insts = _start_streams(registry, streams, frames)
        time.sleep(max(1.5, frames / 30.0 * 0.3))
        retired = []
        for eng in list(registry.hub._engines.values()):
            if hasattr(eng, "scale_down"):
                label = eng.scale_down()
                if label:
                    retired.append(label)
        log(f"phase A: retired shard(s) {retired} mid-traffic")
        for inst in insts:
            inst.wait(timeout=max(30.0, frames / 30.0 * 4))
        states = [i.state.value for i in insts]
        blobs = [store.export(i.id) for i in insts]
        fleet = registry.hub.fleet_summary()
    finally:
        registry.stop_all()
    mig = metrics.get_counter(
        "evam_stream_migrations", labels={"reason": "scale_down"}) - mig0
    decoded, stale, barriers = 0, 0, set()
    for blob in blobs:
        if blob is None:
            continue
        ck = decode(blob)  # raises on CRC/version damage
        decoded += 1
        barriers.add(ck.barrier)
        if ck.is_stale():
            stale += 1
    failed = [s for s in states if s != "COMPLETED"]
    ok = (not failed and int(mig) >= 1 and decoded >= 1 and stale == 0
          and bool(retired))
    return {
        "ok": ok, "states": states, "migrations": int(mig),
        "retired_shards": retired, "checkpoints_decoded": decoded,
        "stale_checkpoints": stale, "barriers": sorted(barriers),
        "fleet": fleet, "elapsed_s": round(time.time() - t0, 1),
    }


def phase_resume(streams: int, frames: int) -> dict:
    """Phase B: drain-checkpoint -> fresh registry resume()."""
    from evam_tpu import state as stream_state
    from evam_tpu.state import is_checkpoint_blob

    state_dir = tempfile.mkdtemp(prefix="evam-migration-")
    t0 = time.time()
    registry = _build_registry(state_dir=state_dir)
    insts = _start_streams(registry, streams, frames, seed0=100)
    # let tracker/gate state accumulate past the capture interval
    time.sleep(max(2.0, frames / 30.0 * 0.4))
    pre_ids = _tracker_next_ids(insts)
    leaked = registry.stop_all()
    entries = json.loads(
        (Path(state_dir) / "streams.json").read_text())
    blob_entries = sum(
        1 for e in entries if is_checkpoint_blob(e.get("state")))
    store = stream_state.active()
    restored0 = store.summary()["restored"]
    registry2 = _build_registry(state_dir=state_dir)
    try:
        resumed = registry2.resume()
        insts2 = list(registry2.instances.values())
        restored_from = [
            i.status().get("checkpoint", {}).get("restored_from")
            for i in insts2
        ]
        post_ids = _tracker_next_ids(insts2)
    finally:
        registry2.stop_all()
    restored = store.summary()["restored"] - restored0
    # identity continuity: the resumed tracker id high-water mark is
    # never BELOW what the first run had assigned
    id_ok = (post_ids and pre_ids
             and min(post_ids.values()) >= min(pre_ids.values()))
    ok = (leaked == 0 and len(entries) == streams
          and blob_entries == streams and resumed == streams
          and restored >= streams
          and all(r is not None for r in restored_from)
          and bool(id_ok))
    return {
        "ok": ok, "leaked": leaked, "entries": len(entries),
        "checkpoint_entries": blob_entries, "resumed": resumed,
        "restored": int(restored), "restored_from": restored_from,
        "pre_next_ids": pre_ids, "post_next_ids": post_ids,
        "elapsed_s": round(time.time() - t0, 1),
    }


def phase_corruption(frames: int) -> dict:
    """Phase C: corrupted checkpoint -> loud cold start, no wedge."""
    from evam_tpu.obs import faults
    from evam_tpu.obs.metrics import metrics

    state_dir = tempfile.mkdtemp(prefix="evam-migration-crc-")
    t0 = time.time()
    registry = _build_registry(state_dir=state_dir)
    _start_streams(registry, 1, frames, seed0=200)
    time.sleep(2.0)
    # arm corruption for the DRAIN capture only: the banked blob's CRC
    # is flipped, so the resume side must take the crc rung of the
    # degradation ladder
    os.environ["EVAM_FAULT_INJECT"] = "ckpt_corrupt=1"
    faults.reset_cache()
    registry.stop_all()
    os.environ["EVAM_FAULT_INJECT"] = ""
    faults.reset_cache()
    crc0 = metrics.get_counter(
        "evam_ckpt_restore_failures", labels={"reason": "crc"})
    registry2 = _build_registry(state_dir=state_dir)
    restarts0 = registry2.hub.readiness()["restarts"]
    try:
        resumed = registry2.resume()
        time.sleep(1.0)
        ready = registry2.hub.readiness()
        states = [i.state.value for i in registry2.instances.values()]
    finally:
        registry2.stop_all()
    crc_failures = metrics.get_counter(
        "evam_ckpt_restore_failures", labels={"reason": "crc"}) - crc0
    ok = (
        resumed == 1
        and int(crc_failures) >= 1              # loud
        and ready["restarts"] - restarts0 == 0  # no budget burn
        and ready["degraded"] == 0              # no wedge
        and all(s in ("RUNNING", "COMPLETED") for s in states)
    )
    return {
        "ok": ok, "resumed": resumed, "crc_failures": int(crc_failures),
        "restart_delta": ready["restarts"] - restarts0,
        "degraded": ready["degraded"], "states": states,
        "elapsed_s": round(time.time() - t0, 1),
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="CI shape: small streams, short windows")
    ap.add_argument("--streams", type=int, default=4)
    ap.add_argument("--frames", type=int, default=240)
    ap.add_argument("--shards", type=int, default=4)
    args = ap.parse_args()
    if args.smoke:
        args.streams, args.frames, args.shards = 2, 150, 3

    from evam_tpu import state as stream_state
    from evam_tpu.config.settings import reset_settings
    from evam_tpu.obs import faults

    os.environ["EVAM_CKPT"] = "on"
    os.environ["EVAM_CKPT_INTERVAL"] = "5"
    os.environ["EVAM_GATE"] = "on"
    os.environ["EVAM_FAULT_INJECT"] = ""
    reset_settings()
    faults.reset_cache()
    stream_state.reset_cache()
    try:
        a = phase_live_migration(args.streams, args.frames, args.shards)
        log(f"phase A (live migration): {a}")
        b = phase_resume(args.streams, args.frames)
        log(f"phase B (resume): {b}")
        c = phase_corruption(args.frames)
        log(f"phase C (corruption): {c}")
    finally:
        for key in ("EVAM_CKPT", "EVAM_CKPT_INTERVAL", "EVAM_GATE",
                    "EVAM_FAULT_INJECT"):
            os.environ.pop(key, None)
        reset_settings()
        faults.reset_cache()
        stream_state.reset_cache()
    ok = a["ok"] and b["ok"] and c["ok"]
    print(json.dumps({
        "metric": "migration_soak_failed_phases",
        "value": sum(1 for p in (a, b, c) if not p["ok"]),
        "unit": "phases",
        "vs_baseline": 0.0,
        "ok": ok,
        "live_migration": a,
        "resume": b,
        "corruption": c,
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
