"""Does block_until_ready actually block on the axon backend?

Round-2's recorded action/audio rates (13.5k vs 157k "streams",
PROFILE.md) were mutually inconsistent by ~10×, and both imply batch
rates far above the measured ~66 ms/dispatch tunnel floor — the prime
suspect is the bench's completion wait. This probe times the SAME
small program three ways:

  a) submit-only (no wait)            — pure dispatch enqueue rate
  b) jax.block_until_ready(out)       — what bench.py's loop does
  c) np.asarray(out)                  — forced device→host readback

On a healthy backend (b) and (c) differ only by the copy time and
both sit at/above the RPC floor; (b) ≈ (a) « (c) instead means
block_until_ready returns before execution completes on this
experimental platform, and every recorded number that relied on it
for small programs must be re-derived from (c).

Prints one JSON line with the three per-call times for the action
encoder (b256) and the audio net (b256).
"""

from __future__ import annotations

import os as _os
_os.environ.setdefault("EVAM_ALLOW_RANDOM_WEIGHTS", "1")  # hermetic profiling tool

import json
import sys
import time

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _time_mode(fn, params, n_calls, mode):
    import jax

    outs = []
    t0 = time.perf_counter()
    for i in range(n_calls):
        out = fn(params, np.uint32(i))
        if mode == "block":
            jax.block_until_ready(out)
        elif mode == "asarray":
            np.asarray(out)
        else:
            outs.append(out)  # keep alive, no wait
    if mode == "submit":
        for o in outs:
            np.asarray(o)  # drain at the end (not timed per-call)
    return (time.perf_counter() - t0) / n_calls * 1e3


def main() -> int:
    import jax
    import jax.numpy as jnp

    from evam_tpu.engine import steps as step_builders
    from evam_tpu.models.registry import ModelRegistry

    dev = jax.devices()[0]
    log(f"device: {dev.platform} {getattr(dev, 'device_kind', '')}")
    registry = ModelRegistry(dtype="bfloat16")
    results = {}
    for cfg, key, build, shape, dtype in [
        ("action", "action_recognition/encoder",
         step_builders.build_action_encode_step, None, jnp.uint8),
        ("audio", "audio_detection/environment",
         step_builders.build_audio_step, (256, 16000), jnp.int16),
    ]:
        model = registry.get(key)
        if cfg == "action":
            step = build(model, wire_format="i420")
            h, w = model.preprocess.height, model.preprocess.width
            shape = (256, h * 3 // 2, w)
        else:
            step = build(model)
        params = jax.device_put(model.params)
        n = int(np.prod(shape))
        name = "windows" if cfg == "audio" else "frames"

        def seeded(params, seed, _step=step, _n=n, _shape=shape,
                   _dtype=dtype, _name=name):
            bits = step_builders.weyl_bits(seed, _n)
            data = (bits >> jnp.uint32(13)).astype(_dtype)
            return _step(params, **{_name: data.reshape(_shape)})

        fn = jax.jit(seeded)
        np.asarray(fn(params, np.uint32(99)))  # compile + settle
        row = {}
        for mode in ("submit", "block", "asarray"):
            row[f"{mode}_ms_per_call"] = round(
                _time_mode(fn, params, 12, mode), 2)
        # the verdict: does block track asarray or submit?
        row["block_really_blocks"] = (
            row["block_ms_per_call"]
            > 0.5 * row["asarray_ms_per_call"]
        )
        results[cfg] = row
        log(f"{cfg}: {row}")
    print(json.dumps(results))
    return 0


if __name__ == "__main__":
    sys.exit(main())
