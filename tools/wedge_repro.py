"""Minimal repro of the serve-path wedge condition (VERDICT r4 item 2).

The serve path is the only bench configuration where a background
bucket-warmup COMPILE overlaps steady-state dispatch RPCs — and the
only one that has ever wedged the axon tunnel (PROFILE.md r3/r4).
This tool reproduces exactly that client-side structure and nothing
else: one thread compiling FRESH programs (a new shape each
iteration → a real compile RPC every time) while the main thread runs
steady-state dispatch of a pre-warmed program.

Two uses:
* ``--platform cpu``: demonstrates the overlap is real at the client
  (``devlock.max_concurrent() > 1``) and that
  ``EVAM_SERIALIZE_COMPILE=1`` (or ``--serialize``) removes it
  (``== 1``) — the CPU half of the evidence, also asserted by
  ``tests/test_engine.py``.
* on the tunnel (no ``--platform``): the hypothesis test. Run LAST in
  a battery under ``timeout`` — if this wedges while the serve
  entries (preload-first + serialize) survived, the overlap
  hypothesis is confirmed and the defense validated. Progress lines
  go to stderr every 2 s so a timeout post-mortem shows which phase
  hung.

Prints ONE JSON line:
  {"platform": ..., "serialize": bool, "dispatches": N, "compiles": N,
   "overlap_max": N, "wedged": false, "seconds": S}
(A wedge never prints — the wrapper timeout is the signal.)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default=None,
                    help="cpu forces jax off the tunnel (axon hook-safe)")
    ap.add_argument("--serialize", action="store_true",
                    help="enable EVAM_SERIALIZE_COMPILE for this run")
    ap.add_argument("--seconds", type=float, default=8.0)
    args = ap.parse_args()

    if args.serialize:
        os.environ["EVAM_SERIALIZE_COMPILE"] = "1"

    import jax
    import jax.numpy as jnp

    if args.platform:
        # the image's .axon_site hook rewrites JAX_PLATFORMS at import;
        # only a post-import config update reliably forces CPU
        jax.config.update("jax_platforms", args.platform)

    from evam_tpu.engine import devlock

    devlock.reset_stats()
    progress = {"phase": "warmup", "dispatches": 0, "compiles": 0}
    stop = threading.Event()

    def monitor() -> None:
        while not stop.wait(2.0):
            print(f"[wedge_repro] {progress}", file=sys.stderr, flush=True)

    threading.Thread(target=monitor, daemon=True).start()

    # steady-state program, fully warmed before any overlap starts
    step = jax.jit(lambda a: (a @ a).sum())
    x = jnp.ones((256, 256), jnp.bfloat16)
    step(x).block_until_ready()

    def compile_loop() -> None:
        # a NEW shape per iteration defeats both the jit cache and the
        # persistent compile cache → every iteration is a compile RPC
        n = 0
        while not stop.is_set():
            shape = 128 + 8 * (n % 64) + 1  # odd sizes, never repeats mod-cycle
            fn = jax.jit(lambda a, _n=n: (a @ a).sum() + _n)
            y = jnp.ones((shape, shape), jnp.bfloat16)
            with devlock.device_call("repro:compile"):
                fn(y).block_until_ready()
            n += 1
            progress["compiles"] = n

    progress["phase"] = "overlap"
    t = threading.Thread(target=compile_loop, daemon=True)
    t.start()

    t0 = time.perf_counter()
    n_dispatch = 0
    while time.perf_counter() - t0 < args.seconds:
        with devlock.device_call("repro:dispatch"):
            step(x).block_until_ready()
        n_dispatch += 1
        progress["dispatches"] = n_dispatch
    stop.set()
    t.join(timeout=10)
    progress["phase"] = "done"

    print(json.dumps({
        "platform": args.platform or jax.default_backend(),
        "serialize": devlock.enabled(),
        "dispatches": n_dispatch,
        "compiles": progress["compiles"],
        "overlap_max": devlock.max_concurrent(),
        "wedged": False,
        "seconds": round(time.perf_counter() - t0, 2),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
