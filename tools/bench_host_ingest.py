"""Host ingest benchmark: decode-side per-frame cost (VERDICT item 5).

Measures the host work a decode worker pays per 1080p frame before
the wire upload — resize to the engine ingest resolution + BGR→I420
wire encoding — via (a) the cv2/numpy fallback path and (b) the
native OpenMP kernels (built on demand), then extrapolates to the
64×1080p30 north star (1,920 frames/s of this work plus decode).

Prints a small JSON report; the committed numbers live in INGEST.md.
"""

from __future__ import annotations

import os as _os
_os.environ.setdefault("EVAM_ALLOW_RANDOM_WEIGHTS", "1")  # hermetic profiling tool

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def bench(fn, frames, seconds=3.0) -> float:
    """Returns frames/second of `fn` over rotating inputs."""
    for f in frames[:2]:
        fn(f)
    n = 0
    t0 = time.perf_counter()
    deadline = t0 + seconds
    while time.perf_counter() < deadline:
        fn(frames[n % len(frames)])
        n += 1
    return n / (time.perf_counter() - t0)


def main() -> int:
    import cv2

    from evam_tpu import native
    from evam_tpu.ops.color import bgr_to_i420_host

    rng = np.random.default_rng(0)
    frames = [
        rng.integers(0, 255, (1080, 1920, 3), np.uint8) for _ in range(4)
    ]
    target = (512, 512)  # flagship detect ingest (H, W)
    cores = os.cpu_count() or 1
    report: dict = {"cores": cores, "target": list(target)}

    # cv2 path: resize then I420 encode (what decode workers do when
    # the native library is absent)
    def cv2_path(f):
        r = cv2.resize(f, (target[1], target[0]))
        return bgr_to_i420_host(r)

    report["cv2_resize_i420_fps_1core"] = round(bench(cv2_path, frames), 1)

    # native fused kernel (EVAM_NATIVE built on demand)
    try:
        native.build()
    except Exception as exc:  # noqa: BLE001
        report["native_error"] = str(exc)
    if native.available():
        def native_path(f):
            return native.resize_bgr_to_i420(f, target[0], target[1])

        report["native_fused_fps_1core"] = round(
            bench(native_path, frames), 1)

    # decode benchmark: cv2 VideoCapture over a generated clip
    clip = "/tmp/ingest_bench.avi"
    if not os.path.exists(clip):
        w = cv2.VideoWriter(
            clip, cv2.VideoWriter_fourcc(*"MJPG"), 30, (1920, 1080))
        for f in frames * 8:
            w.write(f)
        w.release()
    cap = cv2.VideoCapture(clip)
    n, t0 = 0, time.perf_counter()
    while True:
        ok, _ = cap.read()
        if not ok:
            break
        n += 1
    decode_fps = n / (time.perf_counter() - t0)
    cap.release()
    report["cv2_mjpeg_decode_fps_1core"] = round(decode_fps, 1)

    # REAL H.264 decode (VERDICT r4 item 4): intra-only Annex-B from
    # the from-scratch generator — measured through FFmpeg's actual
    # H.264 slice/MB decode path. I_PCM has no inverse transform or
    # prediction, so treat this as a LOWER bound on camera-grade
    # H.264 cost per frame (noted in INGEST.md).
    from evam_tpu.media import h264 as h264_mod

    h264_clip = "/tmp/ingest_bench_h264.h264"
    n_h264_frames = len(frames) * 4
    if not os.path.exists(h264_clip):
        # atomic: a run killed mid-write must not leave a truncated
        # clip that every later run silently reuses
        h264_mod.write_annexb(h264_clip + ".tmp", frames * 4)
        os.replace(h264_clip + ".tmp", h264_clip)
    cap = cv2.VideoCapture(h264_clip)
    n, t0 = 0, time.perf_counter()
    while True:
        ok, _ = cap.read()
        if not ok:
            break
        n += 1
    h264_fps = n / (time.perf_counter() - t0)
    cap.release()
    if n != n_h264_frames:       # stale/corrupt cached clip: rebuild
        os.remove(h264_clip)
        raise RuntimeError(
            f"h264 bench clip decoded {n}/{n_h264_frames} frames — "
            "cached file was corrupt; removed, re-run")
    report["cv2_h264_ipcm_decode_fps_1core"] = round(h264_fps, 1)

    # extrapolation to the 64-stream north star
    need = 64 * 30
    best_prep = max(
        report.get("native_fused_fps_1core", 0),
        report["cv2_resize_i420_fps_1core"],
    )
    per_frame_s = 1.0 / best_prep + 1.0 / decode_fps
    report["northstar_frames_per_s"] = need
    report["est_cores_for_64x1080p30"] = round(need * per_frame_s, 1)
    per_frame_h264_s = 1.0 / best_prep + 1.0 / h264_fps
    report["est_cores_for_64x1080p30_h264"] = round(
        need * per_frame_h264_s, 1)
    print(json.dumps(report, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
