"""Host ingest benchmark: decode-side per-frame cost (VERDICT item 5).

Measures the host work a decode worker pays per 1080p frame before
the wire upload — resize to the engine ingest resolution + BGR→I420
wire encoding — via (a) the cv2/numpy fallback path and (b) the
native OpenMP kernels (built on demand), then extrapolates to the
64×1080p30 north star (1,920 frames/s of this work plus decode).

Prints a small JSON report; the committed numbers live in INGEST.md.
"""

from __future__ import annotations

import os as _os
_os.environ.setdefault("EVAM_ALLOW_RANDOM_WEIGHTS", "1")  # hermetic profiling tool

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def bench(fn, frames, seconds=3.0) -> float:
    """Returns frames/second of `fn` over rotating inputs."""
    for f in frames[:2]:
        fn(f)
    n = 0
    t0 = time.perf_counter()
    deadline = t0 + seconds
    while time.perf_counter() < deadline:
        fn(frames[n % len(frames)])
        n += 1
    return n / (time.perf_counter() - t0)


def main() -> int:
    import cv2

    from evam_tpu import native
    from evam_tpu.ops.color import bgr_to_i420_host

    rng = np.random.default_rng(0)
    frames = [
        rng.integers(0, 255, (1080, 1920, 3), np.uint8) for _ in range(4)
    ]
    target = (512, 512)  # flagship detect ingest (H, W)
    cores = os.cpu_count() or 1
    report: dict = {"cores": cores, "target": list(target)}

    # cv2 path: resize then I420 encode (what decode workers do when
    # the native library is absent)
    def cv2_path(f):
        r = cv2.resize(f, (target[1], target[0]))
        return bgr_to_i420_host(r)

    report["cv2_resize_i420_fps_1core"] = round(bench(cv2_path, frames), 1)

    # native fused kernel (EVAM_NATIVE built on demand)
    try:
        native.build()
    except Exception as exc:  # noqa: BLE001
        report["native_error"] = str(exc)
    if native.available():
        def native_path(f):
            return native.resize_bgr_to_i420(f, target[0], target[1])

        report["native_fused_fps_1core"] = round(
            bench(native_path, frames), 1)

    # decode benchmark: cv2 VideoCapture over a generated clip
    clip = "/tmp/ingest_bench.avi"
    if not os.path.exists(clip):
        w = cv2.VideoWriter(
            clip, cv2.VideoWriter_fourcc(*"MJPG"), 30, (1920, 1080))
        for f in frames * 8:
            w.write(f)
        w.release()
    cap = cv2.VideoCapture(clip)
    n, t0 = 0, time.perf_counter()
    while True:
        ok, _ = cap.read()
        if not ok:
            break
        n += 1
    decode_fps = n / (time.perf_counter() - t0)
    cap.release()
    report["cv2_mjpeg_decode_fps_1core"] = round(decode_fps, 1)

    # extrapolation to the 64-stream north star
    need = 64 * 30
    best_prep = max(
        report.get("native_fused_fps_1core", 0),
        report["cv2_resize_i420_fps_1core"],
    )
    per_frame_s = 1.0 / best_prep + 1.0 / decode_fps
    report["northstar_frames_per_s"] = need
    report["est_cores_for_64x1080p30"] = round(need * per_frame_s, 1)
    print(json.dumps(report, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
