#!/usr/bin/env python
"""Shifting-workload A/B soak: self-tuning controller vs the best
hand-tuned static config.

Three phases, same frame sequence for every candidate (seeded rng,
virtual clock — fully deterministic):

1. **static-heavy** — light demand, near-still scenes. Any config
   coasts; nobody loses goodput here.
2. **motion-heavy overload** — demand ~2x serving capacity with
   repetitive machine motion (high gate scores, but most frames are
   ground-truth redundant: coasting approximates truth). A loose
   static gate runs everything, overloads the queue, and sheds half
   its frames at blown latency; a tight static gate coasts through
   and keeps the queue empty.
3. **region-skew** — light demand but genuinely novel localized
   motion (same scores as phase 2, zero redundancy). The tight gate
   keeps coasting and forfeits nearly all goodput; the loose gate is
   correct again.

No single static threshold wins both 2 and 3 — the distinguishing
signal is *utilization*, which only the control plane consumes: it
tightens ``gate_scale`` when post-gate demand exceeds capacity and
relaxes it only when the skipped demand would fit back under
``util_hi``. The soak gates on the controller beating BOTH statics
on total goodput at equal-or-better steady-state realtime p99.

Goodput: a served inference is always fresh (+1); a skipped frame
counts only when it was ground-truth redundant (the coast was
right); a shed frame counts zero. Realtime p99 is the queue latency
of served frames over each phase's settle window (last 60% — phase
transitions are adaptation lag, measured separately by eye via the
/scheduler action log, not gated here).

The controller is the REAL TuneController on the real signal plumbing
(gate registry skip rates, shed counters, admission-style utilization)
— only the engine behind it is a fluid-flow queue model, so the soak
is CPU-only and runs in seconds. Ticks are driven synchronously on
the virtual clock for determinism. ``--smoke`` is the CI shape.
Prints ONE JSON line on stdout; diagnostics on stderr.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from dataclasses import dataclass
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

#: fluid queue model: serving capacity (frames/s) and the staleness
#: budget after which queued frames are shed (scaled live by the
#: controller's staleness_scale through the usual consult)
CAPACITY_FPS = 300.0
STALENESS_S = 0.25
FPS = 30.0          # per stream
DT = 1.0 / FPS      # one sim step = one frame period
UTIL_WINDOW_S = 1.0  # admission-style utilization smoothing


def log(*a):
    print(*a, file=sys.stderr, flush=True)


@dataclass(frozen=True)
class Phase:
    name: str
    seconds: float
    streams: int
    score: float      # mean luma-diff the gate sees
    redundant: float  # P(frame is ground-truth redundant)


def phases(smoke: bool) -> list[Phase]:
    dur = 15.0 if smoke else 60.0
    return [
        Phase("static_heavy", dur, streams=8, score=0.5, redundant=1.0),
        Phase("motion_heavy", dur, streams=20, score=2.8, redundant=0.7),
        Phase("region_skew", dur, streams=7, score=2.8, redundant=0.0),
    ]


class SimClock:
    def __init__(self) -> None:
        self.now = 0.0

    def __call__(self) -> float:
        return self.now


class SimHub:
    """The controller's hub view of the fluid engine: no per-stage
    timings (those laws idle), live shed totals from the queue model."""

    def __init__(self) -> None:
        self.shed = 0.0
        self.retunes = 0

    def stats(self) -> dict:
        return {}

    def shed_totals(self) -> dict:
        return {"standard": self.shed}

    def retune(self, op) -> None:
        self.retunes += 1


class SimAdmission:
    """Duck-typed admission signals over the fluid queue: utilization
    is the ~1s-smoothed post-gate arrival rate vs capacity."""

    def __init__(self) -> None:
        self._util = 0.0
        self._alpha = DT / UTIL_WINDOW_S

    def observe(self, arrivals: float) -> None:
        inst = arrivals / (CAPACITY_FPS * DT)
        self._util += self._alpha * (inst - self._util)

    def utilization(self) -> float:
        return self._util

    def capacity_fps(self, live: bool = False) -> float:
        return CAPACITY_FPS

    def effective_demand_fps(self) -> float:
        return self._util * CAPACITY_FPS


def weighted_p99(samples: list[tuple[float, float]]) -> float:
    """p99 of (value, weight) samples — weights are fractional served
    frame counts from the fluid model."""
    if not samples:
        return 0.0
    samples = sorted(samples)
    total = sum(w for _, w in samples)
    acc = 0.0
    for val, w in samples:
        acc += w
        if acc >= 0.99 * total:
            return val
    return samples[-1][0]


def run_candidate(name: str, tune: bool, threshold: float,
                  smoke: bool, tick_s: float, seed: int) -> dict:
    """One full 3-phase pass. Same seed => identical frame sequence
    (scores, redundancy draws) for every candidate."""
    os.environ["EVAM_TUNE"] = "on" if tune else "off"
    from evam_tpu.config.settings import reset_settings
    from evam_tpu.control import state as control_state
    from evam_tpu.stages.gate import GateConfig, MotionGate, registry

    reset_settings()
    control_state.reset_cache()
    registry.reset()

    clock = SimClock()
    hub = SimHub()
    adm = SimAdmission()
    ctrl = None
    if tune:
        state = control_state.active()
        assert state is not None
        from evam_tpu.control import TuneController

        ctrl = TuneController(hub, state, admission=adm)

    cfg = GateConfig(enabled=True, threshold=threshold,
                     threshold_lo=threshold / 2.0, max_skip=8,
                     refresh=30, pinned=False)
    max_streams = max(p.streams for p in phases(smoke))
    gates = [MotionGate(cfg, engine_name=f"soak-{i}", clock=clock)
             for i in range(max_streams)]

    rng = np.random.default_rng(seed)
    backlog = 0.0
    goodput = 0.0
    shed_total = 0.0
    next_tick = tick_s
    per_phase: list[dict] = []
    settle_samples: list[tuple[float, float]] = []

    for ph in phases(smoke):
        steps = int(round(ph.seconds / DT))
        settle_from = clock.now + 0.4 * ph.seconds
        ph_good = 0.0
        ph_samples: list[tuple[float, float]] = []
        for _ in range(steps):
            clock.now += DT
            arrivals = 0.0
            for g in gates[:ph.streams]:
                score = ph.score * (0.95 + 0.1 * rng.random())
                redundant = rng.random() < ph.redundant
                if g.apply(score):
                    arrivals += 1.0
                elif redundant:
                    goodput += 1.0
                    ph_good += 1.0
            adm.observe(arrivals)
            # fluid queue: serve up to capacity, shed past staleness
            backlog += arrivals
            served = min(backlog, CAPACITY_FPS * DT)
            backlog -= served
            latency = backlog / CAPACITY_FPS + 1.0 / CAPACITY_FPS
            op = control_state.current_op()
            scale = op.staleness_scale if op is not None else 1.0
            budget = STALENESS_S * scale
            shed = max(0.0, backlog - CAPACITY_FPS * budget)
            backlog -= shed
            shed_total += shed
            hub.shed = shed_total
            goodput += served
            ph_good += served
            if served > 0 and clock.now >= settle_from:
                ph_samples.append((latency, served))
            if ctrl is not None and clock.now >= next_tick:
                next_tick += tick_s
                ctrl.tick()
        settle_samples.extend(ph_samples)
        per_phase.append({
            "phase": ph.name,
            "goodput": round(ph_good, 1),
            "settle_p99_ms": round(weighted_p99(ph_samples) * 1e3, 2),
        })

    op = control_state.current_op()
    result = {
        "name": name,
        "goodput": round(goodput, 1),
        "realtime_p99_ms": round(weighted_p99(settle_samples) * 1e3, 2),
        "shed": round(shed_total, 1),
        "phases": per_phase,
        "final_gate_scale": round(op.gate_scale, 2) if op else 1.0,
    }
    log(f"{name:16s} goodput {result['goodput']:>9.1f}  "
        f"p99 {result['realtime_p99_ms']:>7.2f}ms  "
        f"shed {result['shed']:>8.1f}  "
        f"gate_scale {result['final_gate_scale']}")
    return result


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--smoke", action="store_true",
                   help="CI shape: 15s phases, faster tick/damping")
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--p99-margin", type=float, default=0.25,
                   help="allowed p99 slack vs the best static (frac)")
    args = p.parse_args()

    # hermetic: the soak owns every knob it exercises
    for k in list(os.environ):
        if k.startswith("EVAM_"):
            del os.environ[k]
    if args.smoke:
        # 15s phases need the adaptation inside the settle window:
        # faster cadence, lighter damping — same laws
        os.environ["EVAM_TUNE_INTERVAL_S"] = "0.25"
        os.environ["EVAM_TUNE_DAMPING"] = "2"
        os.environ["EVAM_TUNE_COOLDOWN"] = "1"
        tick_s = 0.25
    else:
        tick_s = 0.5

    total_s = sum(ph.seconds for ph in phases(args.smoke))
    log(f"3 phases x {total_s / 3:.0f}s virtual, capacity "
        f"{CAPACITY_FPS:.0f} f/s, staleness {STALENESS_S * 1e3:.0f}ms")
    loose = run_candidate("static_loose", tune=False, threshold=2.0,
                          smoke=args.smoke, tick_s=tick_s, seed=args.seed)
    tight = run_candidate("static_tight", tune=False, threshold=8.0,
                          smoke=args.smoke, tick_s=tick_s, seed=args.seed)
    tuned = run_candidate("controller", tune=True, threshold=2.0,
                          smoke=args.smoke, tick_s=tick_s, seed=args.seed)

    best_static = max(loose, tight, key=lambda r: r["goodput"])
    p99_cap = (min(loose["realtime_p99_ms"], tight["realtime_p99_ms"])
               * (1.0 + args.p99_margin) + 5.0)
    beats_goodput = (tuned["goodput"] > loose["goodput"]
                     and tuned["goodput"] > tight["goodput"])
    meets_p99 = tuned["realtime_p99_ms"] <= p99_cap
    ok = beats_goodput and meets_p99
    gain = (tuned["goodput"] / best_static["goodput"] - 1.0
            if best_static["goodput"] > 0 else 0.0)

    print(json.dumps({
        "metric": "tune_soak_goodput_gain",
        "value": round(gain, 4),
        "unit": "fraction_vs_best_static",
        "best_static": best_static["name"],
        "controller": tuned,
        "static_loose": loose,
        "static_tight": tight,
        "p99_cap_ms": round(p99_cap, 2),
        "ok": ok,
    }))
    if not beats_goodput:
        log("FAIL: controller goodput does not beat both statics")
        return 1
    if not meets_p99:
        log(f"FAIL: controller p99 {tuned['realtime_p99_ms']:.2f}ms "
            f"> cap {p99_cap:.2f}ms")
        return 1
    log(f"OK: controller +{gain * 100:.1f}% goodput over best static "
        f"({best_static['name']}) at realtime p99")
    return 0


if __name__ == "__main__":
    sys.exit(main())
