"""Host decode throughput, measured in-container (round-2 VERDICT 6).

Encodes a synthetic-but-busy 1080p clip with every encoder this
image's cv2/FFmpeg build can actually produce, then times cold decode.
H.264 specifically cannot be *encoded* here (the bundled avcodec has
only the h264_v4l2m2m hardware wrapper and no /dev/video device, no
libx264/openh264 — verified), so the H.264 row in INGEST.md is derived
from the measured MPEG-4 ASP number with the well-known complexity
ratio rather than from literature alone.

Prints one JSON line: {codec: {encode_fps, decode_fps, mb_per_s,
bytes_per_frame}}.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

import numpy as np


def busy_frames(n: int, h: int = 1080, w: int = 1920, seed: int = 7):
    """Frames with enough structure + noise for realistic bitrates
    (a flat synthetic frame compresses to nothing and skews decode)."""
    rng = np.random.default_rng(seed)
    base = rng.integers(0, 96, (h, w, 3), np.uint8)
    frames = []
    for i in range(n):
        f = base.copy()
        # moving blocks (motion vectors) + per-frame noise (residuals)
        for b in range(24):
            x = (b * 83 + i * 13) % (w - 120)
            y = (b * 47 + i * 11) % (h - 120)
            f[y:y + 120, x:x + 120] = (
                (b * 37) % 255, (b * 59) % 255, (b * 83) % 255)
        noise = rng.integers(0, 24, (h // 4, w // 4, 3), np.uint8)
        f[: h // 4, : w // 4] += noise
        frames.append(f)
    return frames


def main() -> int:
    import cv2

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 90
    h, w = 1080, 1920
    frames = busy_frames(n)
    results = {}
    for fourcc_s, ext in [("mp4v", "mp4"), ("XVID", "avi"),
                          ("MJPG", "avi")]:
        path = os.path.join(tempfile.gettempdir(),
                            f"decode_bench_{fourcc_s}.{ext}")
        wr = cv2.VideoWriter(
            path, cv2.VideoWriter_fourcc(*fourcc_s), 30, (w, h))
        if not wr.isOpened():
            results[fourcc_s] = {"error": "encoder unavailable"}
            continue
        t0 = time.perf_counter()
        for f in frames:
            wr.write(f)
        wr.release()
        t_enc = time.perf_counter() - t0
        size = os.path.getsize(path)

        # cold-ish decode: fresh capture, read all frames
        best = 0.0
        for _ in range(2):
            cap = cv2.VideoCapture(path)
            t0 = time.perf_counter()
            got = 0
            while True:
                ok, _ = cap.read()
                if not ok:
                    break
                got += 1
            dt = time.perf_counter() - t0
            cap.release()
            best = max(best, got / dt)
        results[fourcc_s] = {
            "encode_fps": round(n / t_enc, 1),
            "decode_fps": round(best, 1),
            "mb_per_s": round(best * (h // 16) * (w // 16) / 1e3, 1),
            "bytes_per_frame": size // n,
            "frames": got,
        }
        os.unlink(path)
        print(f"{fourcc_s}: enc {results[fourcc_s]['encode_fps']} fps, "
              f"dec {results[fourcc_s]['decode_fps']} fps "
              f"({results[fourcc_s]['bytes_per_frame']//1024} KiB/frame)",
              file=sys.stderr)
    print(json.dumps(results))
    return 0


if __name__ == "__main__":
    sys.exit(main())
