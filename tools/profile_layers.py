"""Per-layer timing of the SSD backbone on the real chip.

The cumulative ladder (tools/profile_step.py) attributed ~33 ms of the
fused detect step to the backbone forward. This tool breaks that down:
each backbone stage is timed as its own program on seed-synthesized
on-device inputs, and the depthwise implementations are A/B'd
(EVAM_DWCONV=shift vs lax grouped conv) so the round-2 shift-and-add
rewrite (evam_tpu/ops/depthwise.py) has a direct hardware number.
"""

from __future__ import annotations

import os as _os
_os.environ.setdefault("EVAM_ALLOW_RANDOM_WEIGHTS", "1")  # hermetic profiling tool

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def bench_fn(fn, iters=20, warmup=3):
    import jax

    for i in range(warmup):
        jax.block_until_ready(fn(np.int32(i)))
    t0 = time.perf_counter()
    for i in range(iters):
        out = fn(np.int32(100 + i))
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters * 1e3  # ms


def synth_input(shape, dtype):
    import jax
    import jax.numpy as jnp

    n = int(np.prod(shape))

    def synth(seed):
        i = jax.lax.iota(jnp.uint32, n)
        bits = i * jnp.uint32(2654435761) + seed.astype(jnp.uint32)
        return ((bits >> 13).astype(jnp.uint8).astype(jnp.float32) / 255.0
                ).reshape(shape).astype(dtype)

    return synth


def main() -> int:
    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    from jax import lax

    b = int(os.environ.get("EVAM_PROFILE_BATCH", "32"))
    size = 512
    dt = jnp.bfloat16
    dev = jax.devices()[0]
    print(f"device: {dev.platform} batch={b} input={size}x{size} {dt.__name__}",
          flush=True)

    # ---- individual ops: depthwise A/B at representative shapes ----
    from evam_tpu.ops.depthwise import depthwise_conv_shift

    for (hh, cc, ss) in [(256, 32, 2), (128, 64, 1), (64, 128, 2),
                         (64, 128, 1), (32, 256, 1), (16, 512, 1)]:
        synth = synth_input((b, hh, hh, cc), dt)
        key = jax.random.PRNGKey(0)
        k = jax.random.normal(key, (3, 3, 1, cc), dt)

        @jax.jit
        def p_shift(seed, k=k, synth=synth, ss=ss):
            return depthwise_conv_shift(synth(seed), k, (ss, ss)).astype(
                jnp.float32).sum()

        @jax.jit
        def p_lax(seed, k=k, synth=synth, cc=cc, ss=ss):
            return lax.conv_general_dilated(
                synth(seed), k, window_strides=(ss, ss), padding="SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                feature_group_count=cc,
            ).astype(jnp.float32).sum()

        ms_s = bench_fn(p_shift)
        ms_l = bench_fn(p_lax)
        print(f"dw3x3 {hh:3d}^2 c={cc:<4d} s={ss}: shift {ms_s:7.2f} ms | "
              f"lax {ms_l:7.2f} ms  ({ms_l / max(ms_s, 1e-6):.1f}x)",
              flush=True)

    # ---- whole backbone: shift vs lax ----
    from evam_tpu.models.zoo import layers as L

    synth = synth_input((b, size, size, 3), dt)
    for mode in ("shift", "lax"):
        os.environ["EVAM_DWCONV"] = mode
        # rebuild module tree under the switch
        import importlib
        importlib.reload(L)
        net = L.Backbone(width=32, extra_levels=2)
        params = net.init(jax.random.PRNGKey(0), jnp.zeros((1, size, size, 3), dt))
        params = jax.device_put(params)

        @jax.jit
        def fwd(seed, net=net, params=params):
            feats = net.apply(params, synth(seed))
            return sum(f.astype(jnp.float32).sum() for f in feats)

        print(f"backbone[{mode}]: {bench_fn(fwd):7.2f} ms", flush=True)
    os.environ.pop("EVAM_DWCONV", None)
    importlib.reload(L)

    # ---- per-stage ladder of the shift backbone ----
    net = L.Backbone(width=32, extra_levels=2)
    params = jax.device_put(
        net.init(jax.random.PRNGKey(0), jnp.zeros((1, size, size, 3), dt)))

    class Prefix(nn.Module):
        n: int

        @nn.compact
        def __call__(self, x):
            w, q = 32, False
            blocks = [
                L.ConvBlock(w, strides=(2, 2), quant=q),
                L.SeparableConv(w * 2, strides=(2, 2), quant=q),
                L.SeparableConv(w * 2, quant=q),
                L.SeparableConv(w * 4, strides=(2, 2), quant=q),
                L.SeparableConv(w * 4, quant=q),
                L.SeparableConv(w * 8, strides=(2, 2), quant=q),
                L.SeparableConv(w * 8, quant=q),
                L.SeparableConv(w * 16, strides=(2, 2), quant=q),
                L.SeparableConv(w * 16, quant=q),
            ]
            for blk in blocks[: self.n]:
                x = blk(x)
            return x

    prev = 0.0
    for n in range(1, 10):
        net_n = Prefix(n)
        p_n = jax.device_put(
            net_n.init(jax.random.PRNGKey(0), jnp.zeros((1, size, size, 3), dt)))

        @jax.jit
        def fwd_n(seed, net_n=net_n, p_n=p_n):
            return net_n.apply(p_n, synth(seed)).astype(jnp.float32).sum()

        ms = bench_fn(fwd_n)
        print(f"backbone[:{n}] {ms:7.2f} ms (+{ms - prev:6.2f})", flush=True)
        prev = ms
    return 0


if __name__ == "__main__":
    sys.exit(main())
