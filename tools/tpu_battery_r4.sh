#!/bin/bash
# Round-4 battery: the round-3 measurement debt (serve-path TPU bench,
# 40 ms budget, verify_blocking, NHWC gap) plus round-4 additions
# (accuracy-harness on device). Run the moment the axon tunnel answers.
# Arm with:
#   bash tools/tpu_watch.sh tools/tpu_battery_r4.sh /tmp/tpu_battery_r4 43200 BENCH_SERVE_r04.json
set -u
OUT=${1:-/tmp/tpu_battery_r4}
mkdir -p "$OUT"
cd "$(dirname "$0")/.."

FAILED=0
run() {
    name=$1; shift
    echo "=== $name: $* ===" | tee -a "$OUT/battery.log"
    timeout 1200 "$@" >"$OUT/$name.json" 2>"$OUT/$name.err"
    local rc=$?
    echo "rc=$rc $(tail -1 "$OUT/$name.json" 2>/dev/null)" | tee -a "$OUT/battery.log"
    [ $rc -ne 0 ] && FAILED=$((FAILED + 1))
    # fold after EVERY entry: if the round (or the tunnel) dies
    # mid-battery, whatever already ran is in the repo working tree
    python tools/fold_battery2.py "$OUT" BENCH_SERVE_r04.json \
        > "$OUT/folded.md" 2>>"$OUT/watch.log" || true
    return $rc
}

# 0. cheapest headline number FIRST (memory: measure the headline
#    before anything that can wedge the tunnel)
run default python bench.py --seconds 12

# 1. THE round-3/4 artifact: the real serving path on the TPU
#    (source -> runner -> BatchEngine -> track -> classify -> meta ->
#    publish), device-synth ingest, 64 streams.
run serve python bench.py --config serve --streams 64 --seconds 24 --batch 256
run serve_b128 python bench.py --config serve --streams 64 --seconds 16 --batch 128
run serve_file_32 python bench.py --config serve --streams 32 --seconds 12 --batch 256 --serve-publish file

# 2. 40 ms p99 sweep for the record (sla_met=false through the 66 ms
#    tunnel floor is an honest artifact)
run sweep40 python bench.py --sweep --seconds 40 --p99-target-ms 40

# 3. re-measured action/audio with fixed metric definitions, AFTER
#    establishing whether block_until_ready even blocks for small
#    programs on this backend (the r2 inconsistency suspect)
run blocking python tools/verify_blocking.py
run action python bench.py --config action --seconds 8
run audio python bench.py --config audio --seconds 8

# 4. NHWC layout pass: IR vs zoo gap
run ir_layout python tools/profile_ir_layout.py

# 5. IR-backed end-to-end serve (synthesized OMZ models + NHWC pass)
IRDIR=$OUT/omz_models
if [ ! -d "$IRDIR" ]; then
    timeout 900 python -m evam_tpu.cli.main fetch-models \
        --synthesize-omz all --topology manifest --output "$IRDIR" \
        >"$OUT/fetch.log" 2>&1 || true
fi
run detect_ir python bench.py --config detect --models-dir "$IRDIR" --seconds 8
run serve_ir python bench.py --config serve --streams 64 --seconds 16 --batch 256 --models-dir "$IRDIR"

# 6. on-device step times at serving batches (latency budget terms)
run budget python tools/profile_budget.py

# 7. round-4: accuracy harness forward pass on the real chip (same
#    fitted weights as the CPU test; proves device numerics)
if [ -e tools/accuracy_device.py ]; then
    run accuracy python tools/accuracy_device.py
fi

# 8. host-ingest point (tunnel-bound here; recorded for completeness)
run host python bench.py --ingest host --batch 8 --depth 2 --seconds 6

echo "battery r4 complete -> $OUT ($FAILED failed)" | tee -a "$OUT/battery.log"
exit $((FAILED > 0))
