#!/usr/bin/env python
"""Fleet scaling microbench: streams served per fleet, 1 vs N shards.

What this measures — and what it deliberately does not. The gate runs
on a CPU host where every "chip" is an XLA host-platform virtual
device sharing the same cores, so real model FLOPs cannot scale with
shard count (8 shards of matmul on one core is still one core of
matmul). What DOES scale — and what this bench isolates — is the
serving fabric the fleet tentpole added: consistent-hash placement,
per-shard dispatch/launch threads, per-shard staging and bucket
assembly. Each shard's step function emulates its device's service
time with a ``jax.pure_callback`` sleep (SERVICE_MS per batch, the
ballpark of a 1080p detect batch on one chip): the shard's launcher
thread blocks host-side exactly the way a real launcher blocks on a
busy chip, and blocked threads overlap perfectly across shards even
on one core. A fleet whose fabric serializes anywhere (global lock,
single dispatcher, placement hotspot) fails the ratio gate; a fleet
whose shards are truly independent scales ~linearly. Real-compute
numbers on real ICI belong to the next TPU window (ROADMAP battery:
``streams_1080p_30fps_per_fleet``); ``--real-compute`` runs the same
harness with an arithmetic step for that banking run.

Per-stream outputs must be bit-identical between the 1-shard and
N-shard fleets — placement decides WHERE a frame runs, never what it
computes.

Contract (tests/test_bench_contract.py): exactly ONE JSON line on
stdout -- {"metric": "streams_1080p_30fps_per_fleet", "value", "unit",
"vs_baseline", "ok", ...}; diagnostics on stderr; exit 1 when the
scaling ratio or bit-identity gate fails. ``--smoke`` compares 1 vs 2
shards with a 1.5x floor (core-count independent) for CI.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("EVAM_LOG_LEVEL", "warning")
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import numpy as np  # noqa: E402

from evam_tpu.engine.batcher import BatchEngine  # noqa: E402
from evam_tpu.fleet import FleetEngine  # noqa: E402
from evam_tpu.parallel.mesh import build_mesh  # noqa: E402

#: emulated device service time: fixed dispatch cost per batch plus a
#: per-row term (padded rows — the chip pays for the bucket shape it
#: compiled, not the live items in it), ~50ms for a full batch of 8.
#: Deliberately chunky: on the 1-core gate host the python serving
#: fabric costs ~0.2ms/frame SERIALIZED across shards, so the
#: emulated device time must dominate it the way a real detect batch
#: dominates its (multi-core, parallel) host path — otherwise the
#: bench measures the gate container's core count, not the fleet.
SERVICE_BASE_MS = 2.0
SERVICE_ROW_MS = 6.0
FRAME_SHAPE = (16, 16, 3)
MAX_BATCH = 8
#: submit-side concurrency (ingest loops); placement noise is the
#: real scaling limiter at small stream counts, so the defaults use
#: fleet-scale stream counts (hundreds of cameras per 8 chips)
FEEDERS = 32


def _make_step(real_compute: bool):
    """Returns (step_fn, service_switch). The switch starts False so
    the warm pass compiles every bucket program without paying the
    emulated service sleeps (sleep duration is runtime state, not part
    of the traced program)."""
    switch = {"on": False}
    if real_compute:
        def step(params, frames):
            x = frames.astype(np.float32)
            for _ in range(8):
                x = x * 1.0009765625 + 0.5
            return x
        return step, switch

    def _service(x):
        if switch["on"]:
            time.sleep(
                (SERVICE_BASE_MS + SERVICE_ROW_MS * x.shape[0]) / 1e3)
        return x

    def step(params, frames):
        x = frames.astype(np.float32) * 1.0009765625 + 0.5
        return jax.pure_callback(
            _service, jax.ShapeDtypeStruct(x.shape, x.dtype), x)

    return step, switch


def _frames(streams: int, per_stream: int):
    """Deterministic per-(stream, seq) payloads for the identity gate."""
    out = []
    for s in range(streams):
        rng = np.random.default_rng(1000 + s)
        out.append([
            rng.integers(0, 255, FRAME_SHAPE, np.uint8)
            for _ in range(per_stream)])
    return out


def _run_fleet(n_shards: int, frames, real_compute: bool):
    """Serve every frame through an n-shard fleet; returns
    (fps, outputs[stream][seq])."""
    plans = build_mesh(
        devices=list(jax.devices())[:n_shards]).per_device_plans()
    step, service = _make_step(real_compute)

    def shard_factory(plan, label):
        return BatchEngine(
            label, step, params=None, plan=plan, max_batch=MAX_BATCH,
            deadline_ms=1.0, stall_timeout_s=0)

    fleet = FleetEngine(f"bench@{n_shards}", shard_factory, plans)
    streams = len(frames)
    try:
        per_stream = len(frames[0])

        def burst():
            # bounded feeder pool, streams interleaved: arrivals keep
            # hitting every shard throughout the burst, and a feeder
            # blocked on one hot shard's staging ring cannot starve
            # the rest of the fleet (the single-submitter trap)
            import threading

            outs = [[None] * per_stream for _ in range(streams)]

            def feed(fid):
                own = range(fid, streams, FEEDERS)
                futs = []
                for i in range(per_stream):
                    for s in own:
                        futs.append((s, i, fleet.submit(
                            stream=f"cam{s}", frames=frames[s][i])))
                for s, i, fut in futs:
                    outs[s][i] = np.asarray(fut.result(timeout=120))

            threads = [threading.Thread(target=feed, args=(fid,))
                       for fid in range(min(FEEDERS, streams))]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            return outs

        burst()  # warm service-free: compile every bucket, no sleeps
        service["on"] = True
        t0 = time.perf_counter()
        outs = burst()
        elapsed = time.perf_counter() - t0
        total = sum(len(f) for f in frames)
        return total / elapsed, outs
    finally:
        fleet.stop()


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="CI gate: 1 vs 2 shards, ratio >= 1.5x")
    ap.add_argument("--shards", type=int, default=8)
    ap.add_argument("--streams", type=int, default=768)
    ap.add_argument("--frames", type=int, default=2)
    ap.add_argument("--min-ratio", type=float, default=None)
    ap.add_argument("--real-compute", action="store_true",
                    help="arithmetic step instead of emulated service "
                         "time (TPU banking runs)")
    args = ap.parse_args()

    if args.smoke:
        shards, streams, per_stream = 2, 96, 4
        min_ratio = args.min_ratio if args.min_ratio is not None else 1.5
    else:
        shards, streams, per_stream = args.shards, args.streams, args.frames
        min_ratio = args.min_ratio if args.min_ratio is not None else 6.0

    frames = _frames(streams, per_stream)
    fps_1, outs_1 = _run_fleet(1, frames, args.real_compute)
    fps_n, outs_n = _run_fleet(shards, frames, args.real_compute)

    identical = all(
        np.array_equal(a, b)
        for sa, sb in zip(outs_1, outs_n) for a, b in zip(sa, sb))
    ratio = fps_n / fps_1 if fps_1 > 0 else 0.0
    ok = bool(ratio >= min_ratio and identical)

    print(
        f"fleet bench: {streams} streams x {per_stream} frames, "
        f"service {SERVICE_BASE_MS}+{SERVICE_ROW_MS}/row ms: "
        f"1 shard {fps_1:.0f} fps, "
        f"{shards} shards {fps_n:.0f} fps ({ratio:.2f}x, floor "
        f"{min_ratio}x), bit-identical={identical}", file=sys.stderr)

    print(json.dumps({
        "metric": "streams_1080p_30fps_per_fleet",
        "value": round(fps_n / 30.0, 1),
        "unit": "streams",
        "vs_baseline": round(ratio, 2),
        "ok": ok,
        "shards": shards,
        "baseline_streams": round(fps_1 / 30.0, 1),
        "identical": identical,
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
