"""Overload soak: mixed-class synthetic streams vs a sched-enabled hub.

Floods a warmed, QoS-scheduled serving stack (evam_tpu/sched/) with
``realtime``-class paced camera streams plus free-running ``batch``
re-runs whose combined demand exceeds what the engines can serve, and
asserts the overload contract the scheduler exists for:

* realtime end-to-end p99 stays under ``--p99-budget`` ms and NO
  realtime frame is shed;
* the ``batch`` class absorbs the overload: its sheds are nonzero and
  counted in ``evam_sched_shed_total{class="batch"}``;
* every stream still COMPLETES (a ShedError is one counted frame
  error, never a stream kill), and readiness ends healthy.

Overload is forced deterministically the same way tests/test_sched.py
does it at engine scale: the batch class gets a tight staleness
budget while the realtime lanes outrank it at dispatch, so once the
free-running batch streams outpace the engines the batch queue goes
stale and sheds. ``tests/test_sched.py`` is the tier-1 deterministic
variant of exactly this contract (marker ``sched``); this tool is the
full-stack shape for soak batteries.

Usage (defaults are the CI-adjacent quick shape):

    python tools/overload_soak.py --realtime 2 --batch-streams 6 \
        --frames 150 --p99-budget 500
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

# soak harness, not production serving: deterministic random-init
# weights are fine (same opt-in the test suite makes in conftest.py)
os.environ.setdefault("EVAM_ALLOW_RANDOM_WEIGHTS", "1")


def run_soak(
    realtime_streams: int = 2,
    batch_streams: int = 6,
    frames: int = 150,
    p99_budget_ms: float = 500.0,
    batch_staleness_ms: float = 50.0,
    timeout_s: float = 240.0,
) -> dict:
    """Run the overload soak; returns a summary dict with ``ok``.
    Importable for ad-hoc shapes."""
    from evam_tpu.config import Settings
    from evam_tpu.engine import EngineHub
    from evam_tpu.models import ModelRegistry, ZOO_SPECS
    from evam_tpu.obs.metrics import metrics
    from evam_tpu.parallel import build_mesh
    from evam_tpu.sched import SchedConfig
    from evam_tpu.server.registry import PipelineRegistry

    small = {k: (64, 64) for k in ZOO_SPECS}
    small["audio_detection/environment"] = (1, 1600)
    narrow = {k: 8 for k in ZOO_SPECS}
    sched = SchedConfig(
        # admission stays open (the point here is queue/shed, not
        # rejection — tools/../tests cover the 503 path separately)
        admit_util=0.0,
        staleness_ms={
            "realtime": 10_000.0,
            "standard": 10_000.0,
            "batch": batch_staleness_ms,
        },
    )
    settings = Settings(pipelines_dir=str(REPO / "pipelines"))
    hub = EngineHub(
        ModelRegistry(dtype="float32", input_overrides=small,
                      width_overrides=narrow),
        plan=build_mesh(), max_batch=16, deadline_ms=4.0,
        warmup=True, stall_timeout_s=30.0, sched=sched,
    )
    registry = PipelineRegistry(settings, hub=hub)
    registry.preload("object_detection/person_vehicle_bike")
    warm_deadline = time.time() + 180
    while time.time() < warm_deadline:
        ready = hub.readiness()
        if ready["engines"] and not ready["warming"]:
            break
        time.sleep(0.1)
    else:
        registry.stop_all()
        raise RuntimeError("engines never warmed; cannot flood")

    shed0 = dict(hub.shed_totals())
    metrics.reset()  # scope the latency histograms to the flood
    t0 = time.time()
    try:
        rt_insts = [
            registry.start_instance(
                "object_detection", "person_vehicle_bike",
                {
                    "source": {
                        "uri": f"synthetic://96x96@30?count={frames}"
                               f"&seed={i}",
                        "type": "uri",
                        "realtime": True,  # 30 fps camera pacing
                    },
                    "destination": {"metadata": {"type": "null"}},
                    "priority": "realtime",
                },
            )
            for i in range(realtime_streams)
        ]
        bt_insts = [
            registry.start_instance(
                "object_detection", "person_vehicle_bike",
                {
                    # free-running: submits as fast as decode allows —
                    # the bulk re-run shape that outpaces the engines
                    "source": {
                        "uri": f"synthetic://96x96@30?count={frames * 4}"
                               f"&seed={100 + i}",
                        "type": "uri",
                    },
                    "destination": {"metadata": {"type": "null"}},
                    "priority": "batch",
                },
            )
            for i in range(batch_streams)
        ]
        deadline = t0 + timeout_s
        for inst in rt_insts + bt_insts:
            inst.wait(timeout=max(1.0, deadline - time.time()))
        states = [i.state.value for i in rt_insts + bt_insts]
        rt_p99_ms = metrics.quantile(
            "evam_frame_latency_seconds", 0.99,
            labels={"class": "realtime"}) * 1e3
        shed = hub.shed_totals()
        shed_delta = {c: shed.get(c, 0) - shed0.get(c, 0) for c in shed}
        # cross-check the Prometheus series (window-scoped after the
        # metrics.reset above): all-label-set aggregation via
        # MetricsRegistry.counter_total, the bench-style read
        shed_metric_total = int(metrics.counter_total("evam_sched_shed"))
        frames_out = sum(
            i._runner.frames_out if i._runner else 0
            for i in rt_insts + bt_insts)
        errors = sum(
            i._runner.errors if i._runner else 0
            for i in rt_insts + bt_insts)
        ready = hub.readiness()
    finally:
        registry.stop_all()
    ok = (
        all(s == "COMPLETED" for s in states)
        and rt_p99_ms <= p99_budget_ms
        and shed_delta.get("realtime", 0) == 0
        and shed_delta.get("batch", 0) > 0
        and frames_out > 0
        and not ready.get("degraded")
    )
    return {
        "ok": ok,
        "realtime_streams": realtime_streams,
        "batch_streams": batch_streams,
        "states": states,
        "realtime_p99_ms": round(rt_p99_ms, 1),
        "p99_budget_ms": p99_budget_ms,
        "shed": shed_delta,
        "shed_metric_total": shed_metric_total,
        "frames_out": frames_out,
        "errors": errors,
        "readiness": ready,
        "elapsed_s": round(time.time() - t0, 1),
    }


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--realtime", type=int, default=2,
                   help="realtime-class camera streams (30 fps paced)")
    p.add_argument("--batch-streams", type=int, default=6,
                   help="batch-class free-running flood streams")
    p.add_argument("--frames", type=int, default=150,
                   help="frames per realtime stream (batch gets 4x)")
    p.add_argument("--p99-budget", type=float, default=500.0,
                   help="realtime end-to-end p99 ceiling (ms)")
    p.add_argument("--batch-staleness", type=float, default=50.0,
                   help="batch-class staleness budget (ms)")
    p.add_argument("--timeout", type=float, default=240.0)
    args = p.parse_args()
    result = run_soak(
        realtime_streams=args.realtime,
        batch_streams=args.batch_streams,
        frames=args.frames,
        p99_budget_ms=args.p99_budget,
        batch_staleness_ms=args.batch_staleness,
        timeout_s=args.timeout,
    )
    print(json.dumps(result))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
