#!/usr/bin/env python
"""Host batch-assembly microbench: legacy stack+concat vs slot ring.

CPU-only, runs in seconds, no JAX involved — this isolates exactly the
host work `BatchEngine`'s dispatcher used to do per batch (allocate +
``np.stack(rows)`` + zero-pad ``np.concatenate``) against the slot
path (`engine/ringbuf.SlotRing`: pre-allocated staging blocks, row
writes, zeroed-tail seal). The legacy engine path stays selectable at
runtime via ``EVAM_BATCH_ASSEMBLY=legacy`` for end-to-end A/B; this
tool is the cheap, deterministic comparison the CI-adjacent path runs.

Exit status is the assertion: nonzero when slot-mode assembly is
SLOWER than legacy for the measured shape (it must never be — the
slot path exists to make the hot path cheaper). The headline number
to record in PROFILE.md is ``speedup`` at the largest bucket
(acceptance: ≥ 1.5× there; measured ~4.7× full / ~1.9× padded on the
1-vCPU dev box — fresh-allocation page faults dominate legacy cost).

Prints ONE JSON line on stdout; diagnostics go to stderr.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from evam_tpu.engine.ringbuf import SlotRing  # noqa: E402


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def bench_legacy(rows: list[np.ndarray], bucket: int, reps: int) -> float:
    """Median seconds per batch for the stack+concat path (the exact
    shape of the old ``BatchEngine._dispatch_loop`` assembly)."""
    n = len(rows)
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        stacked = np.stack(rows)
        if bucket > n:
            pad = np.zeros((bucket - n,) + stacked.shape[1:],
                           stacked.dtype)
            stacked = np.concatenate([stacked, pad])
        times.append(time.perf_counter() - t0)
        del stacked
    return float(np.median(times))


def bench_slot(rows: list[np.ndarray], bucket: int, reps: int) -> float:
    """Median seconds per batch through the REAL SlotRing (reserve +
    row write + seal + release), depth 2 so slots actually recycle."""
    ring = SlotRing(capacity=bucket, depth=2)
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        for i, r in enumerate(rows):
            ring.write({"frames": r}, i)
        sealed = ring.next_batch(0.0, lambda n: bucket)
        times.append(time.perf_counter() - t0)
        assert sealed is not None and sealed.n == len(rows)
        ring.release(sealed)
    return float(np.median(times))


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--bucket", type=int, default=128,
                   help="batch bucket (block capacity); default is the "
                        "largest bucket at the hub's serving default "
                        "(EngineHub max_batch=128 — the shape whose "
                        "stack+concat cost the slot path removes)")
    p.add_argument("--rows", type=int, default=0,
                   help="items in the batch (0 = full bucket; below "
                        "bucket exercises the zeroed-pad tail)")
    p.add_argument("--height", type=int, default=648,
                   help="wire row height (default: 432x768 ingest in "
                        "I420 wire = 648x768 uint8)")
    p.add_argument("--width", type=int, default=768)
    p.add_argument("--reps", type=int, default=30)
    p.add_argument("--min-speedup", type=float, default=1.0,
                   help="fail below this slot-vs-legacy ratio (the "
                        "CI-adjacent assertion: never slower)")
    args = p.parse_args()

    n = args.rows or args.bucket
    if n > args.bucket:
        p.error("--rows must be <= --bucket")
    rng = np.random.default_rng(0)
    rows = [rng.integers(0, 255, (args.height, args.width), np.uint8)
            for _ in range(n)]
    row_mb = rows[0].nbytes / 1e6
    log(f"assembling {n} rows of {args.height}x{args.width} uint8 "
        f"({row_mb:.2f} MB each) into bucket {args.bucket}, "
        f"{args.reps} reps")

    # interleave the two modes' warmups so neither benefits from a
    # warmer page cache
    bench_legacy(rows, args.bucket, 3)
    bench_slot(rows, args.bucket, 3)
    legacy_s = bench_legacy(rows, args.bucket, args.reps)
    slot_s = bench_slot(rows, args.bucket, args.reps)
    speedup = legacy_s / slot_s if slot_s > 0 else float("inf")
    log(f"legacy {legacy_s * 1e3:.2f} ms/batch, "
        f"slot {slot_s * 1e3:.2f} ms/batch → {speedup:.2f}x")

    print(json.dumps({
        "metric": "host_assembly_speedup",
        "value": round(speedup, 2),
        "unit": "x",
        "legacy_ms": round(legacy_s * 1e3, 3),
        "slot_ms": round(slot_s * 1e3, 3),
        "bucket": args.bucket,
        "rows": n,
        "row_shape": [args.height, args.width],
        "ok": speedup >= args.min_speedup,
    }))
    if speedup < args.min_speedup:
        log(f"FAIL: slot assembly is slower than legacy "
            f"({speedup:.2f}x < {args.min_speedup:.2f}x)")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
