"""Generate the native-format pipeline definitions under pipelines/.

Mirrors the reference's 6 workload families and 11 variants
(SURVEY.md §2c) in evam_tpu's native stage-list format. Run from repo
root: ``python tools/gen_pipelines.py``.
"""

import json
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent / "pipelines"


def src():
    return {"kind": "source", "name": "source"}


def dec():
    return {"kind": "decode", "name": "decode"}


def detect(model="object_detection/person_vehicle_bike", **props):
    d = {"kind": "detect", "name": "detection", "model": model}
    if props:
        d["properties"] = props
    return d


def meta_chain():
    return [
        {"kind": "metaconvert", "name": "metaconvert"},
        {"kind": "publish", "name": "destination"},
        {"kind": "sink", "name": "appsink"},
    ]


def params(**props):
    return {"type": "object", "properties": props}


DETECTION_COMMON = dict(
    (
        ("detection-properties", {"element": {"name": "detection", "format": "element-properties"}}),
        ("detection-device", {"element": {"name": "detection", "property": "device"}, "type": "string", "default": "{env[DETECTION_DEVICE]}"}),
        ("detection-model-instance-id", {"element": {"name": "detection", "property": "model-instance-id"}, "type": "string"}),
        ("inference-interval", {"element": "detection", "type": "integer"}),
        ("threshold", {"element": "detection", "type": "number"}),
    )
)

CLASSIFY_COMMON = dict(
    (
        ("classification-properties", {"element": {"name": "classification", "format": "element-properties"}}),
        ("classification-device", {"element": {"name": "classification", "property": "device"}, "type": "string", "default": "{env[CLASSIFICATION_DEVICE]}"}),
        ("classification-model-instance-id", {"element": {"name": "classification", "property": "model-instance-id"}, "type": "string"}),
        ("object-class", {"element": "classification", "type": "string", "default": "vehicle"}),
        ("reclassify-interval", {"element": "classification", "type": "integer"}),
    )
)


PIPELINES = {}

# -- object_detection (5 variants; reference pipelines/object_detection/*) --
PIPELINES[("object_detection", "person_vehicle_bike")] = {
    "type": "tpu",
    "description": "Person Vehicle Bike Detection (TPU batched engine)",
    "stages": [src(), dec(), detect(), *meta_chain()],
    "parameters": params(**DETECTION_COMMON),
}

PIPELINES[("object_detection", "person")] = {
    "type": "tpu",
    "description": "Person Detection (TPU batched engine)",
    "stages": [src(), dec(), detect("object_detection/person"), *meta_chain()],
    "parameters": params(
        **{k: DETECTION_COMMON[k] for k in ("detection-properties", "detection-device")}
    ),
}

PIPELINES[("object_detection", "vehicle")] = {
    "type": "tpu",
    "description": "Vehicle Detection based on vehicle-detection-0202 (TPU batched engine)",
    "stages": [src(), dec(), detect("object_detection/vehicle"), *meta_chain()],
    "parameters": params(**DETECTION_COMMON),
}

PIPELINES[("object_detection", "object_zone_count")] = {
    "type": "tpu",
    "description": "Detection with zone-count spatial-analytics UDF",
    "stages": [
        src(),
        dec(),
        detect(),
        {
            "kind": "udf",
            "name": "object-zone-count",
            "properties": {
                "class": "ObjectZoneCount",
                "module": "evam_tpu.extensions.object_zone_count",
            },
        },
        {"kind": "metaconvert", "name": "metaconvert"},
        {
            "kind": "udf",
            "name": "event-convert",
            "properties": {"module": "evam_tpu.extensions.event_convert"},
        },
        {"kind": "publish", "name": "destination"},
        {"kind": "sink", "name": "appsink"},
    ],
    "parameters": params(
        **DETECTION_COMMON,
        **{
            "object-zone-count-config": {
                "element": {"name": "object-zone-count", "property": "kwarg", "format": "json"},
                "type": "object",
                "properties": {
                    "zones": {"type": "array", "items": {"type": "object"}},
                    "enable_watermark": {"type": "boolean"},
                    "log_level": {"type": "string"},
                },
            }
        },
    ),
}

PIPELINES[("object_detection", "app_src_dst")] = {
    "type": "tpu",
    "description": "Detection with app source and raw appsink destination",
    "stages": [src(), dec(), detect(), {"kind": "sink", "name": "destination"}],
    "parameters": params(
        **{"detection-model-instance-id": DETECTION_COMMON["detection-model-instance-id"]}
    ),
}

# -- object_classification ------------------------------------------------
PIPELINES[("object_classification", "vehicle_attributes")] = {
    "type": "tpu",
    "description": "Detection + Vehicle Attributes Classification (TPU)",
    "stages": [
        src(),
        dec(),
        detect(),
        {
            "kind": "classify",
            "name": "classification",
            "model": "object_classification/vehicle_attributes",
        },
        *meta_chain(),
    ],
    "parameters": params(
        **CLASSIFY_COMMON,
        **{k: DETECTION_COMMON[k] for k in ("detection-properties", "detection-device", "detection-model-instance-id")},
        **{
            "inference-interval": {
                "element": [
                    {"name": "detection", "property": "inference-interval"},
                    {"name": "classification", "property": "inference-interval"},
                ],
                "type": "integer",
            },
            "detection-threshold": {
                "element": {"name": "detection", "property": "threshold"},
                "type": "number",
            },
            "classification-threshold": {
                "element": {"name": "classification", "property": "threshold"},
                "type": "number",
            },
        },
    ),
}

# -- object_tracking (2 variants) -----------------------------------------
_track_stage = {"kind": "track", "name": "tracking"}

PIPELINES[("object_tracking", "person_vehicle_bike")] = {
    "type": "tpu",
    "description": "Detection + Tracking + Vehicle Attributes Classification (TPU)",
    "stages": [
        src(),
        dec(),
        detect(),
        dict(_track_stage),
        {
            "kind": "classify",
            "name": "classification",
            "model": "object_classification/vehicle_attributes",
        },
        *meta_chain(),
    ],
    "parameters": params(
        **CLASSIFY_COMMON,
        **{k: DETECTION_COMMON[k] for k in ("detection-properties", "detection-device", "detection-model-instance-id")},
        **{
            "tracking-properties": {"element": {"name": "tracking", "format": "element-properties"}},
            "tracking-device": {"element": [{"name": "tracking", "property": "device"}], "type": "string"},
            "tracking-type": {"element": {"name": "tracking", "property": "tracking-type"}, "type": "string", "default": "iou"},
            "inference-interval": {
                "element": [
                    {"name": "detection", "property": "inference-interval"},
                    {"name": "classification", "property": "inference-interval"},
                ],
                "type": "integer",
            },
            "detection-threshold": {"element": {"name": "detection", "property": "threshold"}, "type": "number"},
            "classification-threshold": {"element": {"name": "classification", "property": "threshold"}, "type": "number"},
        },
    ),
}

PIPELINES[("object_tracking", "object_line_crossing")] = {
    "type": "tpu",
    "description": "Detection + Tracking with line-crossing spatial-analytics UDF",
    "stages": [
        src(),
        dec(),
        detect(),
        dict(_track_stage),
        {
            "kind": "udf",
            "name": "object-line-crossing",
            "properties": {
                "class": "ObjectLineCrossing",
                "module": "evam_tpu.extensions.object_line_crossing",
            },
        },
        {"kind": "metaconvert", "name": "metaconvert"},
        {
            "kind": "udf",
            "name": "event-convert",
            "properties": {"module": "evam_tpu.extensions.event_convert"},
        },
        {"kind": "publish", "name": "destination"},
        {"kind": "sink", "name": "appsink"},
    ],
    "parameters": params(
        **DETECTION_COMMON,
        **{
            "tracking-properties": {"element": {"name": "tracking", "format": "element-properties"}},
            "object-line-crossing-config": {
                "element": {"name": "object-line-crossing", "property": "kwarg", "format": "json"},
                "type": "object",
                "properties": {
                    "lines": {"type": "array", "items": {"type": "object"}},
                    "enable_watermark": {"type": "boolean"},
                    "log_level": {"type": "string"},
                },
            },
        },
    ),
}

# -- action_recognition ---------------------------------------------------
PIPELINES[("action_recognition", "general")] = {
    "type": "tpu",
    "description": "General action recognition, 16-frame clip encoder+decoder (TPU)",
    "stages": [
        src(),
        dec(),
        {"kind": "convert", "name": "convert", "properties": {"caps": "video/x-raw", "format": "BGRx"}},
        {
            "kind": "action",
            "name": "action_recognition",
            "properties": {
                "enc-model": "action_recognition/encoder",
                "dec-model": "action_recognition/decoder",
                "model-proc": "action_recognition/decoder",
            },
        },
        {"kind": "metaconvert", "name": "metaconvert", "properties": {"add-tensor-data": True}},
        {"kind": "publish", "name": "destination"},
        {"kind": "sink", "name": "appsink"},
    ],
    "parameters": params(
        **{
            "enc-device": {"element": "action_recognition", "description": "Encoder inference device: [CPU, GPU, TPU]", "type": "string", "default": "{env[DETECTION_DEVICE]}"},
            "dec-device": {"element": "action_recognition", "description": "Decoder inference device: [CPU, GPU, TPU]", "type": "string", "default": "{env[DETECTION_DEVICE]}"},
            "action-recognition-properties": {"element": {"name": "action_recognition", "format": "element-properties"}},
        }
    ),
}

# -- audio_detection ------------------------------------------------------
PIPELINES[("audio_detection", "environment")] = {
    "type": "tpu",
    "description": "Environmental sound detection based on AclNet (TPU)",
    "stages": [
        src(),
        dec(),
        {
            "kind": "convert",
            "name": "audio_format",
            "properties": {"caps": "audio/x-raw", "channels": 1, "format": "S16LE", "rate": 16000},
        },
        {"kind": "audio_mix", "name": "audiomixer"},
        {"kind": "level", "name": "level"},
        {"kind": "audio_detect", "name": "detection", "model": "audio_detection/environment"},
        *meta_chain(),
    ],
    "parameters": params(
        **{
            "device": {"element": "detection", "type": "string", "default": "{env[DETECTION_DEVICE]}"},
            "bus-messages": {"description": "Log bus messages as info", "type": "boolean", "default": False},
            "output-buffer-duration": {"element": "audiomixer", "type": "integer", "default": 100000000},
            "threshold": {"element": "detection", "type": "number"},
            "sliding-window": {"element": "detection", "type": "number", "default": 0.2},
            "post-messages": {"element": "level", "type": "boolean"},
            "detection-properties": {"element": {"name": "detection", "format": "element-properties"}},
        }
    ),
}

# -- video_decode ---------------------------------------------------------
PIPELINES[("video_decode", "app_dst")] = {
    "type": "tpu",
    "description": "Decode-only pipeline with appsink destination",
    "stages": [src(), dec(), {"kind": "sink", "name": "destination"}],
}


def main():
    for (name, version), spec in PIPELINES.items():
        path = ROOT / name / version / "pipeline.json"
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(json.dumps(spec, indent=2) + "\n")
        print(f"wrote {path}")


if __name__ == "__main__":
    main()
