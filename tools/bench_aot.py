#!/usr/bin/env python
"""Cold vs cache-hit spin-up: the AOT executable cache's CI gate.

Three child processes build the same tiny serving hub and measure
spin-up-to-first-batch (hub build + full bucket warmup + one served
batch), then run a seeded frame set and digest the raw result bytes:

* ``cold``  — EVAM_AOT=on against an empty cache dir: every bucket is
  an ``absent`` miss, compiled ahead-of-time once and stored.
* ``warm``  — EVAM_AOT=on against the now-populated dir: every bucket
  must deserialize (aot_hits == buckets, zero compile seconds) — the
  elastic-fleet scale-up path in miniature.
* ``off``   — EVAM_AOT unset (the default): the plain jit path.

Gates: all three digests are BIT-IDENTICAL (the cache may change
where an executable comes from, never a number), the warm child hit
on every bucket, and (full mode only — CI shares cores) the warm
spin-up beats the acceptance bound and the cold spin-up. Prints ONE
JSON line on stdout; diagnostics on stderr.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import subprocess
import sys
import tempfile
import time
from pathlib import Path

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("EVAM_ALLOW_RANDOM_WEIGHTS", "1")
os.environ.setdefault("EVAM_LOG_LEVEL", "warning")

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

MODEL = "object_detection/person_vehicle_bike"


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def child(args) -> int:
    """One measured spin-up in a fresh process (the cache is
    process-memoized — cold/warm/off must not share a jit cache)."""
    import numpy as np

    from evam_tpu.engine.hub import EngineHub
    from evam_tpu.models import ModelRegistry, ZOO_SPECS
    from evam_tpu.ops.color import wire_shape

    # clock starts AFTER the interpreter/jax imports: a fleet scale-up
    # happens inside a running process, so the number that gates is
    # registry + hub build + full bucket warmup + one served batch —
    # the same span FleetEngine._last_spinup_s measures
    t0 = time.perf_counter()
    overrides = {k: (64, 64) for k in ZOO_SPECS}
    overrides["audio_detection/environment"] = (1, 1600)
    registry = ModelRegistry(
        dtype="float32", input_overrides=overrides,
        width_overrides={k: 8 for k in ZOO_SPECS})
    hub = EngineHub(registry, plan=None, max_batch=8, deadline_ms=2.0,
                    supervise=False, stall_timeout_s=0)
    eng = hub.engine("detect", MODEL)
    frame = np.zeros(tuple(wire_shape("i420", 64, 64)), np.uint8)
    eng.set_example(frames=frame)
    eng.warmup()
    eng.submit(stream="bench", frames=frame).result(timeout=300)
    spinup_s = time.perf_counter() - t0

    rng = np.random.default_rng(7)
    digest = hashlib.sha256()
    for _ in range(args.frames):
        f = rng.integers(0, 256, frame.shape, np.uint8)
        out = eng.submit(stream="bench", frames=f).result(timeout=300)
        for leaf in (out if isinstance(out, (list, tuple)) else [out]):
            digest.update(np.ascontiguousarray(leaf).tobytes())

    print(json.dumps({
        "spinup_s": round(spinup_s, 4),
        "digest": digest.hexdigest(),
        "buckets": len(eng.buckets),
        "aot_hits": eng.stats.aot_hits,
        "compile_s": round(eng.stats.compile_seconds, 4),
        "aot_load_s": round(eng.stats.aot_load_seconds, 4),
    }))
    hub.stop()
    return 0


def run_child(mode: str, aot_dir: str, frames: int) -> dict:
    env = dict(os.environ)
    env.pop("EVAM_AOT", None)
    env.pop("EVAM_AOT_DIR", None)
    if mode != "off":
        env["EVAM_AOT"] = "1"
        env["EVAM_AOT_DIR"] = aot_dir
    out = subprocess.run(
        [sys.executable, __file__, "--child", "--frames", str(frames)],
        capture_output=True, text=True, timeout=900, env=env)
    sys.stderr.write(out.stderr)
    if out.returncode != 0:
        raise RuntimeError(f"{mode} child failed rc={out.returncode}")
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    log(f"{mode}: spinup {rec['spinup_s']}s, aot_hits "
        f"{rec['aot_hits']}/{rec['buckets']}, compile "
        f"{rec['compile_s']}s, load {rec['aot_load_s']}s")
    return rec


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--child", action="store_true")
    ap.add_argument("--frames", type=int, default=12)
    ap.add_argument("--smoke", action="store_true",
                    help="identity + hit gates only (no wall-clock "
                         "gate: CI runners share cores)")
    ap.add_argument("--gate-s", type=float, default=5.0,
                    help="warm spin-up-to-first-batch bound (full "
                         "mode; the ISSUE-18 acceptance number)")
    args = ap.parse_args()
    if args.child:
        return child(args)

    with tempfile.TemporaryDirectory(prefix="evam_aot_bench_") as d:
        cold = run_child("cold", d, args.frames)
        warm = run_child("warm", d, args.frames)
        off = run_child("off", d, args.frames)

    identical = (cold["digest"] == warm["digest"] == off["digest"])
    all_hit = (warm["aot_hits"] == warm["buckets"]
               and warm["compile_s"] == 0.0)
    populated = cold["aot_hits"] == 0 and cold["compile_s"] > 0.0
    ok = identical and all_hit and populated
    if not args.smoke:
        ok = ok and warm["spinup_s"] < args.gate_s
        ok = ok and warm["spinup_s"] < cold["spinup_s"]

    print(json.dumps({
        "metric": "aot_warm_spinup_s",
        "value": warm["spinup_s"],
        "unit": "s",
        "vs_baseline": round(warm["spinup_s"] - cold["spinup_s"], 4),
        "ok": ok,
        "cold_spinup_s": cold["spinup_s"],
        "off_spinup_s": off["spinup_s"],
        "bit_identical": identical,
        "warm_hits": warm["aot_hits"],
        "buckets": warm["buckets"],
        "warm_compile_s": warm["compile_s"],
        "smoke": args.smoke,
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
