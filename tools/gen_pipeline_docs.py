#!/usr/bin/env python
"""Generate per-pipeline operator READMEs with CAPTURED expected output.

VERDICT r3 #9: the reference documents each workload family as an
operator walkthrough ending in real expected metadata
(reference pipelines/action_recognition/general/README.md:84-101,
charts/README.md:92-120). Hand-written samples go stale, so this tool
*runs* every pipeline on a synthetic source through the full engine
(decode → stages → metaconvert → publish) and embeds what actually
came out. Regenerate after any metadata-affecting change:

    JAX_PLATFORMS=cpu python tools/gen_pipeline_docs.py

The capture uses tiny model shapes + random-init weights (offline
image), so box geometry/labels in the samples are placeholders — the
SCHEMA is the contract (tests/test_golden.py pins it); a deployment
with installed weights sees the same fields with real values.
"""

from __future__ import annotations

import json
import os
import sys
from pathlib import Path

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("EVAM_ALLOW_RANDOM_WEIGHTS", "1")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# the image's .axon_site hook rewrites JAX_PLATFORMS to "axon,cpu" at
# jax import — force the config back (same dance as tests/conftest.py),
# else this tool hangs on a wedged tunnel
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

# --------------------------------------------------------------- curated copy

#: (family, variant) -> curated sections. "blurb" says what the
#: pipeline does and how the TPU engine runs it; "consume" is the
#: operator's result-consumption command; "extra" is appended verbatim.
DOCS: dict[tuple[str, str], dict] = {
    ("object_detection", "person_vehicle_bike"): dict(
        title="Object Detection — person / vehicle / bike",
        blurb=(
            "Detects persons, vehicles and bikes in every decoded frame "
            "with the crossroad-class SSD detector "
            "(`models/object_detection/person_vehicle_bike`). Frames from "
            "all running instances are batched cross-stream into one "
            "jitted TPU program; detections come back per-stream as the "
            "reference's metadata JSON.\n\n"
            "Reference counterpart: "
            "`pipelines/object_detection/person_vehicle_bike/pipeline.json` "
            "(gvadetect chain)."),
    ),
    ("object_detection", "person"): dict(
        title="Object Detection — person",
        blurb=(
            "Person-only detection (retail face/person class space) on "
            "the shared batched detect engine. Reference counterpart: "
            "`pipelines/object_detection/person/pipeline.json`."),
    ),
    ("object_detection", "vehicle"): dict(
        title="Object Detection — vehicle",
        blurb=(
            "Vehicle detection (vehicle-detection-0202 class space). "
            "Reference counterpart: "
            "`pipelines/object_detection/vehicle/pipeline.json`."),
    ),
    ("object_detection", "object_zone_count"): dict(
        title="Object Detection — zone count (UDF)",
        blurb=(
            "Detection plus a user-defined zone-count extension: polygon "
            "zones are evaluated against each frame's detections and a "
            "`zone-count` event is appended to the metadata — the "
            "`gvapython` UDF flow of the reference "
            "(`object_zone_count/pipeline.json:44-65`), here a host-side "
            "UDF stage (`evam_tpu/extensions/zone_count.py`) between the "
            "TPU detect stage and metaconvert."),
        params_note=(
            "`object-zone-count-config` takes "
            '`{"zones": [{"name": ..., "polygon": [[x,y], ...]}]}` with '
            "polygon vertices in relative 0–1 coordinates."),
    ),
    ("object_detection", "app_src_dst"): dict(
        title="Object Detection — application source / destination",
        blurb=(
            "Detection for embedders: frames are *injected* by the "
            "application (appsrc counterpart — `AppSource`) and results "
            "delivered to an application sink callback alongside the "
            "usual metadata destination. This is the pipeline the EII "
            "manager uses when ingesting frames from the message bus. "
            "Reference counterpart: "
            "`pipelines/object_detection/app_src_dst/pipeline.json`."),
    ),
    ("object_classification", "vehicle_attributes"): dict(
        title="Object Classification — vehicle attributes",
        blurb=(
            "Two-model pipeline: SSD vehicle detection, then a secondary "
            "attributes classifier (color/type) on each detected ROI. On "
            "TPU both run as ONE fused jitted program — ROI crops are "
            "gathered on-device into a fixed ROI budget and classified "
            "in the same step, so adding classification costs far less "
            "than a second dispatch. `object-class` filters which "
            "detections get classified; `reclassify-interval` reuses "
            "cached attributes between refreshes. Reference counterpart: "
            "`pipelines/object_classification/vehicle_attributes/"
            "pipeline.json` (gvadetect → gvaclassify)."),
    ),
    ("object_tracking", "person_vehicle_bike"): dict(
        title="Object Tracking — person / vehicle / bike",
        blurb=(
            "Detection → tracking → classification. The tracker assigns "
            "persistent `object_id`s across frames (`zero-term` exact "
            "IoU matching or `short-term` constant-velocity coasting "
            "through missed detections — the reference's gvatrack "
            "`tracking-type` vocabulary). Classification piggybacks on "
            "the fused detect+classify TPU step. Reference counterpart: "
            "`pipelines/object_tracking/person_vehicle_bike/"
            "pipeline.json`."),
    ),
    ("object_tracking", "object_line_crossing"): dict(
        title="Object Tracking — line crossing (UDF)",
        blurb=(
            "Tracked objects are tested against user-defined lines; a "
            "`line-crossing` event fires when an object's track crosses "
            "one (direction-aware). The reference runs this as a "
            "`gvapython` extension "
            "(`object_line_crossing/pipeline.json:34-55`); here it is "
            "the host-side UDF stage "
            "`evam_tpu/extensions/line_crossing.py` fed by tracker "
            "output."),
        params_note=(
            "`object-line-crossing-config` takes "
            '`{"lines": [{"name": ..., "line": [[x1,y1],[x2,y2]]}]}` in '
            "relative coordinates."),
    ),
    ("action_recognition", "general"): dict(
        title="Action Recognition — general",
        blurb=(
            "Composite encoder/decoder temporal model "
            "(action-recognition-0001): each frame is encoded, a sliding "
            "16-frame clip of embeddings is decoded into 400 Kinetics "
            "class scores. Both halves are separate batched TPU engines "
            "chained by futures, so streams never block on a pending "
            "clip. Metadata carries the full tensor "
            "(`add-tensor-data=true` behavior). Expect the first scores "
            "after the 16-frame warm-up. Reference counterpart: "
            "`pipelines/action_recognition/general/pipeline.json` "
            "(gvaactionrecognitionbin)."),
    ),
    ("audio_detection", "environment"): dict(
        title="Audio Detection — environment",
        blurb=(
            "AclNet-style audio event detection on 16 kHz mono S16LE "
            "input: one-second sliding windows (stride = "
            "`sliding-window`) are batched to the TPU audio engine; "
            "events above `threshold` are published with start/end "
            "timestamps. Reference counterpart: "
            "`pipelines/audio_detection/environment/pipeline.json` "
            "(gvaaudiodetect)."),
        source_note=(
            "Any decodable audio/video URI works; `synthetic-audio://` "
            "generates a deterministic tone mix for offline smoke "
            "tests."),
    ),
    ("video_decode", "app_dst"): dict(
        title="Video Decode — application destination",
        blurb=(
            "Decode-only: no inference, frames are handed to the "
            "application sink (appsink counterpart). Used to feed "
            "downstream EII consumers raw BGR frames, and as the "
            "decode-path microbenchmark. Reference counterpart: "
            "`pipelines/video_decode/app_dst/pipeline.json`."),
    ),
}


# ------------------------------------------------------------------- capture


def capture_samples() -> dict[tuple[str, str], dict]:
    """Run every pipeline on a synthetic source; return captured
    metadata (or frame-shape info for sink-only pipelines)."""
    from evam_tpu.engine import EngineHub
    from evam_tpu.graph import PipelineLoader, resolve_parameters
    from evam_tpu.media import SyntheticSource
    from evam_tpu.media.audio import SyntheticAudioSource
    from evam_tpu.models import ModelRegistry, ZOO_SPECS
    from evam_tpu.parallel import build_mesh
    from evam_tpu.stages import StreamRunner, build_stages

    small = {k: (64, 64) for k in ZOO_SPECS}
    small["audio_detection/environment"] = (1, 1600)
    registry = ModelRegistry(
        dtype="float32", input_overrides=small,
        width_overrides={k: 8 for k in ZOO_SPECS})
    hub = EngineHub(registry, plan=build_mesh(), max_batch=16,
                    deadline_ms=4.0)
    loader = PipelineLoader(REPO / "pipelines")

    run_params: dict[tuple[str, str], dict] = {
        ("object_detection", "object_zone_count"): {
            "threshold": 0.0,
            "object-zone-count-config": {"zones": [{
                "name": "doorway",
                "polygon": [[0, 0], [1, 0], [1, 1], [0, 1]]}]},
        },
        ("object_tracking", "object_line_crossing"): {
            "threshold": 0.0,
            "object-line-crossing-config": {"lines": [{
                "name": "entrance",
                "line": [[0.0, 0.5], [1.0, 0.5]]}]},
        },
        ("object_classification", "vehicle_attributes"): {
            "detection-threshold": 0.0, "object-class": ""},
        ("object_tracking", "person_vehicle_bike"): {
            "detection-threshold": 0.0, "object-class": ""},
        ("audio_detection", "environment"): {
            "threshold": 0.0, "sliding-window": 1.0},
    }
    counts = {("action_recognition", "general"): 20}

    out: dict[tuple[str, str], dict] = {}
    for fam_dir in sorted((REPO / "pipelines").iterdir()):
        for var_dir in sorted(fam_dir.iterdir()):
            if not (var_dir / "pipeline.json").exists():
                continue
            key = (fam_dir.name, var_dir.name)
            spec = loader.get(*key)
            params = run_params.get(key)
            if params is None:
                # zero thresholds where declared so random-init models
                # still produce sample objects; nothing else
                declared = (spec.parameters or {}).get("properties") or {}
                params = {k: 0.0 for k in
                          ("threshold", "detection-threshold")
                          if k in declared}
            stages_spec, _ = resolve_parameters(spec, params)
            metas: list = []
            sink_frames: list = []
            runner = StreamRunner(
                "doc", build_stages(
                    stages_spec, hub, source_uri="synthetic://doc",
                    publish_fn=lambda ctx: metas.append(ctx.metadata),
                    sink_fn=lambda ctx: sink_frames.append(
                        None if ctx.frame is None else ctx.frame.shape),
                ), source_uri="synthetic://doc")
            if key[0] == "audio_detection":
                src = SyntheticAudioSource(seconds=3.0)
            else:
                src = SyntheticSource(
                    width=96, height=64, count=counts.get(key, 6))
            runner.run(src.frames())
            # prefer a sample that actually shows the payload
            sample = None
            for m in metas:
                if m.get("objects") or m.get("events") or m.get("tensors"):
                    sample = m
                    break
            if sample is None and metas:
                sample = metas[0]
            out[key] = {
                "sample": sample,
                "n_meta": len(metas),
                "sink_frames": sink_frames[:1],
            }
            print(f"captured {key}: {len(metas)} messages, "
                  f"sample={'yes' if sample else 'no'}")
    hub.stop()
    return out


# -------------------------------------------------------------------- render


def trim_sample(meta: dict) -> tuple[dict, list[str]]:
    """Keep the sample readable: 2 objects, 8 tensor values."""
    import copy

    m = copy.deepcopy(meta)
    notes: list[str] = []
    objs = m.get("objects")
    if isinstance(objs, list) and len(objs) > 2:
        notes.append(f"showing 2 of {len(objs)} objects")
        m["objects"] = objs[:2]
    tensors = list(m.get("tensors") or [])
    for o in m.get("objects") or []:
        tensors.extend(o.get("tensors") or [])
    for t in tensors:
        d = t.get("data")
        if isinstance(d, list) and len(d) > 8:
            notes.append(
                f"tensor `{t.get('name')}` data: first 8 of {len(d)}")
            t["data"] = d[:8]
    return m, notes


def params_table(pipeline: dict) -> str:
    props = (pipeline.get("parameters") or {}).get("properties") or {}
    if not props:
        return "_This pipeline takes no request parameters._"
    rows = ["| Parameter | Type | Default | Bound to |",
            "|---|---|---|---|"]
    for name, schema in props.items():
        el = schema.get("element")
        if isinstance(el, dict):
            bound = f"`{el.get('name')}` ({el.get('format', 'property')})"
        elif isinstance(el, list):
            bound = ", ".join(
                f"`{e.get('name')}.{e.get('property')}`" for e in el)
        else:
            prop = schema.get("property")
            bound = f"`{el}.{prop}`" if prop else f"`{el}`"
        default = schema.get("default")
        default = "—" if default is None else f"`{json.dumps(default)}`"
        typ = schema.get("type", "object")
        if isinstance(typ, list):  # JSON Schema union, e.g. adaptive
            typ = " \\| ".join(typ)
        rows.append(f"| `{name}` | {typ} | {default} | {bound} |")
    return "\n".join(rows)


def render(key: tuple[str, str], pipeline: dict, captured: dict) -> str:
    fam, var = key
    doc = DOCS.get(key, {})
    title = doc.get("title", f"{fam} / {var}")
    blurb = doc.get("blurb", pipeline.get("description", ""))
    chain = " → ".join(s["kind"] for s in pipeline["stages"])

    if fam == "audio_detection":
        uri = "file:///home/pipeline-server/resources/environment.wav"
    else:
        uri = ("file:///home/pipeline-server/resources/"
               "person-bicycle-car-detection.mp4")
    body: dict = {
        "source": {"uri": uri, "type": "uri"},
        "destination": {"metadata": {
            "type": "mqtt", "host": "localhost:1883",
            "topic": f"evam/{var}"}},
    }
    extra_params = {
        k: v for k, v in {
            "object-zone-count-config": {"zones": [{
                "name": "doorway",
                "polygon": [[0.2, 0.2], [0.8, 0.2],
                            [0.8, 0.8], [0.2, 0.8]]}]},
            "object-line-crossing-config": {"lines": [{
                "name": "entrance",
                "line": [[0.0, 0.5], [1.0, 0.5]]}]},
        }.items() if k in ((pipeline.get("parameters") or {})
                           .get("properties") or {})}
    if extra_params:
        body["parameters"] = extra_params
    curl = (
        f"curl -s localhost:8080/pipelines/{fam}/{var} \\\n"
        "  -H 'Content-Type: application/json' \\\n"
        f"  -d '{json.dumps(body)}'")

    parts = [
        f"# {title}\n",
        blurb + "\n",
        f"**Stage chain:** `{chain}`\n",
        "## Start\n",
        "With the service running (`evam-tpu serve` or "
        "`deploy/docker-compose.yml`):\n",
        "```bash\n" + curl + "\n```\n",
        "The response is the instance id. "
        f"`GET /pipelines/{fam}/{var}/{{id}}/status` reports state and "
        f"per-stream FPS; `DELETE /pipelines/{fam}/{var}/{{id}}` stops "
        "the stream.\n",
        "## Consume results\n",
        doc.get("consume",
                f"```bash\nmosquitto_sub -h localhost -t evam/{var}\n"
                "```\n"),
        "## Parameters\n",
        params_table(pipeline) + "\n",
    ]
    if doc.get("params_note"):
        parts.append(doc["params_note"] + "\n")
    if doc.get("source_note"):
        parts.append(doc["source_note"] + "\n")

    parts.append("## Expected output\n")
    sample = captured.get("sample")
    if sample is not None:
        sample, notes = trim_sample(sample)
        parts.append(
            "One JSON message per processed frame/window (captured "
            "from a live run on a synthetic source with tiny "
            "random-init models — the schema is the contract; real "
            "weights put real values in the same fields"
            + ("; " + "; ".join(notes) if notes else "") + "):\n")
        parts.append(
            "```json\n" + json.dumps(sample, indent=2) + "\n```\n")
    else:
        shapes = captured.get("sink_frames") or []
        parts.append(
            "This pipeline has no metadata destination — decoded "
            "frames are delivered to the application sink "
            f"(captured frame shape: `{shapes[0] if shapes else '?'}` "
            "BGR uint8).\n")
    if doc.get("extra"):
        parts.append(doc["extra"] + "\n")
    parts.append(
        "---\n_Generated by `tools/gen_pipeline_docs.py` from a live "
        "capture; regenerate after metadata-affecting changes._\n")
    return "\n".join(parts)


def main() -> int:
    captured = capture_samples()
    for key, cap in captured.items():
        fam, var = key
        pipeline = json.loads(
            (REPO / "pipelines" / fam / var / "pipeline.json").read_text())
        md = render(key, pipeline, cap)
        out = REPO / "pipelines" / fam / var / "README.md"
        out.write_text(md)
        print("wrote", out.relative_to(REPO))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
