#!/usr/bin/env python
"""Control-plane overhead gate: EVAM_TUNE on vs off through a real
engine.

Two properties hold or the exit code says so:

1. **Off-identity** — with ``EVAM_TUNE=off`` every hot-path consult
   (``control.state.current_op``) is a memoized-None check and the
   engine's outputs are BIT-IDENTICAL to the tuned run while the
   operating point is neutral (the controller retunes WHEN it acts;
   the consult itself never perturbs compute). Same discipline as
   EVAM_TRANSFER / EVAM_GATE / EVAM_TRACE A/B.
2. **Overhead** — with the controller enabled (neutral op, no
   actions — isolating the pure consult cost on the dispatch path),
   sustained submit->result throughput stays within
   ``--max-overhead`` (3% by default) of the off path.

CPU-only (JAX_PLATFORMS=cpu works), runs in seconds; ``--smoke`` is
the CI shape. Prints ONE JSON line on stdout; diagnostics on stderr.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _set_mode(mode: str) -> None:
    """Flip EVAM_TUNE and drop every memo that captured it."""
    os.environ["EVAM_TUNE"] = mode
    from evam_tpu.config.settings import reset_settings
    from evam_tpu.control import state as control_state

    reset_settings()
    control_state.reset_cache()


def run_mode(mode: str, frames: int, reps: int,
             batch: int) -> tuple[float, str]:
    """(median frames/s, output checksum) for one EVAM_TUNE mode.
    A fresh engine per call so neither mode inherits warm state."""
    _set_mode(mode)
    from evam_tpu.engine.batcher import BatchEngine

    eng = BatchEngine(
        f"bench-tune-{mode}", lambda p, x: (x * 2.0 + 1.0),
        params={}, max_batch=batch, input_names=("x",), deadline_ms=2.0)
    rng = np.random.default_rng(0)
    rows = [rng.standard_normal((64,)).astype(np.float32)
            for _ in range(frames)]
    digest = hashlib.sha256()
    rates = []
    try:
        # warmup rep compiles the bucket ladder out of the timing
        for rep in range(reps + 1):
            t0 = time.perf_counter()
            futs = [eng.submit(x=row) for row in rows]
            for fut in futs:
                out = np.asarray(fut.result(timeout=60))
                if rep == 1:
                    digest.update(out.tobytes())
            if rep > 0:
                rates.append(frames / (time.perf_counter() - t0))
    finally:
        eng.stop()
    return float(np.median(rates)), digest.hexdigest()


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--smoke", action="store_true",
                   help="CI shape: fewer frames/reps, same gates")
    p.add_argument("--frames", type=int, default=400)
    p.add_argument("--reps", type=int, default=5)
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--max-overhead", type=float, default=0.03,
                   help="max throughput loss with the consult on (3%%)")
    args = p.parse_args()
    if args.smoke:
        args.frames, args.reps = min(args.frames, 200), min(args.reps, 3)

    log(f"{args.frames} frames x {args.reps} reps, bucket {args.batch}")
    off_fps, off_sum = run_mode("off", args.frames, args.reps, args.batch)
    on_fps, on_sum = run_mode("on", args.frames, args.reps, args.batch)
    overhead = (off_fps - on_fps) / off_fps if off_fps > 0 else 0.0
    identical = off_sum == on_sum
    log(f"off {off_fps:.0f} f/s, on {on_fps:.0f} f/s "
        f"-> overhead {overhead * 100:.2f}%  identity={identical}")

    ok = identical and overhead <= args.max_overhead
    print(json.dumps({
        "metric": "tune_overhead",
        "value": round(overhead, 4),
        "unit": "fraction",
        "off_fps": round(off_fps, 1),
        "on_fps": round(on_fps, 1),
        "identical_outputs": identical,
        "max_overhead": args.max_overhead,
        "ok": ok,
    }))
    if not identical:
        log("FAIL: EVAM_TUNE=on (neutral op) changed the engine outputs")
        return 1
    if overhead > args.max_overhead:
        log(f"FAIL: control-plane consult overhead {overhead * 100:.2f}% "
            f"> {args.max_overhead * 100:.1f}%")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
