#!/bin/bash
# Poll the axon tunnel with a hard-timeout subprocess probe; the moment
# it answers, fire the given battery script. Front-loads TPU work after
# a wedge without burning attention on manual polling.
#   tools/tpu_watch.sh tools/tpu_battery_r4.sh /tmp/tpu_battery_r4 43200 BENCH_SERVE_r04.json
set -u
BATTERY=${1:?battery script}
OUT=${2:?output dir}
MAX_WAIT_S=${3:-28800}
# no default: a stale default here would clobber a PRIOR round's
# committed artifact with this round's fold
DEST=${4:?dest artifact filename (e.g. BENCH_SERVE_r04.json)}
cd "$(dirname "$0")/.."
mkdir -p "$OUT"
start=$(date +%s)
while true; do
    now=$(date +%s)
    if [ $((now - start)) -gt "$MAX_WAIT_S" ]; then
        echo "$(date -Is) giving up after ${MAX_WAIT_S}s" >> "$OUT/watch.log"
        exit 1
    fi
    timeout 150 python - <<'EOF' >> "$OUT/watch.log" 2>&1
import jax, jax.numpy as jnp
x = jnp.ones((256, 256), jnp.bfloat16)
print("probe ok:", float(jax.jit(lambda a: (a @ a).sum())(x)))
EOF
    if [ $? -eq 0 ]; then
        echo "$(date -Is) tunnel alive -> $BATTERY" >> "$OUT/watch.log"
        bash "$BATTERY" "$OUT"
        rc=$?
        # fold results into the repo immediately: if the round ends
        # before a human/agent returns, the driver's end-of-round
        # commit still captures BENCH_SERVE_r03.json
        python tools/fold_battery2.py "$OUT" "$DEST" > "$OUT/folded.md" 2>>"$OUT/watch.log" || true
        echo "$(date -Is) battery rc=$rc; folded -> $DEST" >> "$OUT/watch.log"
        exit $rc
    fi
    echo "$(date -Is) probe failed; retrying in 180s" >> "$OUT/watch.log"
    sleep 180
done
