"""Pinpoint the 33 ms SSD-forward cost: params dtype x dw-impl x parts.

profile_step.py P3 charges ~33 ms/batch-32 to the registry's SSD
forward, yet a standalone bf16-input backbone measures ~10 ms
(profile_layers.py). Candidate explanations, each isolated here on
the real chip:
  * f32 params promote the bf16 input so every conv runs in f32
    (half MXU rate, double bandwidth);
  * the SSD heads (tiny channel counts at /8..) add the rest;
  * shift vs lax depthwise lowering.
"""

from __future__ import annotations

import os as _os
_os.environ.setdefault("EVAM_ALLOW_RANDOM_WEIGHTS", "1")  # hermetic profiling tool

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def bench_fn(fn, iters=20, warmup=3):
    import jax

    for i in range(warmup):
        jax.block_until_ready(fn(np.int32(i)))
    t0 = time.perf_counter()
    for i in range(iters):
        out = fn(np.int32(100 + i))
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters * 1e3


def main() -> int:
    import jax
    import jax.numpy as jnp

    b, size = 32, 512
    print(f"device: {jax.devices()[0].platform} batch={b} {size}^2", flush=True)

    n = b * size * size * 3

    def synth(seed, dt):
        i = jax.lax.iota(jnp.uint32, n)
        bits = i * jnp.uint32(2654435761) + seed.astype(jnp.uint32)
        return ((bits >> 13).astype(jnp.uint8).astype(jnp.float32) / 255.0
                ).reshape(b, size, size, 3).astype(dt)

    import importlib

    from evam_tpu.models.zoo import layers as L
    from evam_tpu.models.zoo import ssd as S

    for dw in ("lax", "shift"):
        os.environ["EVAM_DWCONV"] = dw
        importlib.reload(L)
        importlib.reload(S)
        net = S.SSDDetector(num_classes=4, width=32, extra_levels=2)
        p32 = net.init(jax.random.PRNGKey(0), jnp.zeros((1, size, size, 3)))
        p16 = jax.tree.map(lambda a: a.astype(jnp.bfloat16), p32)
        bb = L.Backbone(width=32, extra_levels=2)
        bbp = {"params": p32["params"]["Backbone_0"]}
        bbp16 = jax.tree.map(lambda a: a.astype(jnp.bfloat16), bbp)

        for label, params, dt in [
            ("ssd  p=f32 x=bf16", p32, jnp.bfloat16),
            ("ssd  p=bf16 x=bf16", p16, jnp.bfloat16),
            ("ssd  p=f32 x=f32 ", p32, jnp.float32),
            ("bbone p=f32 x=bf16", bbp, jnp.bfloat16),
            ("bbone p=bf16 x=bf16", bbp16, jnp.bfloat16),
        ]:
            mod = bb if label.startswith("bbone") else net
            pp = jax.device_put(params)

            @jax.jit
            def fwd(seed, mod=mod, pp=pp, dt=dt):
                out = mod.apply(pp, synth(seed, dt))
                if isinstance(out, dict):
                    return sum(v.astype(jnp.float32).sum() for v in out.values())
                return sum(f.astype(jnp.float32).sum() for f in out)

            print(f"[{dw:5s}] {label}: {bench_fn(fwd):7.2f} ms", flush=True)
    os.environ.pop("EVAM_DWCONV", None)
    return 0


if __name__ == "__main__":
    sys.exit(main())
