#!/usr/bin/env python
"""Decode-pool consolidation experiment (VERDICT r3 item 10).

Measures aggregate decode throughput of K concurrent 1080p file
streams two ways on THIS host:

  A. per-stream — one ``DecodeWorker`` thread per stream (the serving
     default; mirrors the reference's decodebin thread-graph-per-
     pipeline model),
  B. pooled — one shared ``DecodePool`` with M worker threads
     (``--pool-workers``) multiplexing all K streams.

Prints ONE JSON line with both aggregate fps and the pool-efficiency
factor (pooled/per-stream). The factor feeds INGEST.md's H.264
core-count extrapolation: cores_needed(pooled) =
cores_needed(per-stream) / factor. On a 1-vCPU container the factor
mostly reads GIL/scheduler overhead (expect ≈1.0); the pool's
deployment value is the thread-count bound (K+K·ffmpeg → M threads).

Usage: python tools/bench_decode_pool.py [--streams 8]
[--pool-workers 1] [--frames 90]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time
from pathlib import Path

# pure host-side measurement: never let an evam_tpu import reach for
# the axon tunnel (the .axon_site hook rewrites JAX_PLATFORMS)
os.environ["JAX_PLATFORMS"] = "cpu"

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from tools.measure_decode import busy_frames  # noqa: E402


def make_clip(n_frames: int, codec: str = "mp4v") -> str:
    import cv2

    if codec == "h264":
        # genuine H.264 via the from-scratch intra-only generator
        # (media/h264.py) — I_PCM, so a lower bound on camera-grade
        # H.264 decode cost, but through FFmpeg's real H.264 path
        from evam_tpu.media import h264

        path = str(Path(tempfile.gettempdir()) / "pool_bench.h264")
        h264.write_annexb(path, list(busy_frames(n_frames)))
        return path
    path = str(Path(tempfile.gettempdir()) / "pool_bench.mp4")
    wr = cv2.VideoWriter(
        path, cv2.VideoWriter_fourcc(*codec), 30, (1920, 1080))
    if not wr.isOpened():
        raise RuntimeError(f"{codec} encoder unavailable")
    for f in busy_frames(n_frames):
        wr.write(f)
    wr.release()
    return path


def run_per_stream(clip: str, k: int) -> tuple[float, int]:
    from evam_tpu.media import DecodeWorker, FileSource

    counts = [0] * k

    def sink(i):
        def on_frame(ev):
            counts[i] += 1
        return on_frame

    t0 = time.perf_counter()
    workers = [
        DecodeWorker(f"s{i}", lambda: FileSource(clip),
                     on_frame=sink(i)).start()
        for i in range(k)
    ]
    for w in workers:
        while not w.finished:
            time.sleep(0.05)
    dt = time.perf_counter() - t0
    return sum(counts) / dt, sum(counts)


def run_pooled(clip: str, k: int, m: int) -> tuple[float, int]:
    from evam_tpu.media import DecodePool, FileSource

    counts = [0] * k

    def sink(i):
        def on_frame(ev):
            counts[i] += 1
        return on_frame

    pool = DecodePool(workers=m)
    t0 = time.perf_counter()
    streams = [
        pool.add_stream(f"p{i}", lambda: FileSource(clip),
                        on_frame=sink(i))
        for i in range(k)
    ]
    while not all(s.finished for s in streams):
        time.sleep(0.05)
    dt = time.perf_counter() - t0
    pool.stop()
    errors = [s.error for s in streams if s.error]
    if errors:
        raise RuntimeError(f"pooled streams failed: {errors[:3]}")
    return sum(counts) / dt, sum(counts)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--streams", type=int, default=8)
    ap.add_argument("--pool-workers", type=int, default=1)
    ap.add_argument("--frames", type=int, default=90)
    ap.add_argument("--codec", default="mp4v",
                    help="mp4v (default) or h264 (intra-only Annex-B "
                         "from media/h264.py — real FFmpeg H.264 path)")
    args = ap.parse_args()

    clip = make_clip(args.frames, args.codec)
    expected = args.frames * args.streams
    # warm the page cache so both runs read hot
    Path(clip).read_bytes()

    fps_a, n_a = run_per_stream(clip, args.streams)
    fps_b, n_b = run_pooled(clip, args.streams, args.pool_workers)
    assert n_a == expected, (n_a, expected)
    assert n_b == expected, (n_b, expected)

    out = {
        "metric": "decode_pool_efficiency",
        "codec": args.codec,
        "streams": args.streams,
        "pool_workers": args.pool_workers,
        "frames_per_stream": args.frames,
        "per_stream_fps": round(fps_a, 1),
        "pooled_fps": round(fps_b, 1),
        "value": round(fps_b / fps_a, 3),
        "unit": "pooled/per-stream aggregate fps",
        "decode_threads_per_stream_mode": args.streams,
        "decode_threads_pooled_mode": args.pool_workers,
    }
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
