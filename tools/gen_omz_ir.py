"""Generate an OMZ-shaped MobileNet-SSD OpenVINO IR for offline testing.

The reference downloads real OMZ IRs (person-vehicle-bike-detection-
crossroad-0078 etc., reference models_list/models.list.yml:1-34 via
tools/model_downloader); this environment has no egress, so this tool
emits an IR with the *same topology shape* those 2018-era detectors
have — MobileNet-v1 depthwise backbone (GroupConvolution + bias + ReLU
chains), multi-scale 1x1 SSD heads, Transpose→Reshape→Concat wiring,
in-graph conf SoftMax, PriorBoxClustered branches, and a final
DetectionOutput — at configurable scale, with seeded random weights.

Used by tests/test_ir.py to prove the importer handles the real op
sequence end-to-end (imported forward vs an independent torch
implementation built from the same weights), and runnable standalone
to materialize a serving-layout model dir:

    python tools/gen_omz_ir.py models/omz_like/1/FP32 --size 512
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

import numpy as np

_REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(_REPO))

from evam_tpu.models.ir_build import build_crossroad_like_ir  # noqa: E402



def torch_reference_forward(weights: dict, x: np.ndarray,
                            width: int, num_classes: int):
    """Independent forward of the generated topology in torch (CPU).

    Returns (loc [B, A*4-flat], conf_softmaxed [B, A*C-flat]) matching
    the IR's loc_concat/conf_concat outputs (pre-DetectionOutput).
    """
    import torch
    import torch.nn.functional as F

    t = {k: torch.from_numpy(np.ascontiguousarray(v)) for k, v in weights.items()}
    xt = torch.from_numpy(np.ascontiguousarray(x))

    def conv(name, xt, stride, groups=1):
        w = t[f"{name}_w"]
        if groups > 1:
            w = w.reshape(w.shape[0] * w.shape[1], *w.shape[2:])
        kh = w.shape[-1]
        ih = xt.shape[2]
        oh = -(-ih // stride)
        pad = max((oh - 1) * stride + kh - ih, 0)
        lo, hi = pad // 2, pad - pad // 2
        xt = F.pad(xt, (lo, hi, lo, hi))
        y = F.conv2d(xt, w, stride=stride, groups=groups)
        return F.relu(y + t[f"{name}_b"])

    def dw(name, xt):
        stride = {"b2": 2, "b4": 2, "b6": 2}.get(name, 1)
        xt = conv(f"{name}_dw", xt, stride, groups=xt.shape[1])
        return conv(f"{name}_pw", xt, 1)

    xt = conv("conv0", xt, 2)
    for name in ["b1", "b2", "b3", "b4", "b5"]:
        xt = dw(name, xt)
    feat8 = xt
    for name in ["b6", "b7"]:
        xt = dw(name, xt)
    feat16 = xt

    locs, confs = [], []
    for idx, feat in enumerate([feat8, feat16]):
        bsz = feat.shape[0]
        loc = F.conv2d(feat, t[f"head{idx}_loc_w"]) \
            .permute(0, 2, 3, 1).reshape(bsz, -1)
        conf = F.conv2d(feat, t[f"head{idx}_conf_w"]) \
            .permute(0, 2, 3, 1).reshape(bsz, -1, num_classes)
        conf = F.softmax(conf, dim=2).reshape(bsz, -1)
        locs.append(loc)
        confs.append(conf)
    return (torch.cat(locs, dim=1).numpy(),
            torch.cat(confs, dim=1).numpy())


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("target", type=Path)
    p.add_argument("--size", type=int, default=512)
    p.add_argument("--width", type=int, default=32)
    p.add_argument("--classes", type=int, default=4)
    args = p.parse_args()
    xml, weights, meta = build_crossroad_like_ir(
        args.target, args.size, args.width, args.classes)
    print(f"wrote {xml} ({len(weights)} weight tensors, "
          f"{meta['anchors']} anchors)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
