"""ThreadSanitizer stress run for the native media kernels
(SURVEY §5.2 race/sanitizer posture; round-1 VERDICT "the C++ kernels
have no TSAN/stress run").

Builds ``libevam_media_tsan.so`` (-fsanitize=thread) and hammers every
exported kernel from multiple Python threads concurrently — the
serving pattern is N decode workers calling resize/convert with the
GIL released, so cross-thread kernel reentrancy plus each kernel's
internal OpenMP team is exactly what TSAN must see. Exits non-zero on
any data-race report.

Run: ``python tools/tsan_stress.py`` (needs g++; ~20 s).
``--smoke`` shrinks the stress (2 threads x 5 iters, 360p source) to a
seconds-scale CI gate — same build, same kernels, same TSAN abort on
any report; the full shape stays the pre-release soak.
"""

from __future__ import annotations

import os as _os
_os.environ.setdefault("EVAM_ALLOW_RANDOM_WEIGHTS", "1")  # hermetic profiling tool

import ctypes
import os
import subprocess
import sys
import threading

import numpy as np

NATIVE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")


def build() -> str:
    lib = os.path.join(NATIVE, "libevam_media_tsan.so")
    subprocess.run(
        ["g++", "-O1", "-g", "-fPIC", "-fopenmp", "-fsanitize=thread",
         "-Wall", "-std=c++17", "-shared", "-o", lib,
         os.path.join(NATIVE, "evam_media.cpp")],
        check=True,
    )
    return lib


def main(argv: list[str] | None = None) -> int:
    smoke = "--smoke" in (sys.argv[1:] if argv is None else argv)
    n_threads, n_iters = (2, 5) if smoke else (8, 30)
    src_h, src_w = (360, 640) if smoke else (1080, 1920)
    lib_path = build()
    if "libtsan" not in os.environ.get("LD_PRELOAD", ""):
        # dlopen-ing a TSAN-built .so into an unsanitized python hits
        # "cannot allocate memory in static TLS block" — the TSAN
        # runtime must be preloaded; re-exec with LD_PRELOAD set
        import glob

        candidates = glob.glob("/lib/*/libtsan.so*") + glob.glob(
            "/usr/lib/*/libtsan.so*")
        if not candidates:
            print("libtsan not found; skipping", file=sys.stderr)
            return 0
        env = dict(os.environ, LD_PRELOAD=candidates[0],
                   TSAN_OPTIONS="halt_on_error=1 exitcode=66")
        return subprocess.run(
            [sys.executable, os.path.abspath(__file__)]
            + (["--smoke"] if smoke else []), env=env
        ).returncode
    lib = ctypes.CDLL(lib_path)
    u8p = ctypes.POINTER(ctypes.c_uint8)

    lib.resize_bgr_to_i420.argtypes = [
        u8p, ctypes.c_int, ctypes.c_int, u8p, ctypes.c_int, ctypes.c_int]
    lib.resize_bgr.argtypes = [
        u8p, ctypes.c_int, ctypes.c_int, u8p, ctypes.c_int, ctypes.c_int]
    lib.bgr_to_i420.argtypes = [u8p, u8p, ctypes.c_int, ctypes.c_int]

    rng = np.random.default_rng(0)
    src = rng.integers(0, 255, (src_h, src_w, 3), np.uint8)
    errors: list[Exception] = []

    def worker(tid: int) -> None:
        try:
            frame = np.ascontiguousarray(src)
            out_i420 = np.empty((512 * 3 // 2, 512), np.uint8)
            out_bgr = np.empty((512, 512, 3), np.uint8)
            out_full = np.empty((src_h * 3 // 2, src_w), np.uint8)
            for _ in range(n_iters):
                lib.resize_bgr_to_i420(
                    frame.ctypes.data_as(u8p), src_h, src_w,
                    out_i420.ctypes.data_as(u8p), 512, 512)
                lib.resize_bgr(
                    frame.ctypes.data_as(u8p), src_h, src_w,
                    out_bgr.ctypes.data_as(u8p), 512, 512)
                lib.bgr_to_i420(
                    frame.ctypes.data_as(u8p),
                    out_full.ctypes.data_as(u8p), src_h, src_w)
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        print("worker errors:", errors, file=sys.stderr)
        return 1
    print(f"tsan stress: {n_threads} threads x {n_iters} iters x 3 "
          "kernels — no races reported (TSAN aborts the process on a "
          "report)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
