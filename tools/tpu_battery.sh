#!/bin/bash
# Unattended TPU measurement battery — run the moment the axon tunnel
# answers a probe (it wedges unpredictably; front-load everything).
# Results land in /tmp/tpu_battery/ as JSON lines + logs, feeding
# PROFILE.md's after-tables and the bench operating-point choice.
set -u
OUT=${1:-/tmp/tpu_battery}
mkdir -p "$OUT"
cd "$(dirname "$0")/.."

FAILED=0
run() {
    name=$1; shift
    echo "=== $name: $* ===" | tee -a "$OUT/battery.log"
    timeout 900 "$@" >"$OUT/$name.json" 2>"$OUT/$name.err"
    local rc=$?
    echo "rc=$rc $(tail -1 "$OUT/$name.json" 2>/dev/null)" | tee -a "$OUT/battery.log"
    [ $rc -ne 0 ] && FAILED=$((FAILED + 1))
    return $rc
}

# 1. cheapest first: one clean headline number at the current default
run bench_default python bench.py --seconds 8

# 2. cumulative phase ladder (where did the fused-step time go)
run profile python tools/profile_step.py

# 3. operating-point sweep under the latency target
run bench_sweep python bench.py --sweep --seconds 25 --p99-target-ms 100

# 4. int8 vs bf16 A/B at the sweep's shape (fixed 16x2 if unknown)
run bench_int8 python bench.py --precision int8 --batch 16 --depth 2 --seconds 8
run bench_bf16 python bench.py --batch 16 --depth 2 --seconds 8

# 5. NMS settle A/B
EVAM_NMS=unroll run bench_nms_unroll python bench.py --config detect --seconds 6 || true
run bench_nms_while python bench.py --config detect --seconds 6

# 5b. pallas fused int8 GEMM vs XLA int8 (1x1 convs + dense)
EVAM_QGEMM=pallas run bench_int8_pallas python bench.py --precision int8 --batch 16 --depth 2 --seconds 6 || true

# 6. secondary configs for BASELINE coverage
run bench_action python bench.py --config action --seconds 6
run bench_audio python bench.py --config audio --seconds 6

# 7. host-ingest path (true PCIe/tunnel transfer)
run bench_host python bench.py --ingest host --batch 8 --depth 2 --seconds 6

echo "battery complete -> $OUT ($FAILED failed)" | tee -a "$OUT/battery.log"
exit $((FAILED > 0))
