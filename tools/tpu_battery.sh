#!/bin/bash
# Unattended TPU measurement battery — run the moment the axon tunnel
# answers a probe (it wedges unpredictably; front-load everything).
# Results land in /tmp/tpu_battery/ as JSON lines + logs, feeding
# PROFILE.md's after-tables and the bench operating-point choice.
set -u
OUT=${1:-/tmp/tpu_battery}
mkdir -p "$OUT"
cd "$(dirname "$0")/.."

FAILED=0
run() {
    name=$1; shift
    echo "=== $name: $* ===" | tee -a "$OUT/battery.log"
    timeout 900 "$@" >"$OUT/$name.json" 2>"$OUT/$name.err"
    local rc=$?
    echo "rc=$rc $(tail -1 "$OUT/$name.json" 2>/dev/null)" | tee -a "$OUT/battery.log"
    [ $rc -ne 0 ] && FAILED=$((FAILED + 1))
    return $rc
}

# 1. cheapest first: one clean headline number at the current default
#    (b256 x d3 — see PROFILE.md operating-point table)
run bench_default python bench.py --seconds 8

# 2. part-wise profiles (profile_step's .sum() ladder lies for linear
#    phases — XLA collapses them; keep all three views)
run profile python tools/profile_step.py
run profile_parts python tools/profile_ssd_parts.py
run profile_fusion python tools/profile_fusion.py

# 3. operating-point sweep under the latency target
run bench_sweep python bench.py --sweep --seconds 30 --p99-target-ms 100

# 4. int8 vs bf16 A/B at the compute-bound shape
run bench_int8 python bench.py --precision int8 --batch 512 --depth 2 --seconds 8
run bench_bf16 python bench.py --batch 512 --depth 2 --seconds 8

# 5. NMS settle A/B
EVAM_NMS=unroll run bench_nms_unroll python bench.py --config detect --seconds 6 || true
run bench_nms_while python bench.py --config detect --seconds 6

# 5b. pallas fused int8 GEMM vs XLA int8 (1x1 convs + dense)
EVAM_QGEMM=pallas run bench_int8_pallas python bench.py --precision int8 --batch 512 --depth 2 --seconds 6 || true

# 5c. depthwise lowering A/B (lax default won round 2; re-check on new hw)
EVAM_DWCONV=shift run bench_dw_shift python bench.py --config detect --seconds 6 || true

# 6. secondary configs for BASELINE coverage
run bench_action python bench.py --config action --seconds 6
run bench_audio python bench.py --config audio --seconds 6

# 7. host-ingest path (true PCIe/tunnel transfer)
run bench_host python bench.py --ingest host --batch 8 --depth 2 --seconds 6

echo "battery complete -> $OUT ($FAILED failed)" | tee -a "$OUT/battery.log"
exit $((FAILED > 0))
