"""Does the NCHW IR executor pay a TPU layout penalty vs NHWC flax?

The importer executes IR graphs in their native NCHW layout and lets
XLA assign internal layouts. If XLA's transposes don't fuse, imported
real-model serving would be slower than the NHWC zoo path and an
import-time NHWC rewrite pass would be warranted. This measures the
same OMZ-shaped MobileNet-SSD (tools/gen_omz_ir.py) as (a) imported IR
(NCHW) and (b) the equivalent zoo-style NHWC flax net — same weights
scale, batch 32 at 512².
"""

from __future__ import annotations

import os as _os
_os.environ.setdefault("EVAM_ALLOW_RANDOM_WEIGHTS", "1")  # hermetic profiling tool

import os
import sys
import tempfile
import time
from pathlib import Path

import numpy as np

_REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(_REPO))


def bench_fn(fn, iters=20, warmup=3):
    import jax

    for i in range(warmup):
        jax.block_until_ready(fn(np.int32(i)))
    t0 = time.perf_counter()
    for i in range(iters):
        out = fn(np.int32(100 + i))
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters * 1e3


def main() -> int:
    import jax
    import jax.numpy as jnp

    from evam_tpu.models.ir_build import build_crossroad_like_ir
    from evam_tpu.models.registry import ModelRegistry

    b = int(os.environ.get("EVAM_PROFILE_BATCH", "32"))
    size, width = 512, 32
    print(f"device: {jax.devices()[0].platform} batch={b} {size}^2 "
          f"width={width}", flush=True)

    root = Path(tempfile.mkdtemp())
    target = root / "omz_like" / "1" / "FP32"
    build_crossroad_like_ir(target, input_size=size, width=width,
                            num_classes=4)
    reg = ModelRegistry(models_dir=root, dtype="bfloat16")
    ir_model = reg.get("omz_like/1")
    ir_params = jax.device_put(ir_model.params)

    n = b * size * size * 3

    def synth(seed):
        i = jax.lax.iota(jnp.uint32, n)
        bits = i * jnp.uint32(2654435761) + seed.astype(jnp.uint32)
        return ((bits >> 13).astype(jnp.uint8).astype(jnp.float32) / 255.0
                ).reshape(b, size, size, 3).astype(jnp.bfloat16)

    @jax.jit
    def ir_fwd(seed):
        out = ir_model.forward(ir_params, synth(seed))
        return sum(v.astype(jnp.float32).sum() for v in out.values())

    print(f"IR (NCHW import): {bench_fn(ir_fwd):7.2f} ms", flush=True)

    # NHWC zoo counterpart at the same width
    from evam_tpu.models.zoo.ssd import SSDDetector

    net = SSDDetector(num_classes=4, width=width, extra_levels=0)
    params = jax.device_put(
        net.init(jax.random.PRNGKey(0),
                 jnp.zeros((1, size, size, 3), jnp.bfloat16)))

    @jax.jit
    def zoo_fwd(seed):
        out = net.apply(params, synth(seed))
        return sum(v.astype(jnp.float32).sum() for v in out.values())

    print(f"zoo (NHWC flax) : {bench_fn(zoo_fwd):7.2f} ms", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
