#!/usr/bin/env python
"""Async-demux throughput/scaling bench (round 5).

Measures the live-RTSP demux (media/demux.py) at N paced streams ×
M decode workers on THIS host, for both payload formats:

  * jpeg — RFC 2435 (server packetizes cv2 JPEGs)
  * h264 — RFC 6184 intra-only (server packetizes media/h264.py AUs;
    decode pays the per-AU file-shim documented in INGEST.md)

Streams are camera-paced (the server pushes at --fps); consumers
drain instantly, so drops measure the demux+decode layer itself, not
a downstream consumer. Prints ONE JSON line.

Usage: python tools/bench_demux.py [--streams 16] [--workers 2]
[--fps 10] [--seconds 8] [--codec jpeg] [--width 640] [--height 480]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from pathlib import Path

# host-side measurement: never let an evam_tpu import reach the axon
# tunnel (the .axon_site hook rewrites JAX_PLATFORMS at jax import)
os.environ["JAX_PLATFORMS"] = "cpu"

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--streams", type=int, default=16)
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--fps", type=float, default=10.0)
    ap.add_argument("--seconds", type=float, default=8.0)
    ap.add_argument("--codec", choices=["jpeg", "h264"], default="jpeg")
    ap.add_argument("--width", type=int, default=640)
    ap.add_argument("--height", type=int, default=480)
    args = ap.parse_args()

    import numpy as np

    from evam_tpu.media import h264
    from evam_tpu.media.demux import RtspDemux
    from evam_tpu.publish.rtsp import RtspServer

    srv = RtspServer(port=0, host="127.0.0.1")
    srv.start()
    stop = threading.Event()

    # pre-encode the payloads once: the bench charges the DEMUX side,
    # not the camera simulator
    rng = np.random.default_rng(0)
    frames = []
    bh, bw = args.height // 3, args.width // 3     # busy block, fits
    for i in range(4):
        f = np.zeros((args.height, args.width, 3), np.uint8)
        f[:, :] = (40, 30 * i, 160)
        y0 = (args.height // 8) * (i % 4)
        f[y0:y0 + bh, bw:2 * bw] = rng.integers(
            0, 255, (bh, bw, 3), np.uint8)
        frames.append(f)
    if args.codec == "h264":
        payloads = [h264.encode_frames([f]) for f in frames]
    else:
        import cv2

        payloads = [
            cv2.imencode(".jpg", f, [cv2.IMWRITE_JPEG_QUALITY, 80])[1]
            .tobytes() for f in frames
        ]

    def feeder(relay):
        # deadline pacing: sleep(1/fps) per cycle would drift the
        # offered rate below nominal (push time + 64-thread
        # contention), flattering decoded/offered comparisons
        k = 0
        next_t = time.monotonic()
        while not stop.is_set():
            if args.codec == "h264":
                relay.push_annexb(payloads[k % len(payloads)])
            else:
                relay.push_jpeg(payloads[k % len(payloads)])
            k += 1
            next_t += 1 / args.fps
            time.sleep(max(0.0, next_t - time.monotonic()))

    for i in range(args.streams):
        relay = srv.mount(f"cam{i}", codec=args.codec)
        threading.Thread(target=feeder, args=(relay,),
                         daemon=True).start()

    dmx = RtspDemux(decode_workers=args.workers)
    streams = [
        dmx.add_stream(f"rtsp://127.0.0.1:{srv.port}/cam{i}",
                       stream_id=f"s{i}")
        for i in range(args.streams)
    ]
    for s in streams:
        threading.Thread(
            target=lambda s=s: [None for _ in s.frames()],
            daemon=True).start()

    time.sleep(2.0)                       # settle
    base = dmx.stats()
    t0 = time.perf_counter()
    time.sleep(args.seconds)
    dt = time.perf_counter() - t0
    st = dmx.stats()
    stop.set()
    dmx.stop()
    srv.stop()

    decoded = st["decoded"] - base["decoded"]
    dropped = st["dropped"] - base["dropped"]
    offered = args.streams * args.fps
    out = {
        "metric": "demux_decoded_fps",
        "value": round(decoded / dt, 1),
        "unit": "frames/s aggregate",
        "codec": args.codec,
        "streams": args.streams,
        "decode_workers": args.workers,
        "threads_total": st["threads"],
        "offered_fps": offered,
        "dropped": dropped,
        "drop_frac": round(dropped / max(1, decoded + dropped), 4),
        "resolution": [args.height, args.width],
    }
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
