"""Chaos soak: N streams vs a supervised hub under injected faults.

Drives the full serving stack — synthetic sources → StreamRunner →
shared supervised BatchEngines — with ``EVAM_FAULT_INJECT`` active
(wedge/drop/error, obs/faults.py) and asserts the continuous-operation
contract the EngineSupervisor exists for:

* every stream COMPLETES (faults degrade frames, never kill streams);
* injected ``wedge`` faults trip the stall watchdog, the supervisor
  quarantines + rebuilds the engine, and serving resumes — within the
  restart budget (no engine ends the run ``degraded``);
* the readiness payload (/healthz shape) is back to healthy at the end;
* every quarantine leaves a flight-recorder JSONL (obs/trace.py)
  whose pending-batch row names the wedged batch's last completed
  stage — the post-mortem artifact the tracing PR exists for.

Usage (defaults are the CI-adjacent quick shape):

    python tools/chaos_soak.py --streams 4 --frames 210 \
        --fault "wedge=1,wedge_n=1,wedge_s=3,drop=0.02,error=0.01" \
        --seed 7 --stall-timeout 1.0

Engines are built and WARMED before the faults arm (the chaos scenario
is a wedge hitting a serving engine mid-traffic, and a warm bucket is
what the watchdog holds to its plain budget — cold first batches get
the compile grace). The deterministic shape ``wedge=1,wedge_n=K``
then wedges exactly the next K dispatched batches, so the run asserts
>= K restarts instead of hoping a probability fires.
``tests/test_chaos.py`` wires a fast marker-gated variant of exactly
this entrypoint into the tier-1 suite.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def run_soak(
    streams: int = 4,
    frames: int = 210,
    fault: str = "wedge=1,wedge_n=1,wedge_s=3,drop=0.02,error=0.01",
    seed: int = 7,
    stall_timeout_s: float = 1.0,
    max_restarts: int = 5,
    restart_window_s: float = 120.0,
    restart_backoff_s: float = 0.1,
    min_restarts: int | None = None,
    timeout_s: float = 240.0,
) -> dict:
    """Run the soak; returns a summary dict with ``ok``. Importable —
    the tier-1 chaos test calls this with a small shape."""
    from evam_tpu.config import Settings
    from evam_tpu.engine import EngineHub
    from evam_tpu.models import ModelRegistry, ZOO_SPECS
    from evam_tpu.obs import faults
    from evam_tpu.obs.metrics import metrics
    from evam_tpu.parallel import build_mesh
    from evam_tpu.server.registry import PipelineRegistry

    # faults stay DISARMED until the engines are built and warm — the
    # chaos scenario is a wedge hitting a SERVING engine mid-traffic,
    # and a warm bucket is what lets the stall watchdog apply its
    # plain (not first-batch compile grace) budget to the wedge
    os.environ["EVAM_FAULT_INJECT"] = ""
    faults.reset_cache()
    # flight recorder lands in a per-run dir so the post-wedge
    # assertion reads only THIS soak's dumps
    import tempfile

    from evam_tpu.config.settings import reset_settings
    from evam_tpu.obs import trace

    flight_dir = tempfile.mkdtemp(prefix="evam-flight-")
    os.environ["EVAM_TRACE_FLIGHT_DIR"] = flight_dir
    reset_settings()
    trace.reset_cache()
    small = {k: (64, 64) for k in ZOO_SPECS}
    small["audio_detection/environment"] = (1, 1600)
    narrow = {k: 8 for k in ZOO_SPECS}
    settings = Settings(pipelines_dir=str(REPO / "pipelines"))
    hub = EngineHub(
        ModelRegistry(dtype="float32", input_overrides=small,
                      width_overrides=narrow),
        plan=build_mesh(), max_batch=16, deadline_ms=4.0,
        warmup=True, stall_timeout_s=stall_timeout_s,
        supervise=True, max_restarts=max_restarts,
        restart_window_s=restart_window_s,
        restart_backoff_s=restart_backoff_s,
    )
    registry = PipelineRegistry(settings, hub=hub)
    registry.preload("object_detection/person_vehicle_bike")
    warm_deadline = time.time() + 180
    while time.time() < warm_deadline:
        ready = hub.readiness()
        if ready["engines"] and not ready["warming"]:
            break
        time.sleep(0.1)
    else:
        registry.stop_all()
        raise RuntimeError("engines never warmed; cannot arm chaos")
    os.environ["EVAM_FAULT_INJECT"] = fault
    os.environ["EVAM_FAULT_SEED"] = str(seed)
    faults.reset_cache()
    # the metrics registry is process-global: report deltas so a soak
    # embedded in a larger run (tests/test_chaos.py) doesn't count
    # earlier tests' faults/restarts
    wedges0 = metrics.get_counter(
        "evam_faults_injected", labels={"kind": "wedge"})
    t0 = time.time()
    # wedge count the deterministic fault shape guarantees (see module
    # docstring); probabilistic shapes pass min_restarts explicitly
    if min_restarts is None:
        cfg = dict(
            kv.split("=") for kv in fault.split(",") if "=" in kv)
        min_restarts = (int(float(cfg.get("wedge_n", 0)))
                        if float(cfg.get("wedge", 0)) >= 1.0 else 0)
    try:
        insts = [
            registry.start_instance(
                "object_detection", "person_vehicle_bike",
                {
                    # realtime pacing: the stream must OUTLIVE the
                    # wedge→rebuild cycles (a free-running synthetic
                    # source burns every frame into the error path
                    # while the engine is quarantined and completes
                    # before recovery can be observed)
                    "source": {
                        "uri": f"synthetic://96x96@30?count={frames}"
                               f"&seed={i}",
                        "type": "uri",
                        "realtime": True,
                    },
                    "destination": {"metadata": {"type": "null"}},
                },
            )
            for i in range(streams)
        ]
        deadline = t0 + timeout_s
        for inst in insts:
            inst.wait(timeout=max(1.0, deadline - time.time()))
        states = [i.state.value for i in insts]
        frames_out = sum(
            i._runner.frames_out if i._runner else 0 for i in insts)
        errors = sum(i._runner.errors if i._runner else 0 for i in insts)
        ready = hub.readiness()
        eng = hub.stats()
        restarts = sum(v.get("restarts", 0) for v in eng.values())
        degraded = [k for k, v in eng.items() if v.get("state") == "degraded"]
        wedges = metrics.get_counter(
            "evam_faults_injected", labels={"kind": "wedge"}) - wedges0
    finally:
        registry.stop_all()
    # flight-recorder artifact check: every quarantine dumped a JSONL
    # and the wedged (pending at quarantine) batch row names its last
    # completed engine stage
    flight_files = sorted(Path(flight_dir).glob("flight-*.jsonl"))
    flight_last_stage = None
    flight_pending_batches = 0
    for f in flight_files:
        for line in f.read_text(encoding="utf-8").splitlines():
            if not line.strip():
                continue
            row = json.loads(line)
            if row.get("type") == "batch" and row.get("pending"):
                flight_pending_batches += 1
                if row.get("last_stage"):
                    flight_last_stage = row["last_stage"]
    flight_ok = (min_restarts == 0
                 or (bool(flight_files) and flight_last_stage is not None))
    ok = (
        all(s == "COMPLETED" for s in states)
        and not degraded
        and restarts >= min_restarts
        and ready.get("restarting", 0) == 0
        and frames_out > 0
        and flight_ok
    )
    return {
        "ok": ok,
        "flight_dumps": len(flight_files),
        "flight_pending_batches": flight_pending_batches,
        "flight_last_stage": flight_last_stage,
        "flight_dir": flight_dir,
        "streams": streams,
        "states": states,
        "frames_out": frames_out,
        "errors": errors,
        "wedges_injected": int(wedges),
        "engine_restarts": restarts,
        "min_restarts": min_restarts,
        "degraded_engines": degraded,
        "readiness": ready,
        "elapsed_s": round(time.time() - t0, 1),
        "fault": fault,
        "seed": seed,
    }


def run_shard_loss_soak(
    streams: int = 4,
    frames: int = 150,
    shards: int = 4,
    losses: int = 2,
    seed: int = 11,
    timeout_s: float = 240.0,
) -> dict:
    """Shard-loss-during-migration drill (crash-consistent state PR):
    a sharded fleet (EVAM_FLEET=sharded) serves realtime streams with
    checkpointing armed (EVAM_CKPT=on) when ``losses`` consecutive
    chip losses fire (``shard_loss=1,shard_loss_n=K`` — deterministic,
    the second loss lands while the first loss's streams are still
    migrating). Contract:

    * zero realtime failures: every stream COMPLETES — chip loss
      degrades capacity, never a stream's liveness;
    * no duplicate frame resolution: a frame failed over mid-dispatch
      resolves at most once (per-stream frames_out <= frames_in);
    * every migration is counted on
      ``evam_stream_migrations_total{reason="shard_loss"}`` with a
      pre-rebalance checkpoint banked for the moved stream.
    """
    import jax

    from evam_tpu import state as stream_state
    from evam_tpu.config import Settings
    from evam_tpu.config.settings import reset_settings
    from evam_tpu.engine import EngineHub
    from evam_tpu.models import ModelRegistry, ZOO_SPECS
    from evam_tpu.obs import faults
    from evam_tpu.obs.metrics import metrics
    from evam_tpu.parallel import build_mesh
    from evam_tpu.server.registry import PipelineRegistry

    if len(jax.devices()) < shards:
        raise RuntimeError(
            f"need {shards} devices (XLA_FLAGS "
            f"--xla_force_host_platform_device_count), have "
            f"{len(jax.devices())}")
    os.environ["EVAM_FAULT_INJECT"] = ""
    os.environ["EVAM_CKPT"] = "on"
    os.environ["EVAM_CKPT_INTERVAL"] = "10"
    reset_settings()
    faults.reset_cache()
    stream_state.reset_cache()
    try:
        small = {k: (64, 64) for k in ZOO_SPECS}
        small["audio_detection/environment"] = (1, 1600)
        narrow = {k: 8 for k in ZOO_SPECS}
        settings = Settings(pipelines_dir=str(REPO / "pipelines"))
        hub = EngineHub(
            ModelRegistry(dtype="float32", input_overrides=small,
                          width_overrides=narrow),
            plan=build_mesh(devices=list(jax.devices())[:shards]),
            max_batch=16, deadline_ms=4.0, warmup=True,
            supervise=True, max_restarts=3, restart_backoff_s=0.1,
            fleet="sharded",
        )
        registry = PipelineRegistry(settings, hub=hub)
        registry.preload("object_detection/person_vehicle_bike")
        warm_deadline = time.time() + 180
        while time.time() < warm_deadline:
            ready = hub.readiness()
            if ready["engines"] and not ready["warming"]:
                break
            time.sleep(0.1)
        else:
            registry.stop_all()
            raise RuntimeError("fleet never warmed; cannot arm chaos")
        # arm AFTER warmup: the loss must hit a serving shard, and the
        # bounded countdown (shard_loss_n) retires exactly `losses`
        # shards — the second while the first's streams are migrating
        os.environ["EVAM_FAULT_INJECT"] = (
            f"shard_loss=1,shard_loss_n={losses}")
        os.environ["EVAM_FAULT_SEED"] = str(seed)
        faults.reset_cache()
        migrations0 = metrics.get_counter(
            "evam_stream_migrations", labels={"reason": "shard_loss"})
        losses0 = metrics.get_counter(
            "evam_faults_injected", labels={"kind": "shard_loss"})
        t0 = time.time()
        try:
            insts = [
                registry.start_instance(
                    "object_detection", "person_vehicle_bike",
                    {
                        "source": {
                            "uri": f"synthetic://96x96@30?count={frames}"
                                   f"&seed={i}",
                            "type": "uri",
                            "realtime": True,
                        },
                        "destination": {"metadata": {"type": "null"}},
                        "priority": "realtime",
                    },
                )
                for i in range(streams)
            ]
            deadline = t0 + timeout_s
            for inst in insts:
                inst.wait(timeout=max(1.0, deadline - time.time()))
            states = [i.state.value for i in insts]
            per_stream = {
                i.id[:8]: {
                    "in": i._runner.frames_in if i._runner else 0,
                    "out": i._runner.frames_out if i._runner else 0,
                    "errors": i._runner.errors if i._runner else 0,
                } for i in insts
            }
            store = stream_state.active()
            ckpt = store.summary() if store is not None else {}
            fleet = hub.fleet_summary()
        finally:
            registry.stop_all()
        migrations = metrics.get_counter(
            "evam_stream_migrations",
            labels={"reason": "shard_loss"}) - migrations0
        shard_losses = metrics.get_counter(
            "evam_faults_injected",
            labels={"kind": "shard_loss"}) - losses0
        # duplicate-resolution guard: a frame retried onto the new
        # shard must not ALSO resolve on the dying one — resolved
        # frames can never exceed ingested frames, per stream
        duplicate_streams = [
            sid for sid, row in per_stream.items()
            if row["out"] > row["in"] or row["out"] > frames
        ]
        failed_rt = [s for s in states if s != "COMPLETED"]
        ok = (
            not failed_rt
            and not duplicate_streams
            and int(shard_losses) == losses
            and int(migrations) >= 1
            and fleet["degraded_shards"] >= losses
            and sum(row["out"] for row in per_stream.values()) > 0
        )
        return {
            "ok": ok,
            "states": states,
            "per_stream": per_stream,
            "duplicate_streams": duplicate_streams,
            "migrations": int(migrations),
            "shard_losses_injected": int(shard_losses),
            "fleet": fleet,
            "checkpoint": ckpt,
            "elapsed_s": round(time.time() - t0, 1),
            "seed": seed,
        }
    finally:
        for key in ("EVAM_FAULT_INJECT", "EVAM_FAULT_SEED",
                    "EVAM_CKPT", "EVAM_CKPT_INTERVAL"):
            os.environ.pop(key, None)
        reset_settings()
        faults.reset_cache()
        stream_state.reset_cache()


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--streams", type=int, default=4)
    p.add_argument("--frames", type=int, default=210)
    p.add_argument("--fault", default=(
        "wedge=1,wedge_n=1,wedge_s=3,drop=0.02,error=0.01"))
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--stall-timeout", type=float, default=1.0)
    p.add_argument("--max-restarts", type=int, default=5)
    p.add_argument("--min-restarts", type=int, default=None,
                   help="override the wedge_n-derived recovery floor")
    p.add_argument("--timeout", type=float, default=240.0)
    p.add_argument("--scenario", choices=("wedge", "shard-loss"),
                   default="wedge",
                   help="shard-loss: chip loss during migration on a "
                        "sharded fleet with EVAM_CKPT=on")
    p.add_argument("--shards", type=int, default=4)
    p.add_argument("--losses", type=int, default=2)
    args = p.parse_args()
    if args.scenario == "shard-loss":
        result = run_shard_loss_soak(
            streams=args.streams, frames=args.frames,
            shards=args.shards, losses=args.losses, seed=args.seed,
            timeout_s=args.timeout,
        )
    else:
        result = run_soak(
            streams=args.streams, frames=args.frames, fault=args.fault,
            seed=args.seed, stall_timeout_s=args.stall_timeout,
            max_restarts=args.max_restarts,
            min_restarts=args.min_restarts,
            timeout_s=args.timeout,
        )
    print(json.dumps(result))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
