#!/usr/bin/env python
"""Chip-loss + elastic-ramp soaks: reshape the fleet, fail zero streams.

Default mode — chip loss (ISSUE 11): a multi-chip fleet is serving
realtime + standard streams when one chip wedges hard
(``EVAM_FAULT_INJECT wedge``, the PR-4 fault hook, armed mid-run with
a zero restart budget so the supervisor takes the shard to terminal
``degraded`` — a lost chip, not a recoverable stall). The contract
under that loss:

* the shard's streams MIGRATE (consistent-hash drain-and-rebalance,
  counted on ``evam_fleet_rebalance_total`` via
  ``fleet_summary()["rebalances"]``);
* in-flight work on the dead shard resolves or sheds PER CLASS
  POLICY (``evam_sched_shed_total`` / ``hub.shed_totals()``) — it
  does not hang;
* every realtime stream keeps completing frames after the loss:
  chip loss degrades fleet capacity, never a stream's liveness.

``--ramp`` mode — elastic scaling (ISSUE 18): an elastic fleet grows
2→8→2 one shard at a time under live realtime tracking streams,
actuated the way the eighth control law does it — one
``hub.retune(OperatingPoint(fleet_shards=n))`` push per step. A seed
phase first warms a full-peak fleet against a fresh EVAM_AOT_DIR, so
every grow during the ramp is a CACHE-HIT spin-up (deserialize, not
compile). The contract under the ramp:

* every grow joins warm-before-join with spin-up-to-first-batch under
  the acceptance bound (full mode; CI runners share cores);
* streams moved by ring growth/shrink are checkpointed through the
  PR-17 path (``evam_stream_migrations_total{reason="scale_up"|
  "scale_down"}``, pre_rebalance barrier, blobs decode) — identity
  continuity, not cold starts;
* zero realtime streams fail or stop progressing at any fleet size.

Exit 0 iff the mode's contract holds. Prints ONE JSON line on stdout;
diagnostics on stderr.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from pathlib import Path

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("EVAM_ALLOW_RANDOM_WEIGHTS", "1")
os.environ.setdefault("EVAM_LOG_LEVEL", "warning")
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

MODEL = "object_detection/person_vehicle_bike"
#: ramp mode serves the tracking pipeline: gate + IouTracker +
#: coaster state live per stream, so a migrated stream has identity
#: to keep (the checkpoint-path continuity the ramp asserts)
PIPELINE = ("object_tracking", "person_vehicle_bike")


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _build_hub(shards: int):
    import jax

    from evam_tpu.engine.hub import EngineHub
    from evam_tpu.models import ModelRegistry, ZOO_SPECS
    from evam_tpu.parallel.mesh import build_mesh

    overrides = {k: (64, 64) for k in ZOO_SPECS}
    overrides["audio_detection/environment"] = (1, 1600)
    registry = ModelRegistry(
        dtype="float32", input_overrides=overrides,
        width_overrides={k: 8 for k in ZOO_SPECS})
    plan = build_mesh(devices=list(jax.devices())[:shards])
    return EngineHub(
        registry, plan=plan, max_batch=16, deadline_ms=2.0,
        supervise=True, max_restarts=0, stall_timeout_s=1.0,
        first_batch_grace=15.0, fleet="sharded")


def _build_ramp_registry(shards: int, initial: int = 0,
                         max_shards: int = 0):
    """A PipelineRegistry over a sharded hub, warmed. ``initial`` > 0
    starts the fleet smaller than the mesh (the elastic shape);
    0 builds every shard (the seed shape)."""
    import jax

    from evam_tpu.config import Settings
    from evam_tpu.engine.hub import EngineHub
    from evam_tpu.models import ModelRegistry, ZOO_SPECS
    from evam_tpu.parallel.mesh import build_mesh
    from evam_tpu.server.registry import PipelineRegistry

    overrides = {k: (64, 64) for k in ZOO_SPECS}
    overrides["audio_detection/environment"] = (1, 1600)
    registry = ModelRegistry(
        dtype="float32", input_overrides=overrides,
        width_overrides={k: 8 for k in ZOO_SPECS})
    plan = build_mesh(devices=list(jax.devices())[:shards])
    hub = EngineHub(
        registry, plan=plan, max_batch=16, deadline_ms=4.0,
        warmup=True, supervise=True, max_restarts=3,
        restart_backoff_s=0.1, fleet="sharded",
        fleet_max_shards=max_shards, fleet_initial_shards=initial)
    settings = Settings(pipelines_dir=str(REPO / "pipelines"),
                       state_dir="")
    reg = PipelineRegistry(settings, hub=hub)
    reg.preload(f"{PIPELINE[0]}/{PIPELINE[1]}")
    deadline = time.time() + 300
    while time.time() < deadline:
        ready = hub.readiness()
        if ready["engines"] and not ready["warming"]:
            return reg
        time.sleep(0.1)
    reg.stop_all()
    raise RuntimeError("engines never warmed")


def _ramp_streams(reg, n: int):
    """Long-lived synthetic realtime tracking streams: they must
    outlast the whole ramp, so liveness (frame progress at every
    fleet size) is the assertion, not completion."""
    return [
        reg.start_instance(
            *PIPELINE,
            {
                "source": {
                    "uri": f"synthetic://96x96@30?count=1000000&seed={i}",
                    "type": "uri",
                    "realtime": True,
                },
                "destination": {"metadata": {"type": "null"}},
                "priority": "realtime",
            },
        )
        for i in range(n)
    ]


def _progress(insts) -> dict:
    return {i.id: (i._runner.frames_out if i._runner else 0)
            for i in insts}


def ramp(args) -> int:
    """Elastic 2→peak→2 ramp under traffic (ISSUE 18 acceptance)."""
    import tempfile

    # elastic env: persistent AOT cache (fresh dir) + checkpointed
    # migration, resolved before any hub exists
    os.environ["EVAM_AOT"] = "1"
    os.environ["EVAM_AOT_DIR"] = tempfile.mkdtemp(prefix="evam-ramp-aot-")
    os.environ["EVAM_CKPT"] = "1"

    from evam_tpu import aot
    from evam_tpu import state as stream_state
    from evam_tpu.config.settings import reset_settings
    from evam_tpu.control.state import OperatingPoint
    from evam_tpu.state import decode

    reset_settings()
    aot.reset_cache()
    stream_state.reset_cache()

    peak = 4 if args.smoke else args.peak
    base = args.base
    if not base < peak:
        raise SystemExit(f"--base {base} must be < peak {peak}")

    # ---- seed: a full-peak fleet warms once against the empty cache,
    # so an executable exists for every device the ramp grows onto —
    # every scale_up below is then a cache-hit (deserialize) spin-up
    t0 = time.perf_counter()
    reg = _build_ramp_registry(peak)
    reg.stop_all()
    seeded = aot.summary() or {}
    log(f"seed: warmed {peak} shards in {time.perf_counter() - t0:.1f}s "
        f"({seeded.get('entries', 0)} cache entries, "
        f"{seeded.get('misses', {}).get('absent', 0)} cold compiles)")

    # ---- ramp: the elastic fleet starts at base with ckpt on
    reg = _build_ramp_registry(peak, initial=base, max_shards=peak)
    hub = reg.hub
    store = stream_state.active()
    fleets = [e for e in list(hub._engines.values())
              if hasattr(e, "scale_up")]
    spinups: list[float] = []
    stuck = None
    try:
        insts = _ramp_streams(reg, args.streams)
        time.sleep(1.5)  # gate/tracker state accumulates pre-move
        pre = _progress(insts)

        targets = (list(range(base + 1, peak + 1))
                   + list(range(peak - 1, base - 1, -1)))
        prev = base
        for n in targets:
            # one eighth-law push per step: FleetEngine.retune moves
            # ONE shard toward op.fleet_shards (grow on a background
            # thread, shrink inline) — poll until it lands
            hub.retune(OperatingPoint(fleet_shards=n))
            deadline = time.monotonic() + 120.0
            while hub.fleet_summary()["shards"] != n:
                if time.monotonic() >= deadline:
                    stuck = n
                    break
                time.sleep(0.1)
            if stuck is not None:
                log(f"ramp STUCK: fleet never reached {n} shards")
                break
            if n > prev:
                spinups.append(max(f._last_spinup_s for f in fleets))
                log(f"fleet at {n} shard(s) — spin-up "
                    f"{spinups[-1]:.2f}s (warm-before-join)")
            else:
                log(f"fleet at {n} shard(s) — drained one")
            prev = n
            time.sleep(args.dwell_s)

        post = _progress(insts)
        states = [i.state.value for i in insts]
        summary = hub.fleet_summary()
        aot_sum = aot.summary() or {}
        mig = store.summary()["migrations"] if store else {}
        blobs = ([store.export(i.id) for i in insts]
                 if store else [])
    finally:
        reg.stop_all()

    # migrated-identity continuity: every held blob decodes (CRC +
    # schema). The pre-move barrier itself is proven by the
    # migrations counters — only pre_rebalance/retire captures carry
    # a reason — not by blob barriers: a stream's held blob is its
    # LATEST capture, and the steady-state post_resolve refresh can
    # overwrite the pre-move one before export.
    decoded, barriers = 0, set()
    for blob in blobs:
        if blob is None:
            continue
        ck = decode(blob)  # raises on CRC/version damage
        decoded += 1
        barriers.add(ck.barrier)

    stalled = [i.id[:8] for i in insts if post[i.id] <= pre[i.id]]
    errored = [s for s in states if s == "ERROR"]
    hits = aot_sum.get("hits", 0) - seeded.get("hits", 0)
    max_spinup = max(spinups) if spinups else -1.0

    ok = bool(
        stuck is None
        and spinups
        and summary["shards"] == base
        and summary["scale_ups"] >= peak - base
        and summary["scale_downs"] >= peak - base
        and not errored and not stalled
        and mig.get("scale_up", 0) >= 1
        and mig.get("scale_down", 0) >= 1
        and decoded >= 1
        and hits > 0)
    if not args.smoke and spinups:
        # the acceptance wall-clock gate rides only the full shape:
        # CI runners share cores
        ok = ok and max_spinup < args.gate_s

    log(f"ramp {base}->{peak}->{base}: spin-ups "
        f"{[round(s, 2) for s in spinups]}, migrations {mig}, "
        f"stalled {stalled}, errored {len(errored)}, "
        f"aot hits during ramp {hits}, blob barriers "
        f"{sorted(barriers)}")

    print(json.dumps({
        "metric": "fleet_ramp_max_spinup_s",
        "value": round(max_spinup, 3),
        "unit": "s",
        "vs_baseline": args.gate_s,
        "ok": ok,
        "ramp": f"{base}->{peak}->{base}",
        "scale_ups": summary["scale_ups"],
        "scale_downs": summary["scale_downs"],
        "rebalances": summary["rebalances"],
        "failed_realtime_streams": len(errored) + len(stalled),
        "migrations": mig,
        "checkpoints_decoded": decoded,
        "aot_hits": hits,
        "smoke": args.smoke,
    }))
    return 0 if ok else 1


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--shards", type=int, default=4)
    ap.add_argument("--realtime", type=int, default=8)
    ap.add_argument("--standard", type=int, default=4)
    ap.add_argument("--pre-s", type=float, default=3.0,
                    help="healthy traffic before the chip loss")
    ap.add_argument("--post-s", type=float, default=4.0,
                    help="observation window after the loss")
    ap.add_argument("--wedge-s", type=float, default=60.0)
    ap.add_argument("--ramp", action="store_true",
                    help="elastic 2→peak→2 scaling soak (ISSUE 18) "
                         "instead of the chip-loss drill")
    ap.add_argument("--smoke", action="store_true",
                    help="ramp CI shape: peak 4, no wall-clock gate "
                         "(runners share cores)")
    ap.add_argument("--peak", type=int, default=8,
                    help="ramp ceiling (full mode; smoke uses 4)")
    ap.add_argument("--base", type=int, default=2,
                    help="ramp floor / initial fleet size")
    ap.add_argument("--streams", type=int, default=6,
                    help="realtime tracking streams during the ramp")
    ap.add_argument("--dwell-s", type=float, default=1.0,
                    help="traffic window at each fleet size")
    ap.add_argument("--gate-s", type=float, default=5.0,
                    help="cache-hit spin-up-to-first-batch bound "
                         "(full mode; the ISSUE-18 acceptance number)")
    args = ap.parse_args()
    if args.ramp:
        return ramp(args)

    import numpy as np

    from evam_tpu.obs import faults
    from evam_tpu.ops.color import wire_shape

    hub = _build_hub(args.shards)
    eng = hub.engine("detect", MODEL)
    frame = np.zeros(tuple(wire_shape("i420", 64, 64)), np.uint8)

    streams = ([(f"rt{i}", "realtime") for i in range(args.realtime)]
               + [(f"std{i}", "standard") for i in range(args.standard)])

    # warm every shard's hot bucket before arming the fault: the wedge
    # must hit a mid-traffic batch, not a first-compile one
    for sid, prio in streams:
        eng.submit(priority=prio, stream=sid, frames=frame).result(
            timeout=120)
    log(f"warmed {len(streams)} streams over {args.shards} shards")

    stop = threading.Event()
    post_loss = threading.Event()
    done_pre = {sid: 0 for sid, _ in streams}
    done_post = {sid: 0 for sid, _ in streams}
    errors = {sid: 0 for sid, _ in streams}

    def pump(sid, prio):
        while not stop.is_set():
            try:
                fut = eng.submit(priority=prio, stream=sid,
                                 frames=frame)
                fut.result(timeout=10)
            except Exception:
                # shed / restarting / degraded-shard window: the
                # stream retries — liveness is the assertion, not
                # per-frame success during the loss transient
                errors[sid] += 1
                time.sleep(0.05)
                continue
            (done_post if post_loss.is_set() else done_pre)[sid] += 1
            time.sleep(0.02)

    threads = [threading.Thread(target=pump, args=s, daemon=True)
               for s in streams]
    for t in threads:
        t.start()
    time.sleep(args.pre_s)

    # chip loss: wedge exactly one batch for longer than the stall
    # timeout, with a zero restart budget -> terminal degraded shard
    os.environ["EVAM_FAULT_INJECT"] = (
        f"wedge=1,wedge_s={args.wedge_s},wedge_n=1")
    faults.reset_cache()
    log("fault armed: wedge=1 (one batch, terminal)")

    deadline = time.monotonic() + 45.0
    degraded = 0
    while time.monotonic() < deadline:
        degraded = hub.fleet_summary()["degraded_shards"]
        if degraded >= 1:
            break
        time.sleep(0.2)
    log(f"degraded shards: {degraded}")
    post_loss.set()
    time.sleep(args.post_s)
    stop.set()
    for t in threads:
        t.join(timeout=5)

    summary = hub.fleet_summary()
    sheds = hub.shed_totals()
    failed_rt = [sid for sid, prio in streams
                 if prio == "realtime" and done_post[sid] == 0]
    ok = bool(degraded >= 1 and not failed_rt)

    log(f"pre-loss completions: {sum(done_pre.values())}, post-loss: "
        f"{sum(done_post.values())}, transient errors: "
        f"{sum(errors.values())}")
    log(f"fleet: {summary}, sheds: {sheds}, failed realtime streams: "
        f"{failed_rt}")

    print(json.dumps({
        "metric": "fleet_soak_failed_realtime_streams",
        "value": len(failed_rt),
        "unit": "streams",
        "vs_baseline": 0.0,
        "ok": ok,
        "degraded_shards": summary["degraded_shards"],
        "rebalances": summary["rebalances"],
        "sheds": sheds,
        "post_loss_completions": sum(done_post.values()),
        "transient_errors": sum(errors.values()),
    }))
    hub.stop()
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
