#!/usr/bin/env python
"""Chip-loss soak: lose one shard mid-traffic, fail zero streams.

The fleet acceptance scenario (ISSUE 11): a multi-chip fleet is
serving realtime + standard streams when one chip wedges hard
(``EVAM_FAULT_INJECT wedge``, the PR-4 fault hook, armed mid-run with
a zero restart budget so the supervisor takes the shard to terminal
``degraded`` — a lost chip, not a recoverable stall). The contract
under that loss:

* the shard's streams MIGRATE (consistent-hash drain-and-rebalance,
  counted on ``evam_fleet_rebalance_total`` via
  ``fleet_summary()["rebalances"]``);
* in-flight work on the dead shard resolves or sheds PER CLASS
  POLICY (``evam_sched_shed_total`` / ``hub.shed_totals()``) — it
  does not hang;
* every realtime stream keeps completing frames after the loss:
  chip loss degrades fleet capacity, never a stream's liveness.

Exit 0 iff a shard actually degraded AND zero realtime streams
stopped completing. Prints ONE JSON line on stdout; diagnostics on
stderr.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from pathlib import Path

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("EVAM_ALLOW_RANDOM_WEIGHTS", "1")
os.environ.setdefault("EVAM_LOG_LEVEL", "warning")
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

MODEL = "object_detection/person_vehicle_bike"


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _build_hub(shards: int):
    import jax

    from evam_tpu.engine.hub import EngineHub
    from evam_tpu.models import ModelRegistry, ZOO_SPECS
    from evam_tpu.parallel.mesh import build_mesh

    overrides = {k: (64, 64) for k in ZOO_SPECS}
    overrides["audio_detection/environment"] = (1, 1600)
    registry = ModelRegistry(
        dtype="float32", input_overrides=overrides,
        width_overrides={k: 8 for k in ZOO_SPECS})
    plan = build_mesh(devices=list(jax.devices())[:shards])
    return EngineHub(
        registry, plan=plan, max_batch=16, deadline_ms=2.0,
        supervise=True, max_restarts=0, stall_timeout_s=1.0,
        first_batch_grace=15.0, fleet="sharded")


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--shards", type=int, default=4)
    ap.add_argument("--realtime", type=int, default=8)
    ap.add_argument("--standard", type=int, default=4)
    ap.add_argument("--pre-s", type=float, default=3.0,
                    help="healthy traffic before the chip loss")
    ap.add_argument("--post-s", type=float, default=4.0,
                    help="observation window after the loss")
    ap.add_argument("--wedge-s", type=float, default=60.0)
    args = ap.parse_args()

    import numpy as np

    from evam_tpu.obs import faults
    from evam_tpu.ops.color import wire_shape

    hub = _build_hub(args.shards)
    eng = hub.engine("detect", MODEL)
    frame = np.zeros(tuple(wire_shape("i420", 64, 64)), np.uint8)

    streams = ([(f"rt{i}", "realtime") for i in range(args.realtime)]
               + [(f"std{i}", "standard") for i in range(args.standard)])

    # warm every shard's hot bucket before arming the fault: the wedge
    # must hit a mid-traffic batch, not a first-compile one
    for sid, prio in streams:
        eng.submit(priority=prio, stream=sid, frames=frame).result(
            timeout=120)
    log(f"warmed {len(streams)} streams over {args.shards} shards")

    stop = threading.Event()
    post_loss = threading.Event()
    done_pre = {sid: 0 for sid, _ in streams}
    done_post = {sid: 0 for sid, _ in streams}
    errors = {sid: 0 for sid, _ in streams}

    def pump(sid, prio):
        while not stop.is_set():
            try:
                fut = eng.submit(priority=prio, stream=sid,
                                 frames=frame)
                fut.result(timeout=10)
            except Exception:
                # shed / restarting / degraded-shard window: the
                # stream retries — liveness is the assertion, not
                # per-frame success during the loss transient
                errors[sid] += 1
                time.sleep(0.05)
                continue
            (done_post if post_loss.is_set() else done_pre)[sid] += 1
            time.sleep(0.02)

    threads = [threading.Thread(target=pump, args=s, daemon=True)
               for s in streams]
    for t in threads:
        t.start()
    time.sleep(args.pre_s)

    # chip loss: wedge exactly one batch for longer than the stall
    # timeout, with a zero restart budget -> terminal degraded shard
    os.environ["EVAM_FAULT_INJECT"] = (
        f"wedge=1,wedge_s={args.wedge_s},wedge_n=1")
    faults.reset_cache()
    log("fault armed: wedge=1 (one batch, terminal)")

    deadline = time.monotonic() + 45.0
    degraded = 0
    while time.monotonic() < deadline:
        degraded = hub.fleet_summary()["degraded_shards"]
        if degraded >= 1:
            break
        time.sleep(0.2)
    log(f"degraded shards: {degraded}")
    post_loss.set()
    time.sleep(args.post_s)
    stop.set()
    for t in threads:
        t.join(timeout=5)

    summary = hub.fleet_summary()
    sheds = hub.shed_totals()
    failed_rt = [sid for sid, prio in streams
                 if prio == "realtime" and done_post[sid] == 0]
    ok = bool(degraded >= 1 and not failed_rt)

    log(f"pre-loss completions: {sum(done_pre.values())}, post-loss: "
        f"{sum(done_post.values())}, transient errors: "
        f"{sum(errors.values())}")
    log(f"fleet: {summary}, sheds: {sheds}, failed realtime streams: "
        f"{failed_rt}")

    print(json.dumps({
        "metric": "fleet_soak_failed_realtime_streams",
        "value": len(failed_rt),
        "unit": "streams",
        "vs_baseline": 0.0,
        "ok": ok,
        "degraded_shards": summary["degraded_shards"],
        "rebalances": summary["rebalances"],
        "sheds": sheds,
        "post_loss_completions": sum(done_post.values()),
        "transient_errors": sum(errors.values()),
    }))
    hub.stop()
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
