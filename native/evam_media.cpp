// Native media kernels for the host-side frame path.
//
// The reference's decode/convert path is C++ (GStreamer videoconvert /
// decodebin elements); here the host hot loop at N streams is
// per-frame resize + BGR->I420 wire encoding feeding the TPU batch
// engine (evam_tpu/stages/infer.py). These kernels fuse both into one
// pass over the source image (bilinear sample -> YUV in registers ->
// planar store), parallelized with OpenMP and called through ctypes
// (GIL released), so decode worker threads scale across cores instead
// of serializing on Python/cv2.
//
// Build: make -C native   (g++ -O3 -fopenmp -shared; no deps)

#include <cstdint>
#include <cstring>
#include <algorithm>

#if defined(_OPENMP)
#include <omp.h>
#endif

extern "C" {

// BT.601 full-range BGR -> YUV (the cv2 COLOR_BGR2YUV_I420 matrix,
// so native and fallback paths produce matching wire bytes).
static inline void bgr_to_yuv(int b, int g, int r,
                              int &y, int &u, int &v) {
    y = ( 66 * r + 129 * g +  25 * b + 128) >> 8;  y += 16;
    u = (-38 * r -  74 * g + 112 * b + 128) >> 8;  u += 128;
    v = (112 * r -  94 * g -  18 * b + 128) >> 8;  v += 128;
    y = std::min(255, std::max(0, y));
    u = std::min(255, std::max(0, u));
    v = std::min(255, std::max(0, v));
}

// Bilinear-resize src (sh x sw x 3, BGR, uint8) to (dh x dw) and
// write I420 planes into dst (dh*3/2 rows of dw bytes).
// dh must be %4==0 and dw %2==0 (wire contract, ops/color.py).
// Fixed-point (8-bit fractional weights) with precomputed horizontal
// coordinate/weight tables: the inner loop is integer MACs the
// compiler can vectorize; rows parallelize over OpenMP on many-core
// hosts.
void resize_bgr_to_i420(const uint8_t *src, int sh, int sw,
                        uint8_t *dst, int dh, int dw) {
    uint8_t *yp = dst;
    uint8_t *up = dst + (size_t)dh * dw;
    uint8_t *vp = up + (size_t)(dh / 2) * (dw / 2);
    const int32_t sx_fp = (int32_t)(((int64_t)sw << 16) / dw);
    const int32_t sy_fp = (int32_t)(((int64_t)sh << 16) / dh);

    // Horizontal tables: source offsets (in bytes) and 0..256 weights.
    int32_t *x0o = new int32_t[dw * 2];
    int32_t *x1o = x0o + dw;
    int16_t *wx1 = new int16_t[dw];
    for (int ox = 0; ox < dw; ++ox) {
        int64_t fx = ((int64_t)ox * sx_fp + (sx_fp >> 1)) - (1 << 15);
        if (fx < 0) fx = 0;
        int x0 = (int)(fx >> 16);
        int x1 = std::min(x0 + 1, sw - 1);
        x0o[ox] = x0 * 3;
        x1o[ox] = x1 * 3;
        wx1[ox] = (int16_t)((fx >> 8) & 0xFF);
    }

#pragma omp parallel for schedule(static)
    for (int oy2 = 0; oy2 < dh / 2; ++oy2) {
        for (int k = 0; k < 2; ++k) {
            int oy = oy2 * 2 + k;
            int64_t fy = ((int64_t)oy * sy_fp + (sy_fp >> 1)) - (1 << 15);
            if (fy < 0) fy = 0;
            int y0 = (int)(fy >> 16);
            int y1 = std::min(y0 + 1, sh - 1);
            int wy = (int)((fy >> 8) & 0xFF);
            const uint8_t *row0 = src + (size_t)y0 * sw * 3;
            const uint8_t *row1 = src + (size_t)y1 * sw * 3;
            uint8_t *yrow = yp + (size_t)oy * dw;
            uint8_t *urow = up + (size_t)oy2 * (dw / 2);
            uint8_t *vrow = vp + (size_t)oy2 * (dw / 2);
            for (int ox = 0; ox < dw; ++ox) {
                const uint8_t *p00 = row0 + x0o[ox];
                const uint8_t *p01 = row0 + x1o[ox];
                const uint8_t *p10 = row1 + x0o[ox];
                const uint8_t *p11 = row1 + x1o[ox];
                int wx = wx1[ox];
                int b0 = p00[0] + (((p01[0] - p00[0]) * wx) >> 8);
                int g0 = p00[1] + (((p01[1] - p00[1]) * wx) >> 8);
                int r0 = p00[2] + (((p01[2] - p00[2]) * wx) >> 8);
                int b1 = p10[0] + (((p11[0] - p10[0]) * wx) >> 8);
                int g1 = p10[1] + (((p11[1] - p10[1]) * wx) >> 8);
                int r1 = p10[2] + (((p11[2] - p10[2]) * wx) >> 8);
                int b = b0 + (((b1 - b0) * wy) >> 8);
                int g = g0 + (((g1 - g0) * wy) >> 8);
                int r = r0 + (((r1 - r0) * wy) >> 8);
                int yv = ((66 * r + 129 * g + 25 * b + 128) >> 8) + 16;
                yrow[ox] = (uint8_t)std::min(255, std::max(0, yv));
                if ((k | (ox & 1)) == 0) {
                    int uv = ((-38 * r - 74 * g + 112 * b + 128) >> 8) + 128;
                    int vv = ((112 * r - 94 * g - 18 * b + 128) >> 8) + 128;
                    urow[ox >> 1] = (uint8_t)std::min(255, std::max(0, uv));
                    vrow[ox >> 1] = (uint8_t)std::min(255, std::max(0, vv));
                }
            }
        }
    }
    delete[] x0o;
    delete[] wx1;
}

// Plain BGR -> I420 (no resize), same plane layout.
void bgr_to_i420(const uint8_t *src, uint8_t *dst, int h, int w) {
    uint8_t *yp = dst;
    uint8_t *up = dst + (size_t)h * w;
    uint8_t *vp = up + (size_t)(h / 2) * (w / 2);
#pragma omp parallel for schedule(static)
    for (int y = 0; y < h; ++y) {
        const uint8_t *row = src + (size_t)y * w * 3;
        for (int x = 0; x < w; ++x) {
            int yv, uv, vv;
            bgr_to_yuv(row[x * 3], row[x * 3 + 1], row[x * 3 + 2],
                       yv, uv, vv);
            yp[(size_t)y * w + x] = (uint8_t)yv;
            if ((y & 1) == 0 && (x & 1) == 0) {
                up[(size_t)(y / 2) * (w / 2) + x / 2] = (uint8_t)uv;
                vp[(size_t)(y / 2) * (w / 2) + x / 2] = (uint8_t)vv;
            }
        }
    }
}

// Bilinear BGR resize (uint8, 3ch).
void resize_bgr(const uint8_t *src, int sh, int sw,
                uint8_t *dst, int dh, int dw) {
    const float sx = (float)sw / dw;
    const float sy = (float)sh / dh;
#pragma omp parallel for schedule(static)
    for (int oy = 0; oy < dh; ++oy) {
        float fy = (oy + 0.5f) * sy - 0.5f;
        int y0 = (int)fy; if (fy < 0) y0 = 0;
        int y1 = std::min(y0 + 1, sh - 1);
        float wy = fy - y0; if (wy < 0) wy = 0;
        const uint8_t *row0 = src + (size_t)y0 * sw * 3;
        const uint8_t *row1 = src + (size_t)y1 * sw * 3;
        uint8_t *out = dst + (size_t)oy * dw * 3;
        for (int ox = 0; ox < dw; ++ox) {
            float fx = (ox + 0.5f) * sx - 0.5f;
            int x0 = (int)fx; if (fx < 0) x0 = 0;
            int x1 = std::min(x0 + 1, sw - 1);
            float wx = fx - x0; if (wx < 0) wx = 0;
            float w00 = (1 - wy) * (1 - wx), w01 = (1 - wy) * wx;
            float w10 = wy * (1 - wx),       w11 = wy * wx;
            for (int ch = 0; ch < 3; ++ch) {
                out[ox * 3 + ch] = (uint8_t)(
                      w00 * row0[x0 * 3 + ch] + w01 * row0[x1 * 3 + ch]
                    + w10 * row1[x0 * 3 + ch] + w11 * row1[x1 * 3 + ch]
                    + 0.5f);
            }
        }
    }
}

// Downsampled luma grid for the motion gate (evam_tpu/stages/gate.py):
// one gh x gw uint8 grid summarizing the frame's BT.601 luma, sampled
// on a fixed (gh*S) x (gw*S) point lattice instead of a full pass —
// per-frame cost is O(gh*gw*S^2) regardless of resolution, cheap
// enough for the decode/stream thread at 64-stream fan-in. Integer
// math only, and the numpy fallback (evam_tpu/native.py) replays the
// exact same lattice + arithmetic, so gate decisions are identical
// with or without the shared library.
void luma_grid(const uint8_t *src, int h, int w,
               uint8_t *dst, int gh, int gw) {
    const int S = 4;                       // sample points per cell edge
    const int N = gh * S, M = gw * S;
#pragma omp parallel for schedule(static)
    for (int gy = 0; gy < gh; ++gy) {
        for (int gx = 0; gx < gw; ++gx) {
            int acc = 0;
            for (int j = 0; j < S; ++j) {
                int i = gy * S + j;
                int y = (int)(((2LL * i + 1) * h) / (2 * N));
                const uint8_t *row = src + (size_t)y * w * 3;
                for (int k = 0; k < S; ++k) {
                    int jj = gx * S + k;
                    int x = (int)(((2LL * jj + 1) * w) / (2 * M));
                    const uint8_t *p = row + (size_t)x * 3;
                    // BT.601 luma, same matrix as bgr_to_yuv above
                    int yv = ((66 * p[2] + 129 * p[1] + 25 * p[0] + 128)
                              >> 8) + 16;
                    acc += yv < 0 ? 0 : (yv > 255 ? 255 : yv);
                }
            }
            dst[(size_t)gy * gw + gx] = (uint8_t)(acc / (S * S));
        }
    }
}

int evam_native_version() { return 2; }

}  // extern "C"
