#!/bin/bash
# Local/dev runner probing TPU hardware — counterpart of the
# reference's docker/run.sh hardware probe (GPU/NCS2/HDDL device
# cgroups, reference docker/run.sh:83-119) for TPU VMs.

set -euo pipefail

MODE="${RUN_MODE:-EVA}"
PLATFORM=""

probe_tpu() {
    # TPU VM device nodes: /dev/accel* (v4+/v5) or vfio-bound PCI.
    if compgen -G "/dev/accel*" > /dev/null; then
        echo "found TPU device nodes: $(ls /dev/accel* | tr '\n' ' ')"
        return 0
    fi
    if [ -d /dev/vfio ] && compgen -G "/dev/vfio/*" > /dev/null; then
        echo "found vfio TPU devices"
        return 0
    fi
    return 1
}

if ! probe_tpu; then
    echo "no TPU devices found — running on the CPU fake backend" >&2
    PLATFORM="cpu"
    export EVAM_PLATFORM=cpu
    export XLA_FLAGS="${XLA_FLAGS:---xla_force_host_platform_device_count=8}"
fi

# Build native kernels if the toolchain is present.
if command -v g++ > /dev/null; then
    make -C "$(dirname "$0")/../native" >/dev/null 2>&1 || true
fi

echo "starting evam-tpu (mode=$MODE platform=${PLATFORM:-tpu})"
exec python -m evam_tpu.cli.main serve --mode "$MODE" "$@"
