"""EiiManager: the EvasManager counterpart (reference
evas/manager.py:47-162).

Boot sequence mirrors the reference call stack (SURVEY.md §3.1):
read app config → optional msgbus-ingest subscriber → msgbus
publisher → start ONE configured pipeline → run_forever. Differences
are the TPU inversions: the pipeline runs on the shared
PipelineRegistry/EngineHub instead of a per-stream OpenVINO engine,
and the working config watcher replaces the reference's stubbed
`_config_update_callback` (evas/manager.py:157-162) with a real
restart-on-change.

Published message shape matches reference evas/publisher.py:183-230:
``(meta, frame-bytes)`` tuple when ``publish_frame`` else meta only,
meta carrying img_handle / width / height / channels / encoding info
and the per-region ``gva_meta`` list (rect in pixels, object_id,
tensors with name/confidence/label_id/label).
"""

from __future__ import annotations

import os
import secrets
import threading
from typing import Any

import numpy as np

from evam_tpu.config import Settings
from evam_tpu.eii.configmgr import ConfigMgr
from evam_tpu.eii.msgbus import MsgBusPublisher, MsgBusSubscriber
from evam_tpu.media.source import AppSource
from evam_tpu.obs import get_logger, metrics
from evam_tpu.publish.encode import encode_frame
from evam_tpu.server.registry import PipelineRegistry
from evam_tpu.stages.context import FrameContext

log = get_logger("eii.manager")


def _gva_meta(ctx: FrameContext) -> list[dict[str, Any]]:
    """Regions → the reference's gva_meta rects
    (evas/publisher.py:193-230)."""
    out = []
    for r in ctx.regions:
        x, y, w, h = r.rect(ctx.width, ctx.height)
        entry: dict[str, Any] = {
            "x": x, "y": y, "width": w, "height": h,
            "object_id": r.object_id,
            "tensor": [
                {
                    "name": t.name,
                    "confidence": t.confidence,
                    "label_id": t.label_id,
                    "label": t.label,
                }
                for t in r.tensors
            ],
        }
        out.append(entry)
    return out


class EiiManager:
    def __init__(
        self,
        settings: Settings,
        cfg_mgr: ConfigMgr | None = None,
        registry: PipelineRegistry | None = None,
    ):
        self.settings = settings
        self.cfg = cfg_mgr or ConfigMgr(os.environ.get("EVAM_EII_CONFIG"))
        self.registry = registry or PipelineRegistry(settings)
        self._stop = threading.Event()
        self._ingest_stop = threading.Event()
        self._sub_thread: threading.Thread | None = None
        self.subscriber: MsgBusSubscriber | None = None
        self.app_source: AppSource | None = None
        self.instance = None
        self.publish_frame = False
        self.enc_type = None
        self.enc_level = None

        self.publisher: MsgBusPublisher | None = None
        self._pub_cfg_snapshot: str = ""
        #: last hot-reload failure message (None = healthy); the last
        #: config that produced a running pipeline backs the fallback.
        self.reload_error: str | None = None
        self._last_good_cfg: dict[str, Any] | None = None
        self._build_publisher()

        boot_cfg = self.cfg.get_app_config()
        self._start_pipeline(boot_cfg)
        self._last_good_cfg = boot_cfg
        # Working hot-reload: restart the pipeline when the config
        # store changes.
        self.cfg.watch(self._on_config_update)

    def _build_publisher(self) -> None:
        """(Re)create the results publisher from the current interface
        config — hot reload must honor edited Publishers entries too."""
        import json as _json

        if self.cfg.get_num_publishers() < 1:
            raise ValueError(
                "EII config needs at least one interfaces.Publishers entry")
        pub_cfg = self.cfg.get_publisher_by_index(0)
        snapshot = _json.dumps(pub_cfg, sort_keys=True)
        if self.publisher is not None and snapshot == self._pub_cfg_snapshot:
            return
        topics = pub_cfg.get("Topics") or ["evam_tpu"]
        # build-then-swap: a failing new publisher must leave the old
        # one usable for the hot-reload fallback path
        new_pub = MsgBusPublisher(pub_cfg, topics[0])
        if self.publisher is not None:
            self.publisher.close()
        self.publisher = new_pub
        self._pub_cfg_snapshot = snapshot

    # ------------------------------------------------------- pipeline

    def _start_pipeline(self, app_cfg: dict[str, Any]) -> None:
        # Publish-side settings refresh with the pipeline (hot reload
        # must honor edited publish_frame/encoding too).
        self.publish_frame = bool(app_cfg.get("publish_frame", False))
        enc = app_cfg.get("encoding") or {}
        self.enc_type = enc.get("type")
        self.enc_level = enc.get("level")
        pipeline = app_cfg.get(
            "pipeline", "object_detection/person_vehicle_bike"
        )
        name, _, version = pipeline.partition("/")
        request: dict[str, Any] = {
            "source": dict(app_cfg.get("source_parameters") or {}),
            "parameters": dict(app_cfg.get("model_parameters") or {}),
        }
        source_obj = None
        if app_cfg.get("source") == "msgbus":
            # Frames arrive over the bus instead of a decoder
            # (reference evas/manager.py:77-88 + subscriber.py).
            if self.cfg.get_num_subscribers() < 1:
                raise ValueError(
                    "source=msgbus needs an interfaces.Subscribers entry")
            sub_cfg = self.cfg.get_subscriber_by_index(0)
            sub_topic = (sub_cfg.get("Topics") or ["camera1_stream"])[0]
            self._ingest_stop = threading.Event()
            self.subscriber = MsgBusSubscriber(sub_cfg, sub_topic)
            self.app_source = AppSource(maxsize=64)
            source_obj = self.app_source
            request["source"] = {"type": "application"}
            self._sub_thread = threading.Thread(
                target=self._ingest_loop,
                args=(self._ingest_stop, self.subscriber, self.app_source),
                name="msgbus-ingest", daemon=True,
            )
            self._sub_thread.start()
        # Pipelines without a metapublish stage (appsink-terminated,
        # like the reference's EII variants ending in appsink —
        # eii/pipelines/.../pipeline.json:6) publish from the sink.
        spec = self.registry.loader.get(name, version)
        from evam_tpu.graph.spec import StageKind

        has_publish = spec is not None and any(
            s.kind == StageKind.PUBLISH for s in spec.stages
        )
        try:
            self.instance = self.registry.start_instance(
                name, version, request,
                publish_fn=self._publish, source=source_obj,
                sink_fn=None if has_publish else self._publish,
            )
        except Exception:
            # A failed (re)start must not orphan the just-started
            # ingest thread / ZMQ subscription.
            self._teardown_ingest()
            raise
        log.info("EII pipeline %s started (instance %s)",
                 pipeline, self.instance.id[:8])

    def _teardown_ingest(self) -> None:
        """Stop the current subscriber/ingest thread so a restart never
        stacks leaked threads or stale ZMQ subscriptions."""
        self._ingest_stop.set()
        if self._sub_thread is not None:
            self._sub_thread.join(timeout=5)
            self._sub_thread = None
        if self.subscriber is not None:
            self.subscriber.close()
            self.subscriber = None
        self.app_source = None

    def _on_config_update(self, data: dict[str, Any]) -> None:
        log.info("config changed: restarting pipeline")
        if self.instance is not None:
            self.registry.stop_instance(self.instance.id)
            self.instance.wait(timeout=10)
            self.instance = None
        self._teardown_ingest()
        try:
            # publisher rebuild and config fetch can fail on a bad
            # Publishers entry too — everything after the old pipeline
            # stopped must fall back, or the service is left silently
            # pipeline-less while reporting healthy
            self._build_publisher()
            new_cfg = self.cfg.get_app_config()
            self._start_pipeline(new_cfg)
        except Exception as exc:  # noqa: BLE001 — keep serving on bad reload
            # A bad new config must not leave the service silently
            # pipeline-less (the watch loop swallows exceptions): fall
            # back to the last known-good config and flag the failure
            # so /healthz-style monitoring can see it.
            log.error("hot-reload failed (%s); reverting to last "
                      "known-good config", exc)
            self.reload_error = str(exc)
            if self._last_good_cfg is not None:
                try:
                    self._start_pipeline(self._last_good_cfg)
                except Exception as exc2:  # noqa: BLE001
                    log.error("fallback restart also failed: %s", exc2)
            return
        self.reload_error = None
        self._last_good_cfg = new_cfg

    # -------------------------------------------------------- publish

    def _publish(self, ctx: FrameContext) -> None:
        meta: dict[str, Any] = {
            "img_handle": secrets.token_hex(6),
            "width": ctx.width,
            "height": ctx.height,
            "channels": 3,
            "caps": (
                f"video/x-raw, format=BGR, width={ctx.width}, "
                f"height={ctx.height}"
            ),
            "gva_meta": _gva_meta(ctx),
        }
        if ctx.metadata:
            # Keep the EVA-schema fields too (timestamp, source, UDF
            # events) — consumers of either dialect see their keys.
            for k, v in ctx.metadata.items():
                meta.setdefault(k, v)
        blob = None
        if self.publish_frame and ctx.frame is not None:
            if self.enc_type:
                blob = encode_frame(ctx.frame, self.enc_type, self.enc_level)
                meta["encoding_type"] = self.enc_type
                meta["encoding_level"] = self.enc_level
            else:
                blob = np.ascontiguousarray(ctx.frame).tobytes()
        self.publisher.publish(meta, blob)
        metrics.inc("evam_eii_published")

    # --------------------------------------------------------- ingest

    def _ingest_loop(
        self,
        stop: threading.Event,
        subscriber: MsgBusSubscriber,
        app_source: AppSource,
    ) -> None:
        while not self._stop.is_set() and not stop.is_set():
            msg = subscriber.recv()
            if msg is None:
                continue
            meta, blob = msg
            if blob is None:
                continue
            try:
                h = int(meta.get("height", 0))
                w = int(meta.get("width", 0))
                if meta.get("encoding_type"):
                    import cv2

                    frame = cv2.imdecode(
                        np.frombuffer(blob, np.uint8), cv2.IMREAD_COLOR
                    )
                else:
                    frame = np.frombuffer(blob, np.uint8).reshape(h, w, 3)
                app_source.push(frame)
            except Exception as exc:  # noqa: BLE001 — bad frame, keep going
                log.warning("msgbus ingest: dropped bad frame (%s)", exc)
                metrics.inc("evam_eii_ingest_drops")

    # ------------------------------------------------------ lifecycle

    def run_forever(self) -> None:
        """Block until stopped (reference manager.run_forever →
        PipelineServer.wait, evas/manager.py:151-155)."""
        try:
            while not self._stop.wait(0.5):
                pass
        except KeyboardInterrupt:
            pass
        finally:
            self.stop()

    def stop(self) -> None:
        self._stop.set()
        if self.app_source is not None:
            self.app_source.end()
        self._teardown_ingest()
        self.cfg.close()
        self.registry.stop_all()
        self.publisher.close()


def run_eii_service(settings: Settings) -> int:
    """Blocking entrypoint for ``evam-tpu serve --mode EII``."""
    import signal

    from evam_tpu.obs.trace import init_observability

    init_observability(settings)
    manager = EiiManager(settings)

    def _on_term(signum, frame):  # noqa: ARG001 — signal API
        # k8s/compose stop sends SIGTERM: drain the pipeline and close
        # the msgbus sockets instead of dying mid-publish (the
        # reference relies on restart: unless-stopped alone,
        # eii/docker-compose.yml:31)
        log.info("SIGTERM: draining EII service")
        manager._stop.set()

    signal.signal(signal.SIGTERM, _on_term)
    log.info("EII service running")
    manager.run_forever()
    return 0
