"""EII-mode service layer — counterpart of the reference's ``evas``
package (`python3 -m evas`, reference run.sh:26-27): headless service
configured from a config store, one pipeline auto-started at boot,
frames+metadata published over the brokerless message bus
(reference evas/manager.py, evas/publisher.py, evas/subscriber.py)."""

from evam_tpu.eii.configmgr import ConfigMgr
from evam_tpu.eii.manager import EiiManager, run_eii_service
from evam_tpu.eii.msgbus import MsgBusPublisher, MsgBusSubscriber

__all__ = [
    "ConfigMgr",
    "EiiManager",
    "MsgBusPublisher",
    "MsgBusSubscriber",
    "run_eii_service",
]
