"""Message bus: brokerless ZeroMQ pub/sub with EII-style interface
configs.

The reference's EII MsgBus (C library + Python binding, installed as
.debs at reference Dockerfile:57-65) carries ``(json-meta, blob)``
pairs between services over ``zmq_tcp`` (cross-host, EndPoint
host:port — eii/config.json:17-19) or ``zmq_ipc`` (same host, socket
dir — eii/config.json:31-32), with ``zmq_recv_hwm`` backpressure
(:37) and per-topic ``AllowedClients`` ACLs (:23-25). This module
speaks the same interface-config dialect over pyzmq:

    {"Type": "zmq_tcp", "EndPoint": "0.0.0.0:65114",
     "Topics": ["camera1_stream_results"], "AllowedClients": ["*"],
     "zmq_recv_hwm": 50}

Wire framing: multipart [topic, meta-json, blob?] — topic first so
ZMQ's prefix subscription filters server-side (the C MsgBus does the
same). The AllowedClients ACL maps to CURVE auth in the reference's
prod mode; dev mode (DEV_MODE=true, no TLS, reference
eii/docker-compose.yml:61-63) is the supported mode here and the ACL
is recorded but not enforced.
"""

from __future__ import annotations

import json
import os
from typing import Any

from evam_tpu.obs import get_logger

log = get_logger("eii.msgbus")

SOCKET_DIR = os.environ.get("EVAM_SOCKET_DIR", "/tmp/evam_sockets")


def _endpoint(cfg: dict[str, Any], topic: str, bind: bool) -> str:
    btype = cfg.get("Type", "zmq_tcp")
    if btype == "zmq_tcp":
        host_port = cfg.get("EndPoint", "127.0.0.1:65114")
        if bind:
            return f"tcp://{host_port}"
        host, _, port = str(host_port).partition(":")
        host = "127.0.0.1" if host in ("0.0.0.0", "") else host
        return f"tcp://{host}:{port}"
    if btype == "zmq_ipc":
        sock_dir = cfg.get("EndPoint", SOCKET_DIR)
        os.makedirs(sock_dir, exist_ok=True)
        return f"ipc://{sock_dir}/{topic}"
    raise ValueError(f"unsupported msgbus type '{btype}'")


class MsgBusPublisher:
    """Publish ``(meta, blob)`` on one topic (reference
    evas/publisher.py:63-64, 246-250 semantics: message is either a
    meta dict or a (meta, frame-bytes) tuple)."""

    def __init__(self, cfg: dict[str, Any], topic: str):
        import zmq

        self.topic = topic
        self._ctx = zmq.Context.instance()
        self._sock = self._ctx.socket(zmq.PUB)
        self._sock.setsockopt(zmq.SNDHWM, int(cfg.get("zmq_send_hwm", 1000)))
        self._sock.setsockopt(zmq.LINGER, 0)
        ep = _endpoint(cfg, topic, bind=True)
        self._sock.bind(ep)
        self.allowed_clients = list(cfg.get("AllowedClients", ["*"]))
        log.info("msgbus publisher topic=%s endpoint=%s", topic, ep)

    def publish(self, meta: dict, blob: bytes | None = None) -> None:
        import zmq

        parts = [
            self.topic.encode(),
            json.dumps(meta, separators=(",", ":")).encode(),
        ]
        if blob is not None:
            parts.append(blob)
        try:
            self._sock.send_multipart(parts, flags=zmq.NOBLOCK)
        except zmq.Again:
            pass  # slow consumer: drop, never stall the pipeline

    def close(self) -> None:
        self._sock.close(0)


class MsgBusSubscriber:
    """Blocking ``recv() -> (meta, blob|None)`` on one topic
    (reference evas/subscriber.py:92-93)."""

    def __init__(self, cfg: dict[str, Any], topic: str,
                 recv_timeout_ms: int = 1000):
        import zmq

        self.topic = topic
        self._ctx = zmq.Context.instance()
        self._sock = self._ctx.socket(zmq.SUB)
        self._sock.setsockopt(zmq.RCVHWM, int(cfg.get("zmq_recv_hwm", 1000)))
        self._sock.setsockopt(zmq.SUBSCRIBE, topic.encode())
        self._sock.setsockopt(zmq.RCVTIMEO, recv_timeout_ms)
        self._sock.setsockopt(zmq.LINGER, 0)
        ep = _endpoint(cfg, topic, bind=False)
        self._sock.connect(ep)
        log.info("msgbus subscriber topic=%s endpoint=%s", topic, ep)

    def recv(self) -> tuple[dict, bytes | None] | None:
        """One message, or None on timeout (lets callers poll a stop
        flag — the reference thread loops on a stop Event the same
        way, evas/subscriber.py:84-88)."""
        import zmq

        try:
            parts = self._sock.recv_multipart()
        except zmq.Again:
            return None
        meta = json.loads(parts[1]) if len(parts) > 1 else {}
        blob = parts[2] if len(parts) > 2 else None
        return meta, blob

    def close(self) -> None:
        self._sock.close(0)
