"""ConfigMgr: the EII ConfigManager counterpart.

The reference reads service config from etcd through the EII
ConfigManager C binding (`cfg.ConfigMgr()` at evas/__main__.py:34;
app config + publisher/subscriber interfaces at evas/manager.py:58,
80-91; TLS certs via CONFIGMGR_* env, eii/docker-compose.yml:61-63).
etcd3 is not in this image, so the store is a local JSON file with the
same two-section shape as the reference's eii/config.json
(``config`` + ``interfaces``) plus an mtime-poll watcher that delivers
hot-reload callbacks — the reference declares this callback but stubs
it (`_config_update_callback`, evas/manager.py:157-162); here it
works.
"""

from __future__ import annotations

import json
import threading
from pathlib import Path
from typing import Any, Callable

from evam_tpu.obs import get_logger

log = get_logger("eii.configmgr")

DEFAULT_CONFIG: dict[str, Any] = {
    "config": {
        "source": "gstreamer",
        "pipeline": "object_detection/person_vehicle_bike",
        "source_parameters": {
            "type": "uri",
            "uri": "synthetic://768x432@30",
        },
        "publish_frame": False,
        "encoding": {"type": "jpeg", "level": 95},
        "model_parameters": {},
    },
    "interfaces": {
        "Publishers": [
            {
                "Name": "default",
                "Type": "zmq_tcp",
                "EndPoint": "0.0.0.0:65114",
                "Topics": ["camera1_stream_results"],
                "AllowedClients": ["*"],
            }
        ],
        "Subscribers": [],
    },
}


class ConfigMgr:
    def __init__(
        self,
        config_file: str | Path | None = None,
        watch_interval_s: float = 2.0,
    ):
        self.config_file = Path(config_file) if config_file else None
        self.watch_interval_s = watch_interval_s
        self._data = self._load()
        self._mtime = self._stat_mtime()
        self._watcher: threading.Thread | None = None
        self._stop = threading.Event()
        self._callbacks: list[Callable[[dict], None]] = []

    def _load(self) -> dict[str, Any]:
        if self.config_file and self.config_file.exists():
            return json.loads(self.config_file.read_text())
        return json.loads(json.dumps(DEFAULT_CONFIG))  # deep copy

    def _stat_mtime(self) -> float:
        try:
            return self.config_file.stat().st_mtime if self.config_file else 0.0
        except OSError:
            return 0.0

    # ---------------------------------------------------- reference API

    def get_app_config(self) -> dict[str, Any]:
        """App-level config (reference get_app_config().get_dict())."""
        return self._data.get("config", {})

    def get_num_publishers(self) -> int:
        return len(self._data.get("interfaces", {}).get("Publishers", []))

    def get_num_subscribers(self) -> int:
        return len(self._data.get("interfaces", {}).get("Subscribers", []))

    def get_publisher_by_index(self, i: int) -> dict[str, Any]:
        return self._data["interfaces"]["Publishers"][i]

    def get_subscriber_by_index(self, i: int) -> dict[str, Any]:
        return self._data["interfaces"]["Subscribers"][i]

    # -------------------------------------------------------- watching

    def watch(self, callback: Callable[[dict], None]) -> None:
        """Hot-reload hook (working version of the reference's stubbed
        `_config_update_callback`)."""
        self._callbacks.append(callback)
        if self._watcher is None and self.config_file is not None:
            self._watcher = threading.Thread(
                target=self._watch_loop, name="configmgr-watch", daemon=True
            )
            self._watcher.start()

    def _watch_loop(self) -> None:
        while not self._stop.wait(self.watch_interval_s):
            mtime = self._stat_mtime()
            if mtime != self._mtime:
                self._mtime = mtime
                try:
                    self._data = self._load()
                except (OSError, json.JSONDecodeError) as exc:
                    log.warning("config reload failed: %s", exc)
                    continue
                log.info("config file changed; notifying %d watcher(s)",
                         len(self._callbacks))
                for cb in self._callbacks:
                    try:
                        cb(self._data)
                    except Exception as exc:  # noqa: BLE001
                        log.warning("config callback error: %s", exc)

    def close(self) -> None:
        self._stop.set()
