"""ConfigMgr: the EII ConfigManager counterpart.

The reference reads service config from etcd through the EII
ConfigManager C binding (`cfg.ConfigMgr()` at evas/__main__.py:34;
app config + publisher/subscriber interfaces at evas/manager.py:58,
80-91; etcd env at eii/docker-compose.yml:44-47, TLS certs via
CONFIGMGR_* env at :61-63). Two backends behind the same API:

* **file** (default): a local JSON file with the same two-section
  shape as the reference's eii/config.json (``config`` +
  ``interfaces``), mtime-poll watcher;
* **etcd** (``EVAM_ETCD_HOST``/``ETCD_HOST`` set): the etcd v3
  gRPC-gateway HTTP/JSON API (`POST /v3/kv/range`) with keys
  ``{ETCD_PREFIX}/config`` and ``{ETCD_PREFIX}/interfaces``,
  mod_revision-poll watcher (documented divergence: the C binding
  holds a streaming watch; polling keeps this stdlib-only), optional
  TLS via ``CONFIGMGR_CACERT``/``CONFIGMGR_CERT``/``CONFIGMGR_KEY``.

Both deliver working hot-reload callbacks — the reference declares
this callback but stubs it (`_config_update_callback`,
evas/manager.py:157-162); here it works, and a dead etcd falls back
to the file store so boot never blocks on the control plane.
"""

from __future__ import annotations

import base64
import json
import os
import ssl
import threading
import urllib.request
from pathlib import Path
from typing import Any, Callable

from evam_tpu.obs import get_logger

log = get_logger("eii.configmgr")

DEFAULT_CONFIG: dict[str, Any] = {
    "config": {
        "source": "gstreamer",
        "pipeline": "object_detection/person_vehicle_bike",
        "source_parameters": {
            "type": "uri",
            "uri": "synthetic://768x432@30",
        },
        "publish_frame": False,
        "encoding": {"type": "jpeg", "level": 95},
        "model_parameters": {},
    },
    "interfaces": {
        "Publishers": [
            {
                "Name": "default",
                "Type": "zmq_tcp",
                "EndPoint": "0.0.0.0:65114",
                "Topics": ["camera1_stream_results"],
                "AllowedClients": ["*"],
            }
        ],
        "Subscribers": [],
    },
}


class EtcdGatewayStore:
    """etcd v3 HTTP/JSON gateway client (stdlib-only).

    Reads ``{prefix}/config`` and ``{prefix}/interfaces`` (JSON
    values — the layout the reference provisions per-app into etcd).
    ``version()`` is the max mod_revision, the etcd analogue of the
    file store's mtime.
    """

    def __init__(
        self,
        host: str,
        port: int = 2379,
        prefix: str = "/evam_tpu",
        cacert: str | None = None,
        cert: str | None = None,
        key: str | None = None,
        timeout_s: float = 5.0,
    ):
        # TLS keys on ANY of the cert vars — client-cert-only (CA in
        # the system trust store) must not silently downgrade to http
        use_tls = bool(cacert or cert or key)
        scheme = "https" if use_tls else "http"
        self.base = f"{scheme}://{host}:{port}"
        self.prefix = prefix.rstrip("/")
        self.timeout_s = timeout_s
        self._ctx: ssl.SSLContext | None = None
        if use_tls:
            self._ctx = ssl.create_default_context(
                cafile=cacert if cacert else None)
            if cert and key:
                self._ctx.load_cert_chain(cert, key)

    def _range(self, key: str) -> tuple[dict | None, int]:
        payload = json.dumps(
            {"key": base64.b64encode(key.encode()).decode()}
        ).encode()
        req = urllib.request.Request(
            f"{self.base}/v3/kv/range", data=payload,
            headers={"Content-Type": "application/json"}, method="POST",
        )
        with urllib.request.urlopen(
            req, timeout=self.timeout_s, context=self._ctx
        ) as resp:
            body = json.loads(resp.read())
        kvs = body.get("kvs") or []
        if not kvs:
            return None, 0
        value = json.loads(base64.b64decode(kvs[0]["value"]))
        return value, int(kvs[0].get("mod_revision", 0))

    def load(self) -> dict[str, Any]:
        data: dict[str, Any] = {}
        cfg, _ = self._range(f"{self.prefix}/config")
        ifaces, _ = self._range(f"{self.prefix}/interfaces")
        if cfg is None and ifaces is None:
            # single-document fallback: the whole config.json at the prefix
            doc, _ = self._range(self.prefix)
            if doc is None:
                raise KeyError(
                    f"no config at etcd keys {self.prefix}[/config]"
                )
            return doc
        if cfg is not None:
            data["config"] = cfg
        if ifaces is not None:
            data["interfaces"] = ifaces
        return data

    def version(self) -> float:
        revs = []
        for key in (f"{self.prefix}/config", f"{self.prefix}/interfaces",
                    self.prefix):
            try:
                _, rev = self._range(key)
                revs.append(rev)
            except Exception:  # noqa: BLE001 — transient gateway error
                return -1.0  # forces no-change (retry next poll)
        return float(max(revs))

    @classmethod
    def from_env(cls, env=os.environ) -> "EtcdGatewayStore | None":
        host = env.get("EVAM_ETCD_HOST") or env.get("ETCD_HOST")
        if not host:
            return None
        return cls(
            host=host,
            port=int(env.get("ETCD_CLIENT_PORT", "2379")),
            prefix=env.get("ETCD_PREFIX", "/evam_tpu"),
            cacert=env.get("CONFIGMGR_CACERT") or None,
            cert=env.get("CONFIGMGR_CERT") or None,
            key=env.get("CONFIGMGR_KEY") or None,
        )


class ConfigMgr:
    def __init__(
        self,
        config_file: str | Path | None = None,
        watch_interval_s: float = 2.0,
        etcd: EtcdGatewayStore | None = None,
    ):
        self.config_file = Path(config_file) if config_file else None
        self.watch_interval_s = watch_interval_s
        self.etcd = etcd if etcd is not None else EtcdGatewayStore.from_env()
        if self.etcd is not None:
            try:
                self._data = self.etcd.load()
                self._mtime = self.etcd.version()
                log.info("config from etcd gateway %s (rev %d)",
                         self.etcd.base, int(self._mtime))
            except Exception as exc:  # noqa: BLE001 — dead control plane
                log.warning(
                    "etcd gateway %s unavailable (%s); falling back to "
                    "file store", self.etcd.base, exc,
                )
                self.etcd = None
        if self.etcd is None:
            self._data = self._load()
            self._mtime = self._stat_mtime()
        self._watcher: threading.Thread | None = None
        self._stop = threading.Event()
        self._callbacks: list[Callable[[dict], None]] = []

    def _load(self) -> dict[str, Any]:
        if self.etcd is not None:
            return self.etcd.load()
        if self.config_file and self.config_file.exists():
            return json.loads(self.config_file.read_text())
        return json.loads(json.dumps(DEFAULT_CONFIG))  # deep copy

    def _stat_mtime(self) -> float:
        if self.etcd is not None:
            return self.etcd.version()
        try:
            return self.config_file.stat().st_mtime if self.config_file else 0.0
        except OSError:
            return 0.0

    # ---------------------------------------------------- reference API

    def get_app_config(self) -> dict[str, Any]:
        """App-level config (reference get_app_config().get_dict())."""
        return self._data.get("config", {})

    def get_num_publishers(self) -> int:
        return len(self._data.get("interfaces", {}).get("Publishers", []))

    def get_num_subscribers(self) -> int:
        return len(self._data.get("interfaces", {}).get("Subscribers", []))

    def get_publisher_by_index(self, i: int) -> dict[str, Any]:
        return self._data["interfaces"]["Publishers"][i]

    def get_subscriber_by_index(self, i: int) -> dict[str, Any]:
        return self._data["interfaces"]["Subscribers"][i]

    # -------------------------------------------------------- watching

    def watch(self, callback: Callable[[dict], None]) -> None:
        """Hot-reload hook (working version of the reference's stubbed
        `_config_update_callback`)."""
        self._callbacks.append(callback)
        watchable = self.config_file is not None or self.etcd is not None
        if self._watcher is None and watchable:
            self._watcher = threading.Thread(
                target=self._watch_loop, name="configmgr-watch", daemon=True
            )
            self._watcher.start()

    def _watch_loop(self) -> None:
        while not self._stop.wait(self.watch_interval_s):
            mtime = self._stat_mtime()
            if mtime < 0:
                continue  # transient etcd gateway error: retry next poll
            if mtime != self._mtime:
                try:
                    self._data = self._load()
                except Exception as exc:  # noqa: BLE001 — bad file/gateway blip
                    # do NOT commit mtime: unlike the file store (whose
                    # mtime changes again on the next edit), an etcd
                    # revision only moves on writes — committing before
                    # a successful load would drop this update forever
                    log.warning("config reload failed: %s", exc)
                    continue
                self._mtime = mtime
                log.info("config file changed; notifying %d watcher(s)",
                         len(self._callbacks))
                for cb in self._callbacks:
                    try:
                        cb(self._data)
                    except Exception as exc:  # noqa: BLE001
                        log.warning("config callback error: %s", exc)

    def close(self) -> None:
        self._stop.set()
