"""Persistent AOT executable cache (EVAM_AOT).

A content-addressed on-disk store of serialized compiled XLA
executables, shared by supervisor rebuilds, fleet shard spin-up and
every warmup path: a cache hit turns a bucket's cold start from a
jit trace + XLA compile into a millisecond deserialize. Off (the
default) the layer is one memoized ``active()`` None-check —
byte-identical, the same A/B discipline as EVAM_TRANSFER / EVAM_GATE
/ EVAM_TRACE / EVAM_CKPT.
"""

from evam_tpu.aot.cache import (  # noqa: F401
    AotCache,
    MISS_REASONS,
    active,
    cache_key,
    disabled_summary,
    reset_cache,
    summary,
)
