"""Content-addressed on-disk cache of serialized compiled executables.

Why: "millions of users" (ROADMAP) means the fleet grows and shrinks
with load, and today every shard cold-start — supervisor rebuild,
fleet scale-up, process restart — pays a full jit trace + XLA compile
per bucket rung. Compiled executables serialize and reload
(``jax.experimental.serialize_executable``, the Julia→TPU AOT
compilation observation from PAPERS.md), so the second cold start can
be a load measured in milliseconds instead of a compile measured in
seconds.

Contract:

- **Keyed on everything that changes the program.** The cache key
  (:func:`cache_key`) hashes the hub's program fingerprint (engine
  key + wire/synth/ragged/sched config), the bucket rung, every step
  input's shape+dtype, the params aval signature, the device set the
  executable is bound to, the donation tuple and the backend. The
  jax / jaxlib / PJRT platform versions deliberately live in the
  entry HEADER, not the key — a version upgrade then reads as a
  distinguishable ``version`` miss instead of a silent absent one.
- **Never a crash, always a counter.** Every rung of the fallback
  ladder — ``absent``, ``version``, ``crc``, ``deserialize``,
  ``execute`` — lands on
  ``evam_aot_cache_misses_total{engine,reason}`` and falls back to
  the plain jit path loudly. A cache can only ever cost disk.
- **CRC-guarded, size-capped LRU.** Entries are MAGIC + header JSON +
  CRC32 + pickled ``(payload, in_tree, out_tree)``; writes are atomic
  (tmp + rename); hits touch mtime and eviction removes
  oldest-by-mtime entries past ``EVAM_AOT_MAX_BYTES``.

No environment reads here (evamlint knobs pass): configuration
arrives through ``config/settings.py`` (EVAM_AOT / EVAM_AOT_DIR /
EVAM_AOT_MAX_BYTES) only.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import struct
import tempfile
import threading
import zlib
from pathlib import Path

from evam_tpu.obs import get_logger, metrics

log = get_logger("aot.cache")

#: entry-format magic; bump when the on-disk layout changes (an old
#: layout then reads as a ``crc``-class miss, never a crash)
MAGIC = b"EVAOT001"

#: the fallback ladder, in the order the load path walks it — fixed
#: vocabulary so the /healthz ``aot`` block keeps a stable shape
MISS_REASONS = ("absent", "version", "crc", "deserialize", "execute")

_EXT = ".aotx"

try:  # gated: never a hard dependency — absent support disables the layer
    from jax.experimental.serialize_executable import (
        deserialize_and_load,
        serialize,
    )

    _HAVE_SERIALIZE = True
except Exception:  # noqa: BLE001 — old jaxlib / stripped install
    deserialize_and_load = None
    serialize = None
    _HAVE_SERIALIZE = False


class _EntryError(ValueError):
    """A structurally-bad cache entry, tagged with its miss reason."""

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(detail or reason)
        self.reason = reason


def cache_key(program: str, bucket: int, inputs, params_sig,
              devices, donate, backend: str) -> str:
    """Content address for one (program, rung, placement) executable.

    Everything that changes the compiled artifact is in here;
    environment versions are in the entry header instead (see module
    docstring). JSON with sorted keys → sha256, so the key is stable
    across processes and hosts."""
    doc = {
        "program": str(program),
        "bucket": int(bucket),
        "inputs": [[str(n), [int(d) for d in shape], str(dt)]
                   for n, shape, dt in inputs],
        "params": [[[int(d) for d in shape], str(dt)]
                   for shape, dt in params_sig],
        "devices": [str(d) for d in devices],
        "donate": [int(i) for i in donate],
        "backend": str(backend),
    }
    blob = json.dumps(doc, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()


def env_fingerprint() -> dict:
    """The versions an executable is only valid under — compared
    against the entry header at load, never hashed into the key."""
    import jax

    fp = {"jax": getattr(jax, "__version__", ""), "jaxlib": "",
          "backend": "", "platform_version": ""}
    try:
        import jaxlib.version

        fp["jaxlib"] = jaxlib.version.__version__
    except Exception:  # noqa: BLE001 — vendored/renamed jaxlib
        pass
    try:
        fp["backend"] = jax.default_backend()
        fp["platform_version"] = str(
            jax.devices()[0].client.platform_version)
    except Exception:  # noqa: BLE001 — backend not initialized yet
        pass
    return fp


def _pack_entry(header: dict, payload: bytes) -> bytes:
    hdr = json.dumps(header, sort_keys=True).encode()
    return b"".join([
        MAGIC,
        struct.pack("<I", len(hdr)),
        hdr,
        struct.pack("<I", zlib.crc32(payload) & 0xFFFFFFFF),
        struct.pack("<Q", len(payload)),
        payload,
    ])


def _unpack_entry(blob: bytes) -> tuple[dict, bytes]:
    """Inverse of :func:`_pack_entry`; raises :class:`_EntryError`
    tagged ``crc`` for any structural damage (truncation, bad magic,
    bad checksum, unparseable header)."""
    if len(blob) < len(MAGIC) + 4 or blob[:len(MAGIC)] != MAGIC:
        raise _EntryError("crc", "bad magic")
    off = len(MAGIC)
    (hdr_len,) = struct.unpack_from("<I", blob, off)
    off += 4
    if len(blob) < off + hdr_len + 12:
        raise _EntryError("crc", "truncated header")
    try:
        header = json.loads(blob[off:off + hdr_len].decode())
    except Exception as exc:  # noqa: BLE001
        raise _EntryError("crc", f"header unparseable: {exc}") from exc
    off += hdr_len
    (crc,) = struct.unpack_from("<I", blob, off)
    off += 4
    (n,) = struct.unpack_from("<Q", blob, off)
    off += 8
    payload = blob[off:off + n]
    if len(payload) != n:
        raise _EntryError("crc", "truncated payload")
    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
        raise _EntryError("crc", "checksum mismatch")
    return header, payload


class AotCache:
    """One directory of ``.aotx`` entries + the hit/miss bookkeeping.

    The metrics registry can be reset by tests mid-flight, so the
    cache keeps its own counters for the fixed-shape /healthz
    ``aot`` summary and mirrors every event onto the evam_aot_cache_*
    series."""

    #: counters are bumped from every warming engine thread
    SHARED_UNDER = {
        "_hits": "_lock",
        "_misses": "_lock",
        "_evictions": "_lock",
    }

    def __init__(self, root: str | os.PathLike, max_bytes: int):
        self.root = Path(root)
        self.max_bytes = max(0, int(max_bytes))
        self.root.mkdir(parents=True, exist_ok=True)
        self._fingerprint = env_fingerprint()
        self._lock = threading.Lock()
        self._hits = 0
        self._misses = {r: 0 for r in MISS_REASONS}
        self._evictions = 0

    # ------------------------------------------------------------- API

    def _path(self, key: str) -> Path:
        return self.root / f"{key}{_EXT}"

    def load(self, key: str, engine: str = ""):
        """The loaded executable for ``key``, or None after counting
        the miss reason (``absent``/``version``/``crc``/
        ``deserialize``). The caller validates with one execute and
        then confirms via :meth:`hit` (or :meth:`execute_miss`) — a
        deserialized executable is device-bound and the only honest
        validation is running it."""
        path = self._path(key)
        try:
            blob = path.read_bytes()
        except (FileNotFoundError, OSError):
            self._miss("absent", engine)
            return None
        try:
            header, payload = _unpack_entry(blob)
        except _EntryError as exc:
            log.warning("aot entry %s unreadable (%s) — falling back "
                        "to jit", path.name, exc)
            self._miss(exc.reason, engine)
            self._discard(path)
            return None
        if {k: header.get(k) for k in self._fingerprint} \
                != self._fingerprint:
            log.warning(
                "aot entry %s built under %s, running %s — version "
                "miss, falling back to jit", path.name, header,
                self._fingerprint)
            self._miss("version", engine)
            return None
        try:
            unloaded, in_tree, out_tree = pickle.loads(payload)
            loaded = deserialize_and_load(unloaded, in_tree, out_tree)
        except Exception as exc:  # noqa: BLE001 — any pjrt/pickle rot
            log.warning("aot entry %s failed to deserialize (%s) — "
                        "falling back to jit", path.name, exc)
            self._miss("deserialize", engine)
            self._discard(path)
            return None
        try:
            os.utime(path)  # LRU touch
        except OSError:
            pass
        return loaded

    def hit(self, engine: str = "") -> None:
        """Confirm one load as served (post validation-execute)."""
        with self._lock:
            self._hits += 1
        metrics.inc("evam_aot_cache_hits", labels={"engine": engine})

    def execute_miss(self, key: str, engine: str = "") -> None:
        """A deserialized entry that would not execute (wrong device,
        stale placement) — counted and removed so it can't churn."""
        self._miss("execute", engine)
        self._discard(self._path(key))

    def store(self, key: str, compiled, engine: str = "") -> bool:
        """Serialize one compiled executable under ``key`` (atomic
        tmp + rename), then evict past the size cap. Failures are a
        warning, never an error — the executable still serves."""
        try:
            unloaded, in_tree, out_tree = serialize(compiled)
            payload = pickle.dumps(
                (bytes(unloaded), in_tree, out_tree))
        except Exception as exc:  # noqa: BLE001 — backend won't serialize
            log.warning("aot serialize failed for %s (%s) — entry "
                        "skipped", engine or key[:12], exc)
            return False
        blob = _pack_entry(self._fingerprint, payload)
        path = self._path(key)
        try:
            fd, tmp = tempfile.mkstemp(dir=str(self.root),
                                       suffix=".tmp")
            with os.fdopen(fd, "wb") as fh:
                fh.write(blob)
            os.replace(tmp, path)
        except OSError as exc:
            log.warning("aot store failed for %s (%s)", path.name, exc)
            return False
        self._evict()
        return True

    # -------------------------------------------------------- internals

    def _miss(self, reason: str, engine: str) -> None:
        with self._lock:
            self._misses[reason] = self._misses.get(reason, 0) + 1
        metrics.inc("evam_aot_cache_misses",
                    labels={"engine": engine, "reason": reason})

    @staticmethod
    def _discard(path: Path) -> None:
        try:
            path.unlink()
        except OSError:
            pass

    def _entries(self) -> list[tuple[Path, float, int]]:
        out = []
        try:
            for p in self.root.iterdir():
                if p.suffix != _EXT:
                    continue
                try:
                    st = p.stat()
                except OSError:
                    continue
                out.append((p, st.st_mtime, st.st_size))
        except OSError:
            pass
        return out

    def _evict(self) -> None:
        """Oldest-mtime-first eviction past ``max_bytes``. The newest
        entry always survives — a single over-cap executable must not
        thrash store/evict forever."""
        entries = sorted(self._entries(), key=lambda e: e[1])
        total = sum(sz for _, _, sz in entries)
        if self.max_bytes:
            while total > self.max_bytes and len(entries) > 1:
                path, _, sz = entries.pop(0)
                self._discard(path)
                total -= sz
                with self._lock:
                    self._evictions += 1
                log.info("aot cache evicted %s (%d B over cap)",
                         path.name, sz)
        metrics.set("evam_aot_cache_bytes", float(total))

    def summary(self) -> dict:
        """Fixed-shape /healthz block (golden contract — keys stable
        whether the cache is on or off, see :func:`disabled_summary`)."""
        entries = self._entries()
        with self._lock:
            hits = self._hits
            misses = {r: self._misses.get(r, 0) for r in MISS_REASONS}
            evictions = self._evictions
        return {
            "enabled": True,
            "entries": len(entries),
            "bytes": sum(sz for _, _, sz in entries),
            "max_bytes": self.max_bytes,
            "hits": hits,
            "misses": misses,
            "evictions": evictions,
        }


def disabled_summary() -> dict:
    """The same /healthz shape with EVAM_AOT=off."""
    return {
        "enabled": False,
        "entries": 0,
        "bytes": 0,
        "max_bytes": 0,
        "hits": 0,
        "misses": {r: 0 for r in MISS_REASONS},
        "evictions": 0,
    }


#: memoized EVAM_AOT decision — (cache,) once resolved, None before.
#: Same shape as control/state.py and obs/trace.py: the tuple wrapper
#: distinguishes "resolved to disabled" from "not yet resolved".
_resolved: tuple[AotCache | None] | None = None


def active() -> AotCache | None:
    """The process AotCache, or None with EVAM_AOT=off (default) or a
    jax that can't serialize executables. Memoized: the off path costs
    one global load per consult."""
    if _resolved is not None:
        return _resolved[0]
    return _resolve()


def _resolve() -> AotCache | None:
    global _resolved
    from evam_tpu.config.settings import get_settings

    cfg = get_settings().aot
    cache: AotCache | None = None
    if cfg.enabled:
        if not _HAVE_SERIALIZE:
            log.warning(
                "EVAM_AOT=on but this jax has no serialize_executable "
                "support — AOT cache disabled, serving plain jit")
        else:
            root = cfg.dir or os.path.join(
                tempfile.gettempdir(), "evam_aot")
            try:
                cache = AotCache(root, cfg.max_bytes)
            except OSError as exc:
                log.warning("EVAM_AOT dir %s unusable (%s) — AOT "
                            "cache disabled", root, exc)
    _resolved = (cache,)
    return cache


def summary() -> dict:
    """The /healthz ``aot`` block: live cache summary or the disabled
    same-shape zeros."""
    cache = active()
    return disabled_summary() if cache is None else cache.summary()


def reset_cache() -> None:
    """Drop the memo (tests / bench A-B flips)."""
    global _resolved
    _resolved = None
