"""Mixture-of-experts MLP with expert parallelism.

No reference counterpart (the reference's models are small dense
CNNs); this is forward-looking capacity scaling for the temporal
decoder: the transformer MLP becomes E experts with a learned router,
expert weights sharded over a mesh axis so each device holds E/n
experts (expert parallelism — here sharing the tensor-parallel
``model`` axis, the common EP=TP-group layout).

Dispatch is dense (every expert evaluated, outputs weighted by the
router's softmax gate): at zoo scale the expert dimension is small and
dense dispatch keeps everything static-shaped for XLA — no capacity
buckets, no token dropping, and the expert-sharded einsum partitions
cleanly with a single reduce over the expert axis.

FROZEN (round-4 verdict, weak-5): the reference is an
inference microservice with no training/model parallelism
(SURVEY.md §2d) — this module exists for the driver's
multichip-dryrun contract (__graft_entry__.dryrun_multichip)
and the accuracy-harness trainer only. No new feature work
lands here.
"""

from __future__ import annotations

from typing import Callable

import flax.linen as nn
import jax.numpy as jnp


class MoeMlp(nn.Module):
    dim: int
    num_experts: int = 4
    mlp_ratio: int = 4
    #: sharding constraint applied to the per-expert hidden activation
    #: [B, T, E, H] (expert axis over the mesh's model axis)
    expert_constraint: Callable | None = None

    @nn.compact
    def __call__(self, x):
        e, d, h = self.num_experts, self.dim, self.dim * self.mlp_ratio
        gates = nn.softmax(nn.Dense(e, name="router")(x), axis=-1)  # [B,T,E]
        w_up = self.param(
            "experts_up", nn.initializers.lecun_normal(), (e, d, h))
        b_up = self.param("experts_up_bias", nn.initializers.zeros, (e, h))
        w_dn = self.param(
            "experts_down", nn.initializers.lecun_normal(), (e, h, d))
        b_dn = self.param("experts_down_bias", nn.initializers.zeros, (e, d))
        hidden = jnp.einsum("btd,edh->bteh", x, w_up) + b_up
        if self.expert_constraint is not None:
            hidden = self.expert_constraint(hidden)
        hidden = nn.gelu(hidden)
        out = jnp.einsum("bteh,ehd->bted", hidden, w_dn) + b_dn
        # Router-weighted combine reduces the expert axis — XLA emits
        # the cross-device psum when experts are sharded.
        return jnp.einsum("bted,bte->btd", out, gates)
