"""Pipeline parallelism: GPipe-style microbatch rotation over a
``pipe`` mesh axis.

No reference counterpart (SURVEY.md §2d — the reference has no model
parallelism at all); this is the layer-sharding axis for decoders too
deep for one device. Stage s holds layer-stack slice s (params stacked
on a leading stage axis, sharded over ``pipe``); microbatches enter at
stage 0, activations hop stage→stage via `lax.ppermute` (one ICI hop
per step), and after S + M - 1 steps every microbatch has crossed all
stages. Fill/drain bubbles are masked, outputs psum-gathered from the
last stage. Differentiable end-to-end — the same loop trains.

FROZEN (round-4 verdict, weak-5): the reference is an
inference microservice with no training/model parallelism
(SURVEY.md §2d) — this module exists for the driver's
multichip-dryrun contract (__graft_entry__.dryrun_multichip)
and the accuracy-harness trainer only. No new feature work
lands here.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map


def stack_stage_params(param_list):
    """[per-stage param trees] → one tree with a leading stage axis."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs, axis=0), *param_list
    )


def pipeline_apply(
    apply_fn: Callable,
    stacked_params,
    x: jax.Array,
    mesh: Mesh,
    pipe_axis: str = "pipe",
    microbatches: int | None = None,
) -> jax.Array:
    """Run x through S pipelined stages.

    apply_fn(stage_params, h) -> h applies ONE stage (shape-preserving).
    stacked_params: trees with leading stage axis of size S =
    mesh.shape[pipe_axis]. x: [M, mb, ...] pre-split microbatches
    (M defaults to S). Returns [M, mb, ...] outputs.
    """
    n_stages = mesh.shape[pipe_axis]
    m = x.shape[0] if microbatches is None else microbatches
    if x.shape[0] != m:
        raise ValueError(f"x leading dim {x.shape[0]} != microbatches {m}")

    def kernel(params, xs):
        # local: params leading axis 1 (this stage), xs [M, mb, ...]
        stage_params = jax.tree_util.tree_map(lambda a: a[0], params)
        my = jax.lax.axis_index(pipe_axis)
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        # Zero accumulators derived from a device-varying scalar so the
        # scan carry satisfies shard_map's varying-manual-axes typing.
        vary0 = (my * 0).astype(xs.dtype)
        buf = jnp.zeros_like(xs[0]) + vary0
        outs = jnp.zeros_like(xs) + vary0

        def step(carry, t):
            buf, outs = carry
            # Stage 0 ingests microbatch t (clamped); later stages take
            # the neighbor's activation from the previous step.
            x_t = jax.lax.dynamic_index_in_dim(
                xs, jnp.clip(t, 0, m - 1), axis=0, keepdims=False
            )
            inp = jnp.where(my == 0, x_t, buf)
            out = apply_fn(stage_params, inp)
            # Last stage completed microbatch t - (S - 1) this step;
            # predicated write keeps branch types uniform.
            done_idx = jnp.clip(t - (n_stages - 1), 0, m - 1)
            valid = (my == n_stages - 1) & (t >= n_stages - 1)
            cur = jax.lax.dynamic_index_in_dim(
                outs, done_idx, axis=0, keepdims=False
            )
            outs = jax.lax.dynamic_update_index_in_dim(
                outs, jnp.where(valid, out, cur), done_idx, axis=0
            )
            buf = jax.lax.ppermute(out, pipe_axis, perm)
            return (buf, outs), None

        (buf, outs), _ = jax.lax.scan(
            step, (buf, outs), jnp.arange(m + n_stages - 1)
        )
        # Only the last stage holds real outputs; psum replicates them.
        return jax.lax.psum(
            jnp.where(my == n_stages - 1, outs, jnp.zeros_like(outs)),
            pipe_axis,
        )

    in_param_spec = jax.tree_util.tree_map(
        lambda _: P(pipe_axis), stacked_params
    )
    return shard_map(
        kernel,
        mesh=mesh,
        in_specs=(in_param_spec, P()),
        out_specs=P(),
    )(stacked_params, x)


def build_pipe_mesh(devices=None, n_stages: int | None = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    n = n_stages or len(devices)
    return Mesh(np.asarray(devices[:n]).reshape(n), ("pipe",))
