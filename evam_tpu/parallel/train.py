"""Distributed training step: dp x sp x tp over one `jax.sharding.Mesh`.

The reference is inference-only (SURVEY.md §2d) — models arrive
pre-trained from OMZ. A TPU-native framework owns the other half of
the model lifecycle too: this module fine-tunes the action-recognition
model (the largest zoo member, encoder + temporal transformer decoder)
with every parallelism axis the hardware offers, so the same code
scales from one chip to a multi-host pod:

* **data parallel** (``data`` axis): the clip batch shards; XLA
  inserts the gradient psum.
* **sequence parallel** (``seq`` axis): the clip's temporal axis
  shards; decoder attention runs as a ring (evam_tpu.parallel.ring,
  `ppermute` over ICI). For the frame encoder the seq axis is just
  more data parallelism — frames reshape to one (B*T) batch axis
  sharded over data x seq.
* **tensor parallel** (``model`` axis): attention heads and the MLP
  hidden dimension shard Megatron-style via param shardings +
  activation constraints; XLA inserts the all-reduces.

Everything is one `jit` — no hand-scheduled collectives outside the
ring kernel. The driver's `dryrun_multichip` entry point jits this
step over an N-virtual-device mesh.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from evam_tpu.models.zoo.action import ActionDecoder, ActionEncoder
from evam_tpu.obs import get_logger
from evam_tpu.parallel.ring import make_flax_attention_fn

log = get_logger("parallel.train")


def _ckpt_path(path):
    import os

    return os.path.abspath(os.fspath(path))


def factor_mesh(n: int) -> tuple[int, int, int]:
    """Split n devices into (data, seq, model) sizes.

    Greedy powers-of-two: model and seq each take a factor of 2 when
    available (tp wants the fewest devices — it all-reduces every
    layer; sp rings once per attention; dp gets the rest, it
    communicates only at the gradient psum)."""
    tp = 2 if n % 2 == 0 and n >= 8 else 1
    rem = n // tp
    sp = 2 if rem % 2 == 0 else 1
    dp = rem // sp
    return dp, sp, tp


def build_train_mesh(devices=None, shape: tuple[int, int, int] | None = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    dp, sp, tp = shape if shape is not None else factor_mesh(len(devices))
    if dp * sp * tp != len(devices):
        raise ValueError(f"mesh {dp}x{sp}x{tp} != {len(devices)} devices")
    arr = np.asarray(devices).reshape(dp, sp, tp)
    return Mesh(arr, ("data", "seq", "model"))


# --------------------------------------------------------- shardings

def _path_str(path) -> str:
    return "/".join(str(getattr(p, "key", p)) for p in path)


def param_spec(path, leaf) -> P:
    """Megatron-style placement for the decoder transformer; encoder
    convs and small heads replicate."""
    name = _path_str(path)
    if "MoeMlp" in name and "experts" in name:
        # Expert axis (leading dim) shards over the model axis —
        # expert parallelism shares the tp hardware axis.
        return P("model")
    if "MoeMlp" in name:  # router
        return P()
    if "MultiHeadDotProductAttention" in name:
        # qkv kernels [D, H, Dh]; out kernel [H, Dh, D]; biases follow.
        if "/out/" in name:
            return P("model") if leaf.ndim >= 2 else P()
        if leaf.ndim == 3:
            return P(None, "model", None)
        if leaf.ndim == 2:
            return P("model", None)
        return P()
    if "TransformerBlock" in name and "Dense_0" in name:
        # MLP up-projection [D, 4D]: shard the hidden dim.
        return P(None, "model") if leaf.ndim == 2 else P("model")
    if "TransformerBlock" in name and "Dense_1" in name:
        # MLP down-projection [4D, D]: shard the contracting dim.
        return P("model", None) if leaf.ndim == 2 else P()
    return P()


def param_shardings(mesh: Mesh, params) -> Any:
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: NamedSharding(mesh, param_spec(path, leaf)), params
    )


# -------------------------------------------------------- train step

@dataclasses.dataclass
class ActionTrainConfig:
    num_classes: int = 400
    embed_dim: int = 512
    depth: int = 4
    heads: int = 8
    encoder_width: int = 32
    frame_size: tuple[int, int] = (224, 224)
    clip_len: int = 16
    learning_rate: float = 3e-4
    weight_decay: float = 1e-4
    remat_encoder: bool = True
    #: > 0 enables the mixture-of-experts decoder MLP (expert
    #: parallelism over the model axis, evam_tpu.parallel.moe)
    moe_experts: int = 0
    #: sequence-parallel attention strategy: "ring" (K/V ring over
    #: ppermute, scales past head count) or "ulysses" (all-to-all
    #: head exchange, fewer larger transfers; needs heads % seq == 0)
    sp_strategy: str = "ring"


@dataclasses.dataclass
class ActionTrainer:
    """Owns models, optimizer, sharded state, and the jitted step."""

    mesh: Mesh
    config: ActionTrainConfig
    encoder: ActionEncoder
    decoder: ActionDecoder
    tx: optax.GradientTransformation
    train_step: Callable
    state_shardings: Any

    def init_state(self, seed: int = 0):
        cfg = self.config
        h, w = cfg.frame_size
        k_enc, k_dec = jax.random.split(jax.random.PRNGKey(seed))
        # Dummy batch must divide the mesh's data axis (the ring
        # kernel shards even the init trace); params are batch-free.
        b0 = self.mesh.shape["data"]
        enc_params = self.encoder.init(
            k_enc, jnp.zeros((1, h, w, 3), jnp.float32)
        )["params"]
        dec_params = self.decoder.init(
            k_dec, jnp.zeros((b0, cfg.clip_len, cfg.embed_dim), jnp.float32)
        )["params"]
        params = {"enc": enc_params, "dec": dec_params}
        opt_state = self.tx.init(params)
        state = {"params": params, "opt_state": opt_state,
                 "step": jnp.zeros((), jnp.int32)}
        return jax.device_put(state, self.state_shardings)

    def data_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, P("data", "seq"))

    # ---------------------------------------------------- checkpointing

    def save_checkpoint(self, state, path) -> None:
        """Persist the (sharded) train state with orbax — the training
        half of SURVEY.md §5.4 (serving-side resume lives in
        server/registry.py; XLA executable cache in obs/trace.py)."""
        import orbax.checkpoint as ocp

        with ocp.StandardCheckpointer() as ckptr:
            ckptr.save(_ckpt_path(path), state, force=True)

    def restore_checkpoint(self, path):
        """Restore onto this trainer's mesh/shardings (works across
        process restarts and different mesh layouts — orbax reshards)."""
        import orbax.checkpoint as ocp

        example = jax.eval_shape(lambda: self.init_state(0))
        abstract = jax.tree_util.tree_map(
            lambda leaf, sh: jax.ShapeDtypeStruct(
                leaf.shape, leaf.dtype, sharding=sh
            ),
            example,
            self.state_shardings,
        )
        with ocp.StandardCheckpointer() as ckptr:
            return ckptr.restore(_ckpt_path(path), abstract)

    def shard_batch(self, clips: np.ndarray, labels: np.ndarray):
        clip_sh = NamedSharding(self.mesh, P("data", "seq", None, None, None))
        lbl_sh = NamedSharding(self.mesh, P("data"))
        return jax.device_put(clips, clip_sh), jax.device_put(labels, lbl_sh)


def build_action_trainer(
    mesh: Mesh, config: ActionTrainConfig | None = None
) -> ActionTrainer:
    cfg = config or ActionTrainConfig()
    mlp_constraint = functools.partial(
        jax.lax.with_sharding_constraint,
        shardings=NamedSharding(mesh, P("data", "seq", "model")),
    )
    if cfg.sp_strategy == "ulysses":
        from evam_tpu.parallel.ulysses import (
            make_flax_attention_fn as make_ulysses_fn,
        )

        attention_fn = make_ulysses_fn(
            mesh, seq_axis="seq", batch_axis="data", head_axis="model"
        )
    elif cfg.sp_strategy == "ring":
        attention_fn = make_flax_attention_fn(
            mesh, seq_axis="seq", batch_axis="data", head_axis="model"
        )
    else:
        raise ValueError(f"unknown sp_strategy {cfg.sp_strategy!r}")
    moe_constraint = functools.partial(
        jax.lax.with_sharding_constraint,
        shardings=NamedSharding(mesh, P("data", "seq", "model", None)),
    )
    encoder = ActionEncoder(embed_dim=cfg.embed_dim, width=cfg.encoder_width)
    decoder = ActionDecoder(
        num_classes=cfg.num_classes,
        dim=cfg.embed_dim,
        depth=cfg.depth,
        heads=cfg.heads,
        attention_fn=attention_fn,
        mlp_constraint=mlp_constraint,
        moe_experts=cfg.moe_experts,
        moe_constraint=moe_constraint if cfg.moe_experts else None,
    )
    tx = optax.adamw(cfg.learning_rate, weight_decay=cfg.weight_decay)

    enc_apply = encoder.apply
    if cfg.remat_encoder:
        # Trade encoder activations for recompute in backward — HBM is
        # the binding constraint for video batches (B*T frames live).
        enc_apply = jax.checkpoint(enc_apply)

    frames_spec = NamedSharding(mesh, P(("data", "seq"), None, None, None))
    emb_spec = NamedSharding(mesh, P("data", "seq", None))

    def loss_fn(params, clips, labels):
        b, t = clips.shape[:2]
        x = clips.astype(jnp.float32) / 255.0
        frames = x.reshape((b * t,) + x.shape[2:])
        # Encoder: pure data parallelism over data x seq (frames are
        # independent); bf16 activations keep the MXU fed.
        frames = jax.lax.with_sharding_constraint(frames, frames_spec)
        emb = enc_apply({"params": params["enc"]}, frames.astype(jnp.bfloat16))
        emb = emb.reshape(b, t, -1).astype(jnp.float32)
        # Decoder: sequence stays sharded; ring attention inside.
        emb = jax.lax.with_sharding_constraint(emb, emb_spec)
        logits = decoder.apply({"params": params["dec"]}, emb)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        loss = -jnp.take_along_axis(logp, labels[:, None], axis=-1).mean()
        acc = (logits.argmax(-1) == labels).mean()
        return loss, acc

    def step_fn(state, clips, labels):
        (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state["params"], clips, labels
        )
        updates, opt_state = tx.update(
            grads, state["opt_state"], state["params"]
        )
        params = optax.apply_updates(state["params"], updates)
        new_state = {
            "params": params,
            "opt_state": opt_state,
            "step": state["step"] + 1,
        }
        return new_state, {"loss": loss, "accuracy": acc}

    # Sharding structure needs concrete params; init abstractly.
    h, w = cfg.frame_size
    b0 = mesh.shape["data"]
    abstract = jax.eval_shape(
        lambda k: {
            "enc": encoder.init(k, jnp.zeros((1, h, w, 3), jnp.float32))["params"],
            "dec": decoder.init(k, jnp.zeros((b0, cfg.clip_len, cfg.embed_dim),
                                             jnp.float32))["params"],
        },
        jax.random.PRNGKey(0),
    )
    p_shardings = param_shardings(mesh, abstract)
    # Adam moments mirror the param layout; other optax state replicates.
    opt_state_struct = jax.eval_shape(tx.init, abstract)
    opt_shardings = _shard_like_params(
        opt_state_struct, abstract, p_shardings, mesh
    )

    state_shardings = {
        "params": p_shardings,
        "opt_state": opt_shardings,
        "step": NamedSharding(mesh, P()),
    }
    train_step = jax.jit(
        step_fn,
        in_shardings=(
            state_shardings,
            NamedSharding(mesh, P("data", "seq", None, None, None)),
            NamedSharding(mesh, P("data")),
        ),
        out_shardings=(state_shardings, NamedSharding(mesh, P())),
        donate_argnums=(0,),
    )
    return ActionTrainer(
        mesh=mesh,
        config=cfg,
        encoder=encoder,
        decoder=decoder,
        tx=tx,
        train_step=train_step,
        state_shardings=state_shardings,
    )


def _shard_like_params(opt_struct, param_struct, p_shardings, mesh):
    """Adam m/v trees share the param tree structure — shard them the
    same way; scalar/other leaves replicate."""
    param_treedef = jax.tree_util.tree_structure(param_struct)

    def place(node):
        if jax.tree_util.tree_structure(node) == param_treedef:
            return p_shardings
        return jax.tree_util.tree_map(
            lambda _: NamedSharding(mesh, P()), node,
            is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct),
        )

    return jax.tree_util.tree_map(
        place, opt_struct,
        is_leaf=lambda x: jax.tree_util.tree_structure(x) == param_treedef
        or isinstance(x, jax.ShapeDtypeStruct),
    )
