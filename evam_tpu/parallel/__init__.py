from evam_tpu.parallel.mesh import (
    MeshPlan,
    build_mesh,
    batch_sharding,
    replicated,
    shard_batch,
)

__all__ = [
    "MeshPlan",
    "build_mesh",
    "batch_sharding",
    "replicated",
    "shard_batch",
]
