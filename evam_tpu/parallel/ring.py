"""Ring attention: sequence-parallel attention over a mesh axis.

The reference has no sequence parallelism (SURVEY.md §5.7) — its
longest temporal extent is the 16-frame action-recognition clip. The
TPU rebuild makes long-context first-class anyway: when clips (or any
token sequence) outgrow one chip's HBM, the sequence axis shards over
a ``seq`` mesh axis and attention runs as a ring — each device holds
one K/V block, blocks rotate around the ring via `lax.ppermute` (one
ICI hop per step) while every device accumulates its queries' output
with an online-softmax (flash-attention style) running max/sum. Full
attention in O(T/n) memory per device, with communication overlapped
by the compiler across scan steps.

Differentiable end-to-end (`ppermute` has a transpose rule), so the
same kernel serves training (evam_tpu.parallel.train) and inference.

FROZEN (round-4 verdict, weak-5): the reference is an
inference microservice with no training/model parallelism
(SURVEY.md §2d) — this module exists for the driver's
multichip-dryrun contract (__graft_entry__.dryrun_multichip)
and the accuracy-harness trainer only. No new feature work
lands here.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

NEG_INF = -1e30


def _ring_attention_kernel(
    q: jax.Array,  # [B, Tq, H, D] local shard
    k: jax.Array,  # [B, Tk, H, D] local shard
    v: jax.Array,
    *,
    axis_name: str,
    axis_size: int,
    causal: bool,
    scale: float,
) -> jax.Array:
    """Per-shard ring loop. Runs inside shard_map over ``axis_name``."""
    b, tq, h, d = q.shape
    tk = k.shape[1]
    my_idx = jax.lax.axis_index(axis_name)
    qf = q.astype(jnp.float32) * scale
    # Accumulators in [B, H, Tq, ...] layout (scores are bhqk). Derived
    # from qf (not fresh constants) so they carry the same varying
    # manual axes as the scan outputs under shard_map's VMA typing.
    qt = qf.transpose(0, 2, 1, 3) * 0.0
    o = qt
    m = qt[..., 0] + NEG_INF
    l = qt[..., 0]
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def step(carry, i):
        o, m, l, k_blk, v_blk = carry
        # Block i currently holds the K/V shard originally owned by
        # ring neighbor (my_idx - i) mod n.
        owner = (my_idx - i) % axis_size
        s = jnp.einsum(
            "bqhd,bkhd->bhqk", qf, k_blk.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        if causal:
            q_pos = my_idx * tq + jnp.arange(tq)
            k_pos = owner * tk + jnp.arange(tk)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        l = l * alpha + p.sum(axis=-1)
        o = o * alpha[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return (o, m_new, l, k_blk, v_blk), None

    (o, m, l, _, _), _ = jax.lax.scan(
        step, (o, m, l, k, v), jnp.arange(axis_size)
    )
    out = o / jnp.maximum(l[..., None], 1e-30)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)  # [B, Tq, H, D]


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    seq_axis: str = "seq",
    batch_axis: str | None = "data",
    head_axis: str | None = "model",
    causal: bool = False,
) -> jax.Array:
    """Sequence-parallel attention over ``mesh.shape[seq_axis]`` shards.

    q/k/v: [B, T, H, D] global arrays (sharded or not — shard_map
    repartitions). Batch rides ``batch_axis`` (pure data parallel),
    heads ride ``head_axis`` (tensor parallel — heads are independent
    in attention, so no extra collective), sequence rides the ring.
    """
    n = mesh.shape[seq_axis]
    scale = q.shape[-1] ** -0.5
    if n == 1 and mesh.shape.get(head_axis or "", 1) == 1:
        return plain_attention(q, k, v, causal=causal, scale=scale)

    spec = P(
        batch_axis if batch_axis in mesh.axis_names else None,
        seq_axis,
        head_axis if head_axis in mesh.axis_names else None,
        None,
    )
    kernel = functools.partial(
        _ring_attention_kernel,
        axis_name=seq_axis,
        axis_size=n,
        causal=causal,
        scale=scale,
    )
    sharded = shard_map(
        kernel, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )
    return sharded(q, k, v)


def plain_attention(q, k, v, *, causal=False, scale=None):
    """Single-device reference attention (same layout as ring)."""
    scale = q.shape[-1] ** -0.5 if scale is None else scale
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32))
    s = s * scale
    if causal:
        t_q, t_k = q.shape[1], k.shape[1]
        mask = jnp.arange(t_q)[:, None] >= jnp.arange(t_k)[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


def make_flax_attention_fn(
    mesh: Mesh,
    *,
    seq_axis: str = "seq",
    batch_axis: str | None = "data",
    head_axis: str | None = "model",
    causal: bool = False,
) -> Callable:
    """Adapter: ring_attention as a drop-in ``attention_fn`` for
    `flax.linen.MultiHeadDotProductAttention` — the serving model's
    param tree is unchanged, only the attention computation swaps, so
    weights trained sequence-parallel load directly into the serving
    ActionDecoder (evam_tpu.models.zoo.action)."""

    def attention_fn(query, key, value, **kwargs):
        return ring_attention(
            query, key, value, mesh,
            seq_axis=seq_axis, batch_axis=batch_axis, head_axis=head_axis,
            causal=causal,
        )

    return attention_fn
