"""Device mesh construction and sharding rules.

The reference scales by running N independent single-device pipelines
(stream-level parallelism, SURVEY.md §2d-1) across CPU/iGPU/VPU
devices. The TPU design inverts that: one engine per model, its batch
axis sharded over the ``data`` axis of a `jax.sharding.Mesh`, with
XLA inserting the collectives over ICI. A second ``model`` axis is
available for tensor-parallel sharding of large heads (unused by the
small zoo models, exercised by the training step in
evam_tpu.parallel.train and dryrun_multichip).

Multi-host: `initialize_distributed` wires `jax.distributed` so the
same mesh spans hosts over DCN — the TPU-native counterpart of the
reference's cross-host ZeroMQ data plane (SURVEY.md §5.8): tensor
traffic rides ICI/DCN inside XLA, frames/results keep riding
ZeroMQ/MQTT outside it.
"""

from __future__ import annotations

import dataclasses
import os

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from evam_tpu.obs import get_logger

log = get_logger("parallel.mesh")


@dataclasses.dataclass
class MeshPlan:
    mesh: Mesh
    data_axis: str = "data"
    model_axis: str | None = None

    @property
    def data_size(self) -> int:
        return self.mesh.shape[self.data_axis]

    def batch_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, P(self.data_axis))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def pad_batch(self, n: int) -> int:
        """Round n up to a multiple of the data-axis size."""
        d = self.data_size
        return -(-n // d) * d

    def per_device_plans(self) -> list["MeshPlan"]:
        """One single-device data-mesh plan per device of this mesh,
        in mesh order — the fleet mode's shard plans (EVAM_FLEET):
        each shard engine jits over its own chip, so small buckets
        never pay a collective, and ``pad_batch`` is the identity
        (data size 1)."""
        return [
            MeshPlan(mesh=Mesh(np.asarray([dev]), (self.data_axis,)),
                     data_axis=self.data_axis)
            for dev in self.mesh.devices.flat
        ]


def build_mesh(
    shape: list[int] | None = None,
    axes: list[str] | None = None,
    devices: list | None = None,
) -> MeshPlan:
    """Build a mesh over the available devices.

    Default: 1-D ``data`` mesh over all local devices (the right
    layout for inference serving — batch data-parallel, models
    replicated). ``shape`` may contain one -1 wildcard.
    """
    devices = devices if devices is not None else jax.devices()
    axes = list(axes or ["data"])
    shape = list(shape or [-1])
    if len(shape) != len(axes):
        raise ValueError(f"mesh shape {shape} does not match axes {axes}")
    n = len(devices)
    if -1 in shape:
        known = int(np.prod([s for s in shape if s != -1])) if len(shape) > 1 else 1
        if n % known:
            raise ValueError(f"{n} devices not divisible by {known}")
        shape[shape.index(-1)] = n // known
    if int(np.prod(shape)) != n:
        raise ValueError(f"mesh shape {shape} != device count {n}")
    mesh = Mesh(np.asarray(devices).reshape(shape), axes)
    model_axis = "model" if "model" in axes else None
    log.info("mesh: %s over %d devices (%s)", dict(zip(axes, shape)), n,
             devices[0].platform)
    return MeshPlan(mesh=mesh, model_axis=model_axis)


def batch_sharding(plan: MeshPlan) -> NamedSharding:
    return plan.batch_sharding()


def replicated(plan: MeshPlan) -> NamedSharding:
    return plan.replicated()


def shard_batch(plan: MeshPlan, array) -> jax.Array:
    """Place a host batch onto the mesh, sharded along the data axis."""
    return jax.device_put(array, plan.batch_sharding())


def initialize_distributed() -> None:
    """Multi-host init from env (JAX_COORDINATOR, JAX_NUM_PROCESSES,
    JAX_PROCESS_ID) — no-op when unset or single-process."""
    coord = os.environ.get("JAX_COORDINATOR")
    nproc = int(os.environ.get("JAX_NUM_PROCESSES", "1"))
    if not coord or nproc <= 1:
        return
    jax.distributed.initialize(
        coordinator_address=coord,
        num_processes=nproc,
        process_id=int(os.environ.get("JAX_PROCESS_ID", "0")),
    )
    log.info("jax.distributed initialized: %d processes", nproc)
