"""Ulysses-style all-to-all sequence parallelism.

The second of the two standard long-context strategies (alongside
ring attention, evam_tpu.parallel.ring): instead of rotating K/V
blocks around a ring, one ``all_to_all`` re-shards the tensors from
sequence-sharded [B, T/n, H, D] to head-sharded [B, T, H/n, D], full
attention runs locally per head subset (heads are independent), and a
second ``all_to_all`` restores sequence sharding.

Trade-off vs the ring (why both exist):

* Ulysses moves Q, K and V **once** each way (2 collective phases)
  and then computes dense local attention — fewer, larger transfers
  that ride ICI bisection bandwidth; but it caps the sequence-shard
  count at the head count (n must divide H).
* The ring never re-shards Q and overlaps its n-1 K/V hops with
  compute, scales past the head count, and keeps O(T/n) memory for
  scores; but it serializes n matmul steps.

Short-sequence/many-head workloads (the action decoder's clip
transformer) favor Ulysses; very long sequences with few heads favor
the ring. Both are exposed through the same ``attention_fn`` adapter
so the trainer picks per config (`sp_strategy`).

FROZEN (round-4 verdict, weak-5): the reference is an
inference microservice with no training/model parallelism
(SURVEY.md §2d) — this module exists for the driver's
multichip-dryrun contract (__graft_entry__.dryrun_multichip)
and the accuracy-harness trainer only. No new feature work
lands here.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from evam_tpu.parallel.ring import plain_attention


def _ulysses_kernel(
    q: jax.Array,  # [B, T/n, H, D] local shard
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    causal: bool,
    scale: float,
) -> jax.Array:
    def seq_to_heads(x):
        # [B, T/n, H, D] → [B, T, H/n, D]: split the head axis n ways,
        # concatenate the received pieces along sequence
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def heads_to_seq(x):
        return jax.lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    qh = seq_to_heads(q)
    kh = seq_to_heads(k)
    vh = seq_to_heads(v)
    out = plain_attention(qh, kh, vh, causal=causal, scale=scale)
    return heads_to_seq(out).astype(q.dtype)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    seq_axis: str = "seq",
    batch_axis: str | None = "data",
    head_axis: str | None = "model",
    causal: bool = False,
) -> jax.Array:
    """All-to-all sequence-parallel attention over
    ``mesh.shape[seq_axis]`` shards.

    q/k/v: [B, T, H, D] global arrays. Heads additionally shard over
    ``head_axis`` (tensor parallel — heads are independent, mirroring
    ring_attention), so the requirement is
    ``H % (seq_shards * head_shards) == 0`` and ``T % seq_shards == 0``.
    """
    n = mesh.shape[seq_axis]
    m = mesh.shape.get(head_axis, 1) if head_axis in mesh.axis_names else 1
    scale = q.shape[-1] ** -0.5
    if n == 1 and m == 1:
        return plain_attention(q, k, v, causal=causal, scale=scale)
    h, t = q.shape[2], q.shape[1]
    if h % (n * m):
        raise ValueError(
            f"ulysses needs heads % (seq*model shards) == 0, got H={h} "
            f"seq={n} model={m} (use ring_attention to scale past the "
            "head count)"
        )
    if t % n:
        raise ValueError(f"sequence length {t} not divisible by {n} shards")

    spec = P(
        batch_axis if batch_axis in mesh.axis_names else None,
        seq_axis,
        head_axis if head_axis in mesh.axis_names else None,
        None,
    )
    kernel = functools.partial(
        _ulysses_kernel, axis_name=seq_axis, causal=causal, scale=scale)
    sharded = shard_map(
        kernel, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )
    return sharded(q, k, v)


def make_flax_attention_fn(
    mesh: Mesh,
    *,
    seq_axis: str = "seq",
    batch_axis: str | None = "data",
    head_axis: str | None = "model",
    causal: bool = False,
) -> Callable:
    """Ulysses as a drop-in ``attention_fn`` for
    `flax.linen.MultiHeadDotProductAttention` (same adapter contract
    as ring.make_flax_attention_fn — param tree unchanged)."""

    def attention_fn(query, key, value, **kwargs):
        return ulysses_attention(
            query, key, value, mesh,
            seq_axis=seq_axis, batch_axis=batch_axis,
            head_axis=head_axis, causal=causal,
        )

    return attention_fn
