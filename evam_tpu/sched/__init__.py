"""SLO-aware scheduling: admission control, priority classes, load
shedding — the QoS layer between REST and the shared batch engines.

Three cooperating parts (see each module's docstring):

* ``sched.admission`` — AdmissionController: reject over-capacity
  starts at the REST edge (503 + Retry-After) using a capacity model
  driven by the PR-1 stage clock;
* ``sched.classes``   — priority classes (realtime|standard|batch),
  SchedConfig (the EVAM_SCHED_* knob set), and ClassQueues (the
  per-class replacement for the engine's single FIFO, drained
  realtime-first with a starvation-proof weighted pick);
* ``sched.shedder``   — per-class staleness budgets enforced at
  dispatch: stale frames shed oldest-first (freshest-frame-wins),
  futures failed loudly as ShedError.

``EVAM_SCHED=off`` disables the whole layer and keeps the legacy
single-FIFO engine path byte-identical (A/B, like
``EVAM_BATCH_ASSEMBLY=legacy``).
"""

from evam_tpu.sched.admission import (
    CLASS_HEADROOM,
    AdmissionController,
    AdmissionError,
)
from evam_tpu.sched.classes import (
    DEFAULT_PRIORITY,
    PRIORITIES,
    ClassQueues,
    SchedConfig,
    validate_priority,
)
from evam_tpu.sched.shedder import Shedder, ShedError

__all__ = [
    "CLASS_HEADROOM",
    "AdmissionController",
    "AdmissionError",
    "ClassQueues",
    "DEFAULT_PRIORITY",
    "PRIORITIES",
    "SchedConfig",
    "Shedder",
    "ShedError",
    "validate_priority",
]
