"""Load shedding: per-class staleness budgets enforced at dispatch.

A video frame is perishable — detections on a frame the camera
captured two seconds ago are not "late results", they are wrong
results (OCTOPINF's stale-frame argument, PAPERS.md). So under
overload the right policy is freshest-frame-wins: drop the OLDEST
queued frames first and fail their futures loudly, instead of letting
the queue rot and every frame arrive uniformly late.

The ``Shedder`` owns the per-class staleness budgets
(``EVAM_SCHED_STALENESS_MS_*`` → SchedConfig.staleness_ms) and the
accounting: every shed rides ``evam_sched_shed_total{class}`` plus a
reset-proof local counter (the bench contract line and /healthz read
the local counts so a window-scoped ``metrics.reset()`` can't hide
sheds). A shed future fails with ``ShedError`` — a loud, typed error
the per-frame isolation in stages/runner.py absorbs as one counted
frame error, never a stream kill.
"""

from __future__ import annotations

import threading
import time

from evam_tpu.control.state import current_op
from evam_tpu.obs import get_logger, metrics
from evam_tpu.sched.classes import PRIORITIES

log = get_logger("sched.shedder")


class ShedError(RuntimeError):
    """A queued frame exceeded its class staleness budget and was
    dropped at dispatch (oldest-first). Deliberate overload behavior,
    not an engine fault."""

    def __init__(self, priority: str, age_s: float, budget_s: float,
                 engine: str = ""):
        self.priority = priority
        self.age_s = age_s
        self.budget_s = budget_s
        self.engine = engine
        super().__init__(
            f"frame shed: {priority}-class item aged {age_s * 1e3:.0f}ms "
            f"> staleness budget {budget_s * 1e3:.0f}ms"
            f"{f' (engine {engine})' if engine else ''}"
        )


class Shedder:
    """Per-engine staleness enforcement over ClassQueues.

    ``sweep`` runs every dispatcher cycle and sheds expired items
    still WAITING in any class queue (this is what bounds the backlog
    a busy realtime lane starves out of service); ``shed`` filters a
    just-formed batch (items can expire during batch-formation wait).
    Both drop oldest-first by construction: FIFO queues age
    monotonically from head to tail.
    """

    #: dispatcher thread sheds, server/bench threads read the counts —
    #: mutations must hold ``_lock`` (lock-discipline pass).
    SHARED_UNDER = {"counts": "_lock"}

    def __init__(self, engine_name: str, staleness_s: dict[str, float]):
        self.engine_name = engine_name
        self.staleness_s = dict(staleness_s)
        self._lock = threading.Lock()
        #: reset-proof per-class shed counts (bench/healthz source)
        self.counts = {c: 0 for c in PRIORITIES}

    def sweep(self, queues, now: float | None = None) -> int:
        """Shed every expired item waiting in ``queues``; returns the
        number shed."""
        now = time.perf_counter() if now is None else now
        total = 0
        scale = self._staleness_scale()
        for cls, budget in self.staleness_s.items():
            if budget <= 0:
                continue
            budget *= scale
            expired = queues.pop_expired(cls, now - budget)
            if expired:
                self._fail(cls, expired, now, budget)
                total += len(expired)
        return total

    @staticmethod
    def _staleness_scale() -> float:
        """The control plane's staleness multiplier (<1 sheds earlier
        under sustained overload) — applied at use time so the
        controller's current value always wins and EVAM_TUNE=off costs
        one None-check. Per-class budgets pinned via their env vars
        never reach here scaled: the controller clamps the knob to 1.0
        when any EVAM_SCHED_STALENESS_MS_* is set."""
        op = current_op()
        return op.staleness_scale if op is not None else 1.0

    def shed(self, priority: str, items: list,
             now: float | None = None) -> list:
        """Filter a formed batch: fail items over budget, return the
        fresh survivors (order preserved)."""
        budget = self.staleness_s.get(priority, 0.0)
        if budget <= 0 or not items:
            return items
        budget *= self._staleness_scale()
        now = time.perf_counter() if now is None else now
        cutoff = now - budget
        survivors = [it for it in items if it.t_submit >= cutoff]
        dropped = [it for it in items if it.t_submit < cutoff]
        if dropped:
            self._fail(priority, dropped, now, budget)
        return survivors

    def _fail(self, priority: str, items: list, now: float,
              budget: float) -> None:
        with self._lock:
            self.counts[priority] = self.counts.get(priority, 0) + len(items)
        metrics.inc("evam_sched_shed", value=float(len(items)),
                    labels={"class": priority})
        log.warning(
            "engine %s shed %d stale %s-class frame(s) "
            "(oldest %.0fms > budget %.0fms)",
            self.engine_name, len(items), priority,
            (now - items[0].t_submit) * 1e3, budget * 1e3,
        )
        for it in items:
            exc = ShedError(priority, now - it.t_submit, budget,
                            self.engine_name)
            try:
                it.future.set_exception(exc)
            except Exception:  # noqa: BLE001 — already resolved/cancelled
                pass
