"""Admission control: reject over-capacity starts at the REST edge.

Before this module ``POST /pipelines/{name}/{version}`` admitted
every start unconditionally; overload showed up only later and only
indirectly, as queue growth, watchdog stalls and uniformly blown
latency for EVERY stream. OCTOPINF (PAPERS.md) makes the standard
serving argument: an edge box has a knowable frame budget, and the
honest answer to a start request beyond it is an immediate 503 with
``Retry-After`` — not a silent oversubscription that degrades the
streams already admitted.

The capacity model stays out of the hot loop (tf.data's policy/
mechanism split, PAPERS.md) and is driven by observed engine timings:

    capacity_fps = min over engines of
        batches/s (1 / per-batch device-path seconds, from the PR-1
        stage clock: h2d issue + wait + launch + readback residual)
        x mean occupancy x top bucket

i.e. "what the slowest shared engine delivers if every batch were as
full as the measured mix". Operators can pin it instead with
``EVAM_SCHED_CAPACITY_FPS``. Demand is the sum of admitted streams'
DECLARED fps (request ``fps`` field, default
``EVAM_SCHED_DEFAULT_FPS``). A start is rejected when projected
utilization exceeds the class ceiling — ``EVAM_SCHED_ADMIT_UTIL``
scaled by CLASS_HEADROOM, so ``batch`` is turned away first and
``realtime`` last. A cold hub (no measured batches, no declared
capacity) admits everything: you cannot model what you have not run.
"""

from __future__ import annotations

import math
import threading
import uuid

from evam_tpu.control.state import current_op
from evam_tpu.obs import get_logger, metrics
from evam_tpu.sched.classes import PRIORITIES, SchedConfig

log = get_logger("sched.admission")

#: fraction of admit_util each class may fill: under pressure the
#: ceiling is hit by batch first, then standard, then realtime — the
#: admission-side expression of the class ladder.
CLASS_HEADROOM = {"realtime": 1.0, "standard": 0.85, "batch": 0.6}

#: device-path stages of the per-batch clock (engine/ringbuf.STAGES)
#: that bound the serial service time of one batch. With the
#: pipelined transfer h2d_wait and readback are residuals — honest
#: inputs here: overlapped copy time must not be double-counted
#: against capacity.
_SERVICE_STAGES = ("h2d_issue", "h2d_wait", "launch", "readback")


class AdmissionError(RuntimeError):
    """Start rejected for capacity: HTTP 503 + Retry-After."""

    def __init__(self, priority: str, util: float, ceiling: float,
                 retry_after_s: float):
        self.priority = priority
        self.util = util
        self.ceiling = ceiling
        self.retry_after_s = retry_after_s
        super().__init__(
            f"admission rejected: projected utilization {util:.2f} "
            f"exceeds the {priority}-class ceiling {ceiling:.2f}; "
            f"retry after {retry_after_s:.0f}s"
        )


class _Ticket:
    """One admitted stream's capacity reservation. ``release`` is
    idempotent — it runs from both the instance-finish cleanup chain
    and the start-failure unwind."""

    __slots__ = ("_ctrl", "key", "priority", "fps", "_released")

    def __init__(self, ctrl: "AdmissionController", key: str,
                 priority: str, fps: float):
        self._ctrl = ctrl
        self.key = key
        self.priority = priority
        self.fps = fps
        self._released = False

    def release(self) -> None:
        if self._released:
            return
        self._released = True
        self._ctrl._release(self.key)


class AdmissionController:
    """Tracks admitted demand vs modeled capacity for one hub.

    Duck-types the hub: needs only ``hub.stats()`` (per-engine
    batches / mean_occupancy / stage_ms from EngineStats) and
    ``hub.max_batch``. Disabled (``cfg.enabled`` False or
    ``admit_util`` <= 0) it admits everything but still counts
    per-class admissions so the bench contract line and /scheduler
    stay populated.
    """

    #: admit/release run on stream threads, snapshots on the server
    #: thread — mutations must hold ``_lock`` (lock-discipline pass).
    SHARED_UNDER = {
        "_streams": "_lock",
        "_admitted": "_lock",
        "_rejected": "_lock",
    }

    def __init__(self, hub, cfg: SchedConfig):
        self.hub = hub
        self.cfg = cfg
        self._lock = threading.Lock()
        #: ticket key -> (priority, fps)
        self._streams: dict[str, tuple[str, float]] = {}
        #: reset-proof counters (metrics.reset() in bench windows must
        #: not erase admission history)
        self._admitted = {c: 0 for c in PRIORITIES}
        self._rejected = {c: 0 for c in PRIORITIES}

    # ------------------------------------------------------------- API

    def admit(self, priority: str, fps: float) -> _Ticket:
        """Reserve capacity for one stream or raise AdmissionError."""
        enforcing = self.cfg.enabled and self.cfg.admit_util > 0
        if enforcing:
            cap = self.capacity_fps()
            if cap > 0:
                util = (self.effective_demand_fps() + fps) / cap
                ceiling = self.admit_util() * CLASS_HEADROOM.get(
                    priority, 1.0)
                if util > ceiling:
                    retry_after = self._retry_after_s(util, ceiling)
                    with self._lock:
                        self._rejected[priority] += 1
                    metrics.inc("evam_sched_rejected",
                                labels={"class": priority})
                    log.warning(
                        "rejected %s-class start (%.0f fps): projected "
                        "util %.2f > ceiling %.2f (capacity %.0f fps, "
                        "post-gate demand %.0f fps)", priority, fps, util,
                        ceiling, cap, self.effective_demand_fps(),
                    )
                    raise AdmissionError(priority, util, ceiling,
                                         retry_after)
        key = uuid.uuid4().hex
        with self._lock:
            self._streams[key] = (priority, fps)
            self._admitted[priority] += 1
        metrics.inc("evam_sched_admitted", labels={"class": priority})
        return _Ticket(self, key, priority, fps)

    def _release(self, key: str) -> None:
        with self._lock:
            self._streams.pop(key, None)

    # -------------------------------------------------- capacity model

    def demand_fps(self) -> float:
        with self._lock:
            return sum(fps for _, fps in self._streams.values())

    def admit_util(self) -> float:
        """The live utilization ceiling: the controller's override when
        it has stepped off the static EVAM_SCHED_ADMIT_UTIL (shedding
        observed → tightened; headroom → relaxed back toward static),
        else the configured value. One None-check with EVAM_TUNE=off."""
        op = current_op()
        if op is not None and op.admit_util > 0:
            return op.admit_util
        return self.cfg.admit_util

    def effective_demand_fps(self) -> float:
        """Declared demand minus the motion gate's recent
        skipped-frames/s (stages/gate.py registry): frames the gate is
        provably not submitting don't consume engine capacity, so
        admission headroom grows while scenes are static. The credit
        is a live windowed rate — when a static scene starts moving,
        it decays within the rate window and utilization climbs back
        toward the declared projection."""
        from evam_tpu.stages.gate import registry as gate_registry

        return max(0.0, self.demand_fps() - gate_registry.skipped_fps())

    def capacity_fps(self, live: bool = False) -> float:
        """Declared capacity, the controller's published EWMA, or the
        bottleneck projection from live stats; 0 = unknown (cold hub —
        admit). ``live=True`` skips the controller's published setpoint
        and reports the raw projection — the controller itself reads
        this form, so its capacity EWMA feeds on measurements rather
        than on its own output.

        Fleet-aware aggregation (evam_tpu/fleet/): each stats row
        derives ITS OWN capacity from its own EngineStats (per-chip
        service time × per-chip batch fill), rows are summed within
        their ``group`` (the shards of one engine key are parallel
        capacity, Σ shards — not independent bottlenecks), and the
        fleet capacity is the min ACROSS groups (a pipeline is still
        bounded by its slowest engine kind). Single-chip rows are
        their own group, so EVAM_FLEET=off reproduces the old
        bottleneck-engine number exactly."""
        if self.cfg.capacity_fps > 0:
            return self.cfg.capacity_fps
        if not live:
            op = current_op()
            if op is not None and op.capacity_fps > 0:
                return op.capacity_fps
        group_caps: dict[str, float] = {}
        for key, stats in self.hub.stats().items():
            batches = stats.get("batches")
            if not batches:
                continue
            stage_ms = stats.get("stage_ms") or {}
            service_ms = sum(stage_ms.get(s, 0.0) for s in _SERVICE_STAGES)
            if service_ms <= 0:
                continue
            # honest occupancy (the ragged-batching satellite): real
            # items per dispatched batch, straight from the engine
            # counters. The old mean_occupancy × top-bucket projection
            # overstated capacity whenever traffic landed in small
            # buckets (a FULL bucket-4 batch read as occupancy 1.0 of
            # the 128-slot shape). Stats rows without an item count
            # (declared/faked hubs) keep the legacy projection.
            items = stats.get("items")
            if items:
                per_batch = items / batches
            else:
                occ = max(float(stats.get("mean_occupancy", 0.0)), 1e-3)
                per_batch = occ * self.hub.max_batch
            group = stats.get("group") or key
            group_caps[group] = (group_caps.get(group, 0.0)
                                 + (1e3 / service_ms) * per_batch)
        return min(group_caps.values()) if group_caps else 0.0

    def utilization(self) -> float:
        cap = self.capacity_fps()
        return self.effective_demand_fps() / cap if cap > 0 else 0.0

    @staticmethod
    def _retry_after_s(util: float, ceiling: float) -> float:
        """Back off proportionally to how far past the ceiling the
        projection landed — a mild hint, bounded [1, 30]s."""
        over = util / max(ceiling, 1e-6)
        return float(min(30, max(1, math.ceil(2.0 * over))))

    # ------------------------------------------------- introspection

    def counts(self) -> dict[str, dict[str, int]]:
        """Reset-proof per-class admitted/rejected (bench contract)."""
        with self._lock:
            return {
                "admitted": dict(self._admitted),
                "rejected": dict(self._rejected),
            }

    def streams_by_class(self) -> dict[str, int]:
        out = {c: 0 for c in PRIORITIES}
        with self._lock:
            for prio, _ in self._streams.values():
                out[prio] = out.get(prio, 0) + 1
        return out

    def snapshot(self) -> dict:
        """The /scheduler payload core (fixed keys — route golden)."""
        counts = self.counts()
        return {
            "enabled": bool(self.cfg.enabled),
            # the live ceiling (== the static EVAM_SCHED_ADMIT_UTIL
            # unless the controller has stepped it)
            "admit_util": self.admit_util(),
            "capacity_fps": round(self.capacity_fps(), 1),
            "demand_fps": round(self.demand_fps(), 1),
            # post-gate view (stages/gate.py): what the engines
            # actually see after motion-gated skips
            "effective_demand_fps": round(self.effective_demand_fps(), 1),
            "utilization": round(self.utilization(), 3),
            "streams": self.streams_by_class(),
            "admitted": counts["admitted"],
            "rejected": counts["rejected"],
            "deadline_ms": dict(self.cfg.deadline_ms),
            "staleness_ms": dict(self.cfg.staleness_ms),
        }
