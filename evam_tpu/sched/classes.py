"""Priority classes and per-class work queues for the QoS layer.

OCTOPINF (PAPERS.md) argues that an edge video-analytics server must
schedule by workload class: a realtime camera and a bulk file re-run
have opposite latency/throughput needs, and one global FIFO + one
global batch deadline serves both badly. This module defines the
three classes the scheduler speaks —

* ``realtime`` — live cameras; small batch-formation deadline, tight
  staleness budget, drained first;
* ``standard`` — the default; the pre-sched engine behavior;
* ``batch``    — bulk/offline re-runs; big batch-formation deadline
  (fill large buckets), generous staleness budget, first to shed.

— plus the two data structures the rest of ``evam_tpu.sched`` builds
on: ``SchedConfig`` (the resolved knob set, kept OUT of the hot loop
— tf.data's lesson from PAPERS.md: policy is data, the loop only
reads it) and ``ClassQueues`` (per-class FIFOs with a
starvation-proof realtime-first pick, replacing the single unbounded
``BatchEngine._queue``).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Any

#: scheduling classes, highest priority first (drain order)
PRIORITIES = ("realtime", "standard", "batch")

DEFAULT_PRIORITY = "standard"

#: consecutive times a non-empty class may be passed over before it
#: MUST be picked (the starvation guard of the weighted pick). The
#: ratios are the effective drain weights under contention:
#: realtime gets ~4x standard and ~12x batch.
STARVATION_LIMITS = {"standard": 4, "batch": 12}


def coerce_priority(value: Any, default: str = DEFAULT_PRIORITY) -> str:
    """Best-effort priority normalization for restored state
    (evam_tpu/state checkpoints): a sched class decoded from a
    possibly stale or corrupted checkpoint must never raise — an
    unknown value falls back to ``default`` instead."""
    if isinstance(value, str) and value.strip().lower() in PRIORITIES:
        return value.strip().lower()
    return default


def validate_priority(value: Any) -> str:
    """Normalize + validate a request/spec ``priority`` value."""
    if not isinstance(value, str):
        raise ValueError(
            f"priority must be one of {'|'.join(PRIORITIES)}, "
            f"got {value!r}")
    prio = value.strip().lower()
    if prio not in PRIORITIES:
        raise ValueError(
            f"unknown priority {value!r}; valid values: "
            f"{'|'.join(PRIORITIES)}")
    return prio


@dataclasses.dataclass(frozen=True)
class SchedConfig:
    """Resolved scheduler knobs (config/settings.py ``SchedSettings``
    → this runtime view; see that class for the EVAM_SCHED_* env
    surface). Frozen: the dispatcher and admission controller read it
    lock-free."""

    enabled: bool = True
    #: projected-utilization ceiling for admission (0 disables
    #: admission control; classes get headroom-scaled ceilings —
    #: sched/admission.py CLASS_HEADROOM)
    admit_util: float = 0.85
    #: operator-declared serving capacity in frames/s (0 = derive it
    #: from live EngineStats; see AdmissionController.capacity_fps)
    capacity_fps: float = 0.0
    #: assumed per-stream demand when a start request declares no fps
    default_fps: float = 30.0
    #: per-class batch-formation deadline (ms) — replaces the single
    #: EVAM_BATCH_DEADLINE_MS when scheduling is on
    deadline_ms: dict[str, float] = dataclasses.field(
        default_factory=lambda: {
            "realtime": 4.0, "standard": 8.0, "batch": 25.0})
    #: per-class staleness budget (ms): frames older than this at
    #: dispatch are shed (0 = never shed that class)
    staleness_ms: dict[str, float] = dataclasses.field(
        default_factory=lambda: {
            "realtime": 200.0, "standard": 1000.0, "batch": 5000.0})

    def deadline_s(self, priority: str) -> float:
        return self.deadline_ms.get(priority, 8.0) / 1e3

    def staleness_s(self) -> dict[str, float]:
        return {c: ms / 1e3 for c, ms in self.staleness_ms.items()}

    @classmethod
    def from_settings(cls, s,
                      standard_deadline_ms: float | None = None
                      ) -> "SchedConfig":
        """Build from config.settings.SchedSettings.

        ``standard_deadline_ms``: the engine-level
        EVAM_BATCH_DEADLINE_MS. Unless the operator explicitly set
        EVAM_SCHED_DEADLINE_MS_STANDARD, the ``standard`` class
        follows it — turning the scheduler on must not silently
        repeal a tuned global batch deadline (the satellite audit's
        point: that knob must keep reaching the dispatcher)."""
        std = s.deadline_ms_standard
        if (standard_deadline_ms is not None
                and "deadline_ms_standard" not in s.model_fields_set):
            std = standard_deadline_ms
        return cls(
            enabled=s.enabled,
            admit_util=s.admit_util,
            capacity_fps=s.capacity_fps,
            default_fps=s.default_fps,
            deadline_ms={
                "realtime": s.deadline_ms_realtime,
                "standard": std,
                "batch": s.deadline_ms_batch,
            },
            staleness_ms={
                "realtime": s.staleness_ms_realtime,
                "standard": s.staleness_ms_standard,
                "batch": s.staleness_ms_batch,
            },
        )

    @classmethod
    def disabled(cls) -> "SchedConfig":
        return cls(enabled=False, admit_util=0.0)


class ClassQueues:
    """Per-class FIFO queues with a starvation-proof realtime-first
    pick — the sched-mode replacement for ``BatchEngine._queue``.

    Items must expose ``t_submit`` (perf_counter at enqueue) and
    ``future`` (failable on drain) — the engine's ``_WorkItem``
    contract. All state is guarded by one condition variable; the
    enqueue path does a deque append + notify, so submit-side cost
    stays O(1).

    Pick policy: the highest-priority non-empty class wins, EXCEPT
    that a class passed over ``STARVATION_LIMITS[cls]`` consecutive
    times is served first (lowest class checked first so ``batch``
    cannot starve behind a starving ``standard``). Under saturation
    this degenerates to weighted round-robin with weights ~12/3/1;
    with an idle realtime lane it is exactly realtime-first.
    """

    #: every queue mutation happens inside ``with self._cv:`` — the
    #: condition variable doubles as the state lock (lock-discipline
    #: pass enforces it).
    SHARED_UNDER = {
        "_q": "_cv",
        "_starve": "_cv",
        "_closed": "_cv",
    }

    def __init__(self, starvation_limits: dict[str, int] | None = None):
        self._limits = dict(starvation_limits or STARVATION_LIMITS)
        self._cv = threading.Condition()
        self._q: dict[str, deque] = {c: deque() for c in PRIORITIES}
        self._starve = {c: 0 for c in PRIORITIES}
        self._closed = False

    # ------------------------------------------------------ submit side

    def put(self, priority: str, item) -> None:
        with self._cv:
            if self._closed:
                raise RuntimeError("scheduler queues are closed")
            self._q[priority].append(item)
            self._cv.notify_all()

    # -------------------------------------------------- dispatcher side

    def pick(self, timeout: float) -> str | None:
        """Block until any class has work (or ``timeout``); return the
        chosen class per the starvation-aware priority policy, or
        None on timeout / closed-and-empty."""
        deadline = time.perf_counter() + timeout
        with self._cv:
            while True:
                nonempty = [c for c in PRIORITIES if self._q[c]]
                if nonempty:
                    break
                if self._closed:
                    return None
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    return None
                self._cv.wait(remaining)
            chosen = None
            # most-starved lowest class first: batch must not starve
            # behind a starving standard
            for c in reversed(PRIORITIES):
                limit = self._limits.get(c)
                if limit and c in nonempty and self._starve[c] >= limit:
                    chosen = c
                    break
            if chosen is None:
                chosen = nonempty[0]
            for c in nonempty:
                if c != chosen:
                    self._starve[c] += 1
            self._starve[chosen] = 0
            return chosen

    def collect(self, priority: str, max_n: int,
                deadline_s: float) -> list:
        """Form one batch from ``priority``'s queue: wait until it
        holds ``max_n`` items or until ``deadline_s`` past the HEAD
        item's submit time (matches the slot ring's first-write
        deadline semantics — a backlogged queue dispatches a full
        bucket immediately, a trickle dispatches at the deadline)."""
        with self._cv:
            dq = self._q[priority]
            if not dq:
                return []
            deadline = dq[0].t_submit + deadline_s
            while len(dq) < max_n and not self._closed:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                self._cv.wait(remaining)
            return [dq.popleft() for _ in range(min(max_n, len(dq)))]

    def pop_expired(self, priority: str, min_t_submit: float) -> list:
        """Remove and return the head items submitted before
        ``min_t_submit`` — the oldest-first shed primitive (FIFO order
        means every expired item sits at the head; the fresh tail
        survives — freshest-frame-wins)."""
        out = []
        with self._cv:
            dq = self._q[priority]
            while dq and dq[0].t_submit < min_t_submit:
                out.append(dq.popleft())
        return out

    # ------------------------------------------------------- lifecycle

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def drain(self) -> list:
        """Remove and return every queued item (stop/stall/abandon:
        the engine fails their futures)."""
        out = []
        with self._cv:
            for dq in self._q.values():
                out.extend(dq)
                dq.clear()
        return out

    # -------------------------------------------------- introspection

    def empty(self) -> bool:
        with self._cv:
            return not any(self._q.values())

    def depth(self) -> int:
        with self._cv:
            return sum(len(dq) for dq in self._q.values())

    def depth_by_class(self) -> dict[str, int]:
        with self._cv:
            return {c: len(dq) for c, dq in self._q.items()}

    def oldest_age_s(self, now: float | None = None) -> float:
        now = time.perf_counter() if now is None else now
        with self._cv:
            heads = [dq[0].t_submit for dq in self._q.values() if dq]
        return max(0.0, now - min(heads)) if heads else 0.0
