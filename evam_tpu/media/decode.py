"""Decode worker: one lightweight thread per stream.

The reference gives every stream a full GStreamer thread graph; here
a stream costs one decode thread that feeds the shared TPU engines.
Includes the per-stream supervision the reference lacks (SURVEY.md
§5.3): source errors trigger reconnect-with-backoff instead of
killing the engine, and a dead stream never takes the batch engine
down.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable

from evam_tpu.media.source import FrameEvent, VideoSource
from evam_tpu.obs import get_logger, metrics

log = get_logger("media.decode")


def drop_oldest_put(q: "queue.Queue", item) -> int:
    """``put_nowait`` with drop-oldest eviction (live-stream
    backpressure); returns how many queued items were evicted. Shared
    by DecodeWorker and the DecodePool so the accounting semantics
    can't diverge."""
    dropped = 0
    while True:
        try:
            q.put_nowait(item)
            return dropped
        except queue.Full:
            try:
                q.get_nowait()
                dropped += 1
            except queue.Empty:
                pass


class DecodeWorker:
    """Reads a source on a daemon thread into a bounded queue.

    ``on_frame`` (if given) is called inline on the decode thread and
    its return ignored; otherwise frames land in ``self.queue``.
    Bounded queue = backpressure: when the engine falls behind, frames
    drop oldest-first (live-stream semantics) rather than growing
    memory — the behavior knob is ``drop_when_full``.
    """

    def __init__(
        self,
        stream_id: str,
        source_factory: Callable[[], VideoSource],
        maxsize: int = 8,
        drop_when_full: bool = True,
        max_restarts: int = 3,
        restart_backoff_s: float = 0.5,
        on_frame: Callable[[FrameEvent], None] | None = None,
    ):
        self.stream_id = stream_id
        self.source_factory = source_factory
        self.queue: queue.Queue[FrameEvent | None] = queue.Queue(maxsize=maxsize)
        self.drop_when_full = drop_when_full
        self.max_restarts = max_restarts
        self.restart_backoff_s = restart_backoff_s
        self.on_frame = on_frame
        self.frames_decoded = 0
        self.frames_dropped = 0
        self.error: str | None = None
        self._source: VideoSource | None = None
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"decode-{stream_id}", daemon=True
        )

    def start(self) -> "DecodeWorker":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._source is not None:
            self._source.close()
        self._thread.join(timeout=10)

    @property
    def finished(self) -> bool:
        return not self._thread.is_alive()

    def _emit(self, ev: FrameEvent) -> None:
        self.frames_decoded += 1
        metrics.inc("evam_frames_decoded", labels={"stream": self.stream_id})
        if self.on_frame is not None:
            self.on_frame(ev)
            return
        if self.drop_when_full:
            dropped = drop_oldest_put(self.queue, ev)
            if dropped:
                self.frames_dropped += dropped
                metrics.inc(
                    "evam_frames_dropped", dropped,
                    labels={"stream": self.stream_id, "stage": "decode"})
        else:
            while not self._stop.is_set():
                try:
                    self.queue.put(ev, timeout=0.5)
                    return
                except queue.Full:
                    continue

    def _run(self) -> None:
        restarts = 0
        while not self._stop.is_set():
            try:
                self._source = self.source_factory()
                t_d = time.perf_counter()
                for ev in self._source.frames():
                    # time spent inside the source generator ≈ host
                    # decode cost; rides the event into the frame
                    # trace's "decode" span (obs/trace.py)
                    ev.decode_s = time.perf_counter() - t_d
                    if self._stop.is_set():
                        break
                    self._emit(ev)
                    t_d = time.perf_counter()
                break  # clean EOS
            except Exception as exc:  # noqa: BLE001 — supervised restart
                restarts += 1
                self.error = str(exc)
                metrics.inc("evam_stream_errors", labels={"stream": self.stream_id})
                if restarts > self.max_restarts or self._stop.is_set():
                    log.error(
                        "stream %s failed permanently after %d restarts: %s",
                        self.stream_id, restarts - 1, exc,
                    )
                    break
                backoff = self.restart_backoff_s * (2 ** (restarts - 1))
                log.warning(
                    "stream %s source error (%s); restart %d/%d in %.1fs",
                    self.stream_id, exc, restarts, self.max_restarts, backoff,
                )
                time.sleep(backoff)
        if self.on_frame is None:
            self.queue.put(None)  # EOS sentinel
