"""Shared decode pool: M workers decoding N streams (N ≫ M).

Per-stream decoding (`DecodeWorker`, one thread per stream; FFmpeg
additionally spawning its own thread team per open capture) is the
reference's model — decodebin gives every GStreamer pipeline its own
streaming threads. At 64 concurrent 1080p captures on one host that
oversubscribes: 64 reader threads × FFmpeg's per-capture decoder
threads contend for cores that the batch engine's dispatch path also
needs (VERDICT r3 item 10; INGEST.md's 38–62-core H.264 row assumed
per-stream threads scale linearly).

The pool inverts it: a fixed worker team round-robins over all
registered streams, decoding ONE frame per scheduling turn. Total
decode threads = ``workers`` regardless of stream count, fairness
comes from FIFO turn order among ready streams, and realtime streams
are paced by per-stream due-times in a heap. A stream is held by at
most one worker at a time (it leaves the heap while being serviced),
so captures never see concurrent access.

Measured on this 1-vCPU container (``tools/bench_decode_pool.py``,
8×MPEG-4 1080p streams): the pool matches per-stream threads within
noise on aggregate throughput (factor ≈ 1.0 — the GIL already
serializes cv2 reads here) while cutting decode threads 8→1; the win
it buys at deployment scale is bounding thread count (64 streams: 64
threads + FFmpeg teams → ``workers`` ≈ cores) so decode stops
competing with the engine's host path. See INGEST.md "Decode-pool
consolidation".
"""

from __future__ import annotations

import heapq
import itertools
import queue
import threading
import time
from typing import Callable, Iterator

from evam_tpu.media.decode import drop_oldest_put
from evam_tpu.media.source import FrameEvent, VideoSource
from evam_tpu.obs import get_logger, metrics

log = get_logger("media.pool")


class PooledStream:
    """One stream's registration in the pool.

    Mirrors ``DecodeWorker``'s consumption contract: a bounded
    ``queue`` with drop-oldest backpressure, a ``frames()`` iterator
    facade (so it can stand in for ``VideoSource.frames()`` in
    ``StreamRunner``), and decoded/dropped counters.
    """

    def __init__(self, stream_id: str,
                 source_factory: Callable[[], VideoSource],
                 maxsize: int = 8, drop_when_full: bool = True,
                 fps: float | None = None,
                 on_frame: Callable[[FrameEvent], None] | None = None):
        self.stream_id = stream_id
        self.source_factory = source_factory
        self.queue: queue.Queue[FrameEvent | None] = queue.Queue(
            maxsize=maxsize)
        self.drop_when_full = drop_when_full
        self.fps = fps        # None: free-running (file-rate) stream
        self.on_frame = on_frame
        self.frames_decoded = 0
        self.frames_dropped = 0
        self.error: str | None = None
        self.finished = False
        #: per-stream restart budget; set by DecodePool.add_stream
        self.max_restarts = 0
        self._source: VideoSource | None = None
        self._iter: Iterator[FrameEvent] | None = None
        self._removed = False
        #: lossless mode: a decoded frame waiting for queue space.
        #: A full queue must NEVER block a shared pool worker — the
        #: frame parks here and the stream is rescheduled instead.
        self._pending: FrameEvent | None = None
        #: lossless mode: clean EOS waiting for queue space (the
        #: drop-to-make-room EOS in _finish would lose a real frame)
        self._eos_pending = False

    # -------------------------------------------------- consumer side

    def frames(self) -> Iterator[FrameEvent]:
        """Drain the pool's output queue until EOS — drop-in for
        ``VideoSource.frames()`` on the consuming thread."""
        while True:
            ev = self.queue.get()
            if ev is None:
                return
            yield ev

    def close(self) -> None:
        self._removed = True
        src = self._source
        if src is not None:
            try:
                src.close()
            except Exception:  # noqa: BLE001
                pass

    # ----------------------------------------------------- pool side

    def _emit(self, ev: FrameEvent) -> None:
        self.frames_decoded += 1
        metrics.inc("evam_frames_decoded",
                    labels={"stream": self.stream_id})
        if self.on_frame is not None:
            self.on_frame(ev)
            return
        if self.drop_when_full:
            dropped = drop_oldest_put(self.queue, ev)
            if dropped:
                # every pool drop is consumer-side by construction
                # (lossless mode parks instead of dropping): the
                # downstream runner/engine is behind — same stage
                # attribution as DemuxStream.frames_dropped_downstream
                self.frames_dropped += dropped
                metrics.inc("evam_frames_dropped", dropped,
                            labels={"stream": self.stream_id,
                                    "stage": "downstream"})
        else:
            # lossless: park the frame; the pool retries the put on
            # the stream's next turn (never blocks a shared worker)
            try:
                self.queue.put_nowait(ev)
            except queue.Full:
                self._pending = ev

    def _finish(self, error: str | None = None) -> None:
        """Terminal transition (removal / pool stop / drop-mode
        error): deliver EOS without ever blocking a pool worker,
        evicting a queued frame if it must. Lossless streams route
        BOTH clean-EOS and decode-error EOS through ``_eos_pending``
        scheduling in the pool instead, so queued frames survive."""
        self.error = error
        self.finished = True
        if self.on_frame is None:
            drop_oldest_put(self.queue, None)


class DecodePool:
    """Fixed team of decode workers multiplexing many streams.

    ``workers`` bounds TOTAL decode threads (the whole point); each
    scheduling turn decodes one frame of the most-overdue ready
    stream. Streams added with ``fps`` are paced (a turn is scheduled
    every 1/fps); free-running streams re-enter the ready set
    immediately, FIFO-fair among themselves.
    """

    def __init__(self, workers: int = 2, max_restarts: int = 3,
                 restart_backoff_s: float = 0.5):
        if workers < 1:
            raise ValueError("workers must be >= 1")
        self.max_restarts = max_restarts
        self.restart_backoff_s = restart_backoff_s
        #: (due_time, turn_seq, stream, restarts_left)
        self._heap: list = []
        #: cumulative counters of terminated streams (same fold-on-
        #: retire pattern as RtspDemux: long-lived servers churn
        #: streams, dead objects must not accumulate)
        self._retired_decoded = 0
        self._retired_dropped = 0
        self._turn = itertools.count()
        self._cv = threading.Condition()
        self._stop = False
        self._threads = [
            threading.Thread(target=self._work, name=f"decode-pool-{i}",
                             daemon=True)
            for i in range(workers)
        ]
        for t in self._threads:
            t.start()

    # ------------------------------------------------------- registry

    def add_stream(self, stream_id: str,
                   source_factory: Callable[[], VideoSource],
                   maxsize: int = 8, drop_when_full: bool = True,
                   fps: float | None = None, on_frame=None,
                   max_restarts: int | None = None) -> PooledStream:
        """``max_restarts=None`` uses the pool default; pass 0 when an
        outer supervisor (StreamInstance retry) owns reconnection."""
        ps = PooledStream(stream_id, source_factory, maxsize,
                          drop_when_full, fps, on_frame)
        ps.max_restarts = (self.max_restarts if max_restarts is None
                           else max_restarts)
        with self._cv:
            if self._stop:
                raise RuntimeError("pool is stopped")
            heapq.heappush(
                self._heap,
                (time.monotonic(), next(self._turn), ps,
                 ps.max_restarts))
            self._cv.notify()
        return ps

    def stop(self) -> None:
        with self._cv:
            self._stop = True
            pending = [e[2] for e in self._heap]
            self._heap.clear()
            self._cv.notify_all()
        for ps in pending:
            ps.close()
            ps._finish("pool stopped")
            self._fold(ps)
        for t in self._threads:
            t.join(timeout=10)

    def _fold(self, ps: PooledStream) -> None:
        """Fold a terminated stream's counters into the cumulative
        totals (called exactly once per stream: terminal _service
        return, stop-race cleanup, or pool stop)."""
        with self._cv:
            self._retired_decoded += ps.frames_decoded
            self._retired_dropped += ps.frames_dropped

    def stats(self) -> dict:
        """Worker/stream counts + cumulative frame counters for
        /healthz (same shape family as ``RtspDemux.stats``). Pool
        drops are all consumer-side (``dropped_downstream`` ==
        ``dropped``): lossless streams park instead of dropping, and
        drop-when-full only engages when the runner/engine lags —
        decode-bound loss can't happen inside the pool itself."""
        with self._cv:
            live = [e[2] for e in self._heap]
            decoded = self._retired_decoded
            dropped = self._retired_dropped
        decoded += sum(s.frames_decoded for s in live)
        dropped += sum(s.frames_dropped for s in live)
        return {
            "workers": len(self._threads),
            "queued_streams": len(live),
            "decoded": decoded,
            "dropped": dropped,
            "dropped_downstream": dropped,
        }

    # -------------------------------------------------------- workers

    def _work(self) -> None:
        while True:
            with self._cv:
                while not self._stop and (
                        not self._heap
                        or self._heap[0][0] > time.monotonic()):
                    if self._heap:
                        self._cv.wait(
                            max(0.0,
                                self._heap[0][0] - time.monotonic()))
                    else:
                        self._cv.wait()
                if self._stop:
                    return
                due, _seq, ps, restarts_left = heapq.heappop(self._heap)
            requeue = self._service(ps, restarts_left)
            if requeue is not None:
                with self._cv:
                    if not self._stop:
                        heapq.heappush(self._heap, requeue)
                        self._cv.notify()
                        continue
                ps.close()
                ps._finish("pool stopped")
                self._fold(ps)
            else:
                self._fold(ps)  # terminal: stream left the pool

    def _service(self, ps: PooledStream, restarts_left: int):
        """Decode one frame of ``ps``; return its next heap entry or
        None when the stream is done."""
        if ps._removed:
            ps._finish(ps.error)
            return None
        if ps._eos_pending:
            try:
                ps.queue.put_nowait(None)
            except queue.Full:
                return (time.monotonic() + 0.02, next(self._turn),
                        ps, restarts_left)
            ps.finished = True
            return None
        if ps._pending is not None:
            # lossless backlog: retry the parked frame before
            # decoding anything new (preserves order)
            try:
                ps.queue.put_nowait(ps._pending)
                ps._pending = None
            except queue.Full:
                return (time.monotonic() + 0.02, next(self._turn),
                        ps, restarts_left)
        try:
            if ps._iter is None:
                ps._source = ps.source_factory()
                ps._iter = iter(ps._source.frames())
            ev = next(ps._iter, None)
        except Exception as exc:  # noqa: BLE001 — supervised restart
            if ps._removed:
                ps._finish(None)
                return None
            metrics.inc("evam_stream_errors",
                        labels={"stream": ps.stream_id})
            # close the failed capture before dropping the handle:
            # single-connection sources (RTSP cameras) reject the
            # reconnect while the dead connection is still open, and
            # FFmpeg's decoder threads leak with it
            ps._iter = None
            src, ps._source = ps._source, None
            if src is not None:
                try:
                    src.close()
                except Exception:  # noqa: BLE001
                    pass
            if restarts_left <= 0:
                log.error("pooled stream %s failed permanently: %s",
                          ps.stream_id, exc)
                if ps.on_frame is None and not ps.drop_when_full:
                    # lossless: the consumer must still see every
                    # frame decoded before the failure — deliver EOS
                    # through the same rescheduling as clean EOS
                    # instead of evicting the oldest queued frame
                    ps.error = str(exc)
                    ps._eos_pending = True
                    return (time.monotonic() + 0.02,
                            next(self._turn), ps, 0)
                ps._finish(str(exc))
                return None
            # budget is per-stream (add_stream override), not the
            # pool default — a mismatch would corrupt the backoff
            used = ps.max_restarts - restarts_left + 1
            backoff = self.restart_backoff_s * (2 ** (used - 1))
            log.warning(
                "pooled stream %s source error (%s); restart %d/%d "
                "in %.1fs", ps.stream_id, exc, used,
                ps.max_restarts, backoff)
            return (time.monotonic() + backoff, next(self._turn), ps,
                    restarts_left - 1)
        if ev is None:            # clean EOS
            if ps.on_frame is None and not ps.drop_when_full:
                # lossless: EOS must queue without displacing a frame
                try:
                    ps.queue.put_nowait(None)
                except queue.Full:
                    ps._eos_pending = True
                    return (time.monotonic() + 0.02,
                            next(self._turn), ps, restarts_left)
                ps.finished = True
                return None
            ps._finish(None)
            return None
        ps._emit(ev)
        # free-running streams re-enter at NOW (not 0.0): an overdue
        # paced stream must still win its turn, else free-runners
        # starve paced ones
        now = time.monotonic()
        due = now + 1.0 / ps.fps if ps.fps else now
        if ps._pending is not None:
            # consumer is behind: don't decode ahead, retry the put
            due = max(due, now + 0.02)
        return (due, next(self._turn), ps, restarts_left)
