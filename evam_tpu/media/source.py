"""Frame sources — the ``{auto_source}`` resolution layer.

The reference resolves ``{auto_source}`` per request to urisourcebin /
webcam / GigE / appsrc elements feeding decodebin (SURVEY.md §2b
"Template expansion"; request ``source.type`` values uri / webcam /
gige / application). Here each source yields decoded BGR uint8 frames
with nanosecond PTS — decode runs on host CPU (cv2/FFmpeg), the TPU
engine consumes batches downstream.
"""

from __future__ import annotations

import queue
import time
from dataclasses import dataclass
from typing import Iterator, Protocol

import numpy as np

from evam_tpu.obs import get_logger

log = get_logger("media.source")

NS = 1_000_000_000


@dataclass
class FrameEvent:
    """One decoded frame (or audio chunk) entering the pipeline."""

    frame: np.ndarray | None  # BGR uint8 [H, W, 3]; None for audio
    pts_ns: int        # presentation timestamp, ns (reference meta
                       # 'timestamp' field is ns — charts/README.md:117)
    seq: int
    audio: np.ndarray | None = None  # S16LE mono 16 kHz chunk
    #: host decode cost in seconds (set by DecodeWorker) — becomes the
    #: frame trace's "decode" span (obs/trace.py)
    decode_s: float | None = None


class VideoSource(Protocol):
    def frames(self) -> Iterator[FrameEvent]: ...
    def close(self) -> None: ...


class FileSource:
    """File / RTSP / HTTP source via OpenCV (FFmpeg-backed).

    Counterpart of uridecodebin/decodebin in every reference template
    (e.g. pipelines/object_detection/person/pipeline.json:4).
    """

    def __init__(self, uri: str, loop: bool = False, realtime: bool = False):
        self.uri = uri
        self.loop = loop
        self.realtime = realtime
        self._cap = None
        self._closed = False

    def _open(self):
        import cv2

        path = self.uri
        for prefix in ("file://",):
            if path.startswith(prefix):
                path = path[len(prefix):]
        cap = cv2.VideoCapture(path)
        if not cap.isOpened():
            raise IOError(f"cannot open source {self.uri}")
        return cap

    def frames(self) -> Iterator[FrameEvent]:
        self._cap = self._open()
        fps = self._cap.get(5) or 30.0  # CAP_PROP_FPS
        if fps <= 0 or fps > 1000:
            fps = 30.0
        frame_ns = int(NS / fps)
        seq = 0
        t_wall = time.perf_counter()
        while not self._closed:
            ok, frame = self._cap.read()
            if not ok:
                if self.loop and not self._closed:
                    self._cap.release()
                    self._cap = self._open()
                    continue
                break
            yield FrameEvent(frame=frame, pts_ns=seq * frame_ns, seq=seq)
            seq += 1
            if self.realtime:
                t_wall += 1.0 / fps
                delay = t_wall - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
        if self._cap is not None:
            self._cap.release()

    def close(self) -> None:
        self._closed = True


class SyntheticSource:
    """Deterministic generated stream (``synthetic://`` URIs).

    Replaces the reference's sample videos (resources/*.mp4, absent
    from this environment — .MISSING_LARGE_BLOBS) for tests and load
    benchmarks: a moving bright square on a dark background, cheap to
    generate at any resolution/fps.
    """

    def __init__(
        self,
        width: int = 768,
        height: int = 432,
        fps: float = 30.0,
        count: int | None = None,
        realtime: bool = False,
        seed: int = 0,
    ):
        self.width, self.height, self.fps = width, height, fps
        self.count = count
        self.realtime = realtime
        self.seed = seed
        self._closed = False

    @classmethod
    def from_uri(cls, uri: str, realtime: bool = False) -> "SyntheticSource":
        # synthetic://640x480@30?count=100&seed=3
        body = uri.split("://", 1)[1]
        params = {}
        if "?" in body:
            body, q = body.split("?", 1)
            params = dict(p.split("=", 1) for p in q.split("&") if "=" in p)
        size, _, fps = body.partition("@")
        w, _, h = size.partition("x")
        return cls(
            width=int(w or 768),
            height=int(h or 432),
            fps=float(fps or 30),
            count=int(params["count"]) if "count" in params else None,
            seed=int(params.get("seed", 0)),
            realtime=realtime,
        )

    def frames(self) -> Iterator[FrameEvent]:
        frame_ns = int(NS / self.fps)
        base = np.full((self.height, self.width, 3), 16, np.uint8)
        sq = max(8, min(self.height, self.width) // 8)
        seq = 0
        t_wall = time.perf_counter()
        while not self._closed and (self.count is None or seq < self.count):
            frame = base.copy()
            x = (self.seed * 37 + seq * 7) % max(1, self.width - sq)
            y = (self.seed * 53 + seq * 5) % max(1, self.height - sq)
            frame[y : y + sq, x : x + sq] = (64, 160, 240)
            yield FrameEvent(frame=frame, pts_ns=seq * frame_ns, seq=seq)
            seq += 1
            if self.realtime:
                t_wall += 1.0 / self.fps
                delay = t_wall - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)

    def close(self) -> None:
        self._closed = True


class WebcamSource(FileSource):
    """Live camera source (reference docker/run.sh webcam probe,
    :107-113): cv2 camera index instead of a URI."""

    def __init__(self, index: int = 0):
        super().__init__(uri=str(index), realtime=False)
        self.index = index

    def _open(self):
        import cv2

        cap = cv2.VideoCapture(self.index)
        if not cap.isOpened():
            raise IOError(f"cannot open camera {self.index}")
        return cap


def gige_frame_to_bgr(data: np.ndarray, pixel_format: str) -> np.ndarray:
    """GenICam pixel-format → BGR uint8 (pure helper, unit-testable
    without camera hardware)."""
    import cv2

    fmt = pixel_format.lower()
    if fmt in ("mono8", "mono"):
        return cv2.cvtColor(data, cv2.COLOR_GRAY2BGR)
    if fmt.startswith("bayerrg"):
        return cv2.cvtColor(data, cv2.COLOR_BAYER_RG2BGR)
    if fmt.startswith("bayergb"):
        return cv2.cvtColor(data, cv2.COLOR_BAYER_GB2BGR)
    if fmt.startswith("bayergr"):
        return cv2.cvtColor(data, cv2.COLOR_BAYER_GR2BGR)
    if fmt.startswith("bayerbg"):
        return cv2.cvtColor(data, cv2.COLOR_BAYER_BG2BGR)
    if fmt in ("rgb8", "rgb"):
        return cv2.cvtColor(data, cv2.COLOR_RGB2BGR)
    if fmt in ("bgr8", "bgr"):
        return data
    raise ValueError(f"unsupported GenICam pixel format {pixel_format!r}")


class GigeSource:
    """GenICam / GigE Vision camera source — the gencamsrc counterpart
    (reference resolves ``{auto_source}`` to gencamsrc for gige
    cameras; EII compose wires GENICAM + ``GST_DEBUG gencamsrc``,
    reference eii/docker-compose.yml:59).

    Backends, tried in order:

    1. **harvesters** (GenICam GenTL consumer; needs a ``.cti``
       producer from the camera vendor, path via ``cti`` property or
       ``GENICAM_GENTL64_PATH``);
    2. **cv2 + GStreamer** (``aravissrc``/``gencamsrc`` pipeline
       string) when OpenCV is built with GStreamer.

    Neither ships in this image (no egress), so construction is lazy
    and ``frames()`` raises a clear error naming both options — the
    request contract (``source.type: "gige"`` + serial/pixel-format
    properties) is stable either way.
    """

    def __init__(self, serial: str | None = None,
                 pixel_format: str = "BayerRG8",
                 cti: str | None = None):
        self.serial = serial
        self.pixel_format = pixel_format
        self.cti = cti
        self._ia = None        # harvesters image acquirer
        self._cap = None       # cv2 GStreamer capture
        self._closed = False

    def _open(self) -> None:
        import os as _os

        h = None
        try:
            from harvesters.core import Harvester  # type: ignore

            h = Harvester()
            cti = self.cti or _os.environ.get("GENICAM_GENTL64_PATH")
            if cti:
                for p in cti.split(":"):
                    h.add_file(p)
            h.update()
            self._ia = h.create_image_acquirer(
                serial_number=self.serial) if self.serial else \
                h.create_image_acquirer(0)
            self._harvester = h
            self._ia.start_acquisition()
            return
        except Exception as exc:  # noqa: BLE001 — installed-but-no-device
            # falls through to GStreamer: harvesters without a .cti
            # producer or with no camera raises here, not ImportError
            if h is not None:
                h.reset()
            if not isinstance(exc, ImportError):
                log.info("harvesters backend unavailable: %s", exc)

        import cv2

        if "GStreamer" in cv2.getBuildInformation():
            sel = f"serial={self.serial} " if self.serial else ""
            gst = (
                f"aravissrc {sel}! videoconvert ! "
                "video/x-raw,format=BGR ! appsink"
            )
            cap = cv2.VideoCapture(gst, cv2.CAP_GSTREAMER)
            if cap.isOpened():
                self._cap = cap
                return
        raise RuntimeError(
            "no GigE backend available: install a GenICam GenTL "
            "producer (.cti) + the 'harvesters' package, or an OpenCV "
            "build with GStreamer and aravissrc (reference parity: "
            "gencamsrc in the DL Streamer image)"
        )

    def frames(self) -> Iterator[FrameEvent]:
        self._open()
        seq = 0
        packed = self.pixel_format.lower() in ("rgb8", "rgb", "bgr8", "bgr")
        while not self._closed:
            if self._ia is not None:
                with self._ia.fetch_buffer() as buf:
                    comp = buf.payload.components[0]
                    shape = (
                        (comp.height, comp.width, 3) if packed
                        else (comp.height, comp.width)
                    )
                    # copy INSIDE the with-block: fetch_buffer requeues
                    # the GenTL buffer on exit, so a zero-copy view
                    # would be overwritten by the next capture
                    data = np.array(comp.data.reshape(shape), copy=True)
                frame = gige_frame_to_bgr(data, self.pixel_format)
            else:
                ok, frame = self._cap.read()
                if not ok:
                    break
            yield FrameEvent(frame=frame, pts_ns=time.monotonic_ns(), seq=seq)
            seq += 1

    def close(self) -> None:
        self._closed = True
        if self._ia is not None:
            self._ia.stop_acquisition()
            self._ia.destroy()
            self._harvester.reset()
        if self._cap is not None:
            self._cap.release()


class AppSource:
    """Application-injected frames (appsrc / msgbus-source counterpart,
    reference evas/subscriber.py:96-106 wraps raw bytes into the
    pipeline; here callers push numpy frames or raw BGR bytes)."""

    def __init__(self, maxsize: int = 64):
        self._queue: queue.Queue = queue.Queue(maxsize=maxsize)
        self._closed = False
        self._seq = 0

    def push(self, frame: np.ndarray, pts_ns: int | None = None) -> None:
        """Never blocks: when the consumer stalls (or died), the oldest
        queued frame is dropped — live-stream semantics, and it keeps
        feeder threads (msgbus ingest) and shutdown deadlock-free."""
        if self._closed:
            raise RuntimeError("source closed")
        if pts_ns is None:
            pts_ns = time.monotonic_ns()
        ev = FrameEvent(frame=frame, pts_ns=pts_ns, seq=self._seq)
        while True:
            try:
                self._queue.put_nowait(ev)
                break
            except queue.Full:
                try:
                    self._queue.get_nowait()
                except queue.Empty:
                    pass
        self._seq += 1

    def push_raw(self, data: bytes, width: int, height: int,
                 pts_ns: int | None = None) -> None:
        frame = np.frombuffer(data, np.uint8).reshape(height, width, 3)
        self.push(frame, pts_ns)

    def end(self) -> None:
        # _closed doubles as the EOS signal: frames() re-checks it on
        # every queue timeout, so EOS delivery cannot be lost even if a
        # concurrent push()'s drop-oldest get_nowait() consumes the
        # in-band None sentinel (the sentinel is only a wake-up
        # accelerator, not the source of truth).
        self._closed = True
        try:
            self._queue.put_nowait(None)
        except queue.Full:
            pass  # frames() will notice _closed on its next timeout

    def frames(self) -> Iterator[FrameEvent]:
        while True:
            try:
                ev = self._queue.get(timeout=0.2)
            except queue.Empty:
                if self._closed:
                    break
                continue
            if ev is None:
                if self._closed:
                    break
                continue  # stale sentinel displaced by a late push
            yield ev

    def close(self) -> None:
        if not self._closed:
            self.end()


def create_source(source_cfg: dict, realtime: bool = False) -> VideoSource:
    """Resolve a request ``source`` object into a VideoSource.

    Mirrors the reference request schema
    ``{"source": {"uri": ..., "type": "uri"}}``
    (charts/templates/NOTES.txt:9-13).
    """
    stype = source_cfg.get("type", "uri")
    if stype in ("uri", "file"):
        uri = source_cfg["uri"]
        if uri.startswith("synthetic://"):
            return SyntheticSource.from_uri(uri, realtime=realtime)
        if uri.startswith("synthetic-audio://"):
            from evam_tpu.media.audio import SyntheticAudioSource

            return SyntheticAudioSource.from_uri(uri)
        if uri.endswith(".wav"):
            from evam_tpu.media.audio import WavSource

            return WavSource(
                uri,
                loop=bool(source_cfg.get("loop", False)),
                realtime=realtime,
            )
        return FileSource(
            uri,
            loop=bool(source_cfg.get("loop", False)),
            realtime=realtime or bool(source_cfg.get("realtime", False)),
        )
    if stype == "webcam":
        # cv2 needs an int index for camera devices, not a string path
        device = source_cfg.get("device", 0)
        return WebcamSource(int(device))
    if stype == "application":
        return AppSource(maxsize=int(source_cfg.get("queue-size", 64)))
    if stype == "gige":
        # reference {auto_source} resolves gige → gencamsrc
        # (eii/docker-compose.yml:59); properties mirror gencamsrc's
        return GigeSource(
            serial=source_cfg.get("serial"),
            pixel_format=source_cfg.get("pixel-format", "BayerRG8"),
            cti=source_cfg.get("cti"),
        )
    raise ValueError(f"unsupported source type '{stype}'")
