from evam_tpu.media.source import (
    AppSource,
    FileSource,
    FrameEvent,
    SyntheticSource,
    VideoSource,
    create_source,
)
from evam_tpu.media.decode import DecodeWorker

__all__ = [
    "AppSource",
    "FileSource",
    "FrameEvent",
    "SyntheticSource",
    "VideoSource",
    "create_source",
    "DecodeWorker",
]
