from evam_tpu.media.source import (
    AppSource,
    FileSource,
    FrameEvent,
    SyntheticSource,
    VideoSource,
    create_source,
)
from evam_tpu.media.decode import DecodeWorker
from evam_tpu.media.demux import DemuxStream, RtspDemux
from evam_tpu.media.pool import DecodePool, PooledStream

__all__ = [
    "DemuxStream",
    "RtspDemux",
    "AppSource",
    "FileSource",
    "FrameEvent",
    "SyntheticSource",
    "VideoSource",
    "create_source",
    "DecodeWorker",
    "DecodePool",
    "PooledStream",
]
