"""Async RTSP/RTP demux: N live streams on ONE selector thread.

The decode pool (`media/pool.py`) consolidates *free-running* decode
but honestly scopes itself away from live sources: under cv2's
blocking-read model a live RTSP stream pins a reader thread per
camera, so the 64-live-stream north star (BASELINE.md config 5) meant
64 threads plus FFmpeg's per-capture teams on the serving host —
the reference hides the same problem inside GStreamer's bounded
streaming threads (reference
pipelines/object_detection/person/pipeline.json:4 `uridecodebin`).

This module removes the per-stream reader by OWNING the socket
(VERDICT r4 item 3): an RTSP client handshake
(DESCRIBE/SETUP/PLAY) per stream, then every connection registers
with one ``selectors`` loop that parses TCP-interleaved RTP
(RFC 2326 §10.12) and depacketizes RTP/JPEG (RFC 2435) incrementally
— no thread ever blocks on a socket. Complete JPEG frames are handed
to a small decode-worker team (cv2.imdecode) that preserves
per-stream order by servicing at most one frame per stream at a
time. Total threads for N streams = 1 selector + ``decode_workers``,
regardless of N.

Scope: TCP-interleaved transport, two payload formats negotiated from
the DESCRIBE SDP — RTP/MJPEG (RFC 2435: in-band Q≥128 tables and the
Q<128 derive-from-Q path) as ``publish/rtsp.py`` speaks it, so an
evam-tpu deployment can fan its own re-streams back in and any
RFC-2435 camera works; and RTP/H.264 (RFC 6184 packetization-mode 1:
single NAL / STAP-A / FU-A reassembly into Annex-B access units) for
INTRA-ONLY streams — the in-image decoder is cv2's bundled FFmpeg
behind a per-AU file shim (see ``_decode_h264_au``), so inter-coded
cameras stay on the per-stream reader path.

Consumer contract matches ``PooledStream``: ``frames()`` iterator on
a bounded queue with live drop-oldest semantics, decoded/dropped
counters, ``error``/``finished`` terminal state.
"""

from __future__ import annotations

import queue as queue_mod
import re
import selectors
import socket
import struct
import threading
from collections import deque
from urllib.parse import urlparse

import numpy as np

from evam_tpu.media.decode import drop_oldest_put
from evam_tpu.media.source import FrameEvent
from evam_tpu.obs import get_logger, metrics

log = get_logger("media.demux")

RTP_CLOCK = 90_000

# ---------------------------------------------------------------- JFIF
# Standard JPEG Huffman tables (ITU-T T.81 Annex K.3) — RFC 2435
# streams omit them (every compliant encoder uses these unless it
# optimizes coding, which cv2/libjpeg does not by default), so the
# receiver re-emits them when rebuilding a decodable JFIF.

_DC_LUM_BITS = bytes([0, 1, 5, 1, 1, 1, 1, 1, 1, 0, 0, 0, 0, 0, 0, 0])
_DC_LUM_VALS = bytes(range(12))
_DC_CHM_BITS = bytes([0, 3, 1, 1, 1, 1, 1, 1, 1, 1, 1, 0, 0, 0, 0, 0])
_DC_CHM_VALS = bytes(range(12))
_AC_LUM_BITS = bytes([0, 2, 1, 3, 3, 2, 4, 3, 5, 5, 4, 4, 0, 0, 1, 0x7D])
_AC_LUM_VALS = bytes([
    0x01, 0x02, 0x03, 0x00, 0x04, 0x11, 0x05, 0x12,
    0x21, 0x31, 0x41, 0x06, 0x13, 0x51, 0x61, 0x07,
    0x22, 0x71, 0x14, 0x32, 0x81, 0x91, 0xA1, 0x08,
    0x23, 0x42, 0xB1, 0xC1, 0x15, 0x52, 0xD1, 0xF0,
    0x24, 0x33, 0x62, 0x72, 0x82, 0x09, 0x0A, 0x16,
    0x17, 0x18, 0x19, 0x1A, 0x25, 0x26, 0x27, 0x28,
    0x29, 0x2A, 0x34, 0x35, 0x36, 0x37, 0x38, 0x39,
    0x3A, 0x43, 0x44, 0x45, 0x46, 0x47, 0x48, 0x49,
    0x4A, 0x53, 0x54, 0x55, 0x56, 0x57, 0x58, 0x59,
    0x5A, 0x63, 0x64, 0x65, 0x66, 0x67, 0x68, 0x69,
    0x6A, 0x73, 0x74, 0x75, 0x76, 0x77, 0x78, 0x79,
    0x7A, 0x83, 0x84, 0x85, 0x86, 0x87, 0x88, 0x89,
    0x8A, 0x92, 0x93, 0x94, 0x95, 0x96, 0x97, 0x98,
    0x99, 0x9A, 0xA2, 0xA3, 0xA4, 0xA5, 0xA6, 0xA7,
    0xA8, 0xA9, 0xAA, 0xB2, 0xB3, 0xB4, 0xB5, 0xB6,
    0xB7, 0xB8, 0xB9, 0xBA, 0xC2, 0xC3, 0xC4, 0xC5,
    0xC6, 0xC7, 0xC8, 0xC9, 0xCA, 0xD2, 0xD3, 0xD4,
    0xD5, 0xD6, 0xD7, 0xD8, 0xD9, 0xDA, 0xE1, 0xE2,
    0xE3, 0xE4, 0xE5, 0xE6, 0xE7, 0xE8, 0xE9, 0xEA,
    0xF1, 0xF2, 0xF3, 0xF4, 0xF5, 0xF6, 0xF7, 0xF8,
    0xF9, 0xFA,
])
_AC_CHM_BITS = bytes([0, 2, 1, 2, 4, 4, 3, 4, 7, 5, 4, 4, 0, 1, 2, 0x77])
_AC_CHM_VALS = bytes([
    0x00, 0x01, 0x02, 0x03, 0x11, 0x04, 0x05, 0x21,
    0x31, 0x06, 0x12, 0x41, 0x51, 0x07, 0x61, 0x71,
    0x13, 0x22, 0x32, 0x81, 0x08, 0x14, 0x42, 0x91,
    0xA1, 0xB1, 0xC1, 0x09, 0x23, 0x33, 0x52, 0xF0,
    0x15, 0x62, 0x72, 0xD1, 0x0A, 0x16, 0x24, 0x34,
    0xE1, 0x25, 0xF1, 0x17, 0x18, 0x19, 0x1A, 0x26,
    0x27, 0x28, 0x29, 0x2A, 0x35, 0x36, 0x37, 0x38,
    0x39, 0x3A, 0x43, 0x44, 0x45, 0x46, 0x47, 0x48,
    0x49, 0x4A, 0x53, 0x54, 0x55, 0x56, 0x57, 0x58,
    0x59, 0x5A, 0x63, 0x64, 0x65, 0x66, 0x67, 0x68,
    0x69, 0x6A, 0x73, 0x74, 0x75, 0x76, 0x77, 0x78,
    0x79, 0x7A, 0x82, 0x83, 0x84, 0x85, 0x86, 0x87,
    0x88, 0x89, 0x8A, 0x92, 0x93, 0x94, 0x95, 0x96,
    0x97, 0x98, 0x99, 0x9A, 0xA2, 0xA3, 0xA4, 0xA5,
    0xA6, 0xA7, 0xA8, 0xA9, 0xAA, 0xB2, 0xB3, 0xB4,
    0xB5, 0xB6, 0xB7, 0xB8, 0xB9, 0xBA, 0xC2, 0xC3,
    0xC4, 0xC5, 0xC6, 0xC7, 0xC8, 0xC9, 0xCA, 0xD2,
    0xD3, 0xD4, 0xD5, 0xD6, 0xD7, 0xD8, 0xD9, 0xDA,
    0xE2, 0xE3, 0xE4, 0xE5, 0xE6, 0xE7, 0xE8, 0xE9,
    0xEA, 0xF2, 0xF3, 0xF4, 0xF5, 0xF6, 0xF7, 0xF8,
    0xF9, 0xFA,
])


def _dht(table_class: int, table_id: int, bits: bytes,
         vals: bytes) -> bytes:
    body = bytes([(table_class << 4) | table_id]) + bits + vals
    return b"\xff\xc4" + struct.pack(">H", 2 + len(body)) + body


# Q < 128 sends NO tables on the wire (RFC 2435 §4.2): both ends
# derive them from Q by scaling the T.81 Annex K.1 example tables
# with libjpeg's quality curve (RFC 2435 Appendix A is that exact
# formula) and storing them in the JPEG zigzag order DQT uses.
# Validated byte-for-byte against cv2/libjpeg output in
# tests/test_media.py::test_qtables_from_q_match_libjpeg.

_ZIGZAG = (
    0, 1, 8, 16, 9, 2, 3, 10, 17, 24, 32, 25, 18, 11, 4, 5,
    12, 19, 26, 33, 40, 48, 41, 34, 27, 20, 13, 6, 7, 14, 21, 28,
    35, 42, 49, 56, 57, 50, 43, 36, 29, 22, 15, 23, 30, 37, 44, 51,
    58, 59, 52, 45, 38, 31, 39, 46, 53, 60, 61, 54, 47, 55, 62, 63)
_K1_LUMA = (
    16, 11, 10, 16, 24, 40, 51, 61,
    12, 12, 14, 19, 26, 58, 60, 55,
    14, 13, 16, 24, 40, 57, 69, 56,
    14, 17, 22, 29, 51, 87, 80, 62,
    18, 22, 37, 56, 68, 109, 103, 77,
    24, 35, 55, 64, 81, 104, 113, 92,
    49, 64, 78, 87, 103, 121, 120, 101,
    72, 92, 95, 98, 112, 100, 103, 99)
_K1_CHROMA = (
    17, 18, 24, 47, 99, 99, 99, 99,
    18, 21, 26, 66, 99, 99, 99, 99,
    24, 26, 56, 99, 99, 99, 99, 99,
    47, 66, 99, 99, 99, 99, 99, 99,
    99, 99, 99, 99, 99, 99, 99, 99,
    99, 99, 99, 99, 99, 99, 99, 99,
    99, 99, 99, 99, 99, 99, 99, 99,
    99, 99, 99, 99, 99, 99, 99, 99)


def rfc2435_qtables(q: int) -> list[bytes]:
    """Quantization tables for an RFC 2435 Q value in 1..127
    (128..255 carry tables in-band instead)."""
    q = max(1, min(int(q), 99))          # 100..127 reserved: clamp
    scale = 5000 // q if q < 50 else 200 - 2 * q

    def mk(base: tuple) -> bytes:
        return bytes(
            min(255, max(1, (base[_ZIGZAG[k]] * scale + 50) // 100))
            for k in range(64))

    return [mk(_K1_LUMA), mk(_K1_CHROMA)]


def reconstruct_jfif(width: int, height: int, qtables: list[bytes],
                     scan: bytes, subsampling: int = 1) -> bytes:
    """Rebuild a decodable baseline JFIF from RFC 2435 pieces — the
    inverse of ``publish/rtsp.parse_jpeg``. ``subsampling`` is the
    RFC 2435 type: 0 → 4:2:2, 1 → 4:2:0."""
    out = bytearray(b"\xff\xd8")                        # SOI
    for i, tbl in enumerate(qtables[:2]):
        out += b"\xff\xdb" + struct.pack(">H", 3 + len(tbl))
        out += bytes([i]) + tbl                          # Pq=0, Tq=i
    cq = 1 if len(qtables) > 1 else 0
    lum_sampling = 0x22 if subsampling == 1 else 0x21
    out += (b"\xff\xc0" + struct.pack(">HBHHB", 17, 8, height, width, 3)
            + bytes([1, lum_sampling, 0])                # Y
            + bytes([2, 0x11, cq])                       # Cb
            + bytes([3, 0x11, cq]))                      # Cr
    out += _dht(0, 0, _DC_LUM_BITS, _DC_LUM_VALS)
    out += _dht(1, 0, _AC_LUM_BITS, _AC_LUM_VALS)
    out += _dht(0, 1, _DC_CHM_BITS, _DC_CHM_VALS)
    out += _dht(1, 1, _AC_CHM_BITS, _AC_CHM_VALS)
    out += (b"\xff\xda" + struct.pack(">HB", 12, 3)
            + bytes([1, 0x00, 2, 0x11, 3, 0x11, 0, 0x3F, 0]))
    out += scan
    out += b"\xff\xd9"                                   # EOI
    return bytes(out)


def _decode_h264_au(au: bytes):
    """Decode ONE self-contained Annex-B access unit (SPS+PPS+IDR).

    The image has no ffmpeg binary and no libav Python binding — the
    only H.264 decoder reachable in-process is cv2.VideoCapture's
    bundled FFmpeg, which reads files/URLs. Each AU is written to a
    tmpfs-backed file and opened as a one-frame elementary stream.
    This is honest about its scope: it only works when every AU is
    self-contained, i.e. INTRA-ONLY streams (all-I camera mode, or
    media/h264.py output); inter-coded streams need a stateful
    decoder feed and stay on the per-stream reader path. ~1 open per
    frame costs ~ms on tmpfs — fine for the all-I use case, recorded
    in INGEST.md."""
    import os
    import tempfile

    import cv2

    d = "/dev/shm" if os.path.isdir("/dev/shm") else None
    fd, path = tempfile.mkstemp(suffix=".h264", dir=d)
    try:
        with os.fdopen(fd, "wb") as fh:
            fh.write(au)
        cap = cv2.VideoCapture(path)
        ok, img = cap.read()
        cap.release()
        return img if ok else None
    finally:
        try:
            os.unlink(path)
        except OSError:
            pass


def _parse_sdp_media(sdp: str) -> dict:
    """Pull the video payload type + codec + control URL out of a
    DESCRIBE SDP. Static PT 26 = RFC 2435 JPEG; dynamic PTs resolve
    via rtpmap (H264/90000 → the RFC 6184 path). ``a=control:`` is
    tracked at both session level (before any m= line) and video
    media level — media-level wins (RFC 2326 §C.1; real cameras
    advertise trackID-style control URLs, ADVICE r5 item 1)."""
    pt = 26
    codec = "jpeg"
    session_control: str | None = None
    media_control: str | None = None
    in_media = False
    in_video = False
    for line in sdp.splitlines():
        line = line.strip()
        if line.startswith("m="):
            in_media = True
            in_video = line.startswith("m=video")
        if line.lower().startswith("a=control:"):
            val = line.split(":", 1)[1].strip()
            if not in_media:
                session_control = val
            elif in_video and media_control is None:
                media_control = val
            continue
        if line.startswith("m=video"):
            parts = line.split()
            if len(parts) >= 4:
                try:
                    pt = int(parts[3])
                except ValueError:
                    pass
            codec = "jpeg" if pt == 26 else "unknown"
        elif line.lower().startswith(f"a=rtpmap:{pt} "):
            enc = line.split(" ", 1)[1].split("/")[0].strip().upper()
            if enc == "H264":
                codec = "h264"
            elif enc in ("JPEG", "MJPEG"):
                codec = "jpeg"
        elif (line.lower().startswith(f"a=fmtp:{pt} ")
              and "packetization-mode" in line):
            # only mode 0/1 (non-interleaved) is reassembled here;
            # mode 2 (STAP-B/MTAP/FU-B) must be rejected at
            # add_stream, not discovered as a silent stall
            mode = line.split("packetization-mode=", 1)[1]
            if mode.split(";")[0].strip() not in ("0", "1"):
                codec = "unknown"
    return {"pt": pt, "codec": codec,
            "control": media_control or session_control}


def _resolve_control(base: str, control: str | None) -> str:
    """SETUP target from the SDP control attribute, resolved against
    the Content-Base/request URL (RFC 2326 §C.1.1):

    * absolute control → use it verbatim;
    * ``*`` → aggregate control on the base itself;
    * relative (``trackID=1``, ``streamid=0``) → appended to base;
    * absent → the legacy ``streamid=0`` guess, which matches our own
      RtspServer and the streamid-style servers the old hardcoded
      path worked against.
    """
    if control is None:
        return base.rstrip("/") + "/streamid=0"
    if control == "*":
        return base.rstrip("/")
    if "://" in control:
        return control
    return base.rstrip("/") + "/" + control.lstrip("/")


# -------------------------------------------------------------- stream

class DemuxStream:
    """One live stream's registration — same consumer contract as
    ``PooledStream`` (bounded queue, ``frames()`` facade, counters),
    fed by the demux selector + decode workers instead of a reader
    thread."""

    def __init__(self, stream_id: str, url: str, maxsize: int = 8,
                 max_pending: int = 4):
        self.stream_id = stream_id
        self.url = url
        self.queue: queue_mod.Queue = queue_mod.Queue(maxsize=maxsize)
        self.frames_decoded = 0
        #: stage-classified drop counters (VERDICT r5 weak #5 asks
        #: the live-soak drop budget to be ATTRIBUTED, not pooled):
        #: * ``frames_dropped_decode`` — queue-side, taken on the
        #:   selector thread under the demux lock: the shared decode
        #:   workers are behind (decode-bound);
        #: * ``frames_dropped_downstream`` — emit-side, touched only
        #:   by the single decode worker servicing this stream at a
        #:   time (per-stream order guarantee), so it needs no lock —
        #:   which also fixes the old unlocked ``frames_dropped += 1``
        #:   racing the locked increment (ADVICE r5 item 3): the
        #:   consumer (runner/engine) is behind (engine-bound).
        self.frames_dropped_decode = 0
        self.frames_dropped_downstream = 0
        self.error: str | None = None
        self.finished = False
        self.sock: socket.socket | None = None
        self._demux: "RtspDemux | None" = None
        # ---- selector-side state (touched only by the demux thread)
        self._buf = bytearray()      # raw TCP bytes
        self._scan = bytearray()     # current frame's entropy scan
        self._qtables: list[bytes] = []
        self._qtable_q = -1          # Q the derived tables were built for
        self._dims = (0, 0)
        self._last_ts32 = -1         # RTP timestamp unwrap state
        self._ts_ext = 0
        self._codec = "jpeg"         # from the DESCRIBE SDP
        self._pt = 26
        #: interleaved channel pair from the server's Transport reply
        #: (SETUP may assign other than the requested 0-1)
        self._rtp_ch = 0
        self._rtcp_ch = 1
        # ---- RFC 6184 reassembly state (h264 streams)
        self._nals: list[bytes] = []   # current access unit's NALs
        self._fu: bytearray | None = None   # in-flight FU-A NAL
        self._sps: bytes | None = None      # cached parameter sets
        self._pps: bytes | None = None
        self._frame_corrupt = False
        self._seq = 0
        # ---- decode-side state (guarded by the demux lock)
        self._jpegs: deque = deque()          # complete frames waiting
        self._max_pending = max_pending
        self._scheduled = False
        self._eof = False
        self._removed = False
        #: selector-side teardown already ran (close may be requested
        #: from several paths — instance.stop AND the runner's
        #: finally both close; teardown must be idempotent)
        self._gone = False

    @property
    def frames_dropped(self) -> int:
        """Total drops (both stages) — the ``PooledStream`` contract."""
        return self.frames_dropped_decode + self.frames_dropped_downstream

    def frames(self):
        """Drain until EOS — drop-in for ``VideoSource.frames()``."""
        while True:
            ev = self.queue.get()
            if ev is None:
                return
            yield ev

    def close(self) -> None:
        """Consumer-side teardown. MUST route through the selector
        thread: closing a registered fd here would silently drop it
        from the epoll set (no EOF event ever fires) AND leave a
        stale entry in the selector's fd map that poisons the next
        stream whose socket reuses the fd number."""
        self._removed = True
        demux = self._demux
        if demux is not None:
            demux._request_close(self)

    # pool-side emit (decode workers; at most one per stream at a
    # time, so the downstream counter has a single writer)
    def _emit(self, ev: FrameEvent) -> None:
        self.frames_decoded += 1
        metrics.inc("evam_frames_decoded",
                    labels={"stream": self.stream_id})
        dropped = drop_oldest_put(self.queue, ev)   # live: newest wins
        if dropped:
            self.frames_dropped_downstream += dropped
            metrics.inc("evam_frames_dropped", dropped,
                        labels={"stream": self.stream_id,
                                "stage": "downstream"})

    def _finish(self, error: str | None) -> None:
        if self.finished:
            return
        self.error = self.error or error
        self.finished = True
        drop_oldest_put(self.queue, None)


# --------------------------------------------------------------- demux

class RtspDemux:
    """N live RTSP streams through 1 selector thread + M decoders.

    ``add_stream`` performs the (blocking, timeout-bounded) RTSP
    handshake, then hands the socket to the selector; everything
    after that is non-blocking. Per-stream frame order is preserved:
    a stream has at most one frame in decode at any moment.
    """

    def __init__(self, decode_workers: int = 2,
                 connect_timeout_s: float = 5.0):
        if decode_workers < 1:
            raise ValueError("decode_workers must be >= 1")
        self.connect_timeout_s = connect_timeout_s
        self._sel = selectors.DefaultSelector()
        self._lock = threading.Lock()
        self._streams: list[DemuxStream] = []
        #: counters of retired (finished) streams so stats() stays
        #: cumulative without keeping dead DemuxStream objects alive
        self._retired_decoded = 0
        self._retired_dropped_decode = 0
        self._retired_dropped_downstream = 0
        #: consumer-side closes waiting for the selector thread
        self._to_close: list[DemuxStream] = []
        self._ready: "queue_mod.Queue" = queue_mod.Queue()
        self._stop = threading.Event()
        # self-pipe so add_stream/stop can wake the selector
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._sel.register(self._wake_r, selectors.EVENT_READ, None)
        self._sel_thread = threading.Thread(
            target=self._select_loop, name="rtsp-demux", daemon=True)
        self._sel_thread.start()
        self._workers = [
            threading.Thread(target=self._decode_loop,
                             name=f"rtsp-demux-dec-{i}", daemon=True)
            for i in range(decode_workers)
        ]
        for t in self._workers:
            t.start()

    # ------------------------------------------------------- lifecycle

    def add_stream(self, url: str, stream_id: str | None = None,
                   maxsize: int = 8) -> DemuxStream:
        if self._stop.is_set():
            raise RuntimeError("demux is stopped")
        ps = DemuxStream(stream_id or url, url, maxsize=maxsize)
        ps._demux = self
        sock, residue, media = self._handshake(url)
        if media["codec"] == "unknown":
            sock.close()
            raise IOError(
                f"unsupported RTSP media (payload type {media['pt']}) "
                "— the demux speaks RFC 2435 JPEG and RFC 6184 H.264; "
                "unset EVAM_RTSP_DEMUX_WORKERS for this camera")
        ps._codec = media["codec"]
        ps._pt = media["pt"]
        ps._rtp_ch, ps._rtcp_ch = media.get("channels", (0, 1))
        sock.setblocking(False)
        ps.sock = sock
        ps._buf.extend(residue)   # interleaved data behind the PLAY 200
        with self._lock:
            # re-check: stop() may have run during the blocking
            # handshake — registering on a closed selector raises
            # ValueError and would leak the registry entry
            if self._stop.is_set():
                sock.close()
                raise RuntimeError("demux is stopped")
            self._streams.append(ps)
        try:
            self._sel.register(sock, selectors.EVENT_READ, ps)
            self._wake_w.send(b"x")
        except (ValueError, KeyError, OSError) as exc:
            with self._lock:
                if ps in self._streams:
                    self._streams.remove(ps)
            sock.close()
            raise RuntimeError(f"demux is stopping: {exc}") from exc
        return ps

    def stop(self) -> None:
        self._stop.set()
        try:
            self._wake_w.send(b"x")
        except OSError:
            pass
        self._sel_thread.join(timeout=10)
        self._ready.put(None)
        for t in self._workers:
            t.join(timeout=10)
        with self._lock:
            streams = list(self._streams)
        for ps in streams:
            # the selector thread is gone: direct teardown is safe now
            ps._removed = True
            sock = ps.sock
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
            ps._finish("demux stopped")
            self._retire(ps)

    def _request_close(self, ps: DemuxStream) -> None:
        """Hand a consumer-side close to the selector thread (epoll
        teardown must happen where the registration lives). Falls
        back to direct teardown when the selector is already gone."""
        with self._lock:
            if not self._stop.is_set():
                self._to_close.append(ps)
                try:
                    self._wake_w.send(b"x")
                except OSError:
                    pass
                return
        # demux stopped: no selector thread to do it
        sock = ps.sock
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        ps._finish(ps.error)
        self._retire(ps)

    def _retire(self, ps: DemuxStream) -> None:
        """Drop a FINISHED stream from the registry, folding its
        counters into the cumulative totals (long-lived servers churn
        streams; dead objects must not accumulate)."""
        with self._lock:
            if ps in self._streams:
                self._streams.remove(ps)
                self._retired_decoded += ps.frames_decoded
                self._retired_dropped_decode += ps.frames_dropped_decode
                self._retired_dropped_downstream += (
                    ps.frames_dropped_downstream)

    # ------------------------------------------------------- handshake

    def _handshake(self, url: str) -> tuple[socket.socket, bytes, dict]:
        """Minimal RTSP client: DESCRIBE → SETUP (TCP interleaved) →
        PLAY against ``rtsp://host:port/path``. Returns the socket,
        any interleaved bytes that trailed the PLAY 200, and media
        info from the SDP ({"codec": "jpeg"|"h264", "pt": int})."""
        u = urlparse(url)
        host, port = u.hostname, u.port or 554
        sock = socket.create_connection(
            (host, port), timeout=self.connect_timeout_s)
        sock.settimeout(self.connect_timeout_s)
        buf = bytearray()

        def request(method: str, target: str, cseq: int,
                    extra: str = "") -> dict:
            msg = f"{method} {target} RTSP/1.0\r\nCSeq: {cseq}\r\n"
            if extra:
                msg += extra if extra.endswith("\r\n") else extra + "\r\n"
            msg += "\r\n"
            sock.sendall(msg.encode("latin-1"))
            while b"\r\n\r\n" not in buf:
                chunk = sock.recv(4096)
                if not chunk:
                    raise IOError("rtsp server closed during handshake")
                buf.extend(chunk)
            head, _, rest = bytes(buf).partition(b"\r\n\r\n")
            del buf[:len(head) + 4]
            lines = head.decode("latin-1").split("\r\n")
            if " 200 " not in lines[0] + " ":
                raise IOError(f"rtsp {method} failed: {lines[0]}")
            headers = {
                k.strip().lower(): v.strip()
                for k, v in (l.split(":", 1) for l in lines[1:]
                             if ":" in l)
            }
            # drain any Content-Length body (the SDP)
            body_len = int(headers.get("content-length", "0"))
            while len(buf) < body_len:
                chunk = sock.recv(4096)
                if not chunk:
                    raise IOError("rtsp server closed mid-body")
                buf.extend(chunk)
            headers["_body"] = bytes(buf[:body_len]).decode("latin-1")
            del buf[:body_len]
            return headers

        try:
            d = request("DESCRIBE", url, 1, "Accept: application/sdp")
            media = _parse_sdp_media(d.get("_body", ""))
            # control URL per the SDP, resolved against Content-Base
            # (real cameras advertise trackID=N; hardcoding
            # streamid=0 failed their SETUP — ADVICE r5 item 1)
            base = d.get("content-base") or d.get("content-location") or url
            h = request(
                "SETUP", _resolve_control(base, media.get("control")), 2,
                "Transport: RTP/AVP/TCP;unicast;interleaved=0-1")
            # honor the server's channel assignment instead of
            # assuming the requested 0-1 came back
            m = re.search(r"interleaved=(\d+)-(\d+)",
                          h.get("transport", ""))
            media["channels"] = ((int(m.group(1)), int(m.group(2)))
                                 if m else (0, 1))
            session = h.get("session", "0").split(";")[0]
            request("PLAY", url, 3, f"Session: {session}")
        except Exception:
            sock.close()
            raise
        # interleaved data may already trail the PLAY 200 in the same
        # TCP segments — hand it back so no bytes are lost
        return sock, bytes(buf), media

    # -------------------------------------------------------- selector

    def _select_loop(self) -> None:
        while not self._stop.is_set():
            events = self._sel.select(timeout=0.5)
            # consumer-side closes, executed HERE so unregister
            # precedes close (epoll registration hygiene)
            with self._lock:
                to_close, self._to_close = self._to_close, []
            for ps in to_close:
                if ps.sock is not None:
                    try:
                        self._socket_gone(ps.sock, ps, None)
                    except Exception:  # noqa: BLE001
                        log.exception("demux close of %s failed",
                                      ps.stream_id)
            for key, _mask in events:
                if key.data is None:            # wake pipe
                    try:
                        self._wake_r.recv(4096)
                    except OSError:
                        pass
                    continue
                try:
                    self._service_socket(key.fileobj, key.data)
                except Exception:  # noqa: BLE001
                    # one stream's parse must never kill ingest for
                    # every stream — fail that stream, keep looping
                    log.exception("demux stream %s failed",
                                  key.data.stream_id)
                    try:
                        self._socket_gone(
                            key.fileobj, key.data, "demux parse error")
                    except Exception:  # noqa: BLE001
                        pass
        # teardown: unregister everything
        for key in list(self._sel.get_map().values()):
            try:
                self._sel.unregister(key.fileobj)
            except (KeyError, OSError):
                pass
        self._sel.close()

    def _service_socket(self, sock, ps: DemuxStream) -> None:
        try:
            data = sock.recv(65536)
        except (BlockingIOError, InterruptedError):
            return
        except OSError as exc:
            self._socket_gone(sock, ps,
                              None if ps._removed else str(exc))
            return
        if not data:
            self._socket_gone(
                sock, ps, None if ps._removed else "rtsp EOF")
            return
        ps._buf.extend(data)
        self._drain_buffer(ps)

    def _socket_gone(self, sock, ps: DemuxStream,
                     error: str | None) -> None:
        if ps._gone:
            return
        ps._gone = True
        try:
            self._sel.unregister(sock)
        except (KeyError, ValueError, OSError):
            # ValueError: fd already -1 (closed) — unregister of an
            # already-torn-down socket must never kill the selector
            pass
        try:
            sock.close()
        except OSError:
            pass
        if error:
            metrics.inc("evam_stream_errors",
                        labels={"stream": ps.stream_id})
        with self._lock:
            ps._eof = True
            ps.error = ps.error or error
            deliver_now = not ps._scheduled and not ps._jpegs
        if deliver_now:
            ps._finish(ps.error)
            self._retire(ps)

    def _drain_buffer(self, ps: DemuxStream) -> None:
        buf = ps._buf
        while True:
            if len(buf) < 4:
                return
            if buf[0] != 0x24:                  # not '$': RTSP msg
                end = bytes(buf).find(b"\r\n\r\n")
                if end < 0:
                    if len(buf) > 65536:
                        self._socket_gone(
                            ps.sock, ps, "rtsp framing lost")
                    return
                del buf[:end + 4]               # skip server notices
                continue
            length = struct.unpack(">H", buf[2:4])[0]
            if len(buf) < 4 + length:
                return
            channel = buf[1]
            pkt = bytes(buf[4:4 + length])
            del buf[:4 + length]
            if channel == ps._rtp_ch:      # RTCP rides ps._rtcp_ch
                self._on_rtp(ps, pkt)

    def _on_rtp(self, ps: DemuxStream, pkt: bytes) -> None:
        if len(pkt) < 12 or pkt[0] >> 6 != 2:
            return
        pt = pkt[1] & 0x7F
        if pt != ps._pt:
            # not the negotiated payload: fail LOUDLY — silently
            # dropping a codec-switched camera's packets would leave
            # the instance RUNNING forever with zero frames and no
            # visible error
            self._socket_gone(
                ps.sock, ps,
                f"unexpected RTP payload type {pt} (SDP negotiated "
                f"{ps._pt}/{ps._codec}) — unset "
                "EVAM_RTSP_DEMUX_WORKERS for this camera (per-stream "
                "reader handles other codecs via FFmpeg)")
            return
        marker = pkt[1] >> 7
        ts32 = struct.unpack(">I", pkt[4:8])[0]
        # unwrap the 32-bit RTP timestamp (90 kHz wraps every ~13.25 h
        # — a 24/7 camera must not publish a regressing pts)
        if ps._last_ts32 >= 0:
            delta = (ts32 - ps._last_ts32) & 0xFFFFFFFF
            if delta >= 0x80000000:          # backward (reorder) move
                delta -= 1 << 32
            ps._ts_ext += delta
        else:
            ps._ts_ext = ts32
        ps._last_ts32 = ts32
        ts = ps._ts_ext
        # honor the header-extension (X) and padding (P) bits — a
        # camera sending extensions would otherwise have the payload
        # header misparsed on EVERY packet, a zero-frames silent
        # stall (ADVICE r5 item 2). Malformed lengths fail LOUDLY,
        # matching the unsupported-feature policy below.
        off = 12 + 4 * (pkt[0] & 0x0F)          # skip CSRCs
        end = len(pkt)
        if pkt[0] & 0x20:                       # P: trailing padding
            pad = pkt[-1]
            if pad == 0 or off + pad > end:
                self._socket_gone(
                    ps.sock, ps,
                    f"malformed RTP padding (pad={pad}, len={end})")
                return
            end -= pad
        if pkt[0] & 0x10:                       # X: header extension
            if off + 4 > end:
                self._socket_gone(
                    ps.sock, ps, "truncated RTP header extension")
                return
            xwords = struct.unpack(">H", pkt[off + 2:off + 4])[0]
            off += 4 + 4 * xwords
            if off > end:
                self._socket_gone(
                    ps.sock, ps,
                    f"RTP header extension overruns packet "
                    f"({4 * xwords} bytes)")
                return
        payload = pkt[off:end]
        if ps._codec == "h264":
            self._on_rtp_h264(ps, payload, bool(marker), ts)
            return
        if len(payload) < 8:
            return
        # RFC 2435 main JPEG header
        offset = (payload[1] << 16) | (payload[2] << 8) | payload[3]
        jtype, q = payload[4], payload[5]
        width, height = payload[6] * 8, payload[7] * 8
        frag = payload[8:]
        if offset == 0:
            ps._scan.clear()
            ps._frame_corrupt = False
            ps._dims = (width, height)
            if q >= 128:
                if len(frag) < 4:
                    ps._frame_corrupt = True
                    return
                qlen = struct.unpack(">H", frag[2:4])[0]
                qdata = frag[4:4 + qlen]
                ps._qtables = [qdata[i:i + 64]
                               for i in range(0, len(qdata), 64)]
                frag = frag[4 + qlen:]
            else:
                # tables derived from Q (static per Q — cache them)
                if ps._qtable_q != q:
                    ps._qtables = rfc2435_qtables(q)
                    ps._qtable_q = q
        if ps._frame_corrupt:
            return
        if offset != len(ps._scan):
            # TCP keeps order, so a gap means a parse bug or a frame
            # started mid-stream — drop this frame, resync on offset 0
            ps._frame_corrupt = True
            return
        ps._scan.extend(frag)
        if marker:
            jfif = reconstruct_jfif(
                *ps._dims, ps._qtables, bytes(ps._scan),
                subsampling=jtype & 0x3F)
            ps._scan.clear()
            self._queue_jpeg(ps, jfif, ts)

    def _on_rtp_h264(self, ps: DemuxStream, payload: bytes,
                     marker: bool, ts: int) -> None:
        """RFC 6184 depacketization: single NAL units, STAP-A
        aggregates, FU-A fragments → Annex-B access units on the
        marker bit. SPS/PPS are cached and re-prepended so each AU
        handed to decode is self-contained (the file-shim decoder
        needs it; intra-only streams guarantee it suffices)."""
        if not payload:
            return
        nal_type = payload[0] & 0x1F
        if nal_type == 28 and len(payload) >= 2:        # FU-A
            fu = payload[1]
            start, end = fu & 0x80, fu & 0x40
            if start:
                ps._fu = bytearray(
                    bytes([(payload[0] & 0xE0) | (fu & 0x1F)]))
            if ps._fu is not None:
                ps._fu.extend(payload[2:])
                if end:
                    self._h264_nal(ps, bytes(ps._fu))
                    ps._fu = None
        elif nal_type == 24:                            # STAP-A
            i = 1
            while i + 2 <= len(payload):
                size = struct.unpack(">H", payload[i:i + 2])[0]
                self._h264_nal(ps, payload[i + 2:i + 2 + size])
                i += 2 + size
        elif 1 <= nal_type <= 23:                       # single NAL
            self._h264_nal(ps, payload)
        else:
            # STAP-B/MTAP/FU-B (interleaved mode) or reserved types:
            # fail LOUDLY — silently skipping them would leave the
            # stream RUNNING with zero frames forever (the same
            # failure the payload-type check above rejects)
            self._socket_gone(
                ps.sock, ps,
                f"unsupported H.264 RTP NAL type {nal_type} "
                "(packetization-mode 1 only: single NAL / STAP-A / "
                "FU-A) — unset EVAM_RTSP_DEMUX_WORKERS for this "
                "camera")
            return
        if marker and ps._nals:
            nals = ps._nals
            ps._nals = []
            # self-contained AU: ensure BOTH parameter sets lead it
            # (cameras commonly repeat SPS per IDR but send PPS once)
            if not any(n[0] & 0x1F == 8 for n in nals) \
                    and ps._pps is not None:
                nals.insert(0, ps._pps)
            if not any(n[0] & 0x1F == 7 for n in nals) \
                    and ps._sps is not None:
                nals.insert(0, ps._sps)
            au = b"".join(b"\x00\x00\x00\x01" + n for n in nals)
            self._queue_frame(ps, "h264", au, ts)

    def _h264_nal(self, ps: DemuxStream, nal: bytes) -> None:
        if not nal:
            return
        t = nal[0] & 0x1F
        if t == 7:
            ps._sps = nal
        elif t == 8:
            ps._pps = nal
        ps._nals.append(nal)

    def _queue_jpeg(self, ps: DemuxStream, jfif: bytes,
                    ts: int) -> None:
        self._queue_frame(ps, "jpeg", jfif, ts)

    def _queue_frame(self, ps: DemuxStream, kind: str, data: bytes,
                     ts: int) -> None:
        with self._lock:
            if ps._removed or ps.finished:
                return
            ps._jpegs.append((kind, data, ts))
            if len(ps._jpegs) > ps._max_pending:   # live: newest wins
                ps._jpegs.popleft()
                ps.frames_dropped_decode += 1      # under the lock
                metrics.inc("evam_frames_dropped",
                            labels={"stream": ps.stream_id,
                                    "stage": "decode"})
            if not ps._scheduled:
                ps._scheduled = True
                self._ready.put(ps)

    # --------------------------------------------------------- decode

    def _decode_loop(self) -> None:
        import cv2

        from evam_tpu.media.h264 import decode_ipcm_au

        while True:
            ps = self._ready.get()
            if ps is None:
                self._ready.put(None)           # release siblings
                return
            with self._lock:
                if ps._jpegs:
                    item = ps._jpegs.popleft()
                    terminal = False
                else:
                    item = None
                    ps._scheduled = False
                    terminal = ps._eof
            if item is None:
                if terminal:                    # decisions in lock,
                    ps._finish(ps.error)        # actions outside it
                    self._retire(ps)
                continue
            kind, data, ts = item
            if not ps._removed:
                if kind == "h264":
                    # fast path first: our own I_PCM dialect decodes
                    # in one numpy stride pass; anything else (real
                    # cameras' CAVLC) falls to the file shim
                    img = decode_ipcm_au(data)
                    if img is None:
                        img = _decode_h264_au(data)
                else:
                    img = cv2.imdecode(
                        np.frombuffer(data, np.uint8), cv2.IMREAD_COLOR)
                if img is not None:
                    ps._seq += 1
                    ps._emit(FrameEvent(
                        frame=img,
                        pts_ns=int(ts * (1_000_000_000 / RTP_CLOCK)),
                        seq=ps._seq))
            with self._lock:
                if ps._jpegs:
                    self._ready.put(ps)         # stay scheduled
                    deliver_eos = False
                else:
                    ps._scheduled = False
                    deliver_eos = ps._eof
            if deliver_eos:
                ps._finish(ps.error)
                self._retire(ps)

    # ---------------------------------------------------------- stats

    def stats(self) -> dict:
        """Live stream count + CUMULATIVE frame counters (retired
        streams fold their totals in at retirement). Drops are
        stage-attributed: ``dropped_decode`` (shared decode workers
        behind — decode-bound) vs ``dropped_downstream`` (the
        runner/engine consumer behind — engine/framework-bound);
        ``dropped`` is their sum, the pre-attribution contract."""
        with self._lock:
            streams = list(self._streams)
            decoded = self._retired_decoded
            drop_dec = self._retired_dropped_decode
            drop_down = self._retired_dropped_downstream
        drop_dec += sum(s.frames_dropped_decode for s in streams)
        drop_down += sum(s.frames_dropped_downstream for s in streams)
        return {
            "streams": len(streams),
            "threads": 1 + len(self._workers),
            "decoded": decoded + sum(s.frames_decoded for s in streams),
            "dropped": drop_dec + drop_down,
            "dropped_decode": drop_dec,
            "dropped_downstream": drop_down,
        }
