"""Minimal intra-only H.264 (AVC) Annex-B bitstream generator.

Why this exists (VERDICT r4 item 4 / INGEST.md): the reference's
decode path is H.264-first in practice (its sample media and typical
RTSP cameras are H.264), but no H.264 *encoder* ships in this image —
so every prior host-ingest decode measurement used MPEG-4 ASP and the
38–62-core H.264 sizing row was extrapolation. This module writes a
legal baseline-profile H.264 elementary stream from raw frames using
only I_PCM macroblocks (ITU-T H.264 §7.3.5 / §8.3.5: uncompressed
samples carried inside the bitstream), which needs Exp-Golomb headers
and byte-aligned raw samples — no CAVLC/CABAC entropy machinery, no
prediction, no DCT. FFmpeg/cv2 decode it through the full H.264 code
path (NAL parsing, slice decoding, MB reconstruction loop, deblock
decision per MB), giving the decode benches a genuine H.264 input.

Honest scope note (also in INGEST.md): I_PCM skips inverse transform
and intra prediction, so per-frame decode cost is a LOWER bound on
camera-grade H.264; the benches report it as such. Non-16-multiple
frame dimensions (e.g. true 1080p) are edge-padded to the coded size
with the matching SPS crop rectangle emitted.

Every frame is an IDR (intra-only stream), ``idr_pic_id`` alternating
per the spec's consecutive-IDR rule.
"""

from __future__ import annotations

import struct

import numpy as np


class _BitWriter:
    def __init__(self) -> None:
        self._bytes = bytearray()
        self._cur = 0
        self._nbits = 0

    def u(self, value: int, bits: int) -> None:
        for i in range(bits - 1, -1, -1):
            self._cur = (self._cur << 1) | ((value >> i) & 1)
            self._nbits += 1
            if self._nbits == 8:
                self._bytes.append(self._cur)
                self._cur = 0
                self._nbits = 0

    def ue(self, value: int) -> None:
        """Unsigned Exp-Golomb (H.264 §9.1)."""
        v = value + 1
        nbits = v.bit_length()
        self.u(0, nbits - 1)
        self.u(v, nbits)

    def se(self, value: int) -> None:
        """Signed Exp-Golomb: 0,1,-1,2,-2,… → 0,1,2,3,4,…"""
        self.ue(2 * value - 1 if value > 0 else -2 * value)

    def align(self) -> None:
        while self._nbits:
            self.u(0, 1)

    def raw_bytes(self, data: bytes) -> None:
        assert self._nbits == 0, "raw bytes require byte alignment"
        self._bytes.extend(data)

    def trailing(self) -> None:
        """rbsp_trailing_bits: stop bit then align."""
        self.u(1, 1)
        self.align()

    def rbsp(self) -> bytes:
        assert self._nbits == 0
        return bytes(self._bytes)


def _ep_escape(rbsp: bytes) -> bytes:
    """Emulation prevention (§7.4.1.1): 00 00 {00,01,02,03} →
    00 00 03 xx. I_PCM payloads are full of zeros, so this is hot —
    do it with one scan."""
    out = bytearray()
    zeros = 0
    for b in rbsp:
        if zeros >= 2 and b <= 3:
            out.append(3)
            zeros = 0
        out.append(b)
        zeros = zeros + 1 if b == 0 else 0
    return bytes(out)


def _nal(ref_idc: int, nal_type: int, rbsp: bytes) -> bytes:
    return (b"\x00\x00\x00\x01"
            + bytes([(ref_idc << 5) | nal_type])
            + _ep_escape(rbsp))


def _sps(coded_w: int, coded_h: int, crop_right: int = 0,
         crop_bottom: int = 0) -> bytes:
    """``coded_*`` are 16-multiples; crop offsets are in samples
    (must be even — CropUnitX/Y = 2 for 4:2:0 frame macroblocks,
    §7.4.2.1.1), carving e.g. true 1080 out of 1088 coded lines."""
    w = _BitWriter()
    w.u(66, 8)            # profile_idc: baseline
    w.u(0xC0, 8)          # constraint_set0/1, reserved zeros
    w.u(40, 8)            # level_idc 4.0 (1080p-capable)
    w.ue(0)               # seq_parameter_set_id
    w.ue(0)               # log2_max_frame_num_minus4 → max_frame_num 16
    w.ue(2)               # pic_order_cnt_type 2 (output order = decode)
    w.ue(0)               # max_num_ref_frames (intra-only)
    w.u(0, 1)             # gaps_in_frame_num_value_allowed_flag
    w.ue(coded_w // 16 - 1)   # pic_width_in_mbs_minus1
    w.ue(coded_h // 16 - 1)   # pic_height_in_map_units_minus1
    w.u(1, 1)             # frame_mbs_only_flag
    w.u(0, 1)             # direct_8x8_inference_flag
    if crop_right or crop_bottom:
        w.u(1, 1)         # frame_cropping_flag
        w.ue(0)                   # left
        w.ue(crop_right // 2)     # right (CropUnitX = 2)
        w.ue(0)                   # top
        w.ue(crop_bottom // 2)    # bottom (CropUnitY = 2)
    else:
        w.u(0, 1)         # frame_cropping_flag
    w.u(0, 1)             # vui_parameters_present_flag
    w.trailing()
    return _nal(3, 7, w.rbsp())


def _pps() -> bytes:
    w = _BitWriter()
    w.ue(0)               # pic_parameter_set_id
    w.ue(0)               # seq_parameter_set_id
    w.u(0, 1)             # entropy_coding_mode_flag: CAVLC
    w.u(0, 1)             # bottom_field_pic_order_in_frame_present
    w.ue(0)               # num_slice_groups_minus1
    w.ue(0)               # num_ref_idx_l0_default_active_minus1
    w.ue(0)               # num_ref_idx_l1_default_active_minus1
    w.u(0, 1)             # weighted_pred_flag
    w.u(0, 2)             # weighted_bipred_idc
    w.se(0)               # pic_init_qp_minus26
    w.se(0)               # pic_init_qs_minus26
    w.se(0)               # chroma_qp_index_offset
    w.u(0, 1)             # deblocking_filter_control_present_flag
    w.u(0, 1)             # constrained_intra_pred_flag
    w.u(0, 1)             # redundant_pic_cnt_present_flag
    w.trailing()
    return _nal(3, 8, w.rbsp())


def _idr_slice(y: np.ndarray, u: np.ndarray, v: np.ndarray,
               idr_pic_id: int) -> bytes:
    """One IDR slice covering the whole picture, every MB I_PCM."""
    h, wd = y.shape
    mbs_w, mbs_h = wd // 16, h // 16
    w = _BitWriter()
    # slice_header (§7.3.3)
    w.ue(0)               # first_mb_in_slice
    w.ue(7)               # slice_type: I (all slices in picture)
    w.ue(0)               # pic_parameter_set_id
    w.u(0, 4)             # frame_num (log2_max_frame_num = 4)
    w.ue(idr_pic_id)      # idr_pic_id
    # pic_order_cnt_type 2 → nothing; I slice → no ref idx
    w.u(0, 1)             # no_output_of_prior_pics_flag
    w.u(0, 1)             # long_term_reference_flag
    w.se(0)               # slice_qp_delta
    # slice_data: raster MB order
    for mby in range(mbs_h):
        for mbx in range(mbs_w):
            w.ue(25)      # mb_type I_PCM (I-slice table §7-11)
            w.align()     # pcm_alignment_zero_bit(s)
            yb = y[mby * 16:(mby + 1) * 16, mbx * 16:(mbx + 1) * 16]
            ub = u[mby * 8:(mby + 1) * 8, mbx * 8:(mbx + 1) * 8]
            vb = v[mby * 8:(mby + 1) * 8, mbx * 8:(mbx + 1) * 8]
            w.raw_bytes(yb.tobytes() + ub.tobytes() + vb.tobytes())
    w.trailing()
    return _nal(3, 5, w.rbsp())


def encode_frames(frames: "list[np.ndarray] | np.ndarray") -> bytes:
    """Raw I420-planar or BGR frames → intra-only Annex-B H.264.

    Accepts [N,H,W,3] uint8 BGR (converted with the BT.601 studio
    matrix) or a list of (y,u,v) plane tuples. Non-16-multiple frames
    (e.g. true 1080p) are edge-padded to the coded size and the SPS
    carries the matching crop rectangle, like every real encoder.
    """
    out = bytearray()
    first = True
    idr_id = 0
    for f in frames:
        if isinstance(f, tuple):
            y, u, v = f
        else:
            y, u, v = bgr_to_i420_planes(f)
        h, wd = y.shape
        if h % 2 or wd % 2:
            raise ValueError(f"frame dims must be even, got {y.shape}")
        ch, cw = -h % 16, -wd % 16          # pad to coded size
        if ch or cw:
            y = np.pad(y, ((0, ch), (0, cw)), mode="edge")
            u = np.pad(u, ((0, ch // 2), (0, cw // 2)), mode="edge")
            v = np.pad(v, ((0, ch // 2), (0, cw // 2)), mode="edge")
        if first:
            out += _sps(y.shape[1], y.shape[0],
                        crop_right=cw, crop_bottom=ch) + _pps()
            first = False
        out += _idr_slice(
            np.ascontiguousarray(y), np.ascontiguousarray(u),
            np.ascontiguousarray(v), idr_id)
        idr_id ^= 1      # consecutive IDRs must differ (§7.4.3)
    return bytes(out)


def bgr_to_i420_planes(
        bgr: np.ndarray) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """BGR→I420 planes via the SAME conversion the decode workers use
    for the wire (`ops/color.py` → cv2) — one convention, no drift
    between the bench clips and the serving path."""
    from evam_tpu.ops.color import bgr_to_i420_host

    h, wd = bgr.shape[:2]
    planar = bgr_to_i420_host(bgr)       # [(3h/2), w] stacked planes
    y = planar[:h]
    u = planar[h:h + h // 4].reshape(h // 2, wd // 2)
    v = planar[h + h // 4:].reshape(h // 2, wd // 2)
    return y, u, v


def write_annexb(path: str, frames, fps: float = 30.0) -> str:
    """Write an .h264 elementary stream file; returns the path.
    (Raw Annex-B carries no timing — fps is advisory for callers.)"""
    data = encode_frames(frames)
    with open(path, "wb") as fh:
        fh.write(data)
    return path


# ----------------------------------------------------- I_PCM fast decode

class _BitReader:
    def __init__(self, data: bytes):
        self._d = data
        self.pos = 0               # bit position

    def u(self, bits: int) -> int:
        v = 0
        for _ in range(bits):
            byte = self._d[self.pos >> 3]
            v = (v << 1) | ((byte >> (7 - (self.pos & 7))) & 1)
            self.pos += 1
        return v

    def ue(self) -> int:
        zeros = 0
        while self.u(1) == 0:
            zeros += 1
            if zeros > 31:
                raise ValueError("bad Exp-Golomb")
        return (1 << zeros) - 1 + (self.u(zeros) if zeros else 0)

    def se(self) -> int:
        k = self.ue()
        return (k + 1) // 2 if k % 2 else -(k // 2)

    def align(self) -> None:
        self.pos = (self.pos + 7) & ~7


def _unescape(rbsp: bytes) -> bytes:
    """Inverse of emulation prevention: 00 00 03 → 00 00.
    Left-to-right non-overlapping replace is the exact inverse of the
    escaper's left-to-right insertion."""
    return rbsp.replace(b"\x00\x00\x03", b"\x00\x00")


def decode_ipcm_au(au: bytes) -> "np.ndarray | None":
    """From-scratch decoder for the intra-only I_PCM streams THIS
    module emits (every MB is ``mb_type 25``, so the slice body is a
    deterministic 2-byte-header + 384-byte-payload lattice that numpy
    can lift in one stride pass — no per-MB Python loop).

    Why it exists: the only general H.264 decoder in this image is
    cv2's bundled FFmpeg behind a per-AU temp-file open
    (``demux._decode_h264_au``), which costs ~100 ms per frame.
    Loopback fan-in of our own re-streams should not pay that.
    Returns BGR [H, W, 3], or None when the AU is not this exact
    dialect (caller falls back to the general shim — real cameras'
    CAVLC scans land there)."""
    import cv2

    sps_nal = idr_nal = None
    for nal in split_annexb(au):
        t = nal[0] & 0x1F
        if t == 7:
            sps_nal = nal
        elif t == 5:
            idr_nal = nal
    if sps_nal is None or idr_nal is None:
        return None
    try:
        r = _BitReader(_unescape(sps_nal[1:]))
        if r.u(8) != 66:                   # baseline, as we write it
            return None
        r.u(16)                            # constraint flags + level
        r.ue()                             # sps id
        r.ue()                             # log2_max_frame_num_minus4
        if r.ue() != 2:                    # pic_order_cnt_type
            return None
        r.ue()                             # max_num_ref_frames
        r.u(1)                             # gaps allowed
        mbs_w = r.ue() + 1
        mbs_h = r.ue() + 1
        r.u(1)                             # frame_mbs_only
        r.u(1)                             # direct_8x8
        crop_r = crop_b = 0
        if r.u(1):                         # frame_cropping_flag
            # our encoder only crops right/bottom — any other crop is
            # a foreign stream and must take the general decoder
            if r.ue() != 0:                # left
                return None
            crop_r = r.ue() * 2
            if r.ue() != 0:                # top
                return None
            crop_b = r.ue() * 2

        body = _unescape(idr_nal[1:])
        s = _BitReader(body)
        s.ue()                             # first_mb_in_slice
        if s.ue() != 7:                    # slice_type I (all)
            return None
        s.ue()                             # pps id
        s.u(4)                             # frame_num (log2 max = 4)
        s.ue()                             # idr_pic_id
        s.u(2)                             # no_output + long_term
        s.se()                             # slice_qp_delta
        if s.ue() != 25:                   # first MB must be I_PCM
            return None
        s.align()
        o0 = s.pos >> 3
    except (IndexError, ValueError):
        return None

    n_mbs = mbs_w * mbs_h
    need = o0 + (n_mbs - 1) * 386 + 384
    if len(body) < need:
        return None
    arr = np.frombuffer(body, np.uint8, count=need)
    if n_mbs > 1:
        heads = arr[o0 + 384:need].reshape(n_mbs - 1, 386)[:, :2]
        # every inter-MB header is ue(25)+align = 0x0D 0x00
        if not (np.all(heads[:, 0] == 0x0D)
                and np.all(heads[:, 1] == 0x00)):
            return None
    starts = o0 + 386 * np.arange(n_mbs)
    payload = arr[starts[:, None] + np.arange(384)]
    y = (payload[:, :256].reshape(mbs_h, mbs_w, 16, 16)
         .transpose(0, 2, 1, 3).reshape(mbs_h * 16, mbs_w * 16))
    u = (payload[:, 256:320].reshape(mbs_h, mbs_w, 8, 8)
         .transpose(0, 2, 1, 3).reshape(mbs_h * 8, mbs_w * 8))
    v = (payload[:, 320:].reshape(mbs_h, mbs_w, 8, 8)
         .transpose(0, 2, 1, 3).reshape(mbs_h * 8, mbs_w * 8))
    ch, cw = mbs_h * 16 - crop_b, mbs_w * 16 - crop_r
    if ch <= 0 or cw <= 0:
        return None          # nonsense crop: not our dialect
    # standard I420 planar buffer → one cv2 colorspace call
    planar = np.concatenate([
        y.reshape(-1),
        u.reshape(-1),
        v.reshape(-1),
    ]).reshape(mbs_h * 24, mbs_w * 16)
    bgr = cv2.cvtColor(planar, cv2.COLOR_YUV2BGR_I420)
    return np.ascontiguousarray(bgr[:ch, :cw])


# ------------------------------------------------- RFC 6184 (H.264/RTP)

def split_annexb(data: bytes) -> list[bytes]:
    """Split an Annex-B buffer into raw NAL units (start codes
    stripped). Accepts 3- and 4-byte start codes. Scans with
    ``bytes.find`` — emulation prevention guarantees no start code
    inside a NAL payload, and a byte-by-byte Python loop costs
    ~700 ms on a 3 MB 1080p I_PCM access unit."""
    nals = []
    i = data.find(b"\x00\x00\x01")
    if i < 0:
        return []
    pos = i + 3
    while True:
        j = data.find(b"\x00\x00\x01", pos)
        if j < 0:
            nals.append(data[pos:])
            break
        end = j
        # a 4-byte start code (00 00 00 01) leaves one zero before
        # the match; RBSP trailing bits keep real NAL tails nonzero
        if end > pos and data[end - 1] == 0:
            end -= 1
        nals.append(data[pos:end])
        pos = j + 3
    return [x for x in nals if x]


def packetize_rfc6184(access_unit: bytes, seq: int, timestamp: int,
                      ssrc: int, pt: int = 96,
                      mtu: int = 1400) -> tuple[list[bytes], int]:
    """RFC 6184 packetization-mode 1: one Annex-B access unit →
    RTP packets (single NAL unit packets, FU-A fragmentation for
    NALs over the MTU). Marker set on the AU's last packet.
    Returns (packets, next_seq)."""
    nals = split_annexb(access_unit)
    packets: list[bytes] = []

    def rtp(payload: bytes, marker: bool, s: int) -> bytes:
        return struct.pack(
            ">BBHII", 0x80, (0x80 if marker else 0) | pt,
            s & 0xFFFF, timestamp & 0xFFFFFFFF, ssrc) + payload

    for k, nal in enumerate(nals):
        last_nal = k == len(nals) - 1
        if len(nal) <= mtu:
            packets.append(rtp(nal, last_nal, seq))
            seq += 1
            continue
        # FU-A (§5.8): indicator carries NRI+type 28; header carries
        # S/E bits + original NAL type
        indicator = (nal[0] & 0x60) | 28
        nal_type = nal[0] & 0x1F
        body = nal[1:]
        off = 0
        while off < len(body):
            frag = body[off:off + mtu]
            first = off == 0
            off += len(frag)
            end = off >= len(body)
            fu_header = (0x80 if first else 0) | (0x40 if end else 0) \
                | nal_type
            packets.append(rtp(
                bytes([indicator, fu_header]) + frag,
                last_nal and end, seq))
            seq += 1
    return packets, seq
