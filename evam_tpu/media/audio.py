"""Audio sources: WAV files and synthetic tones.

Counterpart of the reference audio path ``decodebin ! audioresample !
audioconvert ! audio/x-raw,channels=1,format=S16LE,rate=16000``
(reference pipelines/audio_detection/environment/pipeline.json:4-5):
sources emit mono S16LE 16 kHz chunks as FrameEvents with ``audio``
payloads."""

from __future__ import annotations

import time
import wave
from typing import Iterator

import numpy as np

from evam_tpu.media.source import FrameEvent, NS

RATE = 16000


class WavSource:
    """Reads a WAV file, converting to 16 kHz mono S16LE."""

    def __init__(self, uri: str, chunk_ms: int = 100, loop: bool = False,
                 realtime: bool = False):
        self.path = uri[len("file://"):] if uri.startswith("file://") else uri
        self.chunk = int(RATE * chunk_ms / 1000)
        self.loop = loop
        self.realtime = realtime
        self._closed = False

    def _read_all(self) -> np.ndarray:
        with wave.open(self.path, "rb") as w:
            rate = w.getframerate()
            channels = w.getnchannels()
            width = w.getsampwidth()
            raw = w.readframes(w.getnframes())
        if width == 2:
            samples = np.frombuffer(raw, np.int16)
        elif width == 1:
            samples = (np.frombuffer(raw, np.uint8).astype(np.int16) - 128) * 256
        else:
            raise ValueError(f"unsupported sample width {width}")
        if channels > 1:
            samples = samples.reshape(-1, channels).mean(axis=1).astype(np.int16)
        if rate != RATE:
            # naive nearest-sample resample — host-side, decode path
            idx = np.clip(
                (np.arange(int(len(samples) * RATE / rate)) * rate / RATE).astype(np.int64),
                0, len(samples) - 1,
            )
            samples = samples[idx]
        return samples

    def frames(self) -> Iterator[FrameEvent]:
        samples = self._read_all()
        if len(samples) < self.chunk:
            return  # shorter than one chunk: nothing to emit, even looped
        seq = 0
        t_wall = time.perf_counter()
        while not self._closed:
            for off in range(0, len(samples) - self.chunk + 1, self.chunk):
                if self._closed:
                    return
                chunk = samples[off : off + self.chunk]
                yield FrameEvent(
                    frame=None,
                    audio=chunk,
                    pts_ns=seq * int(NS * self.chunk / RATE),
                    seq=seq,
                )
                seq += 1
                if self.realtime:
                    t_wall += self.chunk / RATE
                    delay = t_wall - time.perf_counter()
                    if delay > 0:
                        time.sleep(delay)
            if not self.loop:
                return

    def close(self) -> None:
        self._closed = True


class SyntheticAudioSource:
    """Deterministic tone bursts (``synthetic-audio://`` URIs)."""

    def __init__(self, seconds: float = 5.0, chunk_ms: int = 100, seed: int = 0):
        self.total = int(seconds * RATE)
        self.chunk = int(RATE * chunk_ms / 1000)
        self.seed = seed
        self._closed = False

    @classmethod
    def from_uri(cls, uri: str) -> "SyntheticAudioSource":
        body = uri.split("://", 1)[1]
        params = dict(p.split("=", 1) for p in body.split("&") if "=" in p)
        return cls(
            seconds=float(params.get("seconds", 5.0)),
            seed=int(params.get("seed", 0)),
        )

    def frames(self) -> Iterator[FrameEvent]:
        t = np.arange(self.total) / RATE
        freq = 440.0 * (1 + self.seed % 5)
        wavef = (np.sin(2 * np.pi * freq * t) * 12000).astype(np.int16)
        seq = 0
        for off in range(0, self.total - self.chunk + 1, self.chunk):
            if self._closed:
                return
            yield FrameEvent(
                frame=None,
                audio=wavef[off : off + self.chunk],
                pts_ns=seq * int(NS * self.chunk / RATE),
                seq=seq,
            )
            seq += 1

    def close(self) -> None:
        self._closed = True
