"""Line-crossing spatial analytics UDF.

Counterpart of the reference's gvapython extension wired by
pipelines/object_tracking/object_line_crossing/pipeline.json:7 with
``object-line-crossing-config`` ``{lines: [{name, line: [[x1,y1],
[x2,y2]]}], ...}`` (same file :34-55). Requires tracked regions
(object_id from the track stage): an event fires when an object's
anchor point (bottom-center) crosses a line segment between
consecutive frames, with the crossing direction.
"""

from __future__ import annotations

import numpy as np

from evam_tpu.stages.context import FrameContext


def _side(p: np.ndarray, a: np.ndarray, b: np.ndarray) -> float:
    # explicit 2-D cross product: np.cross on 2-D vectors is
    # deprecated (NumPy 2.0) and will be removed
    u, v = b - a, p - a
    return float(u[0] * v[1] - u[1] * v[0])


def _segments_intersect(p1, p2, a, b) -> bool:
    d1 = _side(a, p1, p2)
    d2 = _side(b, p1, p2)
    d3 = _side(p1, a, b)
    d4 = _side(p2, a, b)
    return (d1 * d2 < 0) and (d3 * d4 < 0)


class ObjectLineCrossing:
    def __init__(self, lines: list[dict] | None = None,
                 enable_watermark: bool = False, log_level: str = "INFO",
                 **_ignored):
        self.lines = []
        for line in lines or []:
            pts = np.asarray(line["line"], np.float32)
            self.lines.append((line.get("name", "line"), pts[0], pts[1]))
        self._history: dict[int, np.ndarray] = {}
        self._last_seen: dict[int, int] = {}

    @staticmethod
    def _anchor(region) -> np.ndarray:
        # bottom-center of the box — the conventional footfall anchor
        return np.asarray([(region.x0 + region.x1) / 2.0, region.y1], np.float32)

    MAX_IDLE_FRAMES = 300  # prune anchors for objects gone this long

    def process_frame(self, ctx: FrameContext) -> bool:
        events = []
        # prune history of ids absent from recent frames (bounded memory
        # on 24/7 streams)
        seen_now = {r.object_id for r in ctx.regions if r.object_id is not None}
        for oid in seen_now:
            self._last_seen[oid] = ctx.seq
        stale = [
            oid for oid, s in self._last_seen.items()
            if ctx.seq - s > self.MAX_IDLE_FRAMES
        ]
        for oid in stale:
            self._last_seen.pop(oid, None)
            self._history.pop(oid, None)
        for region in ctx.regions:
            if region.object_id is None:
                continue
            anchor = self._anchor(region)
            prev = self._history.get(region.object_id)
            self._history[region.object_id] = anchor
            if prev is None:
                continue
            for name, a, b in self.lines:
                if _segments_intersect(prev, anchor, a, b):
                    direction = (
                        "clockwise" if _side(anchor, a, b) > 0 else "counterclockwise"
                    )
                    events.append(
                        {
                            "event-type": "object-line-crossing",
                            "line-name": name,
                            "related-objects": [
                                {"id": region.object_id, "roi_type": region.label}
                            ],
                            "directions": [direction],
                        }
                    )
        if events:
            ctx.messages.append({"events": events})
        return True
