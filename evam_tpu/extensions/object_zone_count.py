"""Zone-count spatial analytics UDF.

Counterpart of the reference's gvapython extension wired by
pipelines/object_detection/object_zone_count/pipeline.json:5-9 with
``object-zone-count-config`` ``{zones: [{name, polygon}],
enable_watermark, log_level}`` (same file :44-65). For each frame it
counts detections whose bounding-box corners fall inside each zone
polygon and attaches a zone-counting event per occupied zone.
"""

from __future__ import annotations

import numpy as np

from evam_tpu.stages.context import FrameContext


def _point_in_polygon(x: float, y: float, poly: np.ndarray) -> bool:
    """Ray-casting point-in-polygon (poly: [N,2] normalized coords)."""
    inside = False
    n = len(poly)
    j = n - 1
    for i in range(n):
        xi, yi = poly[i]
        xj, yj = poly[j]
        if (yi > y) != (yj > y) and x < (xj - xi) * (y - yi) / (yj - yi + 1e-12) + xi:
            inside = not inside
        j = i
    return inside


class ObjectZoneCount:
    def __init__(self, zones: list[dict] | None = None,
                 enable_watermark: bool = False, log_level: str = "INFO",
                 **_ignored):
        self.zones = []
        for zone in zones or []:
            self.zones.append(
                (zone.get("name", "zone"), np.asarray(zone["polygon"], np.float32))
            )
        self.enable_watermark = enable_watermark

    def process_frame(self, ctx: FrameContext) -> bool:
        events = []
        for name, poly in self.zones:
            statuses = []
            count = 0
            for region in ctx.regions:
                corners = [
                    (region.x0, region.y0), (region.x1, region.y0),
                    (region.x0, region.y1), (region.x1, region.y1),
                ]
                inside = [_point_in_polygon(x, y, poly) for x, y in corners]
                if all(inside):
                    status = "within"
                elif any(inside):
                    status = "intersects"
                else:
                    continue
                count += 1
                statuses.append({"roi_type": region.label, "status": status})
            if count:
                events.append(
                    {
                        "event-type": "zone-count",
                        "zone-name": name,
                        "zone-count": count,
                        "related-objects": statuses,
                    }
                )
        if events:
            ctx.messages.append({"events": events})
        return True
