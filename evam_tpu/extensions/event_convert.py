"""Event-convert UDF: normalizes UDF events after metaconvert.

Counterpart of the reference's gva_event_convert extension
(pipelines/object_detection/object_zone_count/pipeline.json:7): runs
after metaconvert and lifts events attached by analytics UDFs into
the serialized metadata's top-level ``events`` list.
"""

from __future__ import annotations

from evam_tpu.stages.context import FrameContext


def process_frame(ctx: FrameContext) -> bool:
    if ctx.metadata is None:
        return True
    events = ctx.metadata.get("events")
    if events is None:
        return True
    # normalize: every event carries an event-type string
    ctx.metadata["events"] = [
        e if "event-type" in e else {**e, "event-type": "unknown"} for e in events
    ]
    return True
