"""Stage interfaces.

Sync stages transform a FrameContext inline; async stages submit work
to a shared BatchEngine and are resumed by the StreamRunner when the
batch containing their item completes. The async split is what lets
one stream keep multiple frames in flight (overlapping decode,
batching and TPU steps — the role GStreamer queues play between
elements in the reference, SURVEY.md §2d-5).
"""

from __future__ import annotations

from concurrent.futures import Future

import numpy as np

from evam_tpu.stages.context import FrameContext


class Stage:
    """Synchronous stage: ctx in → list of ctx out (0..n)."""

    name: str = "stage"
    is_async = False

    def process(self, ctx: FrameContext) -> list[FrameContext]:
        raise NotImplementedError

    def flush(self) -> list[FrameContext]:
        """Emit any buffered contexts at end-of-stream."""
        return []

    def close(self) -> None:
        pass

    # ---- stream-state checkpointing (SURVEY §5.4 + §7 "tracking
    # statefulness"): stages with cross-frame state can round-trip a
    # JSON-serializable snapshot through the stream registry's
    # streams.json so a restarted server resumes without breaking
    # downstream invariants (e.g. tracker id monotonicity).

    def snapshot(self) -> dict | None:
        """JSON-serializable cross-frame state, or None (stateless)."""
        return None

    def restore(self, state: dict) -> None:
        """Re-apply a snapshot() on a freshly built stage."""


class AsyncStage(Stage):
    """Engine-backed stage: submit() returns a Future (or None to skip
    inference for this frame), complete() folds the packed result back
    into the context."""

    is_async = True

    def submit(self, ctx: FrameContext) -> Future | None:
        raise NotImplementedError

    def complete(self, ctx: FrameContext, result: np.ndarray | None) -> list[FrameContext]:
        raise NotImplementedError

    def process(self, ctx: FrameContext) -> list[FrameContext]:
        fut = self.submit(ctx)
        return self.complete(ctx, fut.result() if fut is not None else None)
