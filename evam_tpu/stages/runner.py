"""StreamRunner: drives one stream's frames through its stage chain
with multiple frames in flight.

The reference overlaps decode and inference through GStreamer's
per-element threads and queues (SURVEY.md §2d-5). Here a single
runner keeps up to ``window`` frames in flight: a frame walks sync
stages inline, parks at an async (engine-backed) stage, and resumes
— strictly in seq order — once its batch result lands. This is what
lets one stream sustain full rate even when each engine round-trip
costs more than a frame interval (deep pipelining over the device
queue)."""

from __future__ import annotations

from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Callable, Iterator

import time

from evam_tpu.media.source import FrameEvent
from evam_tpu.obs import get_logger, metrics
from evam_tpu.obs import trace
from evam_tpu.obs.faults import from_env as faults_from_env
from evam_tpu.obs.trace import observe_frame_latency, stage_timer
from evam_tpu.sched.shedder import ShedError
from evam_tpu.stages.base import AsyncStage, Stage
from evam_tpu.stages.context import FrameContext
from evam_tpu.state import active as ckpt_active

log = get_logger("stages.runner")


@dataclass
class _Parked:
    ctx: FrameContext
    stage: AsyncStage
    future: Future | None


class StreamRunner:
    def __init__(
        self,
        stream_id: str,
        stages: list[Stage],
        source_uri: str = "",
        window: int = 4,
        on_error: Callable[[Exception], None] | None = None,
        priority: str = "standard",
    ):
        self.stream_id = stream_id
        self.stages = stages
        self.source_uri = source_uri
        #: QoS class stamped on every FrameContext (evam_tpu/sched/)
        self.priority = priority
        self.window = max(1, window)
        self.on_error = on_error
        self.frames_in = 0
        self.frames_out = 0
        self.errors = 0
        self._parked: deque[_Parked] = deque()
        self._stopped = False
        self._faults = faults_from_env()
        #: crash-consistent checkpoints (evam_tpu/state/): resolved
        #: once at construction like the fault injector — None when
        #: EVAM_CKPT=off, so the post-resolve hook is one None-check
        self._ckpt = ckpt_active()
        #: trace-id of the last resolved frame — the checkpoint's
        #: trace-continuity marker (only maintained when ckpt is on)
        self.last_trace_id = ""

    # ----------------------------------------------------------- API

    def run(self, events: Iterator[FrameEvent]) -> None:
        """Consume the event iterator to completion (blocking)."""
        for ev in events:
            if self._stopped:
                break
            self.feed(ev)
        self.drain()

    def stop(self) -> None:
        self._stopped = True

    def feed(self, ev: FrameEvent) -> None:
        self.frames_in += 1
        ctx = FrameContext(
            frame=ev.frame,
            audio=ev.audio,
            pts_ns=ev.pts_ns,
            seq=ev.seq,
            stream_id=self.stream_id,
            source_uri=self.source_uri,
            ingest_t=time.perf_counter(),
            priority=self.priority,
            trace=trace.start_frame(self.stream_id, ev.seq, self.priority),
        )
        if ctx.trace is not None and ev.decode_s is not None:
            # decode happened before ingest; backdate the span so the
            # tree starts where the frame's wall time actually started
            ctx.trace.add_span("decode", ctx.ingest_t - ev.decode_s,
                               ev.decode_s)
        if self._faults is not None:
            try:
                frame = self._faults.apply(ctx.frame)
            except Exception as exc:  # noqa: BLE001 — injected error
                self._handle_error(exc, ctx)
                return
            if frame is None and ctx.frame is not None:
                return  # injected drop
            ctx.frame = frame
        # Free a slot first (blocking only when the window is full),
        # then start this frame down the chain.
        self.pump(block=len(self._parked) >= self.window)
        self._advance(ctx)
        self.pump(block=False)

    def drain(self) -> None:
        while self._parked:
            self.pump(block=True)

    # ------------------------------------------------------ internals

    def pump(self, block: bool) -> None:
        """Resume parked frames whose results are ready (in order)."""
        while self._parked:
            head = self._parked[0]
            if head.future is not None and not head.future.done() and not block:
                return
            self._parked.popleft()
            try:
                result = head.future.result() if head.future is not None else None
                t_c = time.perf_counter()
                with stage_timer(f"{head.stage.name}.complete"):
                    outs = head.stage.complete(head.ctx, result)
                if head.ctx.trace is not None:
                    head.ctx.trace.add_span(
                        f"stage.{head.stage.name}.complete", t_c,
                        time.perf_counter() - t_c)
            except Exception as exc:  # noqa: BLE001 — frame-level fault isolation
                self._handle_error(exc, head.ctx)
                continue
            for ctx in outs:
                ctx.stage_index = head.ctx.stage_index + 1
                if ctx.ingest_t is None:
                    ctx.ingest_t = head.ctx.ingest_t
                if ctx.trace is None:
                    ctx.trace = head.ctx.trace
                self._advance(ctx)
            block = False  # only the head wait is blocking

    def _advance(self, ctx: FrameContext) -> None:
        """Walk sync stages until the chain ends or an async stage parks."""
        i = ctx.stage_index
        while i < len(self.stages):
            stage = self.stages[i]
            ctx.stage_index = i
            if stage.is_async:
                try:
                    fut = stage.submit(ctx)
                except Exception as exc:  # noqa: BLE001
                    self._handle_error(exc, ctx)
                    return
                self._parked.append(_Parked(ctx, stage, fut))
                return
            try:
                t_s = time.perf_counter()
                with stage_timer(stage.name):
                    outs = stage.process(ctx)
                if ctx.trace is not None:
                    ctx.trace.add_span(f"stage.{stage.name}", t_s,
                                       time.perf_counter() - t_s)
            except Exception as exc:  # noqa: BLE001
                self._handle_error(exc, ctx)
                return
            if not outs:
                return  # frame consumed/dropped
            if len(outs) == 1 and outs[0] is ctx:
                i += 1
                continue
            # fan-out (e.g. audio re-chunking): each emitted ctx
            # continues from the next stage, inheriting the parent's
            # ingest time so the latency histogram covers them.
            for out in outs:
                out.stage_index = i + 1
                if out.ingest_t is None:
                    out.ingest_t = ctx.ingest_t
                if out.trace is None:
                    out.trace = ctx.trace
                self._advance(out)
            return
        self.frames_out += 1
        metrics.inc("evam_frames_processed", labels={"stream": self.stream_id})
        if ctx.ingest_t is not None:
            observe_frame_latency(
                self.stream_id, time.perf_counter() - ctx.ingest_t,
                priority=ctx.priority,
                trace_id=ctx.trace.trace_id if ctx.trace is not None else None)
        trace.finish_frame(ctx.trace, "ok")
        if self._ckpt is not None:
            # post-resolve barrier: the frame fully left the chain, so
            # every stage's cross-frame state is consistent — refresh
            # this stream's checkpoint on the capture cadence
            if ctx.trace is not None:
                self.last_trace_id = ctx.trace.trace_id
            if self.frames_out % self._ckpt.interval == 0:
                self._ckpt.capture(self.stream_id,
                                   barrier="post_resolve")

    def _handle_error(self, exc: Exception, ctx: FrameContext) -> None:
        self.errors += 1
        metrics.inc("evam_frame_errors", labels={"stream": self.stream_id})
        log.warning("stream %s frame %d error: %s", self.stream_id, ctx.seq, exc)
        # tail sampling always retains shed/error frames (a shed IS a
        # deadline miss — the staleness budget expired in queue)
        trace.finish_frame(ctx.trace,
                           "shed" if isinstance(exc, ShedError) else "error")
        if self.on_error is not None:
            self.on_error(exc)
