"""Stage factory: resolved StageSpec chain → executable Stage objects.

The graph layer (evam_tpu.graph) parses definitions and binds
parameters; this module instantiates the runtime stages, wiring
engine-backed stages to the shared EngineHub. Source/decode/sink
specs are handled by the StreamInstance (they define IO, not
per-frame transforms)."""

from __future__ import annotations

from typing import Callable

from evam_tpu.engine.hub import EngineHub
from evam_tpu.graph.spec import StageKind, StageSpec
from evam_tpu.stages.base import Stage
from evam_tpu.stages.context import FrameContext
from evam_tpu.stages.infer import (
    ActionStage,
    AudioDetectStage,
    ClassifyStage,
    DetectStage,
    FusedDetectClassifyStage,
)
from evam_tpu.stages.meta import MetaconvertStage, PublishStage, SinkStage
from evam_tpu.stages.misc import AudioMixStage, ConvertStage, LevelStage
from evam_tpu.stages.track import TrackStage
from evam_tpu.stages.udf import UdfStage


def _fusable(specs: list[StageSpec]) -> tuple[int, int] | None:
    """Find (detect_idx, classify_idx) fusable into one engine pass:
    a detect stage whose following stages up to a classify are only
    track/convert (order-insensitive host stages). A classify with
    reclassify-interval > 1 is not fusable — that schedule (reuse
    cached attributes between reclassifications, reference
    object_classification/vehicle_attributes/pipeline.json:68-71)
    is host state the single fused program can't express."""
    for i, spec in enumerate(specs):
        if spec.kind != StageKind.DETECT:
            continue
        for j in range(i + 1, len(specs)):
            kind = specs[j].kind
            if kind == StageKind.CLASSIFY:
                props = specs[j].properties or {}
                if int(props.get("reclassify-interval", 1) or 1) > 1:
                    return None
                return (i, j)
            if kind not in (StageKind.TRACK, StageKind.CONVERT):
                break
    return None


def build_stages(
    specs: list[StageSpec],
    hub: EngineHub,
    source_uri: str = "",
    publish_fn: Callable[[FrameContext], None] | None = None,
    sink_fn: Callable[[FrameContext], None] | None = None,
    fuse: bool = True,
) -> list[Stage]:
    specs = list(specs)
    fused: FusedDetectClassifyStage | None = None
    fused_det_idx = -1
    if fuse:
        pair = _fusable(specs)
        if pair is not None:
            di, ci = pair
            det, cls = specs[di], specs[ci]
            fused = FusedDetectClassifyStage(
                f"{det.name}+{cls.name}",
                det.model, cls.model,
                det.properties, cls.properties, hub,
            )
            # ci > di, so dropping the classify spec leaves di valid.
            specs = [s for k, s in enumerate(specs) if k != ci]
            fused_det_idx = di

    stages: list[Stage] = []
    for idx, spec in enumerate(specs):
        kind = spec.kind
        if kind in (StageKind.SOURCE, StageKind.DECODE):
            continue  # handled by the StreamInstance's DecodeWorker
        if kind == StageKind.DETECT:
            if fused is not None and idx == fused_det_idx:
                stages.append(fused)
            else:
                stages.append(
                    DetectStage(spec.name, spec.model, spec.properties, hub)
                )
        elif kind == StageKind.CLASSIFY:
            stages.append(ClassifyStage(spec.name, spec.model, spec.properties, hub))
        elif kind == StageKind.TRACK:
            stages.append(TrackStage(spec.name, spec.properties))
        elif kind == StageKind.ACTION:
            stages.append(ActionStage(spec.name, spec.properties, hub))
        elif kind == StageKind.AUDIO_DETECT:
            stages.append(
                AudioDetectStage(spec.name, spec.model, spec.properties, hub)
            )
        elif kind == StageKind.UDF:
            stages.append(UdfStage(spec.name, spec.properties))
        elif kind == StageKind.METACONVERT:
            stages.append(
                MetaconvertStage(spec.name, spec.properties, source_uri=source_uri)
            )
        elif kind == StageKind.PUBLISH:
            stages.append(PublishStage(spec.name, publish_fn))
        elif kind == StageKind.SINK:
            stages.append(SinkStage(spec.name, sink_fn))
        elif kind == StageKind.CONVERT:
            stages.append(ConvertStage(spec.name, spec.properties))
        elif kind == StageKind.AUDIO_MIX:
            stages.append(AudioMixStage(spec.name, spec.properties))
        elif kind == StageKind.LEVEL:
            stages.append(LevelStage(spec.name, spec.properties))
        else:
            raise ValueError(f"no runtime stage for kind {kind}")
    return stages
