"""Engine-backed inference stages: detect, classify, action, audio.

These are the TPU counterparts of the reference's gvadetect /
gvaclassify / gvaactionrecognitionbin / gvaaudiodetect elements
(SURVEY.md §2b), sharing per-model BatchEngines across streams
(model-instance-id semantics) instead of owning per-stream OpenVINO
requests.

Thresholds are applied host-side on the packed engine output so
engines stay shareable between pipelines with different ``threshold``
parameters (the engine's in-jit NMS uses a permissive floor).
"""

from __future__ import annotations

import itertools
import time
from collections import deque
from concurrent.futures import Future

import numpy as np

from evam_tpu.engine.hub import EngineHub
from evam_tpu.models.zoo.action import CLIP_LEN
from evam_tpu.obs import get_logger
from evam_tpu.stages.base import AsyncStage
from evam_tpu.stages.context import FrameContext, Region, Tensor
from evam_tpu.stages.gate import maybe_gate
from evam_tpu.stages.track import RegionCoaster

log = get_logger("stages.infer")

#: floor baked into the shared engine's NMS; per-stage thresholds
#: filter above this.
ENGINE_SCORE_FLOOR = 0.1


def _wire_safe_size(size: tuple[int, int]) -> tuple[int, int]:
    """Round an ingest (H, W) up to the I420 wire constraint
    (ops.color.i420_shape: height%4, width%2) so user-set sizes like
    430x768 can't break the planar encoding."""
    h, w = int(size[0]), int(size[1])
    return (-(-h // 4) * 4, -(-w // 2) * 2)


def _resize_for_engine(frame: np.ndarray, size: tuple[int, int]) -> np.ndarray:
    """Host-side resize to the engine's canonical ingest resolution so
    frames from heterogeneous streams stack into one batch."""
    h, w = size
    if frame.shape[0] == h and frame.shape[1] == w:
        return frame
    from evam_tpu import native

    return native.resize_bgr(frame, h, w)


def _encode_wire(frame_bgr: np.ndarray, wire_format: str) -> np.ndarray:
    """Host-side wire encoding (decode-thread side of ops.color)."""
    if wire_format == "i420":
        from evam_tpu import native

        return native.bgr_to_i420(frame_bgr)
    return np.ascontiguousarray(frame_bgr)


#: per-process frame-seed sequence for device_synth mode (the GIL makes
#: itertools.count().__next__ atomic enough for distinct seeds)
_SYNTH_SEQ = itertools.count()


def _timed_gate_decide(gate, ctx: FrameContext) -> bool:
    """Run the motion gate's decision with a "gate.decide" span on the
    frame trace (the gate verdict rides as an attr so a skipped
    frame's tree explains itself)."""
    if ctx.trace is None:
        return gate.decide(ctx.frame)
    t_g = time.perf_counter()
    go = gate.decide(ctx.frame)
    ctx.trace.add_span("gate.decide", t_g, time.perf_counter() - t_g,
                       {"go": bool(go)})
    return go


def _detect_state_snapshot(stage) -> dict | None:
    """Shared ckpt-gated ``Stage.snapshot()`` body for the two
    detect-class stages (DetectStage / FusedDetectClassifyStage):
    gate controller state + coaster regions/velocities + the interval
    counter. Returns None when EVAM_CKPT is off — base-class behavior,
    byte-identical serve path."""
    from evam_tpu import state as stream_state

    if stream_state.active() is None:
        return None
    out: dict = {
        "count": int(stage._count),
        "coaster": stage._coaster.state_dict(),
    }
    if stage.gate is not None:
        out["gate"] = stage.gate.state_dict()
    return out


def _detect_state_restore(stage, state: dict) -> None:
    """Re-apply a ``_detect_state_snapshot`` on a freshly built stage.
    A ``stale`` marker (checkpoint older than the gate's max-skip
    bound — StreamInstance.restore_checkpoint prunes it to this) drops
    the detections/gate anchor and forces a refresh — identities in
    the track stage survive regardless."""
    stage._count = int(state.get("count", 0))
    if state.get("stale"):
        if stage.gate is not None:
            stage.gate.force_refresh()
        return
    if state.get("coaster"):
        stage._coaster.load_state(state["coaster"])
    if stage.gate is not None and state.get("gate"):
        stage.gate.load_state(state["gate"])


def _parse_interval(properties: dict) -> int:
    """``inference-interval``: a positive int, or ``"adaptive"`` —
    the motion gate replaces the static schedule (stages/gate.py), so
    the static interval collapses to 1."""
    iv = properties.get("inference-interval", 1)
    if isinstance(iv, str) and iv.strip().lower() == "adaptive":
        return 1
    return max(1, int(iv))


def _wire_frame(
    frame: np.ndarray, size: tuple[int, int], wire_format: str
) -> np.ndarray:
    """Fused resize + wire encode — ONE pass over the pixels in the
    native kernel (native/evam_media.cpp) instead of a resize pass
    plus a convert pass; this is the per-frame host hot op at high
    stream counts. native.resize_bgr_to_i420 owns the
    native-vs-cv2 policy and fallback.

    The returned array is copied into the engine's staging slot by
    ``BatchEngine.submit`` ON THIS (the stream's) thread — together
    the wire encode and the slot write are the stream's entire
    per-frame host cost; the dispatcher never touches the pixels
    again (engine/ringbuf.py).

    ``wire_format="seed"`` (EngineHub.device_synth, bench.py --config
    serve): the engine synthesizes pixels on-chip, so the stage
    submits only a distinct uint32 per frame."""
    if wire_format == "seed":
        return np.uint32(next(_SYNTH_SEQ) & 0xFFFFFFFF)
    if wire_format == "i420":
        from evam_tpu import native

        return native.resize_bgr_to_i420(frame, size[0], size[1])
    return _encode_wire(_resize_for_engine(frame, size), wire_format)



def _warm_engine(hub: EngineHub, engine, ingest_size, wire_format,
                 **extra_example) -> None:
    """Precompile the engine's batch buckets in the background when the
    hub serves live traffic (hub.warmup). The example is recorded on
    the engine EITHER way (set_example): a supervised rebuild
    (engine/supervisor.py) re-warms the replacement engine from it, so
    recovery never pays the mid-traffic compile spike the original
    warmup was added to kill."""
    h, w = ingest_size
    if wire_format == "seed":
        frame = np.uint32(0)
    else:
        from evam_tpu.ops.color import wire_shape

        frame = np.zeros(wire_shape(wire_format, h, w), np.uint8)
    if hub.warmup:
        engine.warm_async(frames=frame, **extra_example)
    else:
        engine.set_example(frames=frame, **extra_example)


class DetectStage(AsyncStage):
    """gvadetect counterpart. Properties (reference
    pipelines/object_detection/person_vehicle_bike/pipeline.json:18-40):
    device, threshold, inference-interval, model-instance-id."""

    def __init__(self, name: str, model_key: str, properties: dict, hub: EngineHub):
        self.name = name
        self.model_key = model_key
        self.threshold = float(properties.get("threshold", 0.5))
        if self.threshold < ENGINE_SCORE_FLOOR:
            log.warning(
                "detect stage %s threshold %.3f below shared-engine floor %.2f; "
                "effective threshold is %.2f",
                name, self.threshold, ENGINE_SCORE_FLOOR, ENGINE_SCORE_FLOOR,
            )
        self.interval = _parse_interval(properties)
        self.model = hub.model(model_key)
        self.wire = "seed" if hub.device_synth else hub.wire_format
        self.ingest_size = _wire_safe_size(
            (self.model.preprocess.height, self.model.preprocess.width)
        )
        self.engine = hub.engine(
            "detect",
            model_key,
            properties.get("model-instance-id"),
            score_threshold=ENGINE_SCORE_FLOOR,
            synth_wire_hw=self.ingest_size,
        )
        _warm_engine(hub, self.engine, self.ingest_size, self.wire)
        #: content-adaptive motion gate (stages/gate.py): None unless
        #: inference-interval=adaptive or EVAM_GATE=on
        self.gate = maybe_gate(
            properties, engine_name=getattr(self.engine, "name", ""))
        #: CoW reuse + constant-velocity coasting of the last inferred
        #: detections (stages/track.py) — both skip paths share it
        self._coaster = RegionCoaster()
        self._count = 0
        self._last_regions: list[Region] = []

    def submit(self, ctx: FrameContext) -> Future | None:
        self._count += 1
        if self.gate is not None:
            if ctx.frame is not None and not _timed_gate_decide(
                    self.gate, ctx):
                # motion gate skip: coast the last detections forward
                ctx.scratch["gate_coast"] = self.gate.consecutive_skips
                return None
        elif (self._count - 1) % self.interval:
            return None  # inference-interval skip: reuse last regions
        return self.engine.submit(
            priority=ctx.priority,
            stream=ctx.stream_id,
            trace=ctx.trace,
            frames=_wire_frame(ctx.frame, self.ingest_size, self.wire))

    def complete(self, ctx: FrameContext, result: np.ndarray | None) -> list[FrameContext]:
        if result is None:
            # skipped frame: shallow-frozen clones of the last
            # detections (value-equal to the old per-frame deepcopy),
            # velocity-coasted when the motion gate did the skipping.
            steps = ctx.scratch.pop("gate_coast", 0)
            ctx.regions.extend(self._coaster.coast(steps))
            return [ctx]
        labels = self.model.labels
        regions = []
        for row in result:
            x0, y0, x1, y1, score, label_id, valid = row
            if valid < 0.5 or score < self.threshold:
                continue
            lid = int(label_id)
            label = labels[lid] if 0 <= lid < len(labels) else str(lid)
            region = Region(
                x0=float(x0), y0=float(y0), x1=float(x1), y1=float(y1),
                confidence=float(score), label_id=lid, label=label,
            )
            region.tensors.append(
                Tensor(
                    name="detection",
                    confidence=float(score),
                    label_id=lid,
                    label=label,
                    is_detection=True,
                )
            )
            regions.append(region)
        self._last_regions = regions
        self._coaster.observe(regions)
        ctx.regions.extend(regions)
        return [ctx]

    def snapshot(self) -> dict | None:
        return _detect_state_snapshot(self)

    def restore(self, state: dict) -> None:
        _detect_state_restore(self, state)


class ClassifyStage(AsyncStage):
    """gvaclassify counterpart. Properties (reference
    pipelines/object_classification/vehicle_attributes/pipeline.json:63-85):
    object-class, reclassify-interval, threshold, model-instance-id."""

    ROI_BUDGET = 8

    def __init__(self, name: str, model_key: str, properties: dict, hub: EngineHub):
        self.name = name
        self.model_key = model_key
        self.object_class = properties.get("object-class")
        self.interval = max(1, int(properties.get("reclassify-interval", 1)))
        self.threshold = float(properties.get("threshold", 0.0))
        self.wire = "seed" if hub.device_synth else hub.wire_format
        self.model = hub.model(model_key)
        # Crops are taken on-device from the submitted frame; a fixed
        # canonical ingest resolution keeps cross-stream batches
        # stackable while preserving enough pixels for small ROIs.
        self.ingest_size = _wire_safe_size(
            tuple(properties.get("ingest-size", (432, 768)))
        )
        self.engine = hub.engine(
            "classify",
            model_key,
            properties.get("model-instance-id"),
            roi_budget=self.ROI_BUDGET,
            synth_wire_hw=self.ingest_size,
        )
        #: packed-ragged engine (EVAM_RAGGED=packed, engine/ragged.py):
        #: submit the frame's REAL region boxes — shape (k, 4) — and
        #: let the staging ring pack them across the batch, instead of
        #: zero-padding every frame to the ROI budget
        self._packed = getattr(self.engine, "ragged", "off") == "packed"
        _warm_engine(
            hub, self.engine, self.ingest_size, self.wire,
            boxes=np.zeros((self.ROI_BUDGET, 4), np.float32),
        )
        self._count = 0

    def _eligible(self, ctx: FrameContext) -> list[Region]:
        return [
            r
            for r in ctx.regions
            if self.object_class in (None, "", r.label)
        ][: self.ROI_BUDGET]

    def submit(self, ctx: FrameContext) -> Future | None:
        self._count += 1
        if (self._count - 1) % self.interval:
            return None
        regions = self._eligible(ctx)
        if not regions:
            return None
        # packed: exactly the frame's region rows (the ring packs them
        # across the batch); dense: the fixed ROI-budget pad block.
        # ``units`` keeps the engine's occupancy accounting honest
        # about interior padding on BOTH paths.
        rows = len(regions) if self._packed else self.ROI_BUDGET
        boxes = np.zeros((rows, 4), np.float32)
        for i, r in enumerate(regions):
            boxes[i] = [r.x0, r.y0, r.x1, r.y1]
        return self.engine.submit(
            priority=ctx.priority,
            units=len(regions),
            stream=ctx.stream_id,
            trace=ctx.trace,
            frames=_wire_frame(ctx.frame, self.ingest_size, self.wire),
            boxes=boxes)

    def complete(self, ctx: FrameContext, result: np.ndarray | None) -> list[FrameContext]:
        if result is None:
            return [ctx]
        regions = self._eligible(ctx)
        offset = 0
        head_slices = []
        for head_name, n in self.model.spec.heads:
            head_slices.append((head_name, offset, offset + n))
            offset += n
        for i, region in enumerate(regions):
            for head_name, a, b in head_slices:
                probs = result[i, a:b]
                lid = int(np.argmax(probs))
                conf = float(probs[lid])
                if conf < self.threshold:
                    continue
                label_list = self.model.head_labels.get(head_name, [])
                region.tensors.append(
                    Tensor(
                        name=head_name,
                        confidence=conf,
                        label_id=lid,
                        label=label_list[lid] if lid < len(label_list) else str(lid),
                    )
                )
        return [ctx]


class ActionStage(AsyncStage):
    """gvaactionrecognitionbin counterpart: per-frame encoder + 16-frame
    sliding-clip decoder (reference pipelines/action_recognition/general/
    pipeline.json:4, composite model note in that README:13-19)."""

    def __init__(self, name: str, properties: dict, hub: EngineHub):
        self.name = name
        enc_key = properties.get("enc-model", "action_recognition/encoder")
        dec_key = properties.get("dec-model", "action_recognition/decoder")
        self.dec_model = hub.model(dec_key)
        self.enc_model = hub.model(enc_key)
        self.ingest_size = _wire_safe_size((
            self.enc_model.preprocess.height,
            self.enc_model.preprocess.width,
        ))
        self.enc_engine = hub.engine("action_encode", enc_key,
                                     properties.get("model-instance-id"),
                                     synth_wire_hw=self.ingest_size)
        self.dec_engine = hub.engine("action_decode", dec_key)
        self.clip: deque[np.ndarray] = deque(maxlen=CLIP_LEN)
        self.threshold = float(properties.get("threshold", 0.0))
        self.wire = "seed" if hub.device_synth else hub.wire_format
        _warm_engine(hub, self.enc_engine, self.ingest_size, self.wire)
        if hub.warmup:
            embed_dim = getattr(self.enc_model.module, "embed_dim", 512)
            self.dec_engine.warm_async(
                clips=np.zeros((CLIP_LEN, embed_dim), np.float32)
            )

    def submit(self, ctx: FrameContext) -> Future | None:
        """Chain encoder → decoder without ever blocking the runner.

        The returned future resolves to the decoder's class
        probabilities (or None during clip warm-up). The decoder
        submit happens inside the encoder future's callback — on the
        encoder engine's dispatcher thread — so the runner's pump
        never waits on a decoder round-trip inline (round-1 VERDICT
        "ActionStage.complete blocks the stream"): frames keep
        flowing while a decoder batch is pending, and the action
        pipeline runs at encoder throughput.
        """
        prio = ctx.priority
        stream_id = ctx.stream_id
        tr = ctx.trace
        enc_fut = self.enc_engine.submit(
            priority=prio,
            stream=ctx.stream_id,
            trace=tr,
            frames=_wire_frame(ctx.frame, self.ingest_size, self.wire))
        outer: Future = Future()

        def _on_encoded(f: Future) -> None:
            # concurrent.futures swallows exceptions raised inside
            # done-callbacks — any failure here must land on `outer`
            # or the runner's pump would block on it forever.
            try:
                emb = f.result()
                # Encoder futures complete in submission order (FIFO
                # batcher), so appends preserve frame order even
                # though this runs on the dispatcher thread.
                self.clip.append(emb)
                if len(self.clip) < CLIP_LEN:
                    outer.set_result(None)  # warm-up: no action tensor yet
                    return
                clip = np.stack(self.clip)  # [T, D]
                # raises RuntimeError when the engine is stopping
                dec_fut = self.dec_engine.submit(priority=prio,
                                                 stream=stream_id,
                                                 trace=tr,
                                                 clips=clip)
            except Exception as exc:  # noqa: BLE001 — propagate to the runner
                outer.set_exception(exc)
                return

            def _on_decoded(g: Future) -> None:
                try:
                    outer.set_result(g.result())
                except Exception as exc:  # noqa: BLE001
                    outer.set_exception(exc)

            dec_fut.add_done_callback(_on_decoded)

        enc_fut.add_done_callback(_on_encoded)
        return outer

    def complete(self, ctx: FrameContext, result: np.ndarray | None) -> list[FrameContext]:
        if result is None:
            return [ctx]  # clip warm-up (or no inference this frame)
        probs = result
        lid = int(np.argmax(probs))
        conf = float(probs[lid])
        if conf >= self.threshold:
            labels = self.dec_model.labels
            ctx.tensors.append(
                Tensor(
                    name="action",
                    confidence=conf,
                    label_id=lid,
                    label=labels[lid] if lid < len(labels) else str(lid),
                    data=[float(x) for x in probs],
                )
            )
        return [ctx]


class AudioDetectStage(AsyncStage):
    """gvaaudiodetect counterpart: classify 1-second 16 kHz windows
    (reference pipelines/audio_detection/environment/pipeline.json:4-9,
    sliding-window parameter :34-38)."""

    WINDOW = 16000  # 1 s at 16 kHz

    def __init__(self, name: str, model_key: str, properties: dict, hub: EngineHub):
        self.name = name
        self.threshold = float(properties.get("threshold", 0.0))
        # sliding-window: stride as a fraction of the 1 s window
        # (reference default 0.2, pipeline.json:34-38)
        self.stride = max(1, int(self.WINDOW * float(properties.get("sliding-window", 0.2))))
        self.engine = hub.engine(
            "audio", model_key, properties.get("model-instance-id")
        )
        self.model = hub.model(model_key)
        if hub.warmup:
            self.engine.warm_async(
                windows=np.zeros(self.WINDOW, np.int16))
        self._buffer = np.zeros(0, np.int16)
        self._since_last = 0

    def submit(self, ctx: FrameContext) -> Future | None:
        if ctx.audio is None:
            return None
        self._buffer = np.concatenate([self._buffer, ctx.audio])[-self.WINDOW:]
        self._since_last += len(ctx.audio)
        if len(self._buffer) < self.WINDOW or self._since_last < self.stride:
            return None
        self._since_last = 0
        return self.engine.submit(priority=ctx.priority,
                                  stream=ctx.stream_id,
                                  trace=ctx.trace,
                                  windows=self._buffer.copy())

    def complete(self, ctx: FrameContext, result: np.ndarray | None) -> list[FrameContext]:
        if result is None:
            return [ctx]
        lid = int(np.argmax(result))
        conf = float(result[lid])
        if conf >= self.threshold:
            labels = self.model.labels
            ctx.tensors.append(
                Tensor(
                    name="detection",
                    confidence=conf,
                    label_id=lid,
                    label=labels[lid] if lid < len(labels) else str(lid),
                )
            )
        return [ctx]


class FusedDetectClassifyStage(AsyncStage):
    """Detect+classify fused into one engine round-trip.

    Produced by the stage builder's fusion pass when a classify stage
    follows detect in the chain (the standard object_classification /
    object_tracking templates): one frame upload and one packed
    readback replace two of each, doubling effective ingest bandwidth
    — the scarce resource on the host→TPU path. The ``object-class``
    filter runs inside the program (scores of non-matching classes are
    ineligible for the ROI budget); a row whose probability block is
    all-zero was not classified. Known trade-off vs the unfused pair:
    ROI crops come from the frame pre-resized to the detector's input
    (the 8x upload saving at 1080p), not a classification-sized
    ingest; reclassify-interval > 1 disables fusion entirely
    (stages/build.py _fusable)."""

    ROI_BUDGET = 8

    def __init__(
        self,
        name: str,
        det_key: str,
        cls_key: str,
        det_props: dict,
        cls_props: dict,
        hub: EngineHub,
    ):
        self.name = name
        self.det_threshold = float(det_props.get("threshold", 0.5))
        self.cls_threshold = float(cls_props.get("threshold", 0.0))
        self.object_class = cls_props.get("object-class")
        self.interval = _parse_interval(det_props)
        self.det_model = hub.model(det_key)
        allowed = None
        if self.object_class:
            allowed = tuple(
                i for i, lbl in enumerate(self.det_model.labels)
                if lbl == self.object_class
            )
        self.wire = "seed" if hub.device_synth else hub.wire_format
        self.ingest_size = _wire_safe_size(
            (self.det_model.preprocess.height, self.det_model.preprocess.width)
        )
        self.engine = hub.fused_engine(
            det_key,
            cls_key,
            det_props.get("model-instance-id"),
            roi_budget=self.ROI_BUDGET,
            score_threshold=ENGINE_SCORE_FLOOR,
            allowed_label_ids=allowed,
            synth_wire_hw=self.ingest_size,
        )
        self.cls_model = hub.model(cls_key)
        _warm_engine(hub, self.engine, self.ingest_size, self.wire)
        #: motion gate + coasting — same submit-side gating contract
        #: as DetectStage (detect properties drive it)
        self.gate = maybe_gate(
            det_props, engine_name=getattr(self.engine, "name", ""))
        self._coaster = RegionCoaster()
        self._count = 0
        self._last_regions: list[Region] = []

    def submit(self, ctx: FrameContext) -> Future | None:
        self._count += 1
        if self.gate is not None:
            if ctx.frame is not None and not _timed_gate_decide(
                    self.gate, ctx):
                ctx.scratch["gate_coast"] = self.gate.consecutive_skips
                return None
        elif (self._count - 1) % self.interval:
            return None
        return self.engine.submit(
            priority=ctx.priority,
            stream=ctx.stream_id,
            trace=ctx.trace,
            frames=_wire_frame(ctx.frame, self.ingest_size, self.wire))

    def complete(self, ctx: FrameContext, result: np.ndarray | None) -> list[FrameContext]:
        if result is None:
            steps = ctx.scratch.pop("gate_coast", 0)
            ctx.regions.extend(self._coaster.coast(steps))
            return [ctx]
        det_labels = self.det_model.labels
        head_slices = []
        offset = 7
        for head_name, n in self.cls_model.spec.heads:
            head_slices.append((head_name, offset, offset + n))
            offset += n
        regions = []
        for i, row in enumerate(result):
            x0, y0, x1, y1, score, label_id, valid = row[:7]
            if valid < 0.5 or score < self.det_threshold:
                continue
            lid = int(label_id)
            label = det_labels[lid] if 0 <= lid < len(det_labels) else str(lid)
            region = Region(
                x0=float(x0), y0=float(y0), x1=float(x1), y1=float(y1),
                confidence=float(score), label_id=lid, label=label,
            )
            region.tensors.append(
                Tensor(name="detection", confidence=float(score),
                       label_id=lid, label=label, is_detection=True)
            )
            # An all-zero probability block marks an unclassified row
            # (classified blocks are softmaxes summing to #heads).
            if row[7:].sum() > 0.5:
                for head_name, a, b in head_slices:
                    probs = row[a:b]
                    hid = int(np.argmax(probs))
                    conf = float(probs[hid])
                    if conf < self.cls_threshold:
                        continue
                    label_list = self.cls_model.head_labels.get(head_name, [])
                    region.tensors.append(
                        Tensor(
                            name=head_name,
                            confidence=conf,
                            label_id=hid,
                            label=label_list[hid] if hid < len(label_list) else str(hid),
                        )
                    )
            regions.append(region)
        self._last_regions = regions
        self._coaster.observe(regions)
        ctx.regions.extend(regions)
        return [ctx]

    def snapshot(self) -> dict | None:
        return _detect_state_snapshot(self)

    def restore(self, state: dict) -> None:
        _detect_state_restore(self, state)
