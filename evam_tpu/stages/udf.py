"""User-defined function stage (gvapython counterpart).

The reference's gvapython element runs a user Python class inside the
pipeline with ``module``, ``class`` and a JSON ``kwarg``
(reference pipelines/object_detection/object_zone_count/
pipeline.json:5-9, 44-65). Here the UDF API is:

* a class with ``__init__(**kwarg)`` and
  ``process_frame(ctx: FrameContext) -> bool | None`` — returning
  False drops the frame;
* or a module-level ``process_frame(ctx)`` function when no class is
  given.

Built-in extensions under ``evam_tpu.extensions`` mirror the
reference's spatial-analytics extensions (zone count, line crossing,
event convert)."""

from __future__ import annotations

import importlib

from evam_tpu.obs import get_logger
from evam_tpu.stages.base import Stage
from evam_tpu.stages.context import FrameContext

log = get_logger("stages.udf")

#: The reference's container layout mounts its stock extensions at
#: /home/pipeline-server/extensions/** (e.g. pipelines/object_tracking/
#: object_line_crossing/pipeline.json:7,34-55). An unmodified reference
#: pipeline.json therefore names paths that only exist in that image;
#: map their stems onto the built-in counterparts so those files run
#: here verbatim. Stems differing from ours are listed explicitly.
_REFERENCE_EXT_PREFIX = "/home/pipeline-server/extensions/"
_REFERENCE_EXT_ALIASES = {"gva_event_convert": "event_convert"}


def _resolve_reference_extension(path: str):
    from pathlib import Path

    stem = Path(path).stem
    name = _REFERENCE_EXT_ALIASES.get(stem, stem)
    try:
        return importlib.import_module(f"evam_tpu.extensions.{name}")
    except ImportError:
        raise ImportError(
            f"reference extension path {path!r} has no built-in "
            f"counterpart evam_tpu.extensions.{name}"
        ) from None


class UdfStage(Stage):
    def __init__(self, name: str, properties: dict):
        self.name = name
        module_name = properties.get("module")
        if not module_name:
            raise ValueError(f"udf stage '{name}' needs a 'module' property")
        from pathlib import Path as _Path

        if (module_name.startswith(_REFERENCE_EXT_PREFIX)
                and not _Path(module_name).exists()):
            # a real file at that path (mounted, as in the reference
            # container) always wins over the built-in mapping
            module = _resolve_reference_extension(module_name)
        elif module_name.endswith(".py"):
            # path form, as the reference uses absolute .py paths;
            # import under a unique name so same-stem files in
            # different directories never collide.
            module = _import_from_path(module_name)
        else:
            module = importlib.import_module(module_name)
        class_name = properties.get("class")
        kwarg = properties.get("kwarg", {}) or {}
        if class_name:
            self._impl = getattr(module, class_name)(**kwarg)
            self._fn = self._impl.process_frame
        else:
            self._impl = None
            self._fn = module.process_frame

    def process(self, ctx: FrameContext) -> list[FrameContext]:
        try:
            keep = self._fn(ctx)
        except Exception:  # noqa: BLE001 — a broken UDF must not kill the stream
            log.exception("udf %s failed on frame %d", self.name, ctx.seq)
            return [ctx]
        return [] if keep is False else [ctx]


def _import_from_path(path: str):
    import hashlib
    import importlib.util
    import sys
    from pathlib import Path

    p = Path(path).resolve()
    name = f"evam_udf_{p.stem}_{hashlib.sha1(str(p).encode()).hexdigest()[:8]}"
    if name in sys.modules:
        return sys.modules[name]
    spec = importlib.util.spec_from_file_location(name, p)
    if spec is None or spec.loader is None:
        raise ImportError(f"cannot load UDF from {path}")
    module = importlib.util.module_from_spec(spec)
    sys.modules[name] = module
    spec.loader.exec_module(module)
    return module
