"""Per-frame context flowing through a stream's stage chain.

The TPU-native restatement of DL Streamer's VideoFrame/ROI model: the
reference exposes regions with rect / object_id / tensors (name,
confidence, label_id, label) — consumed at
reference evas/publisher.py:193-230 — and JSON messages attached by
UDF extensions. FrameContext carries the same information as plain
Python data, with numpy arrays for geometry so stage math stays
vectorized.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

import numpy as np


@dataclass
class Tensor:
    """One inference result attached to a region (detection or
    classification attribute), mirroring the reference's region
    tensor fields (evas/publisher.py:216-228)."""

    name: str
    confidence: float
    label_id: int
    label: str = ""
    is_detection: bool = False
    data: list[float] | None = None


@dataclass
class Region:
    """A detected object. Geometry normalized [0,1] corners plus the
    pixel rect the reference publishes (charts/README.md:117 has both
    normalized bounding_box and pixel x/y/w/h)."""

    x0: float
    y0: float
    x1: float
    y1: float
    confidence: float
    label_id: int
    label: str
    object_id: int | None = None
    tensors: list[Tensor] = field(default_factory=list)

    def rect(self, width: int, height: int) -> tuple[int, int, int, int]:
        x = int(round(self.x0 * width))
        y = int(round(self.y0 * height))
        w = int(round((self.x1 - self.x0) * width))
        h = int(round((self.y1 - self.y0) * height))
        return x, y, w, h

    @property
    def box(self) -> np.ndarray:
        return np.asarray([self.x0, self.y0, self.x1, self.y1], np.float32)


@dataclass
class FrameContext:
    """State of one frame (or audio window) walking the stage chain."""

    frame: np.ndarray | None  # BGR uint8 [H,W,3]; None for audio
    pts_ns: int
    seq: int
    stream_id: str
    source_uri: str = ""
    regions: list[Region] = field(default_factory=list)
    #: frame-level tensors (action recognition, audio events)
    tensors: list[Tensor] = field(default_factory=list)
    #: JSON messages attached by UDF stages (events etc.)
    messages: list[dict[str, Any]] = field(default_factory=list)
    #: serialized metadata (set by metaconvert)
    metadata: dict[str, Any] | None = None
    #: audio samples for audio pipelines (int16 [S])
    audio: np.ndarray | None = None
    #: stage cursor used by the runner
    stage_index: int = 0
    #: wall-clock ingest time (perf_counter) for latency histograms
    ingest_t: float | None = None
    #: QoS class of the owning stream (realtime|standard|batch) —
    #: engine-backed stages pass it to BatchEngine.submit so the
    #: shared engines schedule per class (evam_tpu/sched/)
    priority: str = "standard"
    #: per-frame trace handle (obs/trace.py FrameTrace), minted at
    #: ingest and threaded into engine submits for batch↔frame span
    #: linkage; None when EVAM_TRACE=off
    trace: Any | None = None
    #: arbitrary cross-stage scratch (e.g. pending futures)
    scratch: dict[str, Any] = field(default_factory=dict)

    @property
    def height(self) -> int:
        return 0 if self.frame is None else int(self.frame.shape[0])

    @property
    def width(self) -> int:
        return 0 if self.frame is None else int(self.frame.shape[1])
