"""Host-side multi-object tracker (gvatrack counterpart).

The reference's gvatrack assigns persistent ``object_id``s visible in
the published metadata (reference evas/publisher.py:210, parameter
surface pipelines/object_tracking/person_vehicle_bike/
pipeline.json:47-53). This is a vectorized-numpy IoU tracker with the
reference's tracking-type semantics made behavioral (round-1 VERDICT
"tracking types silently aliased"):

* ``zero-term`` / ``zero-term-imageless`` — ids persist only across
  consecutive detections: an unmatched track is dropped immediately
  (no coasting, no motion model);
* ``short-term`` / ``short-term-imageless`` — unmatched tracks coast
  for ``max-age`` frames with constant-velocity extrapolation, so a
  briefly-occluded moving object re-acquires its id;
* ``iou`` — plain greedy IoU with age-based expiry, no motion model.

Tracking state is per-stream host state — it never enters the jitted
step, so stream isolation is preserved across batched TPU steps
(SURVEY.md §7 "hard parts": tracking statefulness)."""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from evam_tpu.obs import get_logger
from evam_tpu.stages.base import Stage
from evam_tpu.stages.context import FrameContext, Region

log = get_logger("stages.track")


def _iou_matrix_np(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    area_a = np.maximum(a[:, 2] - a[:, 0], 0) * np.maximum(a[:, 3] - a[:, 1], 0)
    area_b = np.maximum(b[:, 2] - b[:, 0], 0) * np.maximum(b[:, 3] - b[:, 1], 0)
    lt = np.maximum(a[:, None, :2], b[None, :, :2])
    rb = np.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = np.maximum(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    return inter / np.maximum(area_a[:, None] + area_b[None, :] - inter, 1e-9)


@dataclass
class _Track:
    track_id: int
    box: np.ndarray
    label_id: int
    age: int = 0
    hits: int = 1
    vel: np.ndarray = field(
        default_factory=lambda: np.zeros(4, np.float32)
    )


class IouTracker:
    def __init__(
        self,
        iou_threshold: float = 0.3,
        max_age: int = 10,
        extrapolate: bool = False,
    ):
        self.iou_threshold = iou_threshold
        self.max_age = max_age
        self.extrapolate = extrapolate
        self.tracks: list[_Track] = []
        self._next_id = 1

    def update(self, regions: list[Region]) -> None:
        """Assign object_ids to regions in place."""
        if self.tracks and regions:
            det_boxes = np.stack([r.box for r in regions])
            trk_boxes = np.stack([t.box for t in self.tracks])
            iou = _iou_matrix_np(trk_boxes, det_boxes)
            # class-gated: a person detection never continues a car track
            for ti, t in enumerate(self.tracks):
                for di, r in enumerate(regions):
                    if r.label_id != t.label_id:
                        iou[ti, di] = 0.0
        else:
            iou = np.zeros((len(self.tracks), len(regions)), np.float32)

        matched_tracks: set[int] = set()
        matched_dets: set[int] = set()
        if iou.size:
            order = np.dstack(np.unravel_index(np.argsort(-iou, axis=None), iou.shape))[0]
            for ti, di in order:
                if iou[ti, di] < self.iou_threshold:
                    break
                if ti in matched_tracks or di in matched_dets:
                    continue
                matched_tracks.add(int(ti))
                matched_dets.add(int(di))
                track = self.tracks[ti]
                new_box = np.asarray(regions[di].box, np.float32)
                old_box = np.asarray(track.box, np.float32)
                if track.age == 0:
                    # velocity from consecutive hits only — a box that
                    # coasted already has vel applied
                    track.vel = new_box - old_box
                track.box = new_box
                track.age = 0
                track.hits += 1
                regions[di].object_id = track.track_id

        for di, region in enumerate(regions):
            if di in matched_dets:
                continue
            track = _Track(
                self._next_id, np.asarray(region.box, np.float32),
                region.label_id,
            )
            self._next_id += 1
            self.tracks.append(track)
            region.object_id = track.track_id

        survivors = []
        assigned = {r.object_id for r in regions}
        for ti, track in enumerate(self.tracks):
            if ti not in matched_tracks and track.track_id not in assigned:
                track.age += 1
                if self.extrapolate:
                    # constant-velocity coast: the next frame's match
                    # gates against the predicted position, so a
                    # moving object survives a short occlusion
                    track.box = track.box + track.vel
            if track.age <= self.max_age:
                survivors.append(track)
        self.tracks = survivors


class TrackStage(Stage):
    #: tracking-type → (coasting frames override, motion extrapolation)
    _TYPES = {
        "iou": (None, False),
        "zero-term": (0, False),
        "zero-term-imageless": (0, False),
        "short-term": (None, True),
        "short-term-imageless": (None, True),
    }

    def __init__(self, name: str, properties: dict):
        self.name = name
        ttype = properties.get("tracking-type", "iou")
        if ttype not in self._TYPES:
            raise ValueError(f"unsupported tracking-type '{ttype}'")
        max_age_override, extrapolate = self._TYPES[ttype]
        max_age = int(properties.get("max-age", 10))
        if max_age_override is not None:
            max_age = max_age_override
        self.tracker = IouTracker(
            iou_threshold=float(properties.get("iou-threshold", 0.3)),
            max_age=max_age,
            extrapolate=extrapolate,
        )
        log.info(
            "tracker %s: type=%s coasting max_age=%d extrapolate=%s",
            name, ttype, max_age, extrapolate,
        )

    def process(self, ctx: FrameContext) -> list[FrameContext]:
        self.tracker.update(ctx.regions)
        return [ctx]

    def snapshot(self) -> dict | None:
        # id monotonicity is the cross-restart invariant consumers
        # depend on (object_id in published metadata, reference
        # evas/publisher.py:210); track boxes themselves re-associate
        # within a few frames and are not worth serializing
        return {"next_id": self.tracker._next_id}

    def restore(self, state: dict) -> None:
        self.tracker._next_id = int(state.get("next_id", 1))
