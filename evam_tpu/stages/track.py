"""Host-side multi-object tracker (gvatrack counterpart).

The reference's gvatrack assigns persistent ``object_id``s visible in
the published metadata (reference evas/publisher.py:210, parameter
surface pipelines/object_tracking/person_vehicle_bike/
pipeline.json:47-53). This is a vectorized-numpy IoU tracker with the
reference's tracking-type semantics made behavioral (round-1 VERDICT
"tracking types silently aliased"):

* ``zero-term`` / ``zero-term-imageless`` — ids persist only across
  consecutive detections: an unmatched track is dropped immediately
  (no coasting, no motion model);
* ``short-term`` / ``short-term-imageless`` — unmatched tracks coast
  for ``max-age`` frames with constant-velocity extrapolation, so a
  briefly-occluded moving object re-acquires its id;
* ``iou`` — plain greedy IoU with age-based expiry, no motion model.

Tracking state is per-stream host state — it never enters the jitted
step, so stream isolation is preserved across batched TPU steps
(SURVEY.md §7 "hard parts": tracking statefulness)."""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from evam_tpu.obs import get_logger
from evam_tpu.stages.base import Stage
from evam_tpu.stages.context import FrameContext, Region

log = get_logger("stages.track")


def _iou_matrix_np(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    area_a = np.maximum(a[:, 2] - a[:, 0], 0) * np.maximum(a[:, 3] - a[:, 1], 0)
    area_b = np.maximum(b[:, 2] - b[:, 0], 0) * np.maximum(b[:, 3] - b[:, 1], 0)
    lt = np.maximum(a[:, None, :2], b[None, :, :2])
    rb = np.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = np.maximum(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    return inter / np.maximum(area_a[:, None] + area_b[None, :] - inter, 1e-9)


@dataclass
class _Track:
    track_id: int
    box: np.ndarray
    label_id: int
    age: int = 0
    hits: int = 1
    vel: np.ndarray = field(
        default_factory=lambda: np.zeros(4, np.float32)
    )


class IouTracker:
    def __init__(
        self,
        iou_threshold: float = 0.3,
        max_age: int = 10,
        extrapolate: bool = False,
    ):
        self.iou_threshold = iou_threshold
        self.max_age = max_age
        self.extrapolate = extrapolate
        self.tracks: list[_Track] = []
        self._next_id = 1

    def update(self, regions: list[Region]) -> None:
        """Assign object_ids to regions in place."""
        if self.tracks and regions:
            det_boxes = np.stack([r.box for r in regions])
            trk_boxes = np.stack([t.box for t in self.tracks])
            iou = _iou_matrix_np(trk_boxes, det_boxes)
            # class-gated: a person detection never continues a car track
            for ti, t in enumerate(self.tracks):
                for di, r in enumerate(regions):
                    if r.label_id != t.label_id:
                        iou[ti, di] = 0.0
        else:
            iou = np.zeros((len(self.tracks), len(regions)), np.float32)

        matched_tracks: set[int] = set()
        matched_dets: set[int] = set()
        if iou.size:
            order = np.dstack(np.unravel_index(np.argsort(-iou, axis=None), iou.shape))[0]
            for ti, di in order:
                if iou[ti, di] < self.iou_threshold:
                    break
                if ti in matched_tracks or di in matched_dets:
                    continue
                matched_tracks.add(int(ti))
                matched_dets.add(int(di))
                track = self.tracks[ti]
                new_box = np.asarray(regions[di].box, np.float32)
                old_box = np.asarray(track.box, np.float32)
                if track.age == 0:
                    # velocity from consecutive hits only — a box that
                    # coasted already has vel applied
                    track.vel = new_box - old_box
                track.box = new_box
                track.age = 0
                track.hits += 1
                regions[di].object_id = track.track_id

        for di, region in enumerate(regions):
            if di in matched_dets:
                continue
            track = _Track(
                self._next_id, np.asarray(region.box, np.float32),
                region.label_id,
            )
            self._next_id += 1
            self.tracks.append(track)
            region.object_id = track.track_id

        survivors = []
        assigned = {r.object_id for r in regions}
        for ti, track in enumerate(self.tracks):
            if ti not in matched_tracks and track.track_id not in assigned:
                track.age += 1
                if self.extrapolate:
                    # constant-velocity coast: the next frame's match
                    # gates against the predicted position, so a
                    # moving object survives a short occlusion
                    track.box = track.box + track.vel
            if track.age <= self.max_age:
                survivors.append(track)
        self.tracks = survivors

    def state_dict(self) -> dict:
        """Serializable tracker state for a StreamCheckpoint: live
        tracks (boxes, velocities, ages) plus the id counter, so a
        migrated stream re-associates immediately with the SAME
        object ids instead of reissuing."""
        return {
            "next_id": int(self._next_id),
            "tracks": [
                {
                    "track_id": int(t.track_id),
                    "box": [float(v) for v in t.box],
                    "label_id": int(t.label_id),
                    "age": int(t.age),
                    "hits": int(t.hits),
                    "vel": [float(v) for v in t.vel],
                }
                for t in self.tracks
            ],
        }

    def load_state(self, state: dict) -> None:
        self._next_id = max(
            int(state.get("next_id", 1)), self._next_id)
        tracks = []
        for row in state.get("tracks", []):
            try:
                tracks.append(_Track(
                    track_id=int(row["track_id"]),
                    box=np.asarray(row["box"], np.float32),
                    label_id=int(row["label_id"]),
                    age=int(row.get("age", 0)),
                    hits=int(row.get("hits", 1)),
                    vel=np.asarray(
                        row.get("vel", [0, 0, 0, 0]), np.float32),
                ))
            except (KeyError, TypeError, ValueError):
                continue  # a malformed track row is dropped, not fatal
        self.tracks = tracks


class RegionCoaster:
    """Copy-on-write reuse + constant-velocity coasting of the last
    inferred detections, shared by the motion gate's skip path and the
    static ``inference-interval`` reuse path (stages/infer.py).

    The old skip path deep-copied ``_last_regions`` per skipped frame
    per stream — measurable host overhead at 64-stream fan-in, and a
    frozen box under motion. Here:

    * ``observe(regions)`` records each real inference and estimates
      per-region velocity by class-gated greedy IoU match against the
      previous inference (the same association rule as IouTracker);
    * ``reuse()`` returns cheap shallow-frozen clones — fresh Region
      objects (downstream stages mutate ``object_id`` and append to
      ``tensors``) sharing the immutable Tensor payloads, value-equal
      to the old deepcopy;
    * ``coast(steps)`` returns the same clones advanced ``steps``
      frames along the estimated velocity (clipped to [0, 1]) — the
      tracker's short-term extrapolation applied at the detection
      layer, so a gated-away frame still tracks a moving object.
    """

    def __init__(self) -> None:
        self._regions: list[Region] = []
        self._vels: list[np.ndarray] = []

    def observe(self, regions: list[Region]) -> None:
        vels = [np.zeros(4, np.float32) for _ in regions]
        if self._regions and regions:
            prev_boxes = np.stack([r.box for r in self._regions])
            cur_boxes = np.stack([r.box for r in regions])
            iou = _iou_matrix_np(prev_boxes, cur_boxes)
            for pi, p in enumerate(self._regions):
                for ci, c in enumerate(regions):
                    if p.label_id != c.label_id:
                        iou[pi, ci] = 0.0
            used_prev: set[int] = set()
            used_cur: set[int] = set()
            order = np.dstack(
                np.unravel_index(np.argsort(-iou, axis=None), iou.shape))[0]
            for pi, ci in order:
                if iou[pi, ci] < 0.05:
                    break
                if pi in used_prev or ci in used_cur:
                    continue
                used_prev.add(int(pi))
                used_cur.add(int(ci))
                vels[ci] = cur_boxes[ci] - prev_boxes[pi]
        self._regions = regions
        self._vels = vels

    @staticmethod
    def _clone(region: Region, delta: np.ndarray) -> Region:
        box = np.clip(region.box + delta, 0.0, 1.0)
        out = Region(
            x0=float(box[0]), y0=float(box[1]),
            x1=float(box[2]), y1=float(box[3]),
            confidence=region.confidence,
            label_id=region.label_id,
            label=region.label,
            object_id=region.object_id,
            # fresh list, shared (never-mutated) Tensor payloads: a
            # downstream append touches only this frame's clone
            tensors=list(region.tensors),
        )
        return out

    def reuse(self) -> list[Region]:
        """Value-equal stand-ins for the last detections (steps=0) —
        the byte-identical replacement for the old deepcopy path."""
        zero = np.zeros(4, np.float32)
        return [self._clone(r, zero) for r in self._regions]

    def coast(self, steps: int) -> list[Region]:
        """The last detections advanced ``steps`` frames along their
        estimated velocities (the gate's skip path)."""
        if steps <= 0:
            return self.reuse()
        return [
            self._clone(r, v * float(steps))
            for r, v in zip(self._regions, self._vels)
        ]

    def state_dict(self) -> dict:
        """Serializable coaster state for a StreamCheckpoint: the
        last detections' geometry/identity plus per-region velocity.
        Classifier Tensor payloads are NOT carried — a restored
        coast serves boxes+ids until the next real inference refills
        attributes (the same contract as a gate skip after restart)."""
        return {
            "regions": [
                {
                    "box": [r.x0, r.y0, r.x1, r.y1],
                    "confidence": float(r.confidence),
                    "label_id": int(r.label_id),
                    "label": r.label,
                    "object_id": r.object_id,
                }
                for r in self._regions
            ],
            "vels": [[float(v) for v in vel] for vel in self._vels],
        }

    def load_state(self, state: dict) -> None:
        regions, vels = [], []
        rows = state.get("regions", [])
        raw_vels = state.get("vels", [])
        for i, row in enumerate(rows):
            try:
                box = row["box"]
                regions.append(Region(
                    x0=float(box[0]), y0=float(box[1]),
                    x1=float(box[2]), y1=float(box[3]),
                    confidence=float(row.get("confidence", 0.0)),
                    label_id=int(row.get("label_id", 0)),
                    label=str(row.get("label", "")),
                    object_id=row.get("object_id"),
                ))
            except (KeyError, TypeError, ValueError, IndexError):
                continue
            vel = (raw_vels[i] if i < len(raw_vels) else [0, 0, 0, 0])
            vels.append(np.asarray(vel, np.float32))
        self._regions = regions
        self._vels = vels


class TrackStage(Stage):
    #: tracking-type → (coasting frames override, motion extrapolation)
    _TYPES = {
        "iou": (None, False),
        "zero-term": (0, False),
        "zero-term-imageless": (0, False),
        "short-term": (None, True),
        "short-term-imageless": (None, True),
    }

    def __init__(self, name: str, properties: dict):
        self.name = name
        ttype = properties.get("tracking-type", "iou")
        if ttype not in self._TYPES:
            raise ValueError(f"unsupported tracking-type '{ttype}'")
        max_age_override, extrapolate = self._TYPES[ttype]
        max_age = int(properties.get("max-age", 10))
        if max_age_override is not None:
            max_age = max_age_override
        self.tracker = IouTracker(
            iou_threshold=float(properties.get("iou-threshold", 0.3)),
            max_age=max_age,
            extrapolate=extrapolate,
        )
        log.info(
            "tracker %s: type=%s coasting max_age=%d extrapolate=%s",
            name, ttype, max_age, extrapolate,
        )

    def process(self, ctx: FrameContext) -> list[FrameContext]:
        self.tracker.update(ctx.regions)
        return [ctx]

    def snapshot(self) -> dict | None:
        # id monotonicity is the cross-restart invariant consumers
        # depend on (object_id in published metadata, reference
        # evas/publisher.py:210); track boxes themselves re-associate
        # within a few frames and are not worth serializing — UNLESS
        # checkpointing is on (EVAM_CKPT, evam_tpu/state/): a live
        # migration resumes mid-scene, where the full track set is
        # what preserves identities across the move
        from evam_tpu import state as stream_state

        if stream_state.active() is not None:
            return {"next_id": self.tracker._next_id,
                    "tracker": self.tracker.state_dict()}
        return {"next_id": self.tracker._next_id}

    def restore(self, state: dict) -> None:
        self.tracker._next_id = int(state.get("next_id", 1))
        if state.get("tracker"):
            self.tracker.load_state(state["tracker"])
