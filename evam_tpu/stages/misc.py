"""Format/windowing stages: convert, audiomixer, level.

These are pass-through or host-side buffering stages — the TPU engine
handles color/resize in-jit (evam_tpu.ops.preprocess), so the
reference's videoconvert/caps elements reduce to no-ops carrying
format hints, while the audio elements keep their buffering
semantics (reference pipelines/audio_detection/environment/
pipeline.json:4-9, 25-38)."""

from __future__ import annotations

import numpy as np

from evam_tpu.stages.base import Stage
from evam_tpu.stages.context import FrameContext


class ConvertStage(Stage):
    """videoconvert / caps-filter counterpart: format negotiation is
    compiled into the jitted preprocess, so this validates and passes
    through."""

    def __init__(self, name: str, properties: dict | None = None):
        self.name = name
        self.properties = properties or {}

    def process(self, ctx: FrameContext) -> list[FrameContext]:
        return [ctx]


class AudioMixStage(Stage):
    """audiomixer counterpart: re-chunks audio into
    ``output-buffer-duration`` windows (ns, reference default
    100000000 = 100 ms)."""

    def __init__(self, name: str, properties: dict | None = None):
        self.name = name
        props = properties or {}
        duration_ns = int(props.get("output-buffer-duration", 100_000_000))
        self.chunk = max(1, int(16000 * duration_ns / 1_000_000_000))
        self._buffer = np.zeros(0, np.int16)
        self._pts_ns = 0
        self._seq = 0

    def process(self, ctx: FrameContext) -> list[FrameContext]:
        if ctx.audio is None:
            return [ctx]
        self._buffer = np.concatenate([self._buffer, ctx.audio])
        out: list[FrameContext] = []
        while len(self._buffer) >= self.chunk:
            chunk, self._buffer = self._buffer[: self.chunk], self._buffer[self.chunk:]
            out.append(
                FrameContext(
                    frame=None,
                    pts_ns=self._pts_ns,
                    seq=self._seq,
                    stream_id=ctx.stream_id,
                    source_uri=ctx.source_uri,
                    audio=chunk,
                )
            )
            self._pts_ns += int(self.chunk / 16000 * 1_000_000_000)
            self._seq += 1
        return out


class LevelStage(Stage):
    """level counterpart: RMS/peak measurement, attached as a message
    when ``post-messages`` is set (reference pipeline.json:39-41)."""

    def __init__(self, name: str, properties: dict | None = None):
        self.name = name
        props = properties or {}
        self.post_messages = bool(props.get("post-messages", False))

    def process(self, ctx: FrameContext) -> list[FrameContext]:
        if ctx.audio is not None and self.post_messages:
            x = ctx.audio.astype(np.float64) / 32768.0
            rms = float(np.sqrt(np.mean(np.square(x)) + 1e-12))
            peak = float(np.max(np.abs(x)))
            ctx.messages.append(
                {"level": {"rms_db": 20 * np.log10(max(rms, 1e-9)),
                           "peak_db": 20 * np.log10(max(peak, 1e-9))}}
            )
        return [ctx]
