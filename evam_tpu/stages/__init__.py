from evam_tpu.stages.context import FrameContext, Region, Tensor
from evam_tpu.stages.build import build_stages
from evam_tpu.stages.runner import StreamRunner

__all__ = [
    "FrameContext",
    "Region",
    "Tensor",
    "build_stages",
    "StreamRunner",
]
