"""Metadata serialization and delivery stages.

``MetaconvertStage`` is the gvametaconvert counterpart: it renders the
frame's regions/tensors/messages into the exact JSON schema the
reference publishes (sample at reference charts/README.md:117-119):

    {"objects": [{"detection": {"bounding_box": {"x_min": ..,
     "y_min": .., "x_max": .., "y_max": ..}, "confidence": ..,
     "label": "vehicle", "label_id": 2}, "h": 101, "w": 66, "x": 1,
     "y": 56, "roi_type": "vehicle"}],
     "resolution": {"height": 432, "width": 768},
     "source": "<uri>", "timestamp": 49000000000}

plus ``id`` when tracked, classification attributes as extra object
keys, frame-level ``tensors`` (action/audio) with values inlined when
``add-tensor-data`` is true (reference pipelines/action_recognition/
general/pipeline.json:5), and UDF ``events``.

``PublishStage`` hands the rendered metadata to the instance's
destination (gvametapublish counterpart); ``SinkStage`` is the
appsink: results land in the instance's client-visible queue
(app_src_dst / app_dst pipelines, reference
pipelines/object_detection/app_src_dst/pipeline.json:5)."""

from __future__ import annotations

from typing import Any, Callable

from evam_tpu.stages.base import Stage
from evam_tpu.stages.context import FrameContext, Region


def region_to_object(region: Region, width: int, height: int) -> dict[str, Any]:
    x, y, w, h = region.rect(width, height)
    obj: dict[str, Any] = {
        "detection": {
            "bounding_box": {
                "x_min": region.x0,
                "y_min": region.y0,
                "x_max": region.x1,
                "y_max": region.y1,
            },
            "confidence": region.confidence,
            "label": region.label,
            "label_id": region.label_id,
        },
        "x": x,
        "y": y,
        "w": w,
        "h": h,
        "roi_type": region.label,
    }
    if region.object_id is not None:
        obj["id"] = region.object_id
    for tensor in region.tensors:
        if tensor.is_detection:
            continue
        obj[tensor.name] = {
            "label": tensor.label,
            "label_id": tensor.label_id,
            "confidence": tensor.confidence,
        }
    return obj


class MetaconvertStage(Stage):
    def __init__(self, name: str, properties: dict | None = None,
                 source_uri: str = ""):
        self.name = name
        props = properties or {}
        self.add_tensor_data = bool(props.get("add-tensor-data", False))
        self.source_uri = source_uri

    def process(self, ctx: FrameContext) -> list[FrameContext]:
        meta: dict[str, Any] = {
            "objects": [
                region_to_object(r, ctx.width, ctx.height) for r in ctx.regions
            ],
            "resolution": {"height": ctx.height, "width": ctx.width},
            "source": ctx.source_uri or self.source_uri,
            "timestamp": ctx.pts_ns,
        }
        if ctx.tensors:
            tensors = []
            for t in ctx.tensors:
                entry: dict[str, Any] = {
                    "name": t.name,
                    "label": t.label,
                    "label_id": t.label_id,
                    "confidence": t.confidence,
                }
                if self.add_tensor_data and t.data is not None:
                    entry["data"] = t.data
                tensors.append(entry)
            meta["tensors"] = tensors
        for message in ctx.messages:
            # UDF-attached messages merge at top level, matching the
            # reference's message handling (evas/publisher.py:198-201).
            meta.update(message)
        ctx.metadata = meta
        return [ctx]


class PublishStage(Stage):
    def __init__(self, name: str,
                 publish_fn: Callable[[FrameContext], None] | None = None):
        self.name = name
        self.publish_fn = publish_fn

    def process(self, ctx: FrameContext) -> list[FrameContext]:
        if self.publish_fn is not None and ctx.metadata is not None:
            self.publish_fn(ctx)
        return [ctx]


class SinkStage(Stage):
    def __init__(self, name: str,
                 sink_fn: Callable[[FrameContext], None] | None = None):
        self.name = name
        self.sink_fn = sink_fn

    def process(self, ctx: FrameContext) -> list[FrameContext]:
        if self.sink_fn is not None:
            self.sink_fn(ctx)
        return [ctx]
