"""Content-adaptive inference gating: skip engine round-trips on
temporally-redundant frames.

Surveillance-style video is mostly static; the reference's only lever
is a blind static ``inference-interval`` (stages/infer.py). This
module decides per frame, BEFORE ``submit()``, whether inference is
needed: a downsampled luma grid (native.luma_grid — O(grid) point
samples, computed on the decode/stream thread) is diffed against the
grid of the last *inferred* frame, and a small controller with
hysteresis, a max-skip bound and a forced-refresh period turns the
score into a run/skip decision. Skipped frames reuse the last
detections through the tracker's constant-velocity coasting path
(stages/track.py RegionCoaster) instead of a deep copy of stale boxes.

Activation (per stage, at construction):

* ``EVAM_GATE=off`` — hard kill switch: gating never engages, the
  static-interval path runs byte-identically (A/B; serving default
  until a TPU window validates accuracy);
* pipeline property ``inference-interval: "adaptive"`` — enables the
  gate for that stage;
* ``EVAM_GATE=on`` — enables it for every detect-class stage.

Knobs (property beats env): ``gate-threshold`` /
``EVAM_GATE_THRESHOLD`` (mean |Δluma| per pixel, 0-255 scale, above
which the scene counts as moving), ``gate-threshold-lo`` /
``EVAM_GATE_THRESHOLD_LO`` (hysteresis exit, default threshold/2),
``gate-max-skip`` / ``EVAM_GATE_MAX_SKIP`` (hard bound on consecutive
skips — the detection-staleness bound), ``gate-refresh`` /
``EVAM_GATE_REFRESH`` (forced re-inference period in frames, 0=off).

Observability: ``evam_gate_ran_total{engine}`` /
``evam_gate_skipped_total{engine}`` counters, per-stream gate state on
``/pipelines/.../{id}/status``, an aggregate ``gate`` block on
``/healthz`` and the serve bench contract line, and a process-wide
registry whose recent skipped-frames/s feeds the admission
controller's effective post-gate demand (sched/admission.py) — when
scenes are static, admission headroom grows.
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from collections import deque
from dataclasses import dataclass

import numpy as np

from evam_tpu.control.state import current_op
from evam_tpu.obs import get_logger, metrics

log = get_logger("stages.gate")

#: luma-grid resolution fed to native.luma_grid — coarse enough to be
#: free per frame, fine enough that an object crossing a 1/16th of the
#: frame moves the score
GRID_H = 16
GRID_W = 16

#: window over which the registry's skipped-frames/s rate (the
#: admission credit) is computed
RATE_WINDOW_S = 5.0


def _env_float(key: str, default: float) -> float:
    try:
        return float(os.environ.get(key, "") or default)
    except ValueError:
        return default


def _env_int(key: str, default: int) -> int:
    try:
        return int(os.environ.get(key, "") or default)
    except ValueError:
        return default


@dataclass(frozen=True)
class GateConfig:
    """Resolved gate knobs for one stage."""

    enabled: bool = False
    #: mean |Δluma| (0-255) at/above which the scene is "moving"
    threshold: float = 2.0
    #: hysteresis exit: once moving, stay moving until the score drops
    #: to this (default threshold/2) — flicker near the threshold must
    #: not toggle the gate every frame
    threshold_lo: float = 1.0
    #: hard bound on consecutive skipped frames — every object is
    #: re-validated by a real inference within this many frames
    max_skip: int = 8
    #: forced-refresh period: run at least every N frames regardless of
    #: motion state (0 = rely on max_skip alone)
    refresh: int = 30
    #: operator pinned the thresholds (explicit property or env var):
    #: the control plane's gate_scale must leave this gate alone —
    #: clamp-to-pinned-knob, per gate
    pinned: bool = False

    @classmethod
    def from_properties(cls, properties: dict) -> "GateConfig":
        """Property beats env beats default; ``EVAM_GATE=off`` beats
        everything (the byte-identical A/B kill switch)."""
        env_gate = os.environ.get("EVAM_GATE", "").strip().lower()
        interval = properties.get("inference-interval", 1)
        adaptive = (isinstance(interval, str)
                    and interval.strip().lower() == "adaptive")
        if env_gate in ("off", "0", "false"):
            enabled = False
        elif adaptive or env_gate in ("on", "1", "true"):
            enabled = True
        else:
            enabled = False
        thr = float(properties.get(
            "gate-threshold", _env_float("EVAM_GATE_THRESHOLD", 2.0)))
        lo_default = _env_float("EVAM_GATE_THRESHOLD_LO", thr / 2.0)
        lo = float(properties.get("gate-threshold-lo", lo_default))
        # any explicit threshold — per-pipeline property or global env
        # override — pins this gate against the controller's gate_scale
        pinned = ("gate-threshold" in properties
                  or "gate-threshold-lo" in properties
                  or "EVAM_GATE_THRESHOLD" in os.environ
                  or "EVAM_GATE_THRESHOLD_LO" in os.environ)
        return cls(
            enabled=enabled,
            threshold=thr,
            threshold_lo=min(lo, thr),
            max_skip=max(1, int(properties.get(
                "gate-max-skip", _env_int("EVAM_GATE_MAX_SKIP", 8)))),
            refresh=max(0, int(properties.get(
                "gate-refresh", _env_int("EVAM_GATE_REFRESH", 30)))),
            pinned=pinned,
        )


class MotionGate:
    """Per-stream run/skip controller.

    Owned by one inference stage, called from that stream's decode
    thread only — no locking on the decision path. ``decide(frame)``
    computes the luma-grid diff against the last INFERRED frame (not
    the previous frame: slow drift accumulates against the anchor and
    eventually crosses the threshold instead of hiding under it) and
    applies, in order: first-frame / forced-refresh / max-skip bounds,
    then the hysteresis state machine.
    """

    def __init__(self, cfg: GateConfig, engine_name: str = "",
                 clock=time.monotonic):
        self.cfg = cfg
        self.engine_name = engine_name
        self._clock = clock
        self._ref_grid: np.ndarray | None = None
        self._moving = True  # conservative until the first score
        self.ran = 0
        self.skipped = 0
        self.consecutive_skips = 0
        self.max_consecutive_skips = 0
        self._since_run = 0
        self.last_score = 0.0
        #: timestamps of recent skips, pruned to RATE_WINDOW_S — the
        #: admission credit (bounded: one entry per skipped frame)
        self._skip_times: deque[float] = deque(maxlen=8192)
        registry.add(self)

    # ------------------------------------------------------- decision

    def score(self, frame: np.ndarray) -> float:
        """Mean |Δluma| per grid cell (0-255) vs the last inferred
        frame; +inf when no reference exists yet (first frame)."""
        from evam_tpu import native

        self._pending_grid = native.luma_grid(frame, GRID_H, GRID_W)
        if self._ref_grid is None:
            return float("inf")
        d = np.abs(self._pending_grid.astype(np.int16)
                   - self._ref_grid.astype(np.int16))
        return float(d.mean())

    def decide(self, frame: np.ndarray) -> bool:
        """True = run inference on this frame; False = skip (coast)."""
        run = self.apply(self.score(frame))
        if run:
            # the reference anchor advances ONLY on inferred frames:
            # slow drift accumulates against it and eventually crosses
            # the threshold instead of hiding under a per-frame diff
            self._ref_grid = self._pending_grid
        return run

    def apply(self, s: float) -> bool:
        """The pure controller (unit-testable without frames): bounds
        first, then the hysteresis state machine; updates counters."""
        self.last_score = s if np.isfinite(s) else 0.0
        if not np.isfinite(s):
            run = True  # first frame always infers
        elif self.cfg.refresh and self._since_run + 1 >= self.cfg.refresh:
            run = True  # forced refresh: drift bound
        elif self.consecutive_skips >= self.cfg.max_skip:
            run = True  # staleness bound
        else:
            # hysteresis: enter "moving" at threshold, leave at
            # threshold_lo — a score between the two keeps the state.
            # The control plane's gate_scale stretches both bounds
            # (gate harder as utilization climbs) unless this gate's
            # thresholds were explicitly pinned; max_skip and refresh
            # stay untouched — the staleness/drift bounds hold at any
            # operating point.
            thr = self.cfg.threshold
            lo = self.cfg.threshold_lo
            if not self.cfg.pinned:
                op = current_op()
                if op is not None and op.gate_scale != 1.0:
                    thr *= op.gate_scale
                    lo *= op.gate_scale
            if s >= thr:
                self._moving = True
            elif s <= lo:
                self._moving = False
            run = self._moving
        if run:
            self.ran += 1
            self.consecutive_skips = 0
            self._since_run = 0
            metrics.inc("evam_gate_ran", labels={"engine": self.engine_name})
            registry.note(ran=1)
        else:
            self.skipped += 1
            self.consecutive_skips += 1
            self._since_run += 1
            self.max_consecutive_skips = max(
                self.max_consecutive_skips, self.consecutive_skips)
            self._skip_times.append(self._clock())
            metrics.inc("evam_gate_skipped",
                        labels={"engine": self.engine_name})
            registry.note(skipped=1)
        return run

    # -------------------------------------------------- introspection

    def skipped_fps(self, now: float | None = None) -> float:
        """Recent skip rate (frames/s) over RATE_WINDOW_S — the
        engine-side demand this stream is provably NOT generating."""
        now = self._clock() if now is None else now
        cutoff = now - RATE_WINDOW_S
        while self._skip_times and self._skip_times[0] < cutoff:
            self._skip_times.popleft()
        return len(self._skip_times) / RATE_WINDOW_S

    def state_dict(self) -> dict:
        """Serializable controller state for a StreamCheckpoint
        (evam_tpu/state/): the luma reference anchor, the hysteresis
        phase and the skip counters — everything a migrated stream
        needs to keep gating mid-scene instead of re-learning."""
        return {
            "ref_grid": (self._ref_grid.tolist()
                         if self._ref_grid is not None else None),
            "moving": bool(self._moving),
            "consecutive_skips": int(self.consecutive_skips),
            "since_run": int(self._since_run),
            "last_score": float(self.last_score),
        }

    def load_state(self, state: dict) -> None:
        """Re-apply a ``state_dict()`` on a freshly built gate. A
        shape-mismatched grid is dropped (the first frame then infers
        unconditionally — the cold-start rung, never an error)."""
        grid = state.get("ref_grid")
        if grid is not None:
            arr = np.asarray(grid, dtype=np.uint8)
            if arr.shape == (GRID_H, GRID_W):
                self._ref_grid = arr
        self._moving = bool(state.get("moving", True))
        self.consecutive_skips = int(state.get("consecutive_skips", 0))
        self._since_run = int(state.get("since_run", 0))
        self.last_score = float(state.get("last_score", 0.0))

    def force_refresh(self) -> None:
        """Stale-checkpoint rung: drop the reference anchor so the
        next frame re-infers unconditionally (a forced refresh — the
        gate's staleness bound never depends on restored state)."""
        self._ref_grid = None
        self._moving = True
        self.consecutive_skips = 0
        self._since_run = 0

    def snapshot(self) -> dict:
        """Per-stream gate state for /pipelines/.../{id}/status."""
        total = self.ran + self.skipped
        return {
            "enabled": self.cfg.enabled,
            "ran": self.ran,
            "skipped": self.skipped,
            "skip_rate": round(self.skipped / total, 3) if total else 0.0,
            "moving": self._moving,
            "last_score": round(self.last_score, 3),
            "consecutive_skips": self.consecutive_skips,
            "max_consecutive_skips": self.max_consecutive_skips,
            "max_skip": self.cfg.max_skip,
        }


class GateRegistry:
    """Process-wide gate aggregation.

    Two layers: cumulative ran/skipped counters that survive stream
    churn (the /healthz and bench-contract totals must stay
    monotonic), and a weak set of LIVE gates whose recent skip rates
    feed the admission controller's effective post-gate demand.
    """

    #: stream threads record, server/bench threads snapshot —
    #: mutations must hold ``_lock`` (lock-discipline pass).
    SHARED_UNDER = {
        "_gates": "_lock",
        "_ran": "_lock",
        "_skipped": "_lock",
    }

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._gates: "weakref.WeakSet[MotionGate]" = weakref.WeakSet()
        self._ran = 0
        self._skipped = 0

    def add(self, gate: MotionGate) -> None:
        with self._lock:
            if gate.cfg.enabled:
                self._gates.add(gate)

    def note(self, ran: int = 0, skipped: int = 0) -> None:
        with self._lock:
            self._ran += ran
            self._skipped += skipped

    def skipped_fps(self) -> float:
        """Summed recent skipped-frames/s across live gated streams —
        demand the engines are provably not seeing. A stopped stream's
        gate ages out of its own rate window (and out of the weak set
        once collected), so the credit decays on its own."""
        with self._lock:
            gates = list(self._gates)
        return sum(g.skipped_fps() for g in gates)

    def summary(self) -> dict:
        """Fixed-shape aggregate for /healthz and the bench line."""
        with self._lock:
            gates = list(self._gates)
            ran, skipped = self._ran, self._skipped
        total = ran + skipped
        return {
            "streams": len(gates),
            "ran": ran,
            "skipped": skipped,
            "skip_rate": round(skipped / total, 3) if total else 0.0,
            "skipped_fps": round(sum(g.skipped_fps() for g in gates), 1),
        }

    def reset(self) -> None:
        """Test/bench hook: drop cumulative counters and live gates."""
        with self._lock:
            self._gates = weakref.WeakSet()
            self._ran = 0
            self._skipped = 0


#: the process-wide registry (admission + healthz + bench consumers)
registry = GateRegistry()


def maybe_gate(properties: dict, engine_name: str = "") -> MotionGate | None:
    """Stage-side constructor: a MotionGate when the resolved config
    enables gating, else None (the static-interval path, untouched)."""
    cfg = GateConfig.from_properties(properties)
    if not cfg.enabled:
        return None
    log.info(
        "motion gate on (engine %s): threshold %.2f/%.2f, max_skip %d, "
        "refresh %d", engine_name, cfg.threshold, cfg.threshold_lo,
        cfg.max_skip, cfg.refresh,
    )
    return MotionGate(cfg, engine_name=engine_name)
