from evam_tpu.engine.batcher import BatchEngine, EngineStats
from evam_tpu.engine.hub import EngineHub
from evam_tpu.engine.ringbuf import STAGES, SlotRing
from evam_tpu.engine.supervisor import ENGINE_STATES, SupervisedEngine
from evam_tpu.engine.steps import (
    build_detect_step,
    build_classify_step,
    build_action_encode_step,
    build_action_decode_step,
    build_audio_step,
    DETECT_FIELDS,
)

__all__ = [
    "BatchEngine",
    "EngineStats",
    "EngineHub",
    "SlotRing",
    "STAGES",
    "SupervisedEngine",
    "ENGINE_STATES",
    "build_detect_step",
    "build_classify_step",
    "build_action_encode_step",
    "build_action_decode_step",
    "build_audio_step",
    "DETECT_FIELDS",
]
