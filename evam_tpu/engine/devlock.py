"""Global device-interaction serialization — the wedge defense.

Two rounds of hardware evidence (PROFILE.md r3/r4) point at the same
trigger for the irrecoverable axon-tunnel wedge: the serve path is the
only configuration where a background bucket-warmup COMPILE overlaps
steady-state dispatch RPCs from the engine threads — the raw
single-threaded bench loop running the same program sizes survived
every time. ``bench.py --config serve`` already preloads engines
before streams start (removes the overlap in the common case); this
module is the belt-and-braces defense the round-4 verdict asked for:
with ``EVAM_SERIALIZE_COMPILE=1`` every device interaction in the
engine (program launch, bucket-warmup compile, result readback) runs
under ONE process-wide lock, so a compile can never race an execute
RPC no matter what threads exist. The cost is double-buffering (batch
N+1 can no longer be enqueued while batch N computes) — acceptable
for a wedge-proof measurement mode, not the serving default.

The module also keeps an always-on concurrency gauge
(``max_concurrent()``): tests and ``tools/wedge_repro.py`` use it to
*demonstrate* the client-side overlap the serve path uniquely creates
and that the lock removes it (the reference has no analogue — its
inference runtime is an external C++ process; SURVEY.md §2b).
"""

from __future__ import annotations

import contextlib
import os
import threading

_lock = threading.RLock()
_stats_lock = threading.Lock()
_active = 0
_max_concurrent = 0
_depth = threading.local()  # nested spans on one thread count once


def enabled() -> bool:
    """``EVAM_SERIALIZE_COMPILE=1``: serialize every engine device
    call process-wide. Read per-call so a bench/test can flip it."""
    return os.environ.get("EVAM_SERIALIZE_COMPILE", "0").lower() in (
        "1", "true", "yes")


def reset_stats() -> None:
    global _max_concurrent
    with _stats_lock:
        _max_concurrent = 0


def max_concurrent() -> int:
    """High-water mark of concurrent device calls since the last
    ``reset_stats()`` — 1 proves serialization held."""
    with _stats_lock:
        return _max_concurrent


@contextlib.contextmanager
def _track():
    global _active, _max_concurrent
    depth = getattr(_depth, "n", 0)
    _depth.n = depth + 1
    if depth == 0:
        with _stats_lock:
            _active += 1
            _max_concurrent = max(_max_concurrent, _active)
    try:
        yield
    finally:
        _depth.n = depth
        if depth == 0:
            with _stats_lock:
                _active -= 1


@contextlib.contextmanager
def device_call(tag: str = ""):
    """Wrap one device interaction (launch / compile / readback).

    No-op (tracking only) unless serialization is enabled.
    """
    if enabled():
        with _lock, _track():
            yield
    else:
        with _track():
            yield
