"""The shared batch engine — the heart of the framework.

Where the reference runs one inference engine per GStreamer pipeline
(optionally shared via ``model-instance-id``,
reference pipelines/object_detection/person_vehicle_bike/
pipeline.json:26-32), evam_tpu runs ONE BatchEngine per model
instance and multiplexes every active stream into it (BASELINE.json
north_star). Three cooperating threads per engine:

  submit() ──queue──► dispatcher ──in-flight──► completion ──► futures

* the **dispatcher** collects items up to a batch deadline
  (latency/occupancy tension, SURVEY.md §7 "hard parts"), pads to a
  bucketed batch size (bounded compile count), places the batch on
  the mesh (data-axis sharded) and launches the jitted step —
  WITHOUT waiting for the result;
* the **completion** thread performs the single device→host readback
  per batch and resolves per-item futures. Keeping dispatch and
  readback on separate threads double-buffers the device: batch N+1
  is enqueued while batch N computes (the decode-ahead/infer overlap
  the reference gets from GStreamer element threads, SURVEY.md §2d-5);
* an in-flight semaphore bounds device queueing (backpressure, the
  analogue of the reference msgbus ``zmq_recv_hwm``,
  eii/config.json:37).
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from concurrent.futures import Future
from typing import Callable

import jax
import numpy as np

from evam_tpu.engine import devlock
from evam_tpu.obs import get_logger, metrics
from evam_tpu.parallel.mesh import MeshPlan

log = get_logger("engine.batcher")


@dataclasses.dataclass
class _WorkItem:
    inputs: dict[str, np.ndarray]
    future: Future
    t_submit: float


def _safe_set_result(fut: Future, value) -> None:
    """The watchdog may have already failed this future; a late
    success from an unwedged backend must not crash the completer."""
    try:
        fut.set_result(value)
    except Exception:  # noqa: BLE001 — InvalidStateError
        pass


def _safe_set_exception(fut: Future, exc: Exception) -> None:
    try:
        fut.set_exception(exc)
    except Exception:  # noqa: BLE001 — InvalidStateError
        pass


@dataclasses.dataclass
class EngineStats:
    batches: int = 0
    items: int = 0
    occupancy_sum: float = 0.0

    @property
    def mean_occupancy(self) -> float:
        return self.occupancy_sum / self.batches if self.batches else 0.0


class BatchEngine:
    """Deadline-batching dispatcher around one jitted step function.

    ``step_fn(params, **batch) -> packed`` must accept stacked inputs
    (leading batch axis) and return one array whose leading axis
    matches. Bucketed batch sizes keep the number of distinct
    compiled programs small (recompilation-storm guard)."""

    def __init__(
        self,
        name: str,
        step_fn: Callable,
        params,
        plan: MeshPlan | None = None,
        max_batch: int = 32,
        deadline_ms: float = 8.0,
        max_in_flight: int = 3,
        input_names: tuple[str, ...] = ("frames",),
        stall_timeout_s: float = 120.0,
    ):
        self.name = name
        self.plan = plan
        self.max_batch = max_batch
        self.deadline_s = deadline_ms / 1000.0
        self.input_names = input_names
        self.stats = EngineStats()
        #: watchdog bound on one batch's device round-trip; a wedged
        #: backend (e.g. a dead TPU tunnel) blocks the dispatcher in
        #: C++ forever — the watchdog can't unblock it, but it CAN
        #: fail the stranded futures and flag the engine so /healthz
        #: degrades and callers stop queueing into a black hole
        #: (SURVEY §5.3 failure detection; 0 disables).
        self.stall_timeout_s = stall_timeout_s
        #: set when a batch exceeded stall_timeout_s (engine is
        #: considered wedged; submit() fails fast). Cleared if the
        #: wedged call later completes (slow compile, transient hang).
        self.stalled = threading.Event()
        #: every dispatched-but-not-completed batch: id → (t_dispatch,
        #: items). Covers the device launch, the _done queue wait, AND
        #: the readback — a wedge anywhere strands nothing.
        self._outstanding: dict[int, tuple[float, list[_WorkItem]]] = {}
        self._next_batch_id = 0
        self._exec_lock = threading.Lock()

        d = plan.data_size if plan else 1
        top = plan.pad_batch(max_batch) if plan else max_batch
        self.buckets = []
        b = d
        while b < top:
            self.buckets.append(b)
            b *= 2
        self.buckets.append(top)

        if plan is not None:
            self._params = jax.device_put(params, plan.replicated())
            self._jit_step = jax.jit(
                step_fn,
                in_shardings=(
                    plan.replicated(),
                    *([plan.batch_sharding()] * len(input_names)),
                ),
            )
        else:
            self._params = params
            self._jit_step = jax.jit(step_fn)

        self._queue: queue.Queue[_WorkItem | None] = queue.Queue()
        self._done: queue.Queue[tuple | None] = queue.Queue()
        self._warm_lock = threading.Lock()
        self._warming = False
        #: set when background warmup finishes (or fails)
        self.warmed = threading.Event()
        self._in_flight = threading.Semaphore(max_in_flight)
        self._stop = threading.Event()
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name=f"engine-{name}-dispatch", daemon=True
        )
        self._completer = threading.Thread(
            target=self._completion_loop, name=f"engine-{name}-complete", daemon=True
        )
        self._dispatcher.start()
        self._completer.start()
        if self.stall_timeout_s > 0:
            threading.Thread(
                target=self._watchdog_loop,
                name=f"engine-{name}-watchdog", daemon=True,
            ).start()

    # ------------------------------------------------------------- API

    def submit(self, **inputs: np.ndarray) -> Future:
        """Enqueue one item (no batch dim); resolves to its packed row(s)."""
        if self._stop.is_set():
            raise RuntimeError(f"engine {self.name} is stopped")
        if self.stalled.is_set():
            # the dispatcher is wedged inside a device call — queueing
            # more work would strand more futures
            raise RuntimeError(
                f"engine {self.name} is stalled (device call exceeded "
                f"{self.stall_timeout_s:.0f}s — backend wedged?)"
            )
        if set(inputs) != set(self.input_names):
            raise ValueError(
                f"engine {self.name} expects inputs {self.input_names}, got {tuple(inputs)}"
            )
        fut: Future = Future()
        self._queue.put(_WorkItem(inputs, fut, time.perf_counter()))
        return fut

    def warmup(self) -> None:
        """Compile every bucket size ahead of traffic."""
        example = self._example_item()
        for b in self.buckets:
            batch = {
                k: np.broadcast_to(v, (b,) + v.shape).copy()
                for k, v in example.items()
            }
            # whole compile+execute+readback under one devlock span:
            # a warmup must never leave a half-overlapped RPC behind
            with devlock.device_call(f"{self.name}:warmup"):
                np.asarray(self._run(batch))
        log.info("engine %s warmed %d buckets %s", self.name, len(self.buckets), self.buckets)

    def warm_async(self, **example: np.ndarray) -> None:
        """Fire-and-forget bucket precompilation (serving path: kills
        the mid-traffic compile spike when a batch first crosses a
        bucket boundary). Idempotent."""
        with self._warm_lock:
            if self._warming:
                return
            self._warming = True
        self.set_example(**example)
        threading.Thread(
            target=self._warm_guarded,
            name=f"engine-{self.name}-warmup",
            daemon=True,
        ).start()

    def _warm_guarded(self) -> None:
        try:
            self.warmup()
        except Exception as exc:  # noqa: BLE001 — warmup must never kill serving
            log.warning("engine %s warmup failed: %s", self.name, exc)
        finally:
            self.warmed.set()

    def stop(self) -> None:
        self._stop.set()
        self._queue.put(None)
        self._dispatcher.join(timeout=10)
        self._done.put(None)
        self._completer.join(timeout=10)
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if item is not None:
                item.future.set_exception(RuntimeError("engine stopped"))

    # -------------------------------------------------------- internals

    def _example_item(self) -> dict[str, np.ndarray]:
        item = self._peek_shapes
        if item is None:
            raise RuntimeError("warmup requires example_shapes")
        return item

    #: optional dict name -> example array (no batch dim) for warmup
    _peek_shapes: dict[str, np.ndarray] | None = None

    def set_example(self, **example: np.ndarray) -> None:
        self._peek_shapes = example

    def _bucket(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def _run(self, batch: dict[str, np.ndarray]):
        # devlock: with EVAM_SERIALIZE_COMPILE=1 this launch (and any
        # compile it triggers) cannot overlap another engine thread's
        # device RPC — the wedge-proof measurement mode
        with devlock.device_call(f"{self.name}:launch"):
            arrays = []
            for name in self.input_names:
                a = batch[name]
                if self.plan is not None:
                    a = jax.device_put(a, self.plan.batch_sharding())
                arrays.append(a)
            return self._jit_step(self._params, *arrays)

    def _dispatch_loop(self) -> None:
        while not self._stop.is_set():
            try:
                first = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            if first is None:
                break
            items = [first]
            deadline = time.perf_counter() + self.deadline_s
            while len(items) < self.max_batch:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    nxt = self._queue.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is None:
                    self._stop.set()
                    break
                items.append(nxt)

            n = len(items)
            b = self._bucket(n)
            batch: dict[str, np.ndarray] = {}
            for name in self.input_names:
                rows = [it.inputs[name] for it in items]
                stacked = np.stack(rows)
                if b > n:
                    pad = np.zeros((b - n,) + stacked.shape[1:], stacked.dtype)
                    stacked = np.concatenate([stacked, pad])
                batch[name] = stacked

            self._in_flight.acquire()
            t0 = time.perf_counter()
            with self._exec_lock:
                bid = self._next_batch_id
                self._next_batch_id += 1
                self._outstanding[bid] = (t0, items)
            try:
                out = self._run(batch)
            except Exception as exc:  # noqa: BLE001 — surface to every caller
                self._in_flight.release()
                with self._exec_lock:
                    self._outstanding.pop(bid, None)
                for it in items:
                    _safe_set_exception(it.future, exc)
                log.exception("engine %s step failed", self.name)
                continue
            self._done.put((out, items, t0, bid))
            self.stats.batches += 1
            self.stats.items += n
            self.stats.occupancy_sum += n / b
            metrics.observe("evam_batch_occupancy", n / b, {"engine": self.name})
            metrics.set("evam_engine_queue_depth", self._queue.qsize(), {"engine": self.name})

    def _completion_loop(self) -> None:
        while True:
            entry = self._done.get()
            if entry is None:
                break
            out, items, t0, bid = entry
            try:
                with devlock.device_call(f"{self.name}:readback"):
                    host = np.asarray(out)  # single readback per batch
            except Exception as exc:  # noqa: BLE001
                for it in items:
                    _safe_set_exception(it.future, exc)
                self._in_flight.release()
                continue
            finally:
                with self._exec_lock:
                    self._outstanding.pop(bid, None)
            self._in_flight.release()
            if self.stalled.is_set():
                # the "wedged" call was merely slow (e.g. a mid-traffic
                # multichip compile) and has now completed — recover
                # instead of staying bricked until restart
                self.stalled.clear()
                log.warning(
                    "engine %s recovered: a previously-stalled device "
                    "call completed; accepting work again", self.name,
                )
            now = time.perf_counter()
            metrics.observe("evam_step_seconds", now - t0, {"engine": self.name})
            for i, it in enumerate(items):
                metrics.observe(
                    "evam_item_latency_seconds", now - it.t_submit, {"engine": self.name}
                )
                _safe_set_result(it.future, host[i])

    def _watchdog_loop(self) -> None:
        """Fail futures stranded behind a wedged device call and flag
        the engine (the dispatcher/completer threads stay blocked in
        C++ — only the service-level contract can be saved)."""
        interval = max(self.stall_timeout_s / 4.0, 1.0)
        while not self._stop.wait(interval):
            now = time.perf_counter()
            with self._exec_lock:
                slots = list(self._outstanding.values())
            stuck: list[_WorkItem] = []
            for t0, items in slots:
                if now - t0 > self.stall_timeout_s:
                    stuck.extend(items)
            if not stuck:
                continue
            self.stalled.set()
            log.error(
                "engine %s stalled: device call exceeded %.0fs; failing "
                "%d stranded item(s) and rejecting new work",
                self.name, self.stall_timeout_s, len(stuck),
            )
            metrics.inc("evam_engine_stalls", labels={"engine": self.name})
            exc = TimeoutError(
                f"engine {self.name} device call exceeded "
                f"{self.stall_timeout_s:.0f}s (backend wedged)"
            )
            for it in stuck:
                _safe_set_exception(it.future, exc)
            # strand nothing in the queue either
            while True:
                try:
                    queued = self._queue.get_nowait()
                except queue.Empty:
                    break
                if queued is not None:
                    _safe_set_exception(queued.future, exc)
