"""The shared batch engine — the heart of the framework.

Where the reference runs one inference engine per GStreamer pipeline
(optionally shared via ``model-instance-id``,
reference pipelines/object_detection/person_vehicle_bike/
pipeline.json:26-32), evam_tpu runs ONE BatchEngine per model
instance and multiplexes every active stream into it (BASELINE.json
north_star). Three cooperating threads per engine (four with the
pipelined transfer, the default):

  submit() ──slot──► dispatcher ──upload──► launcher ──► completion

* **submit()** (stream threads) writes each item's arrays straight
  into its reserved row of a pre-allocated staging slot
  (engine/ringbuf.py) — the one host copy, parallelized across
  submitters instead of serialized on the dispatcher;
* the **dispatcher** seals a slot at the batch deadline
  (latency/occupancy tension, SURVEY.md §7 "hard parts"): picks the
  bucket (bounded compile count), zeroes only the dirty pad tail
  (no stack, no concat, no allocation), places the block view on the
  mesh (data-axis sharded) and launches the jitted step — WITHOUT
  waiting for the result;
* the **launcher** thread (``EVAM_TRANSFER=pipelined``, the default)
  waits out the residual of the head batch's H2D copy, issues the
  jitted step, and puts the device→host copy in flight immediately
  (``copy_to_host_async``) — so the dispatcher is already sealing and
  ``device_put``-ing batch N+1's slot while batch N's launch is being
  issued, and up to ``depth`` D2H copies ride the device at once.
  ``EVAM_TRANSFER=inline`` reproduces the pre-pipeline serial path
  (H2D + launch back-to-back on the dispatcher) byte-identically for
  A/B (tools/bench_transfer.py); ``EVAM_SERIALIZE_COMPILE=1`` forces
  inline — overlapped device RPCs are exactly what the wedge-proof
  mode exists to forbid;
* the **completion** thread blocks on the single per-batch readback
  residual, resolves per-item futures, and returns the slot to the
  ring. Keeping dispatch and readback on separate threads
  double-buffers the device: batch N+1 is enqueued while batch N
  computes (the decode-ahead/infer overlap the reference gets from
  GStreamer element threads, SURVEY.md §2d-5);
* an in-flight semaphore bounds device queueing (backpressure, the
  analogue of the reference msgbus ``zmq_recv_hwm``,
  eii/config.json:37); the staging ring adds a second, host-side
  bound — a slot is reusable only after its batch's readback (its
  block may back an in-flight H2D transfer until the step consumes
  the device buffer).

Every batch carries a **stage clock** (ringbuf.STAGES: submit_wait →
slot_write → seal → h2d_issue → h2d_wait → launch → readback →
resolve) into ``EngineStats`` and the ``evam_engine_stage_seconds``
histogram, so the serve bench and /healthz can attribute host
overhead instead of hiding it inside a throughput number (VERDICT r5
weak #5) — and, post-transfer-pipeline, attribute transfer cost vs
the dispatch floor honestly (h2d_wait and readback are residuals).

``EVAM_BATCH_ASSEMBLY=legacy`` keeps the old allocate-stack-pad
dispatch path for A/B (tools/bench_hostpath.py measures the delta).
"""

from __future__ import annotations

import dataclasses
import os
import queue
import threading
import time
from concurrent.futures import Future
from typing import Callable

import jax
import numpy as np

from evam_tpu.aot import active as aot_active
from evam_tpu.aot import cache_key as aot_cache_key
from evam_tpu.control.state import current_op
from evam_tpu.engine import devlock
from evam_tpu.engine.ragged import (
    RaggedSpec,
    consolidate_buckets,
    ragged_mode,
)
from evam_tpu.engine.ringbuf import STAGES, SealedBatch, SlotRing
from evam_tpu.obs import get_logger, metrics
from evam_tpu.obs import trace
from evam_tpu.obs.faults import current as active_faults
from evam_tpu.parallel.mesh import MeshPlan
from evam_tpu.sched.classes import (
    DEFAULT_PRIORITY,
    PRIORITIES,
    ClassQueues,
    SchedConfig,
)
from evam_tpu.sched.shedder import Shedder

log = get_logger("engine.batcher")


@dataclasses.dataclass
class _WorkItem:
    inputs: dict[str, np.ndarray]
    future: Future
    t_submit: float
    priority: str = DEFAULT_PRIORITY
    #: real unit rows this item carries (a frame's region count for
    #: classify engines) — honest-occupancy metadata. None = unknown;
    #: accounting then assumes the pessimistic dense budget.
    units: int | None = None
    #: per-frame trace handle (obs/trace.py FrameTrace) — links this
    #: item's frame span tree to the batch it rides in; None when
    #: tracing is off or the caller has no frame context
    trace: object | None = None


class _TunableQueue(queue.Queue):
    """``queue.Queue`` whose bound is retunable live (the control
    plane's upload-queue depth knob). Growing the bound wakes blocked
    putters immediately; shrinking applies lazily as the consumer
    drains below the new bound — no staged batch is ever dropped."""

    def set_depth(self, n: int) -> None:
        with self.mutex:
            self.maxsize = max(1, int(n))
            self.not_full.notify_all()


def _safe_set_result(fut: Future, value) -> None:
    """The watchdog may have already failed this future; a late
    success from an unwedged backend must not crash the completer."""
    try:
        fut.set_result(value)
    except Exception:  # noqa: BLE001 — InvalidStateError
        pass


def _safe_set_exception(fut: Future, exc: Exception) -> None:
    try:
        fut.set_exception(exc)
    except Exception:  # noqa: BLE001 — InvalidStateError
        pass


@dataclasses.dataclass
class EngineStats:
    batches: int = 0
    items: int = 0
    occupancy_sum: float = 0.0
    #: real vs computed unit rows (ragged accounting, engine/ragged.py):
    #: a classify batch COMPUTES bucket × roi_budget unit rows on the
    #: dense path (unit_slots) however few regions the frames really
    #: carried (units). units/unit_slots is the honest occupancy the
    #: per-item n/bucket number silently overstates. Frame-per-row
    #: engines count 1 unit per item, so the two occupancies agree.
    units: int = 0
    unit_slots: int = 0
    #: per-bucket dispatched-batch counts (pad-tax attribution:
    #: which program shapes the traffic actually lands in)
    bucket_batches: dict[int, int] = dataclasses.field(default_factory=dict)
    #: compile-cache accounting: distinct bucket programs this engine
    #: has executed (each cost a jit trace + XLA compile) and the
    #: cumulative wall seconds their first batches took — warmup or
    #: mid-traffic. Bucket consolidation's "compile-cache entries
    #: drop" claim is measured against these, not asserted.
    compiled_programs: int = 0
    compile_seconds: float = 0.0
    #: AOT-cache attribution (evam_tpu/aot/): buckets warmed from a
    #: deserialized executable instead of a jit trace + XLA compile,
    #: and the wall seconds those loads+validations took — the warm
    #: counterpart of compile_seconds, so /engines shows cold vs warm
    #: spin-up honestly (a cache-hit shard: aot_hits == buckets,
    #: compile_seconds ≈ 0)
    aot_hits: int = 0
    aot_load_seconds: float = 0.0
    #: submits past the top bucket that had to be split across batches
    #: instead of silently clamped (oversize-split contract)
    oversize_splits: int = 0
    #: cumulative per-stage host clock (seconds), keyed by
    #: ringbuf.STAGES — submit_wait/slot_write/seal come from the
    #: dispatcher, h2d_issue from the upload span, h2d_wait/launch
    #: from the launch span (launcher thread when pipelined),
    #: readback/resolve from the completion thread. Single writer per
    #: key, so plain dict updates are safe.
    stage_seconds: dict[str, float] = dataclasses.field(default_factory=dict)

    @property
    def mean_occupancy(self) -> float:
        return self.occupancy_sum / self.batches if self.batches else 0.0

    @property
    def unit_occupancy(self) -> float:
        """Real units / computed unit rows — the honest pad-tax view."""
        return self.units / self.unit_slots if self.unit_slots else 0.0

    def absorb(self, other: "EngineStats") -> None:
        """Fold another engine's cumulative counters into this one
        (supervisor rebuild carry — /healthz, /engines and the bench
        line must stay monotonic across quarantine swaps)."""
        self.batches += other.batches
        self.items += other.items
        self.occupancy_sum += other.occupancy_sum
        self.units += other.units
        self.unit_slots += other.unit_slots
        self.compiled_programs += other.compiled_programs
        self.compile_seconds += other.compile_seconds
        self.aot_hits += other.aot_hits
        self.aot_load_seconds += other.aot_load_seconds
        self.oversize_splits += other.oversize_splits
        for b, c in other.bucket_batches.items():
            self.bucket_batches[b] = self.bucket_batches.get(b, 0) + c
        for k, v in other.stage_seconds.items():
            self.stage_seconds[k] = self.stage_seconds.get(k, 0.0) + v

    def add_stage(self, stage: str, dt: float) -> None:
        self.stage_seconds[stage] = self.stage_seconds.get(stage, 0.0) + dt

    def stage_ms_per_batch(self) -> dict[str, float]:
        """Mean per-batch host cost of each pipeline stage (ms)."""
        if not self.batches:
            return {}
        return {
            s: round(1e3 * self.stage_seconds.get(s, 0.0) / self.batches, 3)
            for s in STAGES if s in self.stage_seconds
        }


class BatchEngine:
    """Deadline-batching dispatcher around one jitted step function.

    ``step_fn(params, **batch) -> packed`` must accept stacked inputs
    (leading batch axis) and return one array whose leading axis
    matches. Bucketed batch sizes keep the number of distinct
    compiled programs small (recompilation-storm guard)."""

    #: Cross-thread mutable state and the lock that guards it — the
    #: dispatcher, launcher, completer, watchdog, and warmup threads
    #: all touch these.  ``evam_tpu.analysis`` (lock-discipline pass)
    #: enforces that every mutation happens under ``_exec_lock``.
    SHARED_UNDER = {
        "stats": "_exec_lock",
        "_buckets_done": "_exec_lock",
        "_outstanding": "_exec_lock",
        "_next_batch_id": "_exec_lock",
        "_aot_exec": "_exec_lock",
    }

    def __init__(
        self,
        name: str,
        step_fn: Callable,
        params,
        plan: MeshPlan | None = None,
        max_batch: int = 32,
        deadline_ms: float = 8.0,
        max_in_flight: int = 3,
        input_names: tuple[str, ...] = ("frames",),
        stall_timeout_s: float = 120.0,
        assembly: str | None = None,
        staging_depth: int | None = None,
        donate_inputs: bool | None = None,
        first_batch_grace: float = 10.0,
        sched: SchedConfig | None = None,
        transfer: str | None = None,
        ragged: str | None = None,
        ragged_spec: RaggedSpec | None = None,
        fleet_local: bool = False,
        transfer_depth: int | None = None,
        aot_key: str | None = None,
    ):
        self.name = name
        self.plan = plan
        self.max_batch = max_batch
        self.deadline_s = deadline_ms / 1000.0
        self.input_names = input_names
        self.stats = EngineStats()
        #: host batch assembly: "slot" (pre-allocated staging ring,
        #: default) or "legacy" (per-batch stack+concat) — kept for
        #: A/B via EVAM_BATCH_ASSEMBLY (tools/bench_hostpath.py)
        self.assembly = (assembly
                         or os.environ.get("EVAM_BATCH_ASSEMBLY", "slot"))
        if self.assembly not in ("slot", "legacy"):
            raise ValueError(
                f"EVAM_BATCH_ASSEMBLY must be 'slot' or 'legacy', "
                f"got {self.assembly!r}")
        #: ragged batching (engine/ragged.py, EVAM_RAGGED): "packed"
        #: packs variable-size items into one fixed device shape with
        #: a row_len/row_offset descriptor + masked compute, and thins
        #: the bucket ladder so adjacent shapes share a program; "off"
        #: (default) keeps the dense bucketed path byte-identical for
        #: A/B (tools/bench_ragged.py). Packing needs the staging ring
        #: — the legacy stack+concat assembly forces it off.
        self.ragged = ragged_mode(ragged)
        if self.ragged == "packed" and self.assembly == "legacy":
            log.warning(
                "engine %s: EVAM_RAGGED=packed requires the slot "
                "staging ring; EVAM_BATCH_ASSEMBLY=legacy forces it "
                "off", name)
            self.ragged = "off"
        #: unit-level shape of the one ragged input (classify-family
        #: engines). Attached even in "off" mode so the occupancy
        #: accounting stays honest about per-item ROI padding; packing
        #: itself is mode-gated.
        self.ragged_spec = ragged_spec
        self._packed = self.ragged == "packed" and ragged_spec is not None
        #: device-transfer pipeline: "pipelined" (default) issues the
        #: H2D copy on the dispatcher and launches from a dedicated
        #: launcher thread — batch N+1's upload overlaps batch N's
        #: launch, and D2H copies are put in flight at launch time;
        #: "inline" is the pre-pipeline serial path (H2D + launch
        #: back-to-back on the dispatcher), kept byte-identical for
        #: A/B via EVAM_TRANSFER (tools/bench_transfer.py).
        #: EVAM_SERIALIZE_COMPILE=1 forces inline at construction:
        #: concurrently-issued transfer RPCs are exactly the overlap
        #: the wedge-proof devlock mode exists to forbid.
        self.transfer = transfer or os.environ.get(
            "EVAM_TRANSFER", "pipelined")
        if self.transfer not in ("pipelined", "inline"):
            raise ValueError(
                f"EVAM_TRANSFER must be 'pipelined' or 'inline', "
                f"got {self.transfer!r}")
        self._pipelined = (self.transfer == "pipelined"
                           and not devlock.enabled())
        #: whether the backend keeps transfer streams separate from
        #: compute (TPU: PJRT tracks per-buffer readiness and DMAs
        #: ride their own stream). Gates the device-specific halves of
        #: the pipeline — the explicit plan-less device_put, the
        #: h2d_wait reading (blocking on the CPU "device" would wait
        #: behind the PREVIOUS batch's compute on the shared stream
        #: and re-serialize exactly what the launcher overlaps), and
        #: the async D2H issue (an extra host-side copy when the
        #: "device" is host memory). Same backend-gate discipline as
        #: donate_inputs above; the pipeline STRUCTURE (dispatcher/
        #: launcher split, upload queue, watchdog semantics) runs
        #: identically on CPU so tests exercise it end to end.
        self._device_streams = jax.default_backend() == "tpu"
        #: device identity recorded on batch trace records — a fleet
        #: shard's spans name the chip it serves (obs/trace.py)
        self._trace_device = (str(plan.mesh.devices.flat[0])
                              if plan is not None
                              else jax.default_backend())
        #: QoS scheduling (evam_tpu/sched/): when set (and enabled),
        #: submit routes into per-class queues drained realtime-first
        #: with per-class batch deadlines and staleness shedding.
        #: None/disabled = the legacy single-FIFO path, byte-identical
        #: (EVAM_SCHED=off A/B).
        self.sched = sched if (sched is not None and sched.enabled) else None
        self._classq = ClassQueues() if self.sched is not None else None
        self._shedder = (Shedder(name, self.sched.staleness_s())
                         if self.sched is not None else None)
        #: watchdog bound on one batch's device round-trip; a wedged
        #: backend (e.g. a dead TPU tunnel) blocks the dispatcher in
        #: C++ forever — the watchdog can't unblock it, but it CAN
        #: fail the stranded futures and flag the engine so /healthz
        #: degrades and callers stop queueing into a black hole
        #: (SURVEY §5.3 failure detection; 0 disables).
        self.stall_timeout_s = stall_timeout_s
        #: a bucket's FIRST batch pays jit trace + XLA compile inside
        #: its device round-trip; counting that against stall_timeout_s
        #: makes every cold engine — including every supervisor rebuild
        #: (fresh jit by design) — look wedged and flap until the
        #: restart budget degrades it. Buckets that have completed a
        #: batch get the plain budget; unseen buckets get
        #: stall_timeout_s × first_batch_grace.
        self.first_batch_grace = first_batch_grace
        self._buckets_done: set[int] = set()
        #: set when a batch exceeded stall_timeout_s (engine is
        #: considered wedged; submit() fails fast). Cleared if the
        #: wedged call later completes (slow compile, transient hang).
        self.stalled = threading.Event()
        #: every dispatched-but-not-completed batch: id → (t_dispatch,
        #: items, bucket, stall_deadline). Covers the device launch,
        #: the _done queue wait, AND the readback — a wedge anywhere
        #: strands nothing. The deadline is FIXED at dispatch time
        #: (_track_dispatch): a concurrent warmup finishing mid-flight
        #: must not retroactively shrink an in-flight cold batch's
        #: compile allowance.
        self._outstanding: dict[
            int, tuple[float, list[_WorkItem], int, float]] = {}
        self._next_batch_id = 0
        self._exec_lock = threading.Lock()
        #: persistent AOT executable cache (evam_tpu/aot/): the hub's
        #: program fingerprint for this engine — part of the cache key
        #: together with shapes/devices/donation. None (the EVAM_AOT
        #: default, or a caller that never passes it) keeps warmup and
        #: dispatch byte-identical to the plain jit path.
        self._aot_key = aot_key
        #: bucket → validated AOT executable, installed by warmup;
        #: dispatch (``_exec_for``) prefers it over the jitted step —
        #: both share the ``fn(params, *arrays)`` call signature.
        self._aot_exec: dict[int, object] = {}

        d = plan.data_size if plan else 1
        top = plan.pad_batch(max_batch) if plan else max_batch
        self.buckets = []
        b = d
        while b < top:
            self.buckets.append(b)
            b *= 2
        self.buckets.append(top)
        if self.ragged == "packed":
            # bucket consolidation (engine/ragged.py): adjacent shape
            # buckets share a program instead of each paying compile +
            # program memory + a cold first-batch stall. Rungs are
            # aligned to the data-axis size at BUILD time so sharded
            # dispatch never re-pads a sealed block per batch.
            self.buckets = consolidate_buckets(self.buckets, align=d)
        #: fleet mode's per-batch collective bypass (evam_tpu/fleet/):
        #: sub-data-size rungs are added to the ladder and dispatched
        #: through a second, single-device jit of the SAME step — a
        #: lightly-filled bucket on the mesh engine runs on one chip
        #: instead of paying an 8-way collective for 2 real rows. The
        #: existing bucket fn does the selection (_exec_for); off
        #: (default) leaves ladder and dispatch byte-identical.
        self._fleet_local = bool(fleet_local and plan is not None
                                 and plan.data_size > 1)
        if self._fleet_local:
            sub, s = [], 1
            while s < d:
                sub.append(s)
                s *= 2
            self.buckets = sub + self.buckets

        #: staging ring: blocks sized to the LARGEST bucket so a
        #: sealed batch is always a contiguous [:bucket] prefix view;
        #: max_in_flight + 1 deep (one slot assembling while
        #: max_in_flight batches ride the device) so the ring never
        #: shrinks the device pipeline, while bounding host memory at
        #: depth × top-bucket batches. EVAM_STAGING_DEPTH overrides.
        depth = staging_depth or int(
            os.environ.get("EVAM_STAGING_DEPTH", "0")) or (max_in_flight + 1)
        self._ring = (SlotRing(capacity=self.buckets[-1], depth=depth,
                               ragged=(ragged_spec if self._packed
                                       else None))
                      if self.assembly == "slot" else None)
        #: jit-call input order: the packed-ragged step takes the
        #: segment-id vector after the submit inputs (the stage never
        #: submits it — the ring seals it per batch)
        self._step_inputs = (input_names + ("seg",) if self._packed
                             else input_names)
        if self._packed and "seg" in input_names:
            raise ValueError(
                f"engine {name}: input name 'seg' is reserved by the "
                "packed-ragged path")

        #: donate input device buffers into the jitted step so XLA can
        #: alias them for outputs — a real HBM/bandwidth win on TPU,
        #: a no-op warning on CPU, hence the backend gate. Step
        #: signatures are donation-friendly by construction: inputs
        #: are positional after params and never aliased with them
        #: (engine/steps.py design constraints).
        if donate_inputs is None:
            donate_inputs = jax.default_backend() == "tpu"
        donate = (tuple(range(1, 1 + len(input_names)))
                  if donate_inputs else ())
        #: kept for the AOT cache key — donation changes the compiled
        #: artifact (aliased buffers), so it must address the entry
        self._donate = donate

        if plan is not None:
            self._params = jax.device_put(params, plan.replicated())
            self._jit_step = jax.jit(
                step_fn,
                in_shardings=(
                    plan.replicated(),
                    # every step input is batch-sharded — including
                    # the packed-ragged seg vector, whose unit rows
                    # scale with the (data-divisible) bucket
                    *([plan.batch_sharding()] * len(self._step_inputs)),
                ),
                donate_argnums=donate,
            )
        else:
            self._params = params
            self._jit_step = jax.jit(step_fn, donate_argnums=donate)
        if self._fleet_local:
            # single-device twin of the sharded step for the sub-data
            # rungs: params replicated onto (i.e. copied to) the first
            # mesh device, batch axis "sharded" over a 1-device mesh —
            # XLA emits no collectives for it
            self._local_plan = plan.per_device_plans()[0]
            self._params_local = jax.device_put(
                params, self._local_plan.replicated())
            self._jit_step_local = jax.jit(
                step_fn,
                in_shardings=(
                    self._local_plan.replicated(),
                    *([self._local_plan.batch_sharding()]
                      * len(self._step_inputs)),
                ),
                donate_argnums=donate,
            )
        else:
            self._local_plan = None

        self._queue: queue.Queue[_WorkItem | None] = queue.Queue()
        self._done: queue.Queue[tuple | None] = queue.Queue()
        #: pipelined transfer only: sealed batches whose H2D copy has
        #: been issued, awaiting launch. Default depth 2 — device-side
        #: double buffering (one batch uploading while one launches);
        #: EVAM_TRANSFER_DEPTH pins it, and the control plane
        #: (EVAM_TUNE=on) retunes it live from the h2d_wait/launch
        #: ratio via retune(). Construction reads the live operating
        #: point first so a supervisor rebuild resumes at the
        #: controller's current depth, not the boot value.
        op = current_op()
        live_depth = op.transfer_depth if op is not None else 0
        self.transfer_depth = max(1, int(live_depth
                                         or (transfer_depth or 2)))
        self._upload_q: _TunableQueue = _TunableQueue(
            maxsize=self.transfer_depth)
        self._warm_lock = threading.Lock()
        self._warming = False
        #: set when background warmup finishes (or fails)
        self.warmed = threading.Event()
        self._in_flight = threading.Semaphore(max_in_flight)
        self._stop = threading.Event()
        if self._classq is not None:
            dispatch_loop = self._dispatch_loop_sched
        elif self._ring is not None:
            dispatch_loop = self._dispatch_loop_slot
        else:
            dispatch_loop = self._dispatch_loop_legacy
        self._dispatcher = threading.Thread(
            target=self._thread_guard, args=(dispatch_loop,),
            name=f"engine-{name}-dispatch", daemon=True,
        )
        self._completer = threading.Thread(
            target=self._thread_guard, args=(self._completion_loop,),
            name=f"engine-{name}-complete", daemon=True,
        )
        self._launcher: threading.Thread | None = None
        if self._pipelined:
            self._launcher = threading.Thread(
                target=self._thread_guard, args=(self._launch_loop,),
                name=f"engine-{name}-launch", daemon=True,
            )
            self._launcher.start()
        self._dispatcher.start()
        self._completer.start()
        if self.stall_timeout_s > 0:
            threading.Thread(
                target=self._watchdog_loop,
                name=f"engine-{name}-watchdog", daemon=True,
            ).start()

    def _thread_guard(self, loop_fn: Callable) -> None:
        """Engine worker loops must never escape their thread with a
        raw traceback: a crashed dispatcher/completer is an ENGINE
        failure — logged here, detected by the EngineSupervisor via
        thread liveness, and answered with a quarantine + rebuild."""
        try:
            loop_fn()
        except Exception:  # noqa: BLE001 — terminal thread failure
            log.exception(
                "engine %s worker thread %s died; the engine is wedged "
                "until the supervisor rebuilds it",
                self.name, threading.current_thread().name,
            )

    # ------------------------------------------------------------- API

    def submit(self, priority: str = DEFAULT_PRIORITY,
               units: int | None = None,
               stream: str | None = None,
               trace: "object | None" = None,
               **inputs: np.ndarray) -> Future:
        """Enqueue one item (no batch dim); resolves to its packed row(s).

        ``priority`` selects the scheduling class (realtime|standard|
        batch) when the engine runs the QoS layer (evam_tpu/sched/);
        without it the argument is accepted and ignored — the legacy
        single-FIFO path stays byte-identical.

        ``stream`` is the submitting stream's identity. A single-chip
        engine accepts and ignores it (byte-identical legacy path) —
        it exists so the fleet mode (evam_tpu/fleet/) can pin a
        stream's traffic to a per-chip shard; stages pass it
        unconditionally and the engine kind behind the hub decides
        whether placement applies.

        ``units`` is honest-occupancy metadata: the item's REAL unit
        rows (a frame's region count on classify engines, where the
        dense path pads every item to the ROI budget). On the
        packed-ragged path it is derived from the ragged input's
        leading dim instead; the item then resolves to exactly its
        own rows of the packed output.

        ``trace`` is the submitting frame's FrameTrace handle
        (obs/trace.py) or None: the batch this item lands in records
        the trace id (batch↔frame linkage) and the completion path
        appends queue-wait + dispatch spans to the frame's tree.
        Accepted and ignored — zero-cost — when tracing is off.

        On the slot path this call COPIES the item's arrays into the
        staging block on the calling thread (ringbuf.write) — the
        dispatcher never re-stacks them — and blocks only when every
        staging slot is in flight (host-side backpressure). On the
        sched path the copy moves to the dispatcher (class-ordered
        dispatch needs the item mobile until it is picked)."""
        if self._stop.is_set():
            raise RuntimeError(f"engine {self.name} is stopped")
        if self.stalled.is_set():
            # the dispatcher is wedged inside a device call — queueing
            # more work would strand more futures
            raise RuntimeError(
                f"engine {self.name} is stalled (device call exceeded "
                f"{self.stall_timeout_s:.0f}s — backend wedged?)"
            )
        if set(inputs) != set(self.input_names):
            raise ValueError(
                f"engine {self.name} expects inputs {self.input_names}, got {tuple(inputs)}"
            )
        if self._packed:
            units = int(np.asarray(
                inputs[self.ragged_spec.input]).shape[0])
        fut: Future = Future()
        if self._classq is not None:
            if priority not in PRIORITIES:
                raise ValueError(
                    f"unknown priority {priority!r}; valid: "
                    f"{'|'.join(PRIORITIES)}")
            item = _WorkItem(inputs, fut, time.perf_counter(), priority,
                             units, trace)
            try:
                self._classq.put(priority, item)
            except RuntimeError:
                raise RuntimeError(f"engine {self.name} is stopped") from None
            return fut
        item = _WorkItem(inputs, fut, time.perf_counter(), units=units,
                         trace=trace)
        if self._ring is not None:
            try:
                self._ring.write(inputs, item)
            except RuntimeError:
                raise RuntimeError(f"engine {self.name} is stopped") from None
        else:
            self._queue.put(item)
        return fut

    def queue_depth(self) -> int:
        """Items submitted but not yet dispatched — the previously
        invisible backlog (satellite: queue gauges)."""
        if self._classq is not None:
            return self._classq.depth()
        if self._ring is not None:
            return self._ring.pending_items()
        return self._queue.qsize()

    def queue_age_s(self) -> float:
        """Age (s) of the oldest undispatched item; 0 when idle."""
        now = time.perf_counter()
        if self._classq is not None:
            return self._classq.oldest_age_s(now)
        if self._ring is not None:
            return self._ring.oldest_age_s(now)
        with self._queue.mutex:
            head = self._queue.queue[0] if self._queue.queue else None
        if isinstance(head, _WorkItem):
            return max(0.0, now - head.t_submit)
        return 0.0

    def class_depths(self) -> dict[str, int]:
        """Per-class queued depth ({} when scheduling is off)."""
        if self._classq is None:
            return {}
        return self._classq.depth_by_class()

    def shed_counts(self) -> dict[str, int]:
        """Per-class shed totals ({} when scheduling is off)."""
        if self._shedder is None:
            return {}
        return dict(self._shedder.counts)

    def retune(self, op) -> None:
        """Apply the controller's operating point to this engine's
        structural knobs (control-plane push path — evam_tpu/control/).
        Scalar setpoints (deadline scale, batch cap) are pulled per
        dispatch instead, so rebuilds inherit them for free; only the
        upload-queue depth needs an explicit resize."""
        depth = int(op.transfer_depth or 0)
        if depth and depth != self.transfer_depth:
            self.transfer_depth = max(1, depth)
            self._upload_q.set_depth(self.transfer_depth)

    def warmup(self) -> None:
        """Compile every bucket size ahead of traffic.

        With the AOT cache active (EVAM_AOT=on and an ``aot_key``),
        each rung first tries a deserialized executable from the
        persistent store (validated by actually running the warm
        batch through it); a hit skips trace+compile entirely, a miss
        compiles ahead-of-time once and populates the store. Any
        failure on that path falls through to the plain jit warmup
        below — the cache can degrade serving to cold, never to
        broken."""
        example = self._example_item()
        cache = aot_active() if self._aot_key else None
        for b in self.buckets:
            batch = self._warm_batch(example, b)
            t0 = time.perf_counter()
            if cache is not None and self._warm_bucket_aot(
                    cache, b, batch, t0):
                continue
            # whole compile+execute+readback under one devlock span:
            # a warmup must never leave a half-overlapped RPC behind
            with devlock.device_call(f"{self.name}:warmup"):
                np.asarray(self._run(batch))
            with self._exec_lock:
                if b not in self._buckets_done:
                    # compile-cache accounting: a bucket's first run
                    # pays jit trace + XLA compile — bank it so
                    # consolidation's "fewer programs" claim is
                    # measurable
                    self.stats.compiled_programs += 1
                    self.stats.compile_seconds += (
                        time.perf_counter() - t0)
                # warmed bucket = compiled: its batches get the plain
                # (not first-batch-grace) watchdog budget from here on
                self._buckets_done.add(b)
        log.info("engine %s warmed %d buckets %s", self.name, len(self.buckets), self.buckets)

    # ------------------------------------------- AOT cache (evam_tpu/aot/)

    def _aot_bucket_key(self, b: int,
                        batch: dict[str, np.ndarray]) -> str:
        """Cache key for bucket ``b``'s executable: the hub program
        fingerprint + the exact step-input shapes/dtypes + the params
        aval signature + the device set the executable binds to +
        donation + backend. Fleet-local sub rungs address different
        entries than the mesh rungs by their single-device list."""
        plan = (self._local_plan
                if (self._fleet_local and 0 < b < self.plan.data_size)
                else self.plan)
        if plan is not None:
            devices = [str(d) for d in plan.mesh.devices.flat]
        else:
            devices = [str(jax.devices()[0])]
        inputs = [(name, tuple(batch[name].shape),
                   str(batch[name].dtype))
                  for name in self._step_inputs]
        params_sig = [
            (tuple(getattr(leaf, "shape", ())),
             str(getattr(leaf, "dtype", "")))
            for leaf in jax.tree_util.tree_leaves(self._params)]
        return aot_cache_key(self._aot_key, b, inputs, params_sig,
                             devices, self._donate,
                             jax.default_backend())

    def _aot_arrays(self, b: int, batch: dict[str, np.ndarray]):
        """(params, placed input arrays) for bucket ``b`` — the same
        placement ``_run`` performs, shared by the AOT validate and
        populate paths."""
        _, prm, sharding = self._exec_plain(b)
        arrays = []
        for name in self._step_inputs:
            a = batch[name]
            if sharding is not None:
                a = jax.device_put(a, sharding)
            arrays.append(a)
        return prm, arrays

    def _warm_bucket_aot(self, cache, b: int,
                         batch: dict[str, np.ndarray],
                         t0: float) -> bool:
        """Warm bucket ``b`` through the AOT cache. True = the rung is
        warmed (hit, or compiled+stored); False = fall back to the
        plain jit warmup. Hits bank into aot_hits/aot_load_seconds,
        misses into compile_seconds — /engines attributes cold vs
        warm spin-up from exactly these."""
        key = self._aot_bucket_key(b, batch)
        compiled = None
        with devlock.device_call(f"{self.name}:warmup"):
            prm, arrays = self._aot_arrays(b, batch)
            loaded = cache.load(key, engine=self.name)
            if loaded is not None:
                try:
                    # the only honest validation of a deserialized,
                    # device-bound executable is running it — this IS
                    # the warm run on success
                    np.asarray(loaded(prm, *arrays))
                except Exception as exc:  # noqa: BLE001 — device/placement drift
                    log.warning(
                        "engine %s: cached AOT executable for bucket "
                        "%d would not execute (%s) — recompiling",
                        self.name, b, exc)
                    cache.execute_miss(key, engine=self.name)
                    loaded = None
            if loaded is not None:
                with self._exec_lock:
                    if b not in self._buckets_done:
                        self.stats.compiled_programs += 1
                        self.stats.aot_hits += 1
                        self.stats.aot_load_seconds += (
                            time.perf_counter() - t0)
                    self._aot_exec[b] = loaded
                    self._buckets_done.add(b)
                cache.hit(engine=self.name)
                return True
            try:
                # miss: compile ahead-of-time ONCE (lower().compile()
                # and jit don't share a cache — running both would
                # double the cold-start bill) and use the compiled
                # executable for the warm run and for dispatch
                jit_fn, _, _ = self._exec_plain(b)
                compiled = jit_fn.lower(prm, *arrays).compile()
                np.asarray(compiled(prm, *arrays))
            except Exception as exc:  # noqa: BLE001 — AOT unsupported here
                log.warning(
                    "engine %s: AOT compile path failed for bucket %d "
                    "(%s) — plain jit warmup", self.name, b, exc)
                return False
            with self._exec_lock:
                if b not in self._buckets_done:
                    self.stats.compiled_programs += 1
                    self.stats.compile_seconds += (
                        time.perf_counter() - t0)
                self._aot_exec[b] = compiled
                self._buckets_done.add(b)
        # serialize+write outside the devlock span — disk I/O must not
        # serialize against other engines' device calls
        cache.store(key, compiled, engine=self.name)
        return True

    def _warm_batch(self, example: dict[str, np.ndarray],
                    b: int) -> dict[str, np.ndarray]:
        """Bucket-``b`` warmup batch from a per-item example. Packed
        engines compile the PACKED shapes — the unit block + seg
        vector at ``unit_rows(b)``, all-pad (seg −1) so the masked
        step compiles without touching real data."""
        spec = self.ragged_spec
        batch: dict[str, np.ndarray] = {}
        for k, v in example.items():
            if self._packed and k == spec.input:
                batch[k] = np.zeros(
                    (spec.unit_rows(b),) + tuple(spec.unit_shape),
                    spec.dtype)
            else:
                batch[k] = np.broadcast_to(v, (b,) + v.shape).copy()
        if self._packed:
            batch["seg"] = np.full((spec.unit_rows(b),), -1, np.int32)
        return batch

    def warm_async(self, **example: np.ndarray) -> None:
        """Fire-and-forget bucket precompilation (serving path: kills
        the mid-traffic compile spike when a batch first crosses a
        bucket boundary). Idempotent."""
        with self._warm_lock:
            if self._warming:
                return
            self._warming = True
        self.set_example(**example)
        threading.Thread(
            target=self._warm_guarded,
            name=f"engine-{self.name}-warmup",
            daemon=True,
        ).start()

    def _warm_guarded(self) -> None:
        try:
            self.warmup()
        except Exception as exc:  # noqa: BLE001 — warmup must never kill serving
            log.warning("engine %s warmup failed: %s", self.name, exc)
        finally:
            self.warmed.set()

    def stop(self) -> None:
        self._stop.set()
        if self._classq is not None:
            self._classq.close()
        if self._ring is not None:
            self._ring.close()
        self._queue.put(None)
        self._dispatcher.join(timeout=10)
        if self._launcher is not None:
            try:
                self._upload_q.put_nowait(None)
            except queue.Full:
                pass  # launcher drains the backlog, then exits on _stop
            self._launcher.join(timeout=10)
        self._done.put(None)
        self._completer.join(timeout=10)
        exc = RuntimeError("engine stopped")
        self._drain_upload_q(exc)
        if self._classq is not None:
            for item in self._classq.drain():
                _safe_set_exception(item.future, exc)
        if self._ring is not None:
            for item in self._ring.drain_items():
                _safe_set_exception(item.future, exc)
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if item is not None:
                _safe_set_exception(item.future, exc)

    def _track_dispatch(self, t0: float, items: list[_WorkItem],
                        bucket: int) -> int:
        """Register a dispatched batch with the watchdog; its stall
        deadline is locked in here. A bucket that has never completed
        a batch gets stall_timeout_s × first_batch_grace (its
        round-trip legitimately contains trace + compile). Device
        execution is ordered, so a batch enqueued behind others can't
        finish before them: its deadline is additionally floored at
        the latest outstanding deadline + one plain budget — the tail
        of a cold engine's first wave inherits the compile wait, but
        each queued batch extends detection by only stall_timeout_s,
        so a genuinely wedged engine with a standing backlog is still
        caught in bounded time."""
        with self._exec_lock:
            if bucket not in self._buckets_done:
                deadline = t0 + self.stall_timeout_s * self.first_batch_grace
            else:
                deadline = t0 + self.stall_timeout_s
            if self._outstanding:
                queue_ahead = max(
                    e[3] for e in self._outstanding.values())
                deadline = max(deadline,
                               queue_ahead + self.stall_timeout_s)
            bid = self._next_batch_id
            self._next_batch_id += 1
            self._outstanding[bid] = (t0, items, bucket, deadline)
        return bid

    def abandon(self) -> None:
        """Quarantine teardown (EngineSupervisor): release every
        failable caller WITHOUT joining the worker threads — a wedged
        engine's dispatcher/completer may be blocked in C++ (or an
        injected wedge's sleep) indefinitely, and the supervisor must
        not inherit that wait. The threads are daemons; they observe
        ``_stop``/the closed ring when (if) they ever wake and exit on
        their own. Idempotent."""
        self._stop.set()
        exc = TimeoutError(
            f"engine {self.name} quarantined: wedged device call; "
            "the supervisor is rebuilding the engine"
        )
        if self._classq is not None:
            self._classq.close()
            for item in self._classq.drain():
                _safe_set_exception(item.future, exc)
        if self._ring is not None:
            self._ring.close()
            for item in self._ring.drain_items():
                _safe_set_exception(item.future, exc)
        self._queue.put(None)
        self._done.put(None)
        self._drain_upload_q(exc)
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if item is not None:
                _safe_set_exception(item.future, exc)
        with self._exec_lock:
            stranded = [it for entry in self._outstanding.values()
                        for it in entry[1]]
            self._outstanding.clear()
        for it in stranded:
            _safe_set_exception(it.future, exc)

    # -------------------------------------------------------- internals

    def _example_item(self) -> dict[str, np.ndarray]:
        item = self._peek_shapes
        if item is None:
            raise RuntimeError("warmup requires example_shapes")
        return item

    #: optional dict name -> example array (no batch dim) for warmup
    _peek_shapes: dict[str, np.ndarray] | None = None

    def set_example(self, **example: np.ndarray) -> None:
        self._peek_shapes = example

    def _bucket(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        # n past the top bucket would silently truncate: the dispatch
        # paths split oversize submits across batches BEFORE bucketing
        # (_split_oversize / stage_direct leftovers), so landing here
        # is an accounting bug — be loud, never lossy
        log.warning(
            "engine %s: %d items exceed top bucket %d (oversize split "
            "missed a path); clamping the SHAPE, items are preserved "
            "by the caller's split", self.name, n, self.buckets[-1])
        return self.buckets[-1]

    def _bucket_ragged(self, n: int, units: int) -> int:
        """Packed-ragged bucket pick: the smallest rung that fits both
        the item rows AND the packed unit rows (a few region-heavy
        frames can need a bigger unit block than their item count
        alone suggests)."""
        spec = self.ragged_spec
        for b in self.buckets:
            if n <= b and units <= spec.unit_rows(b):
                return b
        return self.buckets[-1]

    def _count_oversize_split(self, extra: int) -> None:
        with self._exec_lock:
            self.stats.oversize_splits += extra
        metrics.inc("evam_engine_oversize_splits", float(extra),
                    labels={"engine": self.name})

    def _split_oversize(self, items: list[_WorkItem]) -> list[list[_WorkItem]]:
        """Chunk a formed batch at the top bucket instead of letting
        ``_bucket`` silently clamp (and the assembly paths truncate) a
        packed submit past the largest shape. Each extra chunk counts
        on ``evam_engine_oversize_splits``."""
        top = self.buckets[-1]
        if len(items) <= top:
            return [items]
        chunks = [items[i:i + top] for i in range(0, len(items), top)]
        self._count_oversize_split(len(chunks) - 1)
        return chunks

    def _exec_plain(self, b: int):
        """(jit, params, sharding) for one sealed bucket. With the
        fleet mode's local bypass, sub-data-size buckets select the
        single-device twin — the existing bucket fn already routed the
        batch to rung ``b``, so this is the per-batch choice the fleet
        contract names: small batches never pay a collective."""
        if self._fleet_local and 0 < b < self.plan.data_size:
            return (self._jit_step_local, self._params_local,
                    self._local_plan.batch_sharding())
        if self.plan is not None:
            return self._jit_step, self._params, self.plan.batch_sharding()
        return self._jit_step, self._params, None

    def _exec_for(self, b: int):
        """(callable, params, sharding) for one sealed bucket — the
        warmed AOT executable when the cache installed one for this
        rung, the jitted step otherwise. Both share the
        ``fn(params, *arrays)`` call signature, so every dispatch
        path is agnostic to which it got. (Lock-free read: dict get
        is atomic and a rung's entry, once installed by warmup, is
        never replaced.)"""
        jit_fn, prm, sharding = self._exec_plain(b)
        exe = self._aot_exec.get(b)
        return (exe if exe is not None else jit_fn), prm, sharding

    def _run(self, batch: dict[str, np.ndarray],
             clock: dict[str, float] | None = None):
        """Inline transfer path (EVAM_TRANSFER=inline, warmup, and the
        devlock-forced mode): H2D + launch back-to-back on the calling
        thread — the pre-pipeline behavior, byte-identical. h2d_wait
        is 0 by definition here: the launch call itself absorbs any
        residual transfer wait inside the runtime."""
        # chaos hook: an injected `wedge` blocks right here — on the
        # dispatching thread, inside the engine, exactly where a hung
        # backend RPC would — so the watchdog/supervisor path is
        # testable without wedging real hardware (obs/faults.py)
        inj = active_faults()
        if inj is not None:
            inj.maybe_wedge(self.name)
        # devlock: with EVAM_SERIALIZE_COMPILE=1 this launch (and any
        # compile it triggers) cannot overlap another engine thread's
        # device RPC — the wedge-proof measurement mode
        with devlock.device_call(f"{self.name}:launch"):
            t0 = time.perf_counter()
            jit_fn, prm, sharding = self._exec_for(
                batch[self.input_names[0]].shape[0])
            arrays = []
            for name in self._step_inputs:
                a = batch[name]
                if sharding is not None:
                    a = jax.device_put(a, sharding)
                arrays.append(a)
            t1 = time.perf_counter()
            out = jit_fn(prm, *arrays)
            if clock is not None:
                clock["h2d_issue"] = t1 - t0
                clock["h2d_wait"] = 0.0
                clock["launch"] = time.perf_counter() - t1
            return out

    def refresh_queue_gauges(self) -> None:
        """Push the submit-backlog gauges. Called on every dispatch
        (_record_batch) AND from the watchdog/supervisor ticks — a
        wedged or idle engine must not freeze its queue gauges at the
        last dispatch's values while the backlog grows underneath."""
        metrics.set("evam_engine_queue_depth", self.queue_depth(),
                    {"engine": self.name})
        metrics.set("evam_engine_queue_age_s", self.queue_age_s(),
                    {"engine": self.name})

    def _record_batch(self, n: int, b: int, clock: dict[str, float],
                      items: list[_WorkItem] | None = None,
                      sealed: SealedBatch | None = None) -> None:
        spec = self.ragged_spec
        with self._exec_lock:
            self.stats.batches += 1
            self.stats.items += n
            self.stats.occupancy_sum += n / b
            # honest unit accounting (engine/ragged.py): what the
            # program COMPUTED (unit_slots) vs the real work inside it
            # (units). Packed batches know both exactly from the
            # sealed descriptor; dense batches compute bucket ×
            # max_units unit rows and fall back to the pessimistic
            # budget for items that didn't declare their real count.
            # Frame-per-row engines: 1 unit per item.
            if sealed is not None and sealed.row_len is not None:
                self.stats.units += sealed.units
                self.stats.unit_slots += sealed.unit_rows
            elif spec is not None:
                self.stats.unit_slots += b * spec.max_units
                self.stats.units += sum(
                    (it.units if it.units is not None else spec.max_units)
                    for it in (items or []))
            else:
                self.stats.unit_slots += b
                self.stats.units += n
            self.stats.bucket_batches[b] = (
                self.stats.bucket_batches.get(b, 0) + 1)
            for stage, dt in clock.items():
                self.stats.add_stage(stage, dt)
            mean_occ = self.stats.mean_occupancy
            unit_occ = self.stats.unit_occupancy
        metrics.observe("evam_batch_occupancy", n / b, {"engine": self.name})
        # live occupancy for operators (satellite: occupancy export) —
        # both the item-fill mean and the pad-tax-honest unit view
        metrics.set("evam_engine_occupancy", mean_occ,
                    {"engine": self.name})
        metrics.set("evam_engine_unit_occupancy",
                    unit_occ, {"engine": self.name})
        self.refresh_queue_gauges()
        for stage, dt in clock.items():
            metrics.observe(
                "evam_engine_stage_seconds", dt,
                {"engine": self.name, "stage": stage})

    # --------------------------------------------- transfer pipeline

    def _dispatch_batch(self, batch: dict[str, np.ndarray],
                        items: list[_WorkItem], n: int, b: int,
                        clock: dict[str, float],
                        sealed: SealedBatch | None) -> None:
        """Common tail of all three dispatch loops: hand one assembled
        batch to the device path.

        Inline: H2D + launch back-to-back on this thread (``_run``).
        Pipelined: enqueue the H2D copy here (h2d_issue — device_put
        returns once the transfer is in flight) and queue the batch
        for the launcher thread, so the dispatcher is sealing and
        uploading batch N+1 while batch N's launch is being issued."""
        if not self._pipelined:
            self._in_flight.acquire()
            t0 = time.perf_counter()
            bid = self._track_dispatch(t0, items, b)
            # the pending trace record holds the SAME clock dict _run
            # fills in — a flight dump of a wedged batch reads the
            # stages completed so far (obs/trace.py)
            trace.batch_begin(self.name, bid, items, b, n, clock,
                              self._trace_device)
            try:
                out = self._run(batch, clock=clock)
            except Exception as exc:  # noqa: BLE001 — surface to every caller
                self._in_flight.release()
                with self._exec_lock:
                    self._outstanding.pop(bid, None)
                for it in items:
                    _safe_set_exception(it.future, exc)
                trace.batch_complete(self.name, bid, items,
                                     status="error")
                if sealed is not None:
                    self._ring.release(sealed)
                log.exception("engine %s step failed", self.name)
                return
            self._done.put((out, items, t0, bid, sealed))
            self._record_batch(n, b, clock, items=items, sealed=sealed)
            return
        try:
            with devlock.device_call(f"{self.name}:h2d"):
                t0 = time.perf_counter()
                _, _, sharding = self._exec_for(b)
                if sharding is not None:
                    # sharded placement is semantics, not an
                    # optimization — always explicit
                    dev = [jax.device_put(batch[name], sharding)
                           for name in self._step_inputs]
                elif self._device_streams:
                    dev = [jax.device_put(batch[name])
                           for name in self._step_inputs]
                else:
                    # CPU: let the launcher's jit call do the one
                    # host-side conversion exactly like inline does —
                    # an explicit device_put here would be a second
                    # copy with no DMA to overlap
                    dev = [batch[name] for name in self._step_inputs]
                clock["h2d_issue"] = time.perf_counter() - t0
        except Exception as exc:  # noqa: BLE001 — surface to every caller
            for it in items:
                _safe_set_exception(it.future, exc)
            if sealed is not None:
                self._ring.release(sealed)
            log.exception("engine %s H2D upload failed", self.name)
            return
        entry = (dev, items, n, b, clock, sealed)
        while True:
            try:
                self._upload_q.put(entry, timeout=0.1)
                return
            except queue.Full:
                if self._stop.is_set():
                    # launcher is exiting — don't strand the batch
                    exc = RuntimeError(f"engine {self.name} is stopped")
                    for it in items:
                        _safe_set_exception(it.future, exc)
                    if sealed is not None:
                        self._ring.release(sealed)
                    return

    def _launch(self, dev: list, clock: dict[str, float], b: int = 0):
        """Launcher half of the pipelined transfer: wait out the head
        batch's H2D residual where that is measurable without
        re-serializing (``_h2d_sync`` — h2d_wait is ≈0 when the upload
        overlapped the previous launch, the full copy time when it did
        not), issue the jitted step, and put the D2H copy in flight
        immediately so the completer blocks only on the readback
        residual."""
        # chaos hook: same consult as _run — the wedge must block the
        # thread that issues the device RPC
        inj = active_faults()
        if inj is not None:
            inj.maybe_wedge(self.name)
        with devlock.device_call(f"{self.name}:launch"):
            t0 = time.perf_counter()
            if self._device_streams:
                jax.block_until_ready(dev)
            t1 = time.perf_counter()
            jit_fn, prm, _ = self._exec_for(b)
            out = jit_fn(prm, *dev)
            t2 = time.perf_counter()
            clock["h2d_wait"] = t1 - t0
            clock["launch"] = t2 - t1
            if self._device_streams:
                # async D2H: the device→host copy rides along while
                # later batches launch; np.asarray in the completer
                # then pays only the residual (the `readback` stage,
                # now honest)
                copy_async = getattr(out, "copy_to_host_async", None)
                if copy_async is not None:
                    copy_async()
        return out

    def _launch_loop(self) -> None:
        """Pipelined transfer: pop uploaded batches and launch them —
        while this thread is inside a launch (or blocked on a wedged
        backend RPC), the dispatcher keeps sealing and uploading."""
        while True:
            try:
                entry = self._upload_q.get(timeout=0.1)
            except queue.Empty:
                if self._stop.is_set():
                    break
                continue
            if entry is None:
                break
            dev, items, n, b, clock, sealed = entry
            if self._stop.is_set():
                exc = RuntimeError(f"engine {self.name} is stopped")
                for it in items:
                    _safe_set_exception(it.future, exc)
                if sealed is not None:
                    self._ring.release(sealed)
                continue
            self._in_flight.acquire()
            t0 = time.perf_counter()
            bid = self._track_dispatch(t0, items, b)
            # clock by reference — same wedge-visibility contract as
            # the inline path (obs/trace.py)
            trace.batch_begin(self.name, bid, items, b, n, clock,
                              self._trace_device)
            try:
                out = self._launch(dev, clock, b)
            except Exception as exc:  # noqa: BLE001 — surface to every caller
                self._in_flight.release()
                with self._exec_lock:
                    self._outstanding.pop(bid, None)
                for it in items:
                    _safe_set_exception(it.future, exc)
                trace.batch_complete(self.name, bid, items,
                                     status="error")
                if sealed is not None:
                    self._ring.release(sealed)
                log.exception("engine %s step failed", self.name)
                continue
            self._done.put((out, items, t0, bid, sealed))
            self._record_batch(n, b, clock, items=items, sealed=sealed)

    def _drain_upload_q(self, exc: Exception) -> None:
        """Fail every uploaded-but-unlaunched batch (stop/abandon/
        stall). Slots release without waiting on their possibly
        in-flight H2D copies — same contract as the launch-failure
        path: the batch's futures are already failed, so nothing ever
        observes those rows again."""
        while True:
            try:
                entry = self._upload_q.get_nowait()
            except queue.Empty:
                return
            if entry is None:
                continue
            _dev, items, _n, _b, _clock, sealed = entry
            for it in items:
                _safe_set_exception(it.future, exc)
            if sealed is not None:
                self._ring.release(sealed)

    # ------------------------------------------------ sched dispatch

    def _dispatch_loop_sched(self) -> None:
        """QoS dispatch (evam_tpu/sched/): drain per-class queues
        realtime-first (starvation-proof weighted pick), form batches
        under the CLASS deadline — cameras keep a small latency floor
        while bulk traffic fills big buckets — and shed frames that
        outlived their class staleness budget (oldest-first) before
        they waste a device slot."""
        cq = self._classq
        shedder = self._shedder
        while True:
            if self._stop.is_set():
                exc = RuntimeError("engine stopped")
                for it in cq.drain():
                    _safe_set_exception(it.future, exc)
                break
            # shed expired waiters across ALL classes first: the
            # backlog a busy realtime lane starves must fail loudly
            # instead of rotting in queue
            shedder.sweep(cq)
            cls = cq.pick(timeout=0.05)
            if cls is None:
                continue
            # live setpoints (control plane): one None-check with
            # EVAM_TUNE=off — deadlines scale, formation caps at the
            # demanded bucket rung
            op = current_op()
            cap = self.max_batch
            deadline = self.sched.deadline_s(cls)
            if op is not None:
                if op.batch_cap:
                    cap = min(cap, op.batch_cap)
                deadline *= op.deadline_scale
            items = cq.collect(cls, cap, deadline)
            # the batch-formation wait itself can age items past
            # budget (and a realtime burst can delay a picked batch
            # class) — filter the formed batch too
            items = shedder.shed(cls, items)
            if not items:
                continue
            self._launch_sched(items)

    def _launch_sched(self, items: list[_WorkItem]) -> None:
        """Assemble + launch one class-ordered batch: through the
        staging ring (zero per-batch allocation, copies on this
        thread) or the legacy stack+concat when
        EVAM_BATCH_ASSEMBLY=legacy. A pick that exceeds the top
        bucket's rows — or, packed, the unit block — is split across
        batches in dispatch order instead of silently clamped
        (oversize-split contract)."""
        if self._ring is not None:
            bucket_fn = (self._bucket_ragged if self._packed
                         else self._bucket)
            staged = [(it.inputs, it) for it in items]
            dispatched = 0
            while staged:
                clock: dict[str, float] = {
                    "submit_wait":
                        time.perf_counter() - staged[0][1].t_submit,
                }
                try:
                    sealed, staged = self._ring.stage_direct(
                        staged, bucket_fn, clock)
                except RuntimeError:
                    exc = RuntimeError(f"engine {self.name} is stopped")
                    for _, it in staged:
                        _safe_set_exception(it.future, exc)
                    return
                if sealed is None:
                    continue  # every staged row failed its shape check
                dispatched += 1
                self._dispatch_batch(sealed.arrays, sealed.items,
                                     sealed.n, sealed.bucket,
                                     sealed.clock, sealed)
            if dispatched > 1:
                self._count_oversize_split(dispatched - 1)
            return
        for chunk in self._split_oversize(items):
            clock = {
                "submit_wait": time.perf_counter() - chunk[0].t_submit,
            }
            n = len(chunk)
            b = self._bucket(n)
            t_asm = time.perf_counter()
            batch = {}
            for name in self.input_names:
                rows = [it.inputs[name] for it in chunk]
                stacked = np.stack(rows)
                if b > n:
                    pad = np.zeros((b - n,) + stacked.shape[1:],
                                   stacked.dtype)
                    stacked = np.concatenate([stacked, pad])
                batch[name] = stacked
            clock["slot_write"] = time.perf_counter() - t_asm
            self._dispatch_batch(batch, chunk, n, b, clock, None)

    # ------------------------------------------------- slot dispatch

    def _dispatch_loop_slot(self) -> None:
        """Seal staged slots at the batch deadline and launch them —
        no stack, no pad concat, no per-batch allocation."""
        bucket_fn = self._bucket_ragged if self._packed else self._bucket
        while True:
            op = current_op()
            deadline = (self.deadline_s * op.deadline_scale
                        if op is not None else self.deadline_s)
            sealed = self._ring.next_batch(deadline, bucket_fn)
            if sealed is None:
                if self._stop.is_set():
                    break
                continue
            if self._stop.is_set():
                exc = RuntimeError("engine stopped")
                for it in sealed.items:
                    _safe_set_exception(it.future, exc)
                self._ring.release(sealed)
                continue  # drain whatever else is staged, then exit

            self._dispatch_batch(sealed.arrays, sealed.items, sealed.n,
                                 sealed.bucket, sealed.clock, sealed)

    # ----------------------------------------------- legacy dispatch

    def _dispatch_loop_legacy(self) -> None:
        """Pre-ring path (EVAM_BATCH_ASSEMBLY=legacy): per-batch
        stack + zero-pad concat on the dispatcher thread. Kept for
        A/B measurement — tools/bench_hostpath.py."""
        while not self._stop.is_set():
            try:
                first = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            if first is None:
                break
            op = current_op()
            cap = self.max_batch
            deadline_s = self.deadline_s
            if op is not None:
                if op.batch_cap:
                    cap = min(cap, op.batch_cap)
                deadline_s *= op.deadline_scale
            items = [first]
            deadline = time.perf_counter() + deadline_s
            while len(items) < cap:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    nxt = self._queue.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is None:
                    self._stop.set()
                    break
                items.append(nxt)

            for chunk in self._split_oversize(items):
                n = len(chunk)
                b = self._bucket(n)
                clock: dict[str, float] = {
                    "submit_wait":
                        time.perf_counter() - chunk[0].t_submit,
                }
                t_asm = time.perf_counter()
                batch: dict[str, np.ndarray] = {}
                for name in self.input_names:
                    rows = [it.inputs[name] for it in chunk]
                    stacked = np.stack(rows)
                    if b > n:
                        pad = np.zeros((b - n,) + stacked.shape[1:],
                                       stacked.dtype)
                        stacked = np.concatenate([stacked, pad])
                    batch[name] = stacked
                clock["slot_write"] = time.perf_counter() - t_asm

                self._dispatch_batch(batch, chunk, n, b, clock, None)

    # ------------------------------------------------------ completion

    def _completion_loop(self) -> None:
        while True:
            entry = self._done.get()
            if entry is None:
                break
            out, items, t0, bid, sealed = entry
            t_rb = time.perf_counter()
            try:
                with devlock.device_call(f"{self.name}:readback"):
                    # single readback per batch; with the pipelined
                    # transfer the D2H copy is already in flight
                    # (copy_to_host_async at launch), so this blocks
                    # only on the residual
                    host = np.asarray(out)
            except Exception as exc:  # noqa: BLE001
                for it in items:
                    _safe_set_exception(it.future, exc)
                trace.batch_complete(self.name, bid, items,
                                     status="error")
                self._in_flight.release()
                if sealed is not None:
                    self._ring.release(sealed)
                continue
            finally:
                with self._exec_lock:
                    done = self._outstanding.pop(bid, None)
            self._in_flight.release()
            if done is not None:
                # bucket compiled + round-tripped: plain watchdog
                # budget (no first-batch grace) from here on — and a
                # mid-traffic cold bucket's round-trip IS its compile
                # (compile-cache accounting; warmup banks warmed
                # buckets before traffic instead)
                with self._exec_lock:
                    if done[2] not in self._buckets_done:
                        self.stats.compiled_programs += 1
                        self.stats.compile_seconds += (
                            time.perf_counter() - done[0])
                    self._buckets_done.add(done[2])
            if sealed is not None:
                # the staging block is free the moment the readback
                # materialized the output on host
                self._ring.release(sealed)
            if self.stalled.is_set():
                # the "wedged" call was merely slow (e.g. a mid-traffic
                # multichip compile) and has now completed — recover
                # instead of staying bricked until restart
                self.stalled.clear()
                log.warning(
                    "engine %s recovered: a previously-stalled device "
                    "call completed; accepting work again", self.name,
                )
            now = time.perf_counter()
            metrics.observe("evam_step_seconds", now - t0, {"engine": self.name})
            readback_s = now - t_rb
            t_res = time.perf_counter()
            # ragged scatter-back: a packed batch's output rows are
            # unit rows — item i owns host[offset[i] : offset[i] +
            # row_len[i]] (exactly its real region rows, zero-region
            # items resolve to an empty slice). Dense batches keep the
            # one-row-per-item contract.
            ragged = (sealed is not None and sealed.row_len is not None)
            for i, it in enumerate(items):
                metrics.observe(
                    "evam_item_latency_seconds", now - it.t_submit, {"engine": self.name}
                )
                if ragged:
                    off = int(sealed.row_offset[i])
                    _safe_set_result(
                        it.future,
                        host[off:off + int(sealed.row_len[i])])
                else:
                    _safe_set_result(it.future, host[i])
            resolve_s = time.perf_counter() - t_res
            # retire the batch trace record (appends queue-wait +
            # dispatch spans to every member frame's tree and banks
            # the completion-side stages the clock never sees)
            trace.batch_complete(self.name, bid, items,
                                 readback_s=readback_s,
                                 resolve_s=resolve_s)
            with self._exec_lock:
                self.stats.add_stage("readback", readback_s)
                self.stats.add_stage("resolve", resolve_s)
            metrics.observe("evam_engine_stage_seconds", readback_s,
                            {"engine": self.name, "stage": "readback"})
            metrics.observe("evam_engine_stage_seconds", resolve_s,
                            {"engine": self.name, "stage": "resolve"})

    def _watchdog_loop(self) -> None:
        """Fail futures stranded behind a wedged device call and flag
        the engine (the dispatcher/completer threads stay blocked in
        C++ — only the service-level contract can be saved). A
        bucket's first batch gets stall_timeout_s × first_batch_grace:
        its round-trip legitimately contains trace + XLA compile, and
        without the grace every cold start — especially a supervisor
        rebuild's fresh jit — reads as a wedge."""
        # floor 0.2 s (was 1.0): supervised tests run sub-second stall
        # budgets; production timeouts (120 s) still poll every 30 s
        interval = max(self.stall_timeout_s / 4.0, 0.2)
        while not self._stop.wait(interval):
            # keep the backlog gauges live even when nothing
            # dispatches — a wedged or idle engine must not show the
            # last batch's queue depth while work piles up
            self.refresh_queue_gauges()
            now = time.perf_counter()
            with self._exec_lock:
                slots = list(self._outstanding.values())
            stuck: list[_WorkItem] = []
            for _t0, items, _b, deadline in slots:
                if now > deadline:
                    stuck.extend(items)
            if not stuck:
                continue
            self.stalled.set()
            log.error(
                "engine %s stalled: device call exceeded %.0fs; failing "
                "%d stranded item(s) and rejecting new work",
                self.name, self.stall_timeout_s, len(stuck),
            )
            metrics.inc("evam_engine_stalls", labels={"engine": self.name})
            exc = TimeoutError(
                f"engine {self.name} device call exceeded "
                f"{self.stall_timeout_s:.0f}s (backend wedged)"
            )
            for it in stuck:
                _safe_set_exception(it.future, exc)
            # strand nothing in the class queues, staging ring,
            # upload queue or legacy queue either
            self._drain_upload_q(exc)
            if self._classq is not None:
                for it in self._classq.drain():
                    _safe_set_exception(it.future, exc)
            if self._ring is not None:
                for it in self._ring.drain_items():
                    _safe_set_exception(it.future, exc)
            while True:
                try:
                    queued = self._queue.get_nowait()
                except queue.Empty:
                    break
                if queued is not None:
                    _safe_set_exception(queued.future, exc)
