"""EngineSupervisor: quarantine-and-rebuild for wedged BatchEngines.

The stall watchdog (engine/batcher.py) detects a wedged device call,
fails the stranded futures and flags the engine — but until this
module the engine then stayed dead: /healthz sat at 503 "stalled" and
every stream sharing the engine failed until someone restarted the
process (the exact outage documented across BENCH_r03–r05; the
reference's only recovery story is container restart policy,
SURVEY.md §5.3). ``SupervisedEngine`` closes that gap with in-process
recovery, the continuous-operation discipline OCTOPINF (PAPERS.md)
treats as table stakes for edge video serving:

* a **stable handle**: the hub caches ONE SupervisedEngine per key and
  stages capture it once (`stages/infer.py`); the live BatchEngine
  underneath is swappable, so a rebuild is invisible to every holder —
  no re-resolution, no stage rebuild, no stream restart;
* a **monitor thread** watches the live engine (stalled flag set by
  the watchdog, or a dead dispatcher/completer thread) and, on a trip:
  **quarantines** the old engine (``BatchEngine.abandon()`` — fail
  everything failable, never join the wedged-in-C++ threads), waits an
  exponential backoff, **rebuilds** via the factory (fresh jitted
  step, fresh SlotRing, fresh warmup from the captured example) and
  atomically swaps the replacement in;
* a **restart budget**: at most ``max_restarts`` rebuilds within a
  sliding ``restart_window_s``. Exhausting it is a terminal
  ``degraded`` state — the engine stops flapping, /healthz reports
  503 "degraded" (vs the transient 503 "restarting"), and the
  operator's restart policy takes over with full information.

In-flight streams see exactly one transient ``TimeoutError`` per
wedge (stranded futures from the watchdog; submits during the rebuild
window) — absorbed by the per-frame error isolation in
``stages/runner.py`` and the per-stream retry loop in
``server/instance.py`` — instead of permanent failure.

States ride ``evam_engine_state`` (gauge: 0=running, 1=restarting,
2=degraded) and rebuilds ride ``evam_engine_restarts`` (counter), both
surfaced on /healthz, /engines and the serve bench contract line.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Callable

from evam_tpu.analysis.annotations import locked_by
from evam_tpu.engine.batcher import BatchEngine, EngineStats
from evam_tpu.obs import get_logger, metrics
from evam_tpu.obs import trace

log = get_logger("engine.supervisor")

#: gauge encoding for evam_engine_state, index = value
ENGINE_STATES = ("running", "restarting", "degraded")


def _engine_snapshot(eng) -> dict:
    """Best-effort queue/in-flight snapshot of a wedged engine for the
    flight-recorder header — taken before abandon() fails the stranded
    futures and zeroes the evidence."""
    try:
        return {
            "queue_depth": eng.queue_depth(),
            "class_depths": eng.class_depths(),
            "shed_counts": eng.shed_counts(),
            "outstanding": len(eng._outstanding),
            "stalled": eng.stalled.is_set(),
            "batches": eng.stats.batches,
        }
    except Exception:  # noqa: BLE001 — engine mid-teardown
        return {}


class SupervisedEngine:
    """Stable, restartable handle around a replaceable BatchEngine.

    Duck-types the BatchEngine surface the stages and hub use
    (``submit``/``warm_async``/``set_example``/``stats``/``warmed``/
    ``stalled``/...): unknown attributes delegate to the live engine,
    so existing callers — including tests poking ``buckets`` or
    ``_bucket`` — keep working unchanged.
    """

    #: Shared between the monitor thread and every caller thread
    #: (submit/stop/healthz snapshots); guarded by ``_lock``
    #: (enforced by the ``evam_tpu.analysis`` lock-discipline pass).
    SHARED_UNDER = {
        "state": "_lock",
        "restarts": "_lock",
        "last_stall_ts": "_lock",
        "_shed_carry": "_lock",
        "_stats_carry": "_lock",
        "_example": "_lock",
        "_warm_requested": "_lock",
        "_engine": "_lock",
    }

    def __init__(
        self,
        name: str,
        factory: Callable[[], BatchEngine],
        max_restarts: int = 3,
        restart_window_s: float = 300.0,
        backoff_s: float = 0.5,
        max_backoff_s: float = 30.0,
        poll_interval_s: float = 0.1,
    ):
        self.name = name
        self._factory = factory
        self.max_restarts = max_restarts
        self.restart_window_s = restart_window_s
        self.backoff_s = backoff_s
        self.max_backoff_s = max_backoff_s
        self.poll_interval_s = poll_interval_s
        #: lifecycle stats surfaced on /engines and /healthz
        self.restarts = 0
        self.last_stall_ts: float | None = None
        self._restart_times: deque[float] = deque()
        #: cumulative counters folded in from quarantined engines
        #: (_absorb_counters): a rebuild swaps in a fresh BatchEngine
        #: with zeroed local counts, and /healthz, /engines and the
        #: bench contract line must stay MONOTONIC across it
        self._shed_carry: dict[str, int] = {}
        self._stats_carry: EngineStats | None = None
        self._example: dict | None = None
        self._warm_requested = False
        self._lock = threading.RLock()
        self.state = "running"
        self._engine = factory()
        metrics.set("evam_engine_state", 0.0, {"engine": name})
        self._stop_evt = threading.Event()
        self._monitor = threading.Thread(
            target=self._monitor_loop,
            name=f"engine-{name}-supervisor", daemon=True,
        )
        self._monitor.start()

    # ------------------------------------------------------------- API

    def submit(self, priority: str = "standard",
               units: int | None = None,
               stream: str | None = None,
               trace: "object | None" = None, **inputs) -> Future:
        with self._lock:
            state = self.state
            eng = self._engine
        if state == "degraded":
            raise RuntimeError(
                f"engine {self.name} is degraded: restart budget "
                f"({self.max_restarts} rebuilds in "
                f"{self.restart_window_s:.0f}s) exhausted; serving this "
                "engine requires a process restart"
            )
        if state == "restarting":
            # same transient contract as a stranded future: the stream
            # retry/error-isolation layer absorbs it and the next
            # submit after the swap succeeds
            raise TimeoutError(
                f"engine {self.name} is restarting after a wedge; "
                "retry shortly"
            )
        return eng.submit(priority=priority, units=units, stream=stream,
                          trace=trace, **inputs)

    def warm_async(self, **example) -> None:
        with self._lock:
            self._example = dict(example)
            self._warm_requested = True
            eng = self._engine
        eng.warm_async(**example)

    def set_example(self, **example) -> None:
        with self._lock:
            self._example = dict(example)
            eng = self._engine
        eng.set_example(**example)

    def stop(self) -> None:
        self._stop_evt.set()
        with self._lock:
            eng = self._engine
            state = self.state
        if state == "running":
            eng.stop()
        else:
            # a quarantined/degraded engine may hold threads wedged in
            # C++ — abandon (non-blocking) instead of joining them
            eng.abandon()
        self._monitor.join(timeout=5)

    # --------------------------------------- cumulative counter carry

    @property
    def stats(self) -> EngineStats:
        """Cumulative EngineStats: the live engine's counts plus
        everything absorbed from quarantined predecessors. With no
        restarts this is the live object itself (zero overhead); after
        a rebuild it is a merged read-only snapshot."""
        live = object.__getattribute__(self, "_engine").stats
        with self._lock:
            carry = self._stats_carry
            if carry is None:
                return live
            merged = EngineStats()
            merged.absorb(carry)
        merged.absorb(live)
        return merged

    def shed_counts(self) -> dict[str, int]:
        """Per-class shed totals including quarantined predecessors —
        keeps hub.shed_totals() (and with it /healthz and the bench
        line) monotonic across supervisor rebuilds."""
        live = object.__getattribute__(self, "_engine").shed_counts()
        with self._lock:
            if not self._shed_carry:
                return live
            out = dict(self._shed_carry)
        for c, n in live.items():
            out[c] = out.get(c, 0) + n
        return out

    def _absorb_counters(self, eng: BatchEngine) -> None:
        """Fold a quarantined engine's cumulative counters into the
        carry BEFORE it is abandoned and replaced.

        The fleet layer (evam_tpu/fleet/engine.py) applies this same
        carry discipline one level up when a PLACEMENT MOVE retires a
        degraded shard: the shard's merged counters (which already
        include this carry) are absorbed into the fleet-level carry,
        so /healthz and the bench line stay monotonic fleet-wide."""
        try:
            shed = eng.shed_counts()
            live = eng.stats
        except Exception:  # noqa: BLE001 — engine mid-teardown
            return
        with self._lock:
            for c, n in shed.items():
                self._shed_carry[c] = self._shed_carry.get(c, 0) + n
            if self._stats_carry is None:
                self._stats_carry = EngineStats()
            # absorb() covers the full counter surface (items, unit
            # occupancy, bucket counts, compile-cache bill, oversize
            # splits) so /engines and the bench line stay monotonic
            # across rebuilds for the new fields too
            self._stats_carry.absorb(live)

    # ------------------------------------------------------ delegation

    def __getattr__(self, item):
        # only called for attributes NOT found on the proxy: stats,
        # warmed, stalled, assembly, buckets, _ring, _bucket, ...
        return getattr(object.__getattribute__(self, "_engine"), item)

    # ------------------------------------------------------- internals

    @locked_by("_lock")
    def _set_state(self, state: str) -> None:
        self.state = state
        metrics.set("evam_engine_state", float(ENGINE_STATES.index(state)),
                    {"engine": self.name})

    def _wedged(self, eng: BatchEngine) -> str | None:
        """Reason string when the live engine needs a rebuild."""
        if eng.stalled.is_set():
            return "stall watchdog fired"
        if eng._stop.is_set():
            return None  # deliberate stop, not a wedge
        if not eng._dispatcher.is_alive():
            return "dispatcher thread died"
        if not eng._completer.is_alive():
            return "completion thread died"
        if eng._launcher is not None and not eng._launcher.is_alive():
            return "transfer launcher thread died"
        return None

    def _monitor_loop(self) -> None:
        while not self._stop_evt.wait(self.poll_interval_s):
            with self._lock:
                if self.state == "degraded":
                    return
                eng = self._engine
            # keep the backlog gauges live even while the engine is
            # wedged/idle (they otherwise refresh only on dispatch)
            try:
                eng.refresh_queue_gauges()
            except Exception:  # noqa: BLE001 — engine mid-teardown
                pass
            reason = self._wedged(eng)
            if reason is not None:
                self._quarantine_and_rebuild(eng, reason)

    def _quarantine_and_rebuild(self, eng: BatchEngine, reason: str) -> None:
        with self._lock:
            self.last_stall_ts = time.time()
        log.error("engine %s wedged (%s); quarantining", self.name, reason)
        # flight recorder: dump the last-N spans + the wedged engine's
        # queue/in-flight state to a JSONL artifact BEFORE abandon()
        # fails the stranded futures and mutates the evidence
        trace.flight_dump(self.name, reason, state=_engine_snapshot(eng))
        # crash-consistency barrier (evam_tpu/state/): snapshot every
        # registered stream's cross-frame state before the swap — if
        # this rebuild cascades into a process restart, the resumed
        # streams restore from a checkpoint no older than the wedge
        from evam_tpu.state import active as ckpt_active

        ckpt = ckpt_active()
        if ckpt is not None:
            ckpt.capture_all(barrier="pre_rebuild")
        self._absorb_counters(eng)
        eng.abandon()
        while not self._stop_evt.is_set():
            now = time.time()
            while (self._restart_times
                   and now - self._restart_times[0] > self.restart_window_s):
                self._restart_times.popleft()
            if len(self._restart_times) >= self.max_restarts:
                with self._lock:
                    self._set_state("degraded")
                trace.flight_dump(
                    self.name, "restart budget exhausted; degraded",
                    state=_engine_snapshot(eng))
                log.error(
                    "engine %s restart budget exhausted (%d rebuilds in "
                    "%.0fs); entering terminal degraded state — process "
                    "restart required",
                    self.name, self.max_restarts, self.restart_window_s,
                )
                return
            self._restart_times.append(now)
            with self._lock:
                self.restarts += 1
                self._set_state("restarting")
            metrics.inc("evam_engine_restarts", labels={"engine": self.name})
            attempt = len(self._restart_times)
            delay = min(self.backoff_s * (2 ** (attempt - 1)),
                        self.max_backoff_s)
            log.warning(
                "engine %s rebuild %d/%d in %.2fs (window %.0fs)",
                self.name, attempt, self.max_restarts, delay,
                self.restart_window_s,
            )
            if self._stop_evt.wait(delay):
                return
            try:
                new = self._factory()
            except Exception:  # noqa: BLE001 — a failed build consumes budget
                log.exception("engine %s rebuild failed", self.name)
                continue
            with self._lock:
                warm = self._warm_requested and self._example is not None
                example = self._example
            if warm:
                # re-admit WARM: swapping in a cold engine makes every
                # stream pay (and contend with) the fresh jit's
                # compile inside a dispatched batch — on a loaded host
                # that reads as another stall and the engine flaps.
                # While warming, the handle stays `restarting`
                # (healthz 503) and submits fail fast and cheap. A
                # warmup that never finishes means the backend is
                # still broken: abandon, consume budget, retry.
                new.warm_async(**example)
                warm_timeout = max(
                    new.stall_timeout_s * new.first_batch_grace
                    * max(len(new.buckets), 1), 10.0)
                warm_deadline = time.time() + warm_timeout
                warm_ok = True
                while not new.warmed.wait(timeout=0.2):
                    if self._stop_evt.is_set():
                        new.abandon()
                        return
                    if time.time() > warm_deadline:
                        warm_ok = False
                        break
                if not warm_ok:
                    log.error(
                        "engine %s rebuild warmup did not finish in "
                        "%.0fs; treating as a failed rebuild",
                        self.name, warm_timeout,
                    )
                    new.abandon()
                    continue
            else:
                if example is not None:
                    new.set_example(**example)
                # no warmup was requested: the fresh engine is as
                # ready as the original ever was
                new.warmed.set()
            with self._lock:
                self._engine = new
                self._set_state("running")
            log.warning(
                "engine %s rebuilt and re-admitted (restart %d, fresh "
                "jitted step + staging ring)", self.name, self.restarts,
            )
            return
