"""Ragged batching: masked packing + bucket consolidation.

Engines bucket by batch size and zero-pad every block's tail, and the
classify-family engines additionally pad every ITEM to a fixed ROI
budget (`stages/infer.py` fills a [ROI_BUDGET, 4] box block whatever
the frame's real region count is). On a heterogeneous fleet — mixed
resolutions, mixed models, ragged per-frame region counts — that
fragments the device into half-empty buckets where occupancy, not
FLOPs, is the throughput ceiling (ROADMAP "Ragged batching"). Ragged
Paged Attention (PAPERS.md) shows the TPU-native answer: ONE
fixed-shape program over a packed block, with per-row length/offset
vectors and masked compute, instead of one program per
(shape, fill) combination.

``EVAM_RAGGED=packed`` turns on two cooperating mechanisms:

* **masked packing** (classify-family engines): each submitted item
  carries its REAL region rows (``boxes`` shape ``(k, 4)``, k in
  [0, max_units]); the staging ring packs them end to end into one
  fixed unit block with a segment-id vector (``seg[j]`` = the batch
  row that owns packed unit j, −1 on the pad tail), and the jitted
  step computes per-unit with the pad rows masked to zero
  (`steps.build_classify_step_ragged`). The completer scatters
  results back per item via the sealed batch's ``row_len`` /
  ``row_offset`` vectors. Unit occupancy becomes
  Σk / unit_rows(bucket) instead of the dense path's silent
  Σk / (bucket × max_units);
* **bucket consolidation** (every engine): adjacent batch-size
  buckets share a program — the ladder keeps every other rung
  (plus the floor and the top), halving compile count, program
  memory and cold first-batch stalls (the batch-size study,
  PAPERS.md). Pad rows were always discarded at completion, so
  coarser buckets change occupancy accounting, never results.

``EVAM_RAGGED=off`` (the default until a TPU accuracy window) keeps
today's bucketed dense path byte-identical — the same A/B discipline
as ``EVAM_TRANSFER`` / ``EVAM_GATE``. Supervisor rebuilds inherit the
mode through the hub's factory closure.
"""

from __future__ import annotations

import dataclasses
import os

import numpy as np

#: valid EVAM_RAGGED values
RAGGED_MODES = ("packed", "off")


def ragged_mode(value: str | None = None) -> str:
    """Resolve + validate the ragged mode (explicit arg beats env)."""
    mode = value or os.environ.get("EVAM_RAGGED", "off")
    if mode not in RAGGED_MODES:
        raise ValueError(
            f"EVAM_RAGGED must be one of {'|'.join(RAGGED_MODES)}, "
            f"got {mode!r}")
    return mode


@dataclasses.dataclass(frozen=True)
class RaggedSpec:
    """Declares ONE engine input as ragged (variable leading dim).

    The spec rides the engine even when ``EVAM_RAGGED=off`` so the
    occupancy accounting can stay honest (a dense classify batch
    computes ``bucket × max_units`` unit rows whatever the real
    region counts were); packing itself only happens in ``packed``
    mode.
    """

    #: name of the ragged input ("boxes" for classify engines)
    input: str
    #: per-unit trailing shape ((4,) — one normalized box)
    unit_shape: tuple[int, ...]
    #: unit dtype
    dtype: np.dtype = np.float32
    #: per-ITEM unit cap (the stage-level ROI budget); a dense item
    #: always carries exactly this many rows, a packed one 0..max
    max_units: int = 8
    #: packed unit rows budgeted PER BATCH ROW in the device shape —
    #: the knob that converts "8 ROI slots per frame, mostly empty"
    #: into "unit_budget slots per frame, shared across the batch".
    #: Floored at max_units so a lone full item always fits.
    unit_budget: int = 4

    def unit_rows(self, bucket: int) -> int:
        """Packed unit rows in the device shape for ``bucket`` items."""
        return max(self.max_units, bucket * self.unit_budget)


def consolidate_buckets(buckets: list[int], align: int = 1) -> list[int]:
    """Thin a power-of-two bucket ladder so adjacent shapes share a
    program: keep the floor, the top, and every OTHER rung between
    (descending from the top so the serving bucket keeps its exact
    shape). Halves compiled-program count; batches that would have
    used a dropped rung round up one rung — their pad rows are masked
    or discarded exactly as before.

    ``align`` is the mesh data-axis size: every kept rung >= align is
    rounded up to a multiple of it AT LADDER BUILD (MeshPlan.pad_batch
    applied here, once), so a sealed block dispatched sharded is never
    re-padded per batch — a rung that isn't divisible by the data axis
    would force an extra host-side copy on EVERY dispatch through that
    bucket. Rungs below align (the fleet mode's single-device small
    buckets) are left alone: they dispatch locally, unsharded."""
    if len(buckets) <= 2:
        kept = list(buckets)
    else:
        keep = {buckets[0], buckets[-1]}
        # every other rung, walking DOWN from the top
        for i in range(len(buckets) - 1, -1, -2):
            keep.add(buckets[i])
        kept = sorted(keep)
    if align > 1:
        kept = sorted({
            -(-b // align) * align if b >= align else b for b in kept
        })
    return kept
