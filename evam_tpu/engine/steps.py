"""Jitted step builders: one fused XLA program per model stage.

Each builder returns ``(step_fn, params)`` where ``step_fn(params,
**batch)`` maps a uint8 host batch to ONE packed float32 array.
Replaces the reference's per-frame OpenVINO infer requests inside
gvadetect/gvaclassify/gvaactionrecognitionbin/gvaaudiodetect
(SURVEY.md §2b) with cross-stream batched programs.

Design constraints (measured on the tunneled v5e, see engine tests):
* single packed output array — each extra device→host readback costs
  a full RTT (~70 ms through the tunnel), so steps never return
  tuples;
* everything fused — preprocess, net, decode, NMS in one jit, frames
  cross the host boundary exactly once as uint8;
* static shapes — batch size is bucketed by the caller, ROI budget
  and NMS K are fixed;
* donation-friendly signatures — batch inputs are positional after
  ``params``, never aliased with params and never returned, so the
  BatchEngine can ``donate_argnums`` the staged input buffers on TPU
  and XLA reuses their HBM for outputs (free at the 256×1080p wire
  batch sizes the serve default ships).
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp

from evam_tpu.models.registry import LoadedModel
from evam_tpu.ops.boxes import decode_boxes, yolo_gather
from evam_tpu.ops.color import crop_rois_i420
from evam_tpu.ops.nms import batched_nms
from evam_tpu.ops.preprocess import (
    crop_rois,
    decode_wire,
    preprocess_bgr,
    preprocess_wire,
)

#: Packed detection row layout: [x0, y0, x1, y1, score, label, valid]
DETECT_FIELDS = 7


def weyl_bits(seeds, n: int) -> jnp.ndarray:
    """[...]-shaped uint32 seeds → [..., n] uint32 Weyl-sequence bits.

    THE on-chip synthetic-data generator: bench.py --ingest device,
    the serve bench's device-synth mode (wrap_device_synth), the
    action-decoder mini-measure and tools/profile_budget.py all draw
    from this one recipe, so "same generator as the headline bench"
    stays true by construction. Plain iota arithmetic, not the PRNG —
    smallest possible op surface on experimental backends.
    """
    i = jax.lax.iota(jnp.uint32, n)
    return i * jnp.uint32(2654435761) + jnp.asarray(
        seeds, jnp.uint32)[..., None]


def wrap_device_synth(step_fn, wire_shape: tuple[int, ...]) -> Callable:
    """Device-synth serving ingest: per-item uint32 seeds replace wire
    frames, and the uint8 wire batch is synthesized ON-CHIP (the same
    Weyl-sequence generator as ``bench.py --ingest device``) before the
    wrapped step runs.

    Used by ``EngineHub(device_synth=True)`` so ``bench.py --config
    serve`` can measure the REAL serving path — source →
    StreamRunner → BatchEngine dispatcher/completer → tracker →
    metaconvert → publish — without the per-frame host→device pixel
    copy, which in this environment rides a ~18 MB/s tunnel and would
    measure the link rather than the framework (PROFILE.md "ingest").
    Every other byte of the serving path (threads, queues, deadline
    batching, bucket padding, readback, host postprocess) is exercised
    unchanged; only ``frames`` arrives as a [B] seed vector.
    """
    import numpy as np

    n = int(np.prod(wire_shape))

    def synth_step(params, seeds, *rest):
        b = seeds.shape[0]
        mix = seeds.astype(jnp.uint32) * jnp.uint32(0x9E3779B9)
        frames = (weyl_bits(mix, n) >> jnp.uint32(13)).astype(jnp.uint8)
        return step_fn(params, frames.reshape((b,) + tuple(wire_shape)),
                       *rest)

    return synth_step


def _head_probs(model, name: str, out) -> jnp.ndarray:
    """Per-head probabilities, honoring in-graph SoftMax of IR imports."""
    x = out[name].astype(jnp.float32)
    if model.head_is_prob.get(name, False):
        return x
    return jax.nn.softmax(x, axis=-1)


def _wire_spec(model: LoadedModel, wire_format: str):
    """Model preprocess spec bound to the step's wire format."""
    return dataclasses.replace(model.preprocess, wire_format=wire_format)


def _detect_packed(params, x, model, anchors, max_detections,
                   iou_threshold, score_threshold):
    """Preprocessed input → (packed [B,K,7], boxes). See DETECT_FIELDS."""
    out = model.forward(params, x)
    if model.detector_kind == "yolo":
        # RegionYolo-cut IR: raw grid maps, decoded here (fused) —
        # scores come out as probabilities with a background column.
        # Numeric sort: lexicographic would pair yolo_10 with head 2's
        # anchors on 11+-head models.
        keys = sorted(out, key=lambda k: int(k.rsplit("_", 1)[1]))
        if len(keys) != len(model.yolo_specs):
            raise ValueError(
                f"{len(keys)} yolo outputs vs {len(model.yolo_specs)} "
                "anchor specs — importer/model mismatch"
            )
        maps = [out[k].astype(jnp.float32) for k in keys]
        boxes, scores = yolo_gather(
            maps, model.yolo_specs,
            (model.preprocess.height, model.preprocess.width),
            model.spec.num_classes,
        )
    else:
        boxes = decode_boxes(
            out["loc"].astype(jnp.float32), anchors,
            variances=model.variances,
        )
        conf = out["conf"].astype(jnp.float32)
        # IR-imported graphs usually softmax in-graph (OMZ convention,
        # models/ir.py output_is_prob); re-softmaxing flattens scores.
        scores = conf if model.conf_is_prob else jax.nn.softmax(conf, axis=-1)
    bx, sc, lb, valid = batched_nms(
        boxes,
        scores,
        max_outputs=max_detections,
        iou_threshold=iou_threshold,
        score_threshold=score_threshold,
    )
    packed = jnp.concatenate(
        [
            bx,
            sc[..., None],
            lb[..., None].astype(jnp.float32),
            valid[..., None].astype(jnp.float32),
        ],
        axis=-1,
    )
    return packed, bx


def build_detect_step(
    model: LoadedModel,
    max_detections: int = 32,
    iou_threshold: float = 0.45,
    score_threshold: float = 0.3,
    wire_format: str = "bgr",
) -> Callable:
    """Wire-encoded uint8 frames → packed detections [B,K,7] float32."""
    anchors = jnp.asarray(model.anchors) if model.anchors is not None else None
    spec = _wire_spec(model, wire_format)

    def step(params, frames):
        x = preprocess_wire(frames, spec)
        packed, _ = _detect_packed(
            params, x, model, anchors, max_detections,
            iou_threshold, score_threshold,
        )
        return packed

    return step


def build_detect_classify_step(
    det_model: LoadedModel,
    cls_model: LoadedModel,
    max_detections: int = 32,
    roi_budget: int = 8,
    iou_threshold: float = 0.45,
    score_threshold: float = 0.3,
    wire_format: str = "bgr",
    allowed_label_ids: tuple[int, ...] | None = None,
) -> Callable:
    """Fused gvadetect+gvaclassify: ONE frame upload, ONE readback.

    The reference runs detection and classification as separate
    engines with the frame crossing the CPU pipeline between them
    (pipelines/object_classification/vehicle_attributes/
    pipeline.json:4-5); fusing them into one XLA program keeps the
    decoded frame in HBM: preprocess → SSD → NMS → on-device ROI crop
    of the top-R eligible boxes → classifier — one jit.
    ``allowed_label_ids`` is the object-class filter applied BEFORE
    ROI selection (gvaclassify filters by class first, then
    classifies — stages/infer.py _eligible), so budget slots are
    never wasted on filtered-out classes. Output
    [B, K, 7 + total_classes]: packed detections; a row's probability
    block is all-zero iff that detection was not classified
    (softmaxed blocks sum to #heads otherwise).
    """
    anchors = (jnp.asarray(det_model.anchors)
               if det_model.anchors is not None else None)
    head_total = sum(n for _, n in cls_model.spec.heads)
    cls_pre = cls_model.preprocess
    det_spec = _wire_spec(det_model, wire_format)

    def step(params, frames):
        x = preprocess_wire(frames, det_spec)
        packed, bx = _detect_packed(
            params["det"], x, det_model, anchors, max_detections,
            iou_threshold, score_threshold,
        )
        b = frames.shape[0]
        eligible = packed[..., 6] > 0.5
        if allowed_label_ids is not None:
            labels = packed[..., 5]
            ok = jnp.zeros_like(eligible)
            for lid in allowed_label_ids:
                ok = ok | (labels == float(lid))
            eligible = eligible & ok
        # Stable sort: eligible rows first, NMS score order preserved
        # within each group.
        order = jnp.argsort(
            (~eligible).astype(jnp.int32), axis=1, stable=True
        )
        roi_idx = order[:, :roi_budget]
        roi_boxes = jnp.take_along_axis(bx, roi_idx[..., None], axis=1)
        roi_ok = jnp.take_along_axis(eligible, roi_idx, axis=1)
        if wire_format == "i420":
            # Crop straight from the wire planes — the full-res float
            # BGR batch (800 MB at 1080p/32) never materializes.
            crops = crop_rois_i420(
                frames, roi_boxes, (cls_pre.height, cls_pre.width))
        else:
            crops = crop_rois(
                decode_wire(frames, wire_format), roi_boxes,
                (cls_pre.height, cls_pre.width))
        crops = crops.reshape((b * roi_budget,) + crops.shape[2:])
        cls_in = preprocess_bgr(crops, cls_pre)
        out = cls_model.forward(params["cls"], cls_in)
        probs = jnp.concatenate(
            [_head_probs(cls_model, name, out) for name, _ in cls_model.spec.heads],
            axis=-1,
        ).reshape(b, roi_budget, head_total)
        probs = probs * roi_ok[..., None]
        # Scatter each ROI's probs back onto its detection row.
        full = jnp.zeros((b, packed.shape[1], head_total), jnp.float32)
        full = full.at[jnp.arange(b)[:, None], roi_idx].set(probs)
        return jnp.concatenate([packed, full], axis=-1)

    return step


def build_classify_step(
    model: LoadedModel, roi_budget: int = 8, wire_format: str = "bgr"
) -> Callable:
    """Frames + ROI boxes → packed per-ROI head probabilities.

    ``frames`` uint8 [B,H,W,3]; ``boxes`` float32 [B,R,4] normalized
    corners (R = roi_budget, invalid rows zeroed). Output
    [B, R, total_classes] — concatenated per-head probability vectors
    (head order = model.spec.heads). ROI crop happens on-device so
    detection output never has to round-trip through the host between
    the detect and classify engines beyond the box coordinates.
    """
    preproc = model.preprocess
    forward = model.forward
    head_sizes = [n for _, n in model.spec.heads]

    def step(params, frames, boxes):
        b, r = boxes.shape[:2]
        if wire_format == "i420":
            crops = crop_rois_i420(
                frames, boxes, (preproc.height, preproc.width))
        else:
            crops = crop_rois(
                decode_wire(frames, wire_format), boxes,
                (preproc.height, preproc.width))
        crops = crops.reshape((b * r,) + crops.shape[2:])
        x = preprocess_bgr(crops, preproc)
        out = forward(params, x)  # dict head -> [B*R, n]
        probs = [_head_probs(model, name, out) for name, _ in model.spec.heads]
        packed = jnp.concatenate(probs, axis=-1)
        return packed.reshape(b, r, sum(head_sizes))

    return step


def build_classify_step_ragged(
    model: LoadedModel, roi_budget: int = 8, wire_format: str = "bgr"
) -> Callable:
    """Packed-ragged classify (EVAM_RAGGED=packed, engine/ragged.py):
    frames + a PACKED box block + segment ids → per-unit head probs.

    The dense step (`build_classify_step`) computes ``B × roi_budget``
    ROI crops whatever the frames' real region counts — on the
    serving mix most of those unit rows are per-item zero-pad (the
    invisible half of the pad tax). Here the staging ring packs every
    frame's REAL boxes end to end: ``boxes`` is ``[U, 4]``, ``seg[j]``
    names the batch row that owns packed unit j (−1 on the pad tail),
    and the step computes exactly the packed block — one fixed-shape
    program for every fill level, Ragged Paged Attention style
    (PAPERS.md).

    Masked compute: pad rows gather a clamped (valid) frame index so
    the program stays branch-free, and their outputs are zeroed by
    the validity mask. Real rows multiply by exactly 1.0, so a unit's
    output is bit-identical to the dense step's row for the same
    (frame, box) pair — the EVAM_RAGGED A/B contract. Output
    ``[U, total_classes]``; the completer scatters rows back per item
    via the sealed batch's row_len/row_offset.
    """
    preproc = model.preprocess
    forward = model.forward

    def step(params, frames, boxes, seg):
        u = boxes.shape[0]
        valid = seg >= 0
        src = jnp.clip(seg, 0, frames.shape[0] - 1)
        f = jnp.take(frames, src, axis=0)  # [U, wire...]
        if wire_format == "i420":
            crops = crop_rois_i420(
                f, boxes[:, None, :], (preproc.height, preproc.width))
        else:
            crops = crop_rois(
                decode_wire(f, wire_format), boxes[:, None, :],
                (preproc.height, preproc.width))
        crops = crops.reshape((u,) + crops.shape[2:])
        x = preprocess_bgr(crops, preproc)
        out = forward(params, x)  # dict head -> [U, n]
        probs = [_head_probs(model, name, out) for name, _ in model.spec.heads]
        packed = jnp.concatenate(probs, axis=-1)
        return packed * valid[:, None].astype(packed.dtype)

    return step


def build_action_encode_step(
    model: LoadedModel, wire_format: str = "bgr"
) -> Callable:
    """Wire-encoded uint8 frames → embeddings [B,D] float32."""
    spec = _wire_spec(model, wire_format)
    forward = model.forward

    def step(params, frames):
        x = preprocess_wire(frames, spec)
        return forward(params, x).astype(jnp.float32)

    return step


def build_action_decode_step(model: LoadedModel) -> Callable:
    """Embedding clips [B,T,D] float32 → class probabilities [B,C]."""
    forward = model.forward
    is_prob = model.out_is_prob  # IR graphs may softmax in-graph

    def step(params, clips):
        out = forward(params, clips).astype(jnp.float32)
        return out if is_prob else jax.nn.softmax(out, axis=-1)

    return step


def build_audio_step(model: LoadedModel) -> Callable:
    """int16 audio windows [B,S] → class probabilities [B,C].

    Normalization of S16LE to [-1, 1] happens on-device (the
    reference's gvaaudiodetect consumes S16LE directly,
    pipelines/audio_detection/environment/pipeline.json:5).
    """
    forward = model.forward
    is_prob = model.out_is_prob  # IR graphs may softmax in-graph

    def step(params, windows):
        x = windows.astype(jnp.float32) / 32768.0
        out = forward(params, x).astype(jnp.float32)
        return out if is_prob else jax.nn.softmax(out, axis=-1)

    return step
