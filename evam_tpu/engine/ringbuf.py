"""Slot-based host staging ring for zero-copy batch assembly.

The legacy dispatch path allocated per batch: ``np.stack(rows)`` plus
a zero-pad ``np.concatenate`` — two fresh multi-megabyte arrays per
dispatched batch, built on the single dispatcher thread, page-faulted
on every first touch (a 256×1080p I420 batch is ~760 MB/s of pure
assembly traffic at the north-star fan-in). This module replaces that
with the tf.data-style staging discipline (PAPERS.md): a small ring of
pre-allocated host blocks, one block per input name, each sized to the
engine's LARGEST bucket and 2–3 deep so assembly of batch N+1 overlaps
the device round-trip of batch N.

Zero-copy here means *zero per-batch allocation and zero re-stacking*:

* ``write()`` runs on the SUBMITTING stream thread and copies each
  item's arrays straight into its reserved row of the open slot — the
  one unavoidable host copy, moved off the dispatcher's critical path
  and parallelized across stream threads (numpy row copies release
  the GIL);
* the dispatcher ``seal()``s a slot — pick the bucket, zero only the
  dirty tail rows (the pad is "already zeroed" by invariant, not a
  fresh concat) — and hands a contiguous ``block[:bucket]`` view to
  ``device_put``;
* ``release()`` returns the slot to the free list after the batch's
  readback, so a block is never overwritten while its transfer may
  still be in flight.

Concurrency contract: row indices are reserved under the ring lock,
row copies happen OUTSIDE the lock (each row has exactly one writer),
and a seal waits for all in-flight writers of that slot. Items resolve
in row order, so per-batch future fan-out stays positionally correct.

Measured on this box (``tools/bench_hostpath.py``, serving-default
bucket 128 at the 432×768 I420 wire shape): 3.1× cheaper than
stack+concat at full occupancy, 7.5× with a padded tail (legacy pays
stack + pad + a second full copy through concatenate). The win comes
from (a) no per-batch allocation — blocks > glibc's 32 MB mmap cap
are freshly mapped and page-faulted on EVERY legacy batch, and
(b) pad rows being pre-zeroed instead of re-concatenated. Below
~32 MB the allocator recycles legacy's buffer and the two paths are
comparable; the serving shapes (batch 128–256) sit well above it.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any

import numpy as np

#: stage names of the per-batch host clock, in pipeline order.
#: submit_wait covers slot backpressure AND the deadline-batching
#: formation wait; slot_write is the summed per-item row copies
#: (spent on stream threads, overlapped across submitters). The
#: device boundary is split transfer-honestly (EVAM_TRANSFER):
#: h2d_issue is the time for device_put to ENQUEUE the host→device
#: copy, h2d_wait the residual wait for that copy at launch (≈0 when
#: the pipelined uploader overlapped it with the previous launch; 0
#: by definition on the inline path, where the launch itself absorbs
#: it), and readback the device→host residual the completer still
#: has to block on after the async D2H copy was put in flight.
STAGES = (
    "submit_wait", "slot_write", "seal",
    "h2d_issue", "h2d_wait", "launch", "readback", "resolve",
)


class _Slot:
    """One staging block set: per-input pre-allocated (capacity, …)
    arrays plus fill bookkeeping. All mutable fields are guarded by
    the owning ring's condition variable except the row contents
    themselves (single writer per reserved row, written unlocked)."""

    __slots__ = ("arrays", "items", "count", "high", "writers",
                 "t_first", "closed", "wait_sum", "write_sum", "gen")

    def __init__(self, arrays: dict[str, np.ndarray]):
        self.arrays = arrays
        self.items: list[Any] = []
        self.count = 0
        #: exclusive upper bound of possibly-nonzero rows left behind
        #: by previous uses — the only rows a seal must memset
        self.high = 0
        self.writers = 0
        self.t_first = 0.0
        self.closed = False
        self.wait_sum = 0.0   # summed per-item slot-acquire waits
        self.write_sum = 0.0  # summed per-item row-copy times
        #: bumped on every recycle (release/drain) so a dispatcher
        #: that slept through a watchdog drain can detect its claim
        #: went stale instead of double-dispatching the slot
        self.gen = 0


class SealedBatch:
    """A sealed slot ready for dispatch: contiguous ``[:bucket]``
    views over the staging blocks, the items in row order, and the
    host-clock readings accumulated so far."""

    __slots__ = ("slot", "arrays", "items", "n", "bucket", "clock")

    def __init__(self, slot: _Slot, arrays: dict[str, np.ndarray],
                 items: list, n: int, bucket: int,
                 clock: dict[str, float]):
        self.slot = slot
        self.arrays = arrays
        self.items = items
        self.n = n
        self.bucket = bucket
        self.clock = clock


class SlotRing:
    """Ring of ``depth`` pre-allocated staging slots for one engine.

    Blocks are allocated lazily on the first ``write()`` (item shapes
    are not known at engine construction) and NEVER reallocated —
    ``blocks_allocated`` is the test hook pinning that invariant.
    """

    def __init__(self, capacity: int, depth: int = 4):
        if capacity < 1 or depth < 2:
            raise ValueError("capacity >= 1 and depth >= 2 required")
        self.capacity = capacity
        self.depth = depth
        self._cv = threading.Condition()
        self._free: deque[_Slot] = deque()
        self._full: deque[_Slot] = deque()
        self._open: _Slot | None = None
        self._closed = False
        self._shapes: dict[str, tuple[tuple[int, ...], np.dtype]] | None = None
        #: total staging-block allocations ever performed (one per
        #: input name per slot; constant after first write)
        self.blocks_allocated = 0

    # ------------------------------------------------------- submit side

    def write(self, inputs: dict[str, np.ndarray], item) -> None:
        """Reserve the next row of the open slot and copy ``inputs``
        into it (copy happens outside the ring lock). Blocks while
        every slot is in flight — natural backpressure. Raises
        RuntimeError once the ring is closed."""
        arrays = {k: np.asarray(v) for k, v in inputs.items()}
        t0 = time.perf_counter()
        with self._cv:
            if self._shapes is None:
                self._allocate(arrays)
            else:
                self._check_shapes(arrays)
            while (self._open is None and not self._free
                   and not self._closed):
                self._cv.wait(0.1)
            if self._closed:
                raise RuntimeError("staging ring is closed")
            waited = time.perf_counter() - t0
            if self._open is None:
                slot = self._free.popleft()
                slot.t_first = time.perf_counter()
                self._open = slot
            slot = self._open
            row = slot.count
            slot.count += 1
            slot.writers += 1
            slot.items.append(item)
            slot.wait_sum += waited
            filled = slot.count >= self.capacity
            if filled:
                slot.closed = True
                self._full.append(slot)
                self._open = None
            if row == 0 or filled:
                # wake the dispatcher only on the edges it waits for
                # (first work / slot full) — a notify per row is pure
                # overhead at high fan-in
                self._cv.notify_all()
        t1 = time.perf_counter()
        try:
            for name, a in arrays.items():
                slot.arrays[name][row] = a  # row exclusively owned
        finally:
            with self._cv:
                slot.write_sum += time.perf_counter() - t1
                slot.writers -= 1
                if slot.writers == 0 and slot.closed:
                    self._cv.notify_all()

    # --------------------------------------------------- dispatcher side

    def next_batch(self, deadline_s: float, bucket_fn) -> SealedBatch | None:
        """Wait for rows, honor the batch-fill deadline (measured from
        the open slot's FIRST write), then seal: close the slot, wait
        out in-flight row writers, zero the dirty pad tail, and return
        contiguous ``[:bucket]`` views. Returns None once the ring is
        closed and drained."""
        with self._cv:
            while True:
                if self._full:
                    slot = self._full.popleft()
                elif self._open is not None and self._open.count > 0:
                    slot = self._open
                    gen = slot.gen
                    deadline = slot.t_first + deadline_s
                    while (not slot.closed and slot.gen == gen
                           and not self._closed):
                        remaining = deadline - time.perf_counter()
                        if remaining <= 0:
                            break
                        self._cv.wait(remaining)
                    if slot.gen != gen:
                        continue  # drained (stall/stop) mid-wait
                    if slot.closed:
                        # filled while we waited — it is in _full now;
                        # claim that entry
                        try:
                            self._full.remove(slot)
                        except ValueError:
                            continue
                    else:
                        slot.closed = True
                        if self._open is slot:
                            self._open = None
                elif self._closed:
                    return None
                else:
                    self._cv.wait(0.1)
                    continue
                # slot is now exclusively claimed (in neither _open
                # nor _full — drain/release can no longer touch it)
                while slot.writers:
                    self._cv.wait(0.05)
                if slot.count == 0:
                    # lost a race with a drain that emptied it just
                    # before we claimed — recycle and keep waiting
                    slot.closed = False
                    self._free.append(slot)
                    continue
                n = slot.count
                items = list(slot.items)
                submit_wait = (time.perf_counter() - slot.t_first
                               + slot.wait_sum)
                write_sum = slot.write_sum
                break
        t0 = time.perf_counter()
        bucket = bucket_fn(n)
        dirty = min(slot.high, bucket)
        for arr in slot.arrays.values():
            if dirty > n:
                arr[n:dirty] = 0
        views = {k: a[:bucket] for k, a in slot.arrays.items()}
        clock = {
            "submit_wait": submit_wait,
            "slot_write": write_sum,
            "seal": time.perf_counter() - t0,
        }
        return SealedBatch(slot, views, items, n, bucket, clock)

    # ------------------------------------------------------- completion

    def release(self, sealed: SealedBatch) -> None:
        """Return a dispatched slot to the free list (call after the
        batch's readback — the staging block may back an in-flight
        transfer until then)."""
        slot = sealed.slot
        with self._cv:
            # rows [n, bucket) were zeroed at seal; rows beyond the
            # bucket may still hold older data
            if slot.high <= sealed.bucket:
                slot.high = sealed.n
            slot.count = 0
            slot.items = []
            slot.closed = False
            slot.wait_sum = 0.0
            slot.write_sum = 0.0
            slot.gen += 1
            self._free.append(slot)
            self._cv.notify_all()

    # -------------------------------------------------------- lifecycle

    def close(self) -> None:
        """Reject new writes and wake every waiter (submitters raise,
        the dispatcher drains and exits)."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def drain_items(self) -> list:
        """Remove and return every written-but-undispatched item (open
        + full slots) so the engine can fail their futures on stop or
        stall. Slots return to the free list."""
        out: list = []
        with self._cv:
            slots = list(self._full)
            self._full.clear()
            if self._open is not None:
                slots.append(self._open)
                self._open = None
            for slot in slots:
                while slot.writers:
                    self._cv.wait(0.05)
                out.extend(slot.items)
                slot.high = max(slot.high, slot.count)
                slot.count = 0
                slot.items = []
                slot.closed = False
                slot.wait_sum = 0.0
                slot.write_sum = 0.0
                slot.gen += 1
                self._free.append(slot)
            self._cv.notify_all()
        return out

    def pending_items(self) -> int:
        """Rows written but not yet sealed (the slot-path analogue of
        the legacy queue depth gauge)."""
        with self._cv:
            n = sum(s.count for s in self._full)
            if self._open is not None:
                n += self._open.count
            return n

    def oldest_age_s(self, now: float | None = None) -> float:
        """Age of the oldest staged-but-undispatched work (seconds):
        the earliest first-write time across full + open slots. The
        queue-age gauge's slot-path source — an invisible backlog
        shows up here long before the stall watchdog would trip."""
        now = time.perf_counter() if now is None else now
        with self._cv:
            firsts = [s.t_first for s in self._full if s.count]
            if self._open is not None and self._open.count:
                firsts.append(self._open.t_first)
        return max(0.0, now - min(firsts)) if firsts else 0.0

    # ------------------------------------------- dispatcher-side staging

    def stage_direct(self, staged: list[tuple[dict, Any]], bucket_fn,
                     clock: dict[str, float]) -> SealedBatch | None:
        """Stage a dispatcher-assembled batch into a free slot (the
        sched path: items arrive from per-class queues, so the row
        copies happen HERE on the dispatcher thread instead of on the
        submitting stream threads — the trade the QoS layer makes for
        class-ordered dispatch, still zero per-batch allocation).

        ``staged`` is ``[(inputs, item), ...]`` in dispatch order. A
        row whose arrays mismatch the ring shapes fails only ITS
        item's future; survivors compact into contiguous rows. Blocks
        while every slot is in flight (the same host-side
        backpressure as the submit path); raises RuntimeError once
        the ring is closed; returns None when no row survived."""
        first = {k: np.asarray(v) for k, v in staged[0][0].items()}
        with self._cv:
            if self._shapes is None:
                self._allocate(first)
            while not self._free and not self._closed:
                self._cv.wait(0.1)
            if self._closed:
                raise RuntimeError("staging ring is closed")
            slot = self._free.popleft()
        t0 = time.perf_counter()
        ok_items: list = []
        row = 0
        for inputs, item in staged:
            try:
                arrays = {k: np.asarray(v) for k, v in inputs.items()}
                self._check_shapes(arrays)
                for name, a in arrays.items():
                    slot.arrays[name][row] = a
            except Exception as exc:  # noqa: BLE001 — fail only this item
                try:
                    item.future.set_exception(exc)
                except Exception:  # noqa: BLE001 — already resolved
                    pass
                continue
            ok_items.append(item)
            row += 1
        clock["slot_write"] = time.perf_counter() - t0
        if not ok_items:
            with self._cv:
                slot.count = 0
                slot.items = []
                slot.closed = False
                slot.gen += 1
                self._free.append(slot)
                self._cv.notify_all()
            return None
        t1 = time.perf_counter()
        n = row
        bucket = bucket_fn(n)
        dirty = min(slot.high, bucket)
        for arr in slot.arrays.values():
            if dirty > n:
                arr[n:dirty] = 0
        views = {k: a[:bucket] for k, a in slot.arrays.items()}
        clock["seal"] = time.perf_counter() - t1
        slot.count = n
        return SealedBatch(slot, views, ok_items, n, bucket, clock)

    # -------------------------------------------------------- internals

    def _allocate(self, example: dict[str, np.ndarray]) -> None:
        self._shapes = {
            k: (tuple(a.shape), a.dtype) for k, a in example.items()
        }
        for _ in range(self.depth):
            arrays = {
                k: np.zeros((self.capacity,) + shape, dtype)
                for k, (shape, dtype) in self._shapes.items()
            }
            self.blocks_allocated += len(arrays)
            self._free.append(_Slot(arrays))

    def _check_shapes(self, arrays: dict[str, np.ndarray]) -> None:
        for k, a in arrays.items():
            want = self._shapes.get(k)
            if want is None or (tuple(a.shape), a.dtype) != want:
                raise ValueError(
                    f"staging ring configured for {self._shapes}, got "
                    f"{k}: shape {tuple(a.shape)} dtype {a.dtype} — "
                    "engines batch fixed ingest shapes; use a distinct "
                    "model-instance-id for a different resolution"
                )
