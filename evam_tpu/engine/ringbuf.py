"""Slot-based host staging ring for zero-copy batch assembly.

The legacy dispatch path allocated per batch: ``np.stack(rows)`` plus
a zero-pad ``np.concatenate`` — two fresh multi-megabyte arrays per
dispatched batch, built on the single dispatcher thread, page-faulted
on every first touch (a 256×1080p I420 batch is ~760 MB/s of pure
assembly traffic at the north-star fan-in). This module replaces that
with the tf.data-style staging discipline (PAPERS.md): a small ring of
pre-allocated host blocks, one block per input name, each sized to the
engine's LARGEST bucket and 2–3 deep so assembly of batch N+1 overlaps
the device round-trip of batch N.

Zero-copy here means *zero per-batch allocation and zero re-stacking*:

* ``write()`` runs on the SUBMITTING stream thread and copies each
  item's arrays straight into its reserved row of the open slot — the
  one unavoidable host copy, moved off the dispatcher's critical path
  and parallelized across stream threads (numpy row copies release
  the GIL);
* the dispatcher ``seal()``s a slot — pick the bucket, zero only the
  dirty tail rows (the pad is "already zeroed" by invariant, not a
  fresh concat) — and hands a contiguous ``block[:bucket]`` view to
  ``device_put``;
* ``release()`` returns the slot to the free list after the batch's
  readback, so a block is never overwritten while its transfer may
  still be in flight.

Concurrency contract: row indices are reserved under the ring lock,
row copies happen OUTSIDE the lock (each row has exactly one writer),
and a seal waits for all in-flight writers of that slot. Items resolve
in row order, so per-batch future fan-out stays positionally correct.

**Ragged packing** (``engine/ragged.py``, ``EVAM_RAGGED=packed``): a
ring built with a ``RaggedSpec`` additionally packs ONE declared
input's variable-length unit rows (a frame's real region boxes, shape
``(k, unit_shape)``) end to end into a fixed unit block, maintaining a
segment-id vector (``seg[j]`` = owning batch row, −1 on the pad tail)
and per-item ``row_len``/``row_offset`` vectors the completer uses to
scatter results back. An item reserves 1 batch row + k unit rows; a
slot seals when either runs out, so a packed batch never overflows its
fixed device shape. Everything else — slot reuse, dirty-tail zeroing,
writer accounting — is the same discipline extended to the unit block.

Measured on this box (``tools/bench_hostpath.py``, serving-default
bucket 128 at the 432×768 I420 wire shape): 3.1× cheaper than
stack+concat at full occupancy, 7.5× with a padded tail (legacy pays
stack + pad + a second full copy through concatenate). The win comes
from (a) no per-batch allocation — blocks > glibc's 32 MB mmap cap
are freshly mapped and page-faulted on EVERY legacy batch, and
(b) pad rows being pre-zeroed instead of re-concatenated. Below
~32 MB the allocator recycles legacy's buffer and the two paths are
comparable; the serving shapes (batch 128–256) sit well above it.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any

import numpy as np

from evam_tpu.engine.ragged import RaggedSpec

#: stage names of the per-batch host clock, in pipeline order.
#: submit_wait covers slot backpressure AND the deadline-batching
#: formation wait; slot_write is the summed per-item row copies
#: (spent on stream threads, overlapped across submitters). The
#: device boundary is split transfer-honestly (EVAM_TRANSFER):
#: h2d_issue is the time for device_put to ENQUEUE the host→device
#: copy, h2d_wait the residual wait for that copy at launch (≈0 when
#: the pipelined uploader overlapped it with the previous launch; 0
#: by definition on the inline path, where the launch itself absorbs
#: it), and readback the device→host residual the completer still
#: has to block on after the async D2H copy was put in flight.
STAGES = (
    "submit_wait", "slot_write", "seal",
    "h2d_issue", "h2d_wait", "launch", "readback", "resolve",
)


class _Slot:
    """One staging block set: per-input pre-allocated (capacity, …)
    arrays plus fill bookkeeping. All mutable fields are guarded by
    the owning ring's condition variable except the row contents
    themselves (single writer per reserved row, written unlocked)."""

    __slots__ = ("arrays", "items", "count", "high", "writers",
                 "t_first", "closed", "wait_sum", "write_sum", "gen",
                 "unit_count", "unit_high", "row_len", "seg")

    def __init__(self, arrays: dict[str, np.ndarray],
                 capacity: int = 0, unit_capacity: int = 0):
        self.arrays = arrays
        self.items: list[Any] = []
        self.count = 0
        #: exclusive upper bound of possibly-nonzero rows left behind
        #: by previous uses — the only rows a seal must memset
        self.high = 0
        self.writers = 0
        self.t_first = 0.0
        self.closed = False
        self.wait_sum = 0.0   # summed per-item slot-acquire waits
        self.write_sum = 0.0  # summed per-item row-copy times
        #: bumped on every recycle (release/drain) so a dispatcher
        #: that slept through a watchdog drain can detect its claim
        #: went stale instead of double-dispatching the slot
        self.gen = 0
        #: ragged packing bookkeeping (unused on dense rings)
        self.unit_count = 0
        self.unit_high = 0
        self.row_len = (np.zeros(capacity, np.int32)
                        if unit_capacity else None)
        self.seg = (np.full(unit_capacity, -1, np.int32)
                    if unit_capacity else None)


class SealedBatch:
    """A sealed slot ready for dispatch: contiguous ``[:bucket]``
    views over the staging blocks, the items in row order, and the
    host-clock readings accumulated so far.

    On a ragged ring the batch additionally carries the packed-unit
    descriptor: ``row_len[i]``/``row_offset[i]`` locate item i's unit
    rows in the packed block (COPIES — the slot recycles before the
    completer resolves), ``units`` is the real packed-unit count and
    ``unit_rows`` the computed unit rows of the device shape (the
    honest-occupancy denominator)."""

    __slots__ = ("slot", "arrays", "items", "n", "bucket", "clock",
                 "row_len", "row_offset", "units", "unit_rows")

    def __init__(self, slot: _Slot, arrays: dict[str, np.ndarray],
                 items: list, n: int, bucket: int,
                 clock: dict[str, float],
                 row_len: np.ndarray | None = None,
                 row_offset: np.ndarray | None = None,
                 units: int = 0, unit_rows: int = 0):
        self.slot = slot
        self.arrays = arrays
        self.items = items
        self.n = n
        self.bucket = bucket
        self.clock = clock
        self.row_len = row_len
        self.row_offset = row_offset
        self.units = units
        self.unit_rows = unit_rows


class SlotRing:
    """Ring of ``depth`` pre-allocated staging slots for one engine.

    Blocks are allocated lazily on the first ``write()`` (item shapes
    are not known at engine construction) and NEVER reallocated —
    ``blocks_allocated`` is the test hook pinning that invariant.

    ``ragged`` (a RaggedSpec) switches the declared input to packed
    unit-row staging; its bucket callbacks then take ``(n, units)``
    instead of ``(n)``.
    """

    def __init__(self, capacity: int, depth: int = 4,
                 ragged: RaggedSpec | None = None):
        if capacity < 1 or depth < 2:
            raise ValueError("capacity >= 1 and depth >= 2 required")
        self.capacity = capacity
        self.depth = depth
        self.ragged = ragged
        #: fixed unit rows of the packed block (0 on dense rings)
        self.unit_capacity = ragged.unit_rows(capacity) if ragged else 0
        self._cv = threading.Condition()
        self._free: deque[_Slot] = deque()
        self._full: deque[_Slot] = deque()
        self._open: _Slot | None = None
        self._closed = False
        self._shapes: dict[str, tuple[tuple[int, ...], np.dtype]] | None = None
        #: total staging-block allocations ever performed (one per
        #: input name per slot; constant after first write)
        self.blocks_allocated = 0

    # ------------------------------------------------------- submit side

    def write(self, inputs: dict[str, np.ndarray], item) -> None:
        """Reserve the next row of the open slot and copy ``inputs``
        into it (copy happens outside the ring lock). Blocks while
        every slot is in flight — natural backpressure. On a ragged
        ring the item also reserves its ``k`` unit rows; an item that
        would overflow the open slot's unit block seals that slot and
        takes the next one. Raises RuntimeError once the ring is
        closed."""
        arrays = {k: np.asarray(v) for k, v in inputs.items()}
        spec = self.ragged
        k = int(arrays[spec.input].shape[0]) if spec is not None else 0
        t0 = time.perf_counter()
        with self._cv:
            if self._shapes is None:
                self._allocate(arrays)
            else:
                self._check_shapes(arrays)
            while True:
                if self._closed:
                    raise RuntimeError("staging ring is closed")
                if self._open is not None:
                    slot = self._open
                    if (spec is None
                            or slot.unit_count + k <= self.unit_capacity):
                        break
                    # packed units would overflow the fixed block:
                    # seal what's staged and take a fresh slot
                    slot.closed = True
                    self._full.append(slot)
                    self._open = None
                    self._cv.notify_all()
                    continue
                if self._free:
                    slot = self._free.popleft()
                    slot.t_first = time.perf_counter()
                    self._open = slot
                    break
                self._cv.wait(0.1)
            waited = time.perf_counter() - t0
            row = slot.count
            off = slot.unit_count
            slot.count += 1
            slot.unit_count += k
            if spec is not None:
                slot.row_len[row] = k
            slot.writers += 1
            slot.items.append(item)
            slot.wait_sum += waited
            filled = (slot.count >= self.capacity
                      or (spec is not None
                          and slot.unit_count >= self.unit_capacity))
            if filled:
                slot.closed = True
                self._full.append(slot)
                self._open = None
            if row == 0 or filled:
                # wake the dispatcher only on the edges it waits for
                # (first work / slot full) — a notify per row is pure
                # overhead at high fan-in
                self._cv.notify_all()
        t1 = time.perf_counter()
        try:
            for name, a in arrays.items():
                if spec is not None and name == spec.input:
                    if k:  # packed span exclusively owned
                        slot.arrays[name][off:off + k] = a
                        slot.seg[off:off + k] = row
                else:
                    slot.arrays[name][row] = a  # row exclusively owned
        finally:
            with self._cv:
                slot.write_sum += time.perf_counter() - t1
                slot.writers -= 1
                if slot.writers == 0 and slot.closed:
                    self._cv.notify_all()

    # --------------------------------------------------- dispatcher side

    def next_batch(self, deadline_s: float, bucket_fn) -> SealedBatch | None:
        """Wait for rows, honor the batch-fill deadline (measured from
        the open slot's FIRST write), then seal: close the slot, wait
        out in-flight row writers, zero the dirty pad tail, and return
        contiguous ``[:bucket]`` views. On a ragged ring ``bucket_fn``
        is called with ``(n, units)`` and the packed block/seg tail is
        masked too. Returns None once the ring is closed and
        drained."""
        with self._cv:
            while True:
                if self._full:
                    slot = self._full.popleft()
                elif self._open is not None and self._open.count > 0:
                    slot = self._open
                    gen = slot.gen
                    deadline = slot.t_first + deadline_s
                    while (not slot.closed and slot.gen == gen
                           and not self._closed):
                        remaining = deadline - time.perf_counter()
                        if remaining <= 0:
                            break
                        self._cv.wait(remaining)
                    if slot.gen != gen:
                        continue  # drained (stall/stop) mid-wait
                    if slot.closed:
                        # filled while we waited — it is in _full now;
                        # claim that entry
                        try:
                            self._full.remove(slot)
                        except ValueError:
                            continue
                    else:
                        slot.closed = True
                        if self._open is slot:
                            self._open = None
                elif self._closed:
                    return None
                else:
                    self._cv.wait(0.1)
                    continue
                # slot is now exclusively claimed (in neither _open
                # nor _full — drain/release can no longer touch it)
                while slot.writers:
                    self._cv.wait(0.05)
                if slot.count == 0:
                    # lost a race with a drain that emptied it just
                    # before we claimed — recycle and keep waiting
                    slot.closed = False
                    self._free.append(slot)
                    continue
                n = slot.count
                items = list(slot.items)
                submit_wait = (time.perf_counter() - slot.t_first
                               + slot.wait_sum)
                write_sum = slot.write_sum
                break
        t0 = time.perf_counter()
        sealed = self._seal(slot, items, n, bucket_fn)
        sealed.clock.update({
            "submit_wait": submit_wait,
            "slot_write": write_sum,
        })
        sealed.clock["seal"] = time.perf_counter() - t0
        return sealed

    def _seal(self, slot: _Slot, items: list, n: int,
              bucket_fn) -> SealedBatch:
        """Common seal tail (deadline path + stage_direct): pick the
        bucket, zero the dirty pad tails (dense rows AND, on a ragged
        ring, the packed unit block + seg vector), and build the
        contiguous views + ragged descriptor."""
        spec = self.ragged
        if spec is not None:
            units = slot.unit_count
            bucket = bucket_fn(n, units)
            u = min(spec.unit_rows(bucket), self.unit_capacity)
            dirty = min(slot.high, bucket)
            views: dict[str, np.ndarray] = {}
            for name, arr in slot.arrays.items():
                if name == spec.input:
                    udirty = min(slot.unit_high, u)
                    if udirty > units:
                        arr[units:udirty] = 0
                    views[name] = arr[:u]
                else:
                    if dirty > n:
                        arr[n:dirty] = 0
                    views[name] = arr[:bucket]
            # the seg pad tail is ALWAYS −1 (the masked-compute
            # sentinel), whatever an earlier batch left behind
            slot.seg[units:u] = -1
            views["seg"] = slot.seg[:u]
            row_len = slot.row_len[:n].copy()
            row_offset = np.zeros(n, np.int32)
            np.cumsum(row_len[:-1], out=row_offset[1:])
            return SealedBatch(slot, views, items, n, bucket, {},
                               row_len=row_len, row_offset=row_offset,
                               units=units, unit_rows=u)
        bucket = bucket_fn(n)
        dirty = min(slot.high, bucket)
        for arr in slot.arrays.values():
            if dirty > n:
                arr[n:dirty] = 0
        views = {k: a[:bucket] for k, a in slot.arrays.items()}
        return SealedBatch(slot, views, items, n, bucket, {})

    # ------------------------------------------------------- completion

    def release(self, sealed: SealedBatch) -> None:
        """Return a dispatched slot to the free list (call after the
        batch's readback — the staging block may back an in-flight
        transfer until then)."""
        slot = sealed.slot
        with self._cv:
            # rows [n, bucket) were zeroed at seal; rows beyond the
            # bucket may still hold older data
            if slot.high <= sealed.bucket:
                slot.high = sealed.n
            if self.ragged is not None:
                if slot.unit_high <= sealed.unit_rows:
                    slot.unit_high = sealed.units
                slot.unit_count = 0
            slot.count = 0
            slot.items = []
            slot.closed = False
            slot.wait_sum = 0.0
            slot.write_sum = 0.0
            slot.gen += 1
            self._free.append(slot)
            self._cv.notify_all()

    # -------------------------------------------------------- lifecycle

    def close(self) -> None:
        """Reject new writes and wake every waiter (submitters raise,
        the dispatcher drains and exits)."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def drain_items(self) -> list:
        """Remove and return every written-but-undispatched item (open
        + full slots) so the engine can fail their futures on stop or
        stall. Slots return to the free list."""
        out: list = []
        with self._cv:
            slots = list(self._full)
            self._full.clear()
            if self._open is not None:
                slots.append(self._open)
                self._open = None
            for slot in slots:
                while slot.writers:
                    self._cv.wait(0.05)
                out.extend(slot.items)
                slot.high = max(slot.high, slot.count)
                slot.count = 0
                if self.ragged is not None:
                    slot.unit_high = max(slot.unit_high, slot.unit_count)
                    slot.unit_count = 0
                slot.items = []
                slot.closed = False
                slot.wait_sum = 0.0
                slot.write_sum = 0.0
                slot.gen += 1
                self._free.append(slot)
            self._cv.notify_all()
        return out

    def pending_items(self) -> int:
        """Rows written but not yet sealed (the slot-path analogue of
        the legacy queue depth gauge)."""
        with self._cv:
            n = sum(s.count for s in self._full)
            if self._open is not None:
                n += self._open.count
            return n

    def oldest_age_s(self, now: float | None = None) -> float:
        """Age of the oldest staged-but-undispatched work (seconds):
        the earliest first-write time across full + open slots. The
        queue-age gauge's slot-path source — an invisible backlog
        shows up here long before the stall watchdog would trip."""
        now = time.perf_counter() if now is None else now
        with self._cv:
            firsts = [s.t_first for s in self._full if s.count]
            if self._open is not None and self._open.count:
                firsts.append(self._open.t_first)
        return max(0.0, now - min(firsts)) if firsts else 0.0

    # ------------------------------------------- dispatcher-side staging

    def stage_direct(self, staged: list[tuple[dict, Any]], bucket_fn,
                     clock: dict[str, float],
                     ) -> tuple[SealedBatch | None, list]:
        """Stage a dispatcher-assembled batch into a free slot (the
        sched path: items arrive from per-class queues, so the row
        copies happen HERE on the dispatcher thread instead of on the
        submitting stream threads — the trade the QoS layer makes for
        class-ordered dispatch, still zero per-batch allocation).

        ``staged`` is ``[(inputs, item), ...]`` in dispatch order. A
        row whose arrays mismatch the ring shapes fails only ITS
        item's future; survivors compact into contiguous rows. Items
        past the slot's capacity — batch rows, or packed unit rows on
        a ragged ring — are NOT silently clamped: they come back as
        the second element for the caller to stage as another batch
        (the oversize-split contract). Blocks while every slot is in
        flight (the same host-side backpressure as the submit path);
        raises RuntimeError once the ring is closed; the sealed batch
        is None when no row survived."""
        first = {k: np.asarray(v) for k, v in staged[0][0].items()}
        spec = self.ragged
        with self._cv:
            if self._shapes is None:
                self._allocate(first)
            while not self._free and not self._closed:
                self._cv.wait(0.1)
            if self._closed:
                raise RuntimeError("staging ring is closed")
            slot = self._free.popleft()
        t0 = time.perf_counter()
        ok_items: list = []
        remaining: list = []
        row = 0
        off = 0
        for idx, (inputs, item) in enumerate(staged):
            if row >= self.capacity:
                remaining = list(staged[idx:])
                break
            try:
                arrays = {k: np.asarray(v) for k, v in inputs.items()}
                self._check_shapes(arrays)
            except Exception as exc:  # noqa: BLE001 — fail only this item
                try:
                    item.future.set_exception(exc)
                except Exception:  # noqa: BLE001 — already resolved
                    pass
                continue
            if spec is not None:
                k = int(arrays[spec.input].shape[0])
                if off + k > self.unit_capacity:
                    remaining = list(staged[idx:])
                    break
                for name, a in arrays.items():
                    if name == spec.input:
                        if k:
                            slot.arrays[name][off:off + k] = a
                            slot.seg[off:off + k] = row
                    else:
                        slot.arrays[name][row] = a
                slot.row_len[row] = k
                off += k
            else:
                for name, a in arrays.items():
                    slot.arrays[name][row] = a
            ok_items.append(item)
            row += 1
        clock["slot_write"] = time.perf_counter() - t0
        if not ok_items:
            with self._cv:
                slot.count = 0
                slot.unit_count = 0
                slot.items = []
                slot.closed = False
                slot.gen += 1
                self._free.append(slot)
                self._cv.notify_all()
            return None, remaining
        t1 = time.perf_counter()
        slot.count = row
        slot.unit_count = off
        sealed = self._seal(slot, ok_items, row, bucket_fn)
        sealed.clock.update(clock)
        sealed.clock["seal"] = time.perf_counter() - t1
        return sealed, remaining

    # -------------------------------------------------------- internals

    def _allocate(self, example: dict[str, np.ndarray]) -> None:
        spec = self.ragged
        self._shapes = {}
        for k, a in example.items():
            if spec is not None and k == spec.input:
                # ragged input: the leading dim is per-item variable;
                # pin only the unit shape + dtype
                self._shapes[k] = (tuple(spec.unit_shape),
                                   np.dtype(spec.dtype))
            else:
                self._shapes[k] = (tuple(a.shape), a.dtype)
        for _ in range(self.depth):
            arrays = {}
            for k, (shape, dtype) in self._shapes.items():
                rows = (self.unit_capacity
                        if spec is not None and k == spec.input
                        else self.capacity)
                arrays[k] = np.zeros((rows,) + shape, dtype)
            self.blocks_allocated += len(arrays)
            self._free.append(
                _Slot(arrays, capacity=self.capacity,
                      unit_capacity=self.unit_capacity))

    def _check_shapes(self, arrays: dict[str, np.ndarray]) -> None:
        spec = self.ragged
        for k, a in arrays.items():
            want = self._shapes.get(k)
            if spec is not None and k == spec.input:
                if (want is None
                        or (tuple(a.shape[1:]), a.dtype) != want
                        or a.shape[0] > spec.max_units):
                    raise ValueError(
                        f"ragged input {k}: want (<= {spec.max_units}, "
                        f"{want[0] if want else '?'}) "
                        f"{want[1] if want else '?'}, got shape "
                        f"{tuple(a.shape)} dtype {a.dtype}")
                continue
            if want is None or (tuple(a.shape), a.dtype) != want:
                raise ValueError(
                    f"staging ring configured for {self._shapes}, got "
                    f"{k}: shape {tuple(a.shape)} dtype {a.dtype} — "
                    "engines batch fixed ingest shapes; use a distinct "
                    "model-instance-id for a different resolution"
                )
