"""EngineHub: model-instance-id → shared BatchEngine.

Implements the reference's engine-sharing contract: pipelines that
pass the same ``model-instance-id`` share one inference engine and
its batch queue (reference pipelines/object_detection/
person_vehicle_bike/pipeline.json:26-32, SURVEY.md §2d-2). Pipelines
that omit it share per-model-key engines — the cross-stream batching
default that the TPU design is built around.
"""

from __future__ import annotations

import os
import threading

import numpy as np

from evam_tpu.engine import steps as step_builders
from evam_tpu.engine.batcher import BatchEngine
from evam_tpu.engine.ragged import RaggedSpec, ragged_mode
from evam_tpu.engine.supervisor import SupervisedEngine
from evam_tpu.models.registry import LoadedModel, ModelRegistry
from evam_tpu.obs import get_logger, metrics
from evam_tpu.parallel.mesh import MeshPlan
from evam_tpu.sched.classes import PRIORITIES, SchedConfig

log = get_logger("engine.hub")

_BUILDERS = {
    "detect": (step_builders.build_detect_step, ("frames",), True),
    "classify": (step_builders.build_classify_step, ("frames", "boxes"), True),
    "action_encode": (step_builders.build_action_encode_step, ("frames",), True),
    "action_decode": (step_builders.build_action_decode_step, ("clips",), False),
    "audio": (step_builders.build_audio_step, ("windows",), False),
}


class EngineHub:
    """Creates/caches engines; one per (kind, model key or instance id)."""

    def __init__(
        self,
        registry: ModelRegistry,
        plan: MeshPlan | None = None,
        max_batch: int = 128,  # serving default, see TPUSettings.max_batch
        deadline_ms: float = 8.0,
        wire_format: str = "i420",
        warmup: bool = False,
        stall_timeout_s: float = 120.0,
        device_synth: bool = False,
        supervise: bool = True,
        max_restarts: int = 3,
        restart_window_s: float = 300.0,
        restart_backoff_s: float = 0.5,
        first_batch_grace: float = 10.0,
        sched: SchedConfig | None = None,
        transfer: str | None = None,
        transfer_depth: int = 0,
        ragged: str | None = None,
        ragged_unit_budget: int = 0,
        fleet: str | None = None,
        fleet_shard_max_batch: int = 0,
        fleet_max_shards: int = 0,
        fleet_initial_shards: int = 0,
    ):
        #: serving sets True: stages precompile every batch bucket in
        #: the background right after engine creation
        self.warmup = warmup
        self.registry = registry
        self.plan = plan
        self.max_batch = max_batch
        self.deadline_ms = deadline_ms
        self.stall_timeout_s = stall_timeout_s
        #: host→device frame encoding for video engines ("i420" halves
        #: ingest bandwidth; see evam_tpu.ops.color)
        self.wire_format = wire_format
        #: bench-only mode (bench.py --config serve --serve-ingest
        #: seed): video stages submit uint32 seeds and each engine's
        #: step synthesizes its wire batch on-chip
        #: (steps.wrap_device_synth) — the serving path minus only the
        #: host→device pixel copy
        self.device_synth = device_synth
        #: engine supervision (engine/supervisor.py): wedged engines
        #: are quarantined and rebuilt in place, with a restart budget
        #: (EVAM_ENGINE_MAX_RESTARTS within EVAM_ENGINE_RESTART_WINDOW_S)
        self.supervise = supervise
        self.max_restarts = max_restarts
        self.restart_window_s = restart_window_s
        self.restart_backoff_s = restart_backoff_s
        #: stall-watchdog multiplier for a bucket's first (compiling)
        #: batch — see BatchEngine._track_dispatch
        self.first_batch_grace = first_batch_grace
        #: QoS scheduling config (evam_tpu/sched/): engines get
        #: per-class queues, deadlines and staleness shedding. Part of
        #: the rebuild recipe — a supervisor-rebuilt engine inherits
        #: the class queues because the factory closure carries it.
        #: None = the legacy single-FIFO engines (EVAM_SCHED=off).
        self.sched = sched if (sched is not None and sched.enabled) else None
        #: device-transfer pipeline (EVAM_TRANSFER): "pipelined"
        #: (default) overlaps H2D upload / launch / async D2H inside
        #: every engine; "inline" is the serial pre-pipeline path
        #: (A/B, tools/bench_transfer.py). Part of the rebuild recipe:
        #: the factory closure carries it, so a supervisor-rebuilt
        #: engine keeps its transfer mode. None = engine reads the env.
        self.transfer = transfer
        #: pipelined upload-queue bound (EVAM_TRANSFER_DEPTH): the
        #: static boot value; the control plane (evam_tpu/control/)
        #: retunes the live bound through ``retune``. Part of the
        #: rebuild recipe — but BatchEngine construction consults the
        #: live operating point first, so a supervisor rebuild resumes
        #: at the controller's current depth, not this boot value.
        self.transfer_depth = transfer_depth
        #: ragged batching (engine/ragged.py, EVAM_RAGGED): "packed"
        #: gives classify-family engines masked region packing (the
        #: ragged builder + a RaggedSpec'd staging ring) and every
        #: engine a consolidated bucket ladder; "off" (default) is the
        #: byte-identical dense path. Part of the rebuild recipe — the
        #: factory closure carries mode + spec, so supervisor rebuilds
        #: inherit EVAM_RAGGED.
        self.ragged = ragged_mode(ragged)
        #: packed unit rows budgeted per batch row (EVAM_RAGGED_UNIT_
        #: BUDGET): the knob that turns "roi_budget slots per frame,
        #: mostly empty" into a shared pool sized for the real mix
        self.ragged_unit_budget = ragged_unit_budget or int(
            os.environ.get("EVAM_RAGGED_UNIT_BUDGET", "4"))
        #: fleet serving mode (evam_tpu/fleet/, EVAM_FLEET): "sharded"
        #: fronts every engine key with a FleetEngine — one per-chip
        #: shard per mesh device behind a consistent-hash stream
        #: placer, plus a mesh-sharded twin for batch-class big
        #: buckets; "off" (default) is the byte-identical single-chip
        #: path. Needs a multi-device plan — on one device the modes
        #: are the same thing, so sharded quietly degrades to off.
        from evam_tpu.fleet.engine import fleet_mode
        self.fleet = fleet_mode(fleet)
        self.fleet_active = (
            self.fleet == "sharded" and plan is not None
            and plan.data_size > 1)
        if self.fleet == "sharded" and not self.fleet_active:
            log.warning(
                "EVAM_FLEET=sharded needs a multi-device mesh plan "
                "(have %s) — running single-chip",
                plan.data_size if plan else "none")
        #: per-shard ladder top: a chip serving 1/N of the streams
        #: does not need the fleet-wide max_batch — capping it keeps
        #: shard compile bills and staging memory proportional
        self.fleet_shard_max_batch = fleet_shard_max_batch or (
            max(1, max_batch // plan.data_size) if self.fleet_active
            else max_batch)
        #: autoscaling ceiling (EVAM_FLEET_MAX_SHARDS): how many
        #: shards the eighth control law may grow the fleet to,
        #: bounded by the mesh. 0 (default) keeps the law inert —
        #: fleet_summary reports max_shards 0 and the controller
        #: never proposes a move.
        self.fleet_max_shards = fleet_max_shards
        #: boot fleet size (EVAM_FLEET_SHARDS when autoscaling):
        #: FleetEngines start with this many shards and grow/shrink
        #: between 1 and the ceiling. 0 = all plan devices (the
        #: pre-autoscaling behavior).
        self.fleet_initial_shards = fleet_initial_shards
        self._engines: dict[str, BatchEngine | SupervisedEngine] = {}
        #: device_synth only: engine key → the (H, W) its on-chip
        #: generator was compiled for (cache-hit mismatch guard)
        self._synth_hw: dict[str, tuple[int, int] | None] = {}
        self._models: dict[str, LoadedModel] = {}
        # RLock: engine() calls model() while holding the lock.
        self._lock = threading.RLock()

    def model(self, model_key: str) -> LoadedModel:
        with self._lock:
            if model_key not in self._models:
                self._models[model_key] = self.registry.get(model_key)
            return self._models[model_key]

    def engine(
        self,
        kind: str,
        model_key: str,
        instance_id: str | None = None,
        **builder_kwargs,
    ) -> BatchEngine:
        """Get or create the shared engine for (kind, model, instance).

        ``instance_id`` is the model-instance-id parameter; None
        defaults to sharing by model key (maximum batching).
        """
        if kind not in _BUILDERS:
            raise ValueError(f"no step builder for stage kind '{kind}'")
        synth_hw = builder_kwargs.pop("synth_wire_hw", None)
        key = f"{kind}:{instance_id or model_key}"
        with self._lock:
            if key not in self._engines:
                model = self.model(model_key)
                builder, input_names, wired = _BUILDERS[kind]
                if wired:
                    builder_kwargs.setdefault("wire_format", self.wire_format)
                spec = self._ragged_spec(kind, builder_kwargs)
                if spec is not None and self.ragged == "packed":
                    # masked region packing: one fixed-shape program
                    # over the packed unit block (engine/ragged.py)
                    builder = step_builders.build_classify_step_ragged
                step_fn = builder(model, **builder_kwargs)
                if self.device_synth and wired:
                    step_fn = self._synth_wrap(step_fn, synth_hw, key)
                    self._synth_hw[key] = tuple(synth_hw)
                self._engines[key] = self._build(
                    key, step_fn, model.params, input_names,
                    ragged_spec=spec)
                log.info("created engine %s (model %s)", key, model_key)
            elif self.device_synth and synth_hw is not None:
                self._check_synth_hw(key, synth_hw)
            return self._engines[key]

    def fused_engine(
        self,
        det_key: str,
        cls_key: str,
        instance_id: str | None = None,
        **builder_kwargs,
    ) -> BatchEngine:
        """Fused detect+classify engine: one upload, one readback per
        frame (see steps.build_detect_classify_step). Builder kwargs
        (e.g. the object-class filter) are part of the cache key —
        pipelines may only share a fused program when the compiled
        semantics match."""
        synth_hw = builder_kwargs.pop("synth_wire_hw", None)
        kw_sig = ",".join(f"{k}={v}" for k, v in sorted(builder_kwargs.items()))
        key = f"detect_classify:{instance_id or det_key + '+' + cls_key}:{kw_sig}"
        with self._lock:
            if key not in self._engines:
                det = self.model(det_key)
                cls = self.model(cls_key)
                builder_kwargs.setdefault("wire_format", self.wire_format)
                step_fn = step_builders.build_detect_classify_step(
                    det, cls, **builder_kwargs
                )
                if self.device_synth:
                    step_fn = self._synth_wrap(step_fn, synth_hw, key)
                    self._synth_hw[key] = tuple(synth_hw)
                self._engines[key] = self._build(
                    key, step_fn,
                    {"det": det.params, "cls": cls.params}, ("frames",))
                log.info("created fused engine %s", key)
            elif self.device_synth and synth_hw is not None:
                self._check_synth_hw(key, synth_hw)
            return self._engines[key]

    def _ragged_spec(self, kind: str, builder_kwargs: dict
                     ) -> RaggedSpec | None:
        """Unit-level shape declaration for classify-family engines
        (the per-item ROI budget the dense path pads to). Attached in
        BOTH ragged modes so occupancy accounting is honest about
        interior padding; packing itself is mode-gated."""
        if kind != "classify":
            return None
        budget = int(builder_kwargs.get("roi_budget", 8))
        return RaggedSpec(
            input="boxes", unit_shape=(4,), dtype=np.float32,
            max_units=budget,
            unit_budget=min(self.ragged_unit_budget, budget),
        )

    def _build(self, key: str, step_fn, params, input_names,
               ragged_spec: RaggedSpec | None = None):
        """Construct the engine for ``key`` — as a SupervisedEngine
        (the stable handle whose live BatchEngine a wedge-triggered
        rebuild swaps underneath) unless supervision is disabled. The
        factory closure is the rebuild recipe: a replacement engine
        gets a fresh ``jax.jit`` wrapper and a fresh SlotRing from the
        same step function and params (and the same EVAM_RAGGED mode +
        unit spec — a rebuild must not flip the batch layout).

        Fleet mode builds the same recipe once per mesh device
        (single-device plan, shard-capped ladder) behind a FleetEngine
        plus one full-mesh twin for the batch-class big buckets — each
        shard individually supervised, so a wedge on one chip is that
        shard's quarantine, not the fleet's."""

        # AOT cache program fingerprint (evam_tpu/aot/): everything at
        # the hub level that changes what the step COMPUTES. Shapes,
        # devices, donation and params avals are appended per bucket
        # by the engine (BatchEngine._aot_bucket_key) — so supervisor
        # rebuilds and fleet shard spin-ups of the same program land
        # on the same entries, while a wire-format or ragged-mode flip
        # addresses different ones.
        aot_key = (f"{key}|wire={self.wire_format}"
                   f"|synth={int(self.device_synth)}"
                   f"|ragged={self.ragged}|ub={self.ragged_unit_budget}"
                   f"|sched={int(self.sched is not None)}")

        def make(plan, name, max_batch, fleet_local=False):
            def factory() -> BatchEngine:
                return BatchEngine(
                    name=name,
                    step_fn=step_fn,
                    params=params,
                    plan=plan,
                    max_batch=max_batch,
                    deadline_ms=self.deadline_ms,
                    input_names=input_names,
                    stall_timeout_s=self.stall_timeout_s,
                    first_batch_grace=self.first_batch_grace,
                    sched=self.sched,
                    transfer=self.transfer,
                    transfer_depth=self.transfer_depth or None,
                    ragged=self.ragged,
                    ragged_spec=ragged_spec,
                    fleet_local=fleet_local,
                    aot_key=aot_key,
                )

            if not self.supervise:
                return factory()
            return SupervisedEngine(
                name, factory,
                max_restarts=self.max_restarts,
                restart_window_s=self.restart_window_s,
                backoff_s=self.restart_backoff_s,
            )

        if not self.fleet_active:
            return make(self.plan, key, self.max_batch)
        from evam_tpu.fleet.engine import FleetEngine
        return FleetEngine(
            key,
            shard_factory=lambda plan, label: make(
                plan, label, self.fleet_shard_max_batch),
            plans=self.plan.per_device_plans(),
            mesh_factory=lambda label: make(
                self.plan, label, self.max_batch, fleet_local=True),
            initial=self.fleet_initial_shards,
        )

    def _check_synth_hw(self, key: str, synth_hw) -> None:
        """Device-synth cache hits must agree on the wire resolution —
        seeds carry no shape, so unlike the host pixel path nothing
        downstream would catch a mismatch (it would silently measure
        the wrong wire size)."""
        have = self._synth_hw.get(key)
        if have is not None and tuple(synth_hw) != have:
            raise ValueError(
                f"engine {key}: device_synth compiled for wire {have} "
                f"but a stage requested {tuple(synth_hw)} — give the "
                "stages matching ingest sizes or distinct "
                "model-instance-ids"
            )

    def _synth_wrap(self, step_fn, synth_hw: tuple[int, int] | None, key: str):
        """Wrap a wire-frame step for device_synth mode (the stage must
        pass its ingest (H, W) as ``synth_wire_hw`` so the on-chip
        generator produces wire batches of the exact serving shape)."""
        if synth_hw is None:
            raise ValueError(
                f"engine {key}: EngineHub(device_synth=True) requires the "
                "stage to pass synth_wire_hw=(H, W)"
            )
        from evam_tpu.ops.color import wire_shape

        h, w = synth_hw
        return step_builders.wrap_device_synth(
            step_fn, wire_shape(self.wire_format, h, w))

    @staticmethod
    def _stat_row(e, shard: str | None, device: str | None,
                  group: str) -> dict:
        return {
            "batches": e.stats.batches,
            "items": e.stats.items,
            "mean_occupancy": e.stats.mean_occupancy,
            "warmed": e.warmed.is_set(),
            "assembly": e.assembly,
            # effective device-transfer mode (EVAM_TRANSFER;
            # devlock may have forced a pipelined request to
            # inline — report what actually runs)
            "transfer": ("pipelined" if getattr(
                e, "_pipelined", False) else "inline"),
            # ragged batching (engine/ragged.py): effective
            # mode, the honest units/computed-unit-rows
            # occupancy (the pad tax n/bucket hides), where
            # traffic lands per program shape, and the
            # compile-cache bill bucket consolidation exists
            # to shrink
            "ragged": getattr(e, "ragged", "off"),
            "unit_occupancy": round(e.stats.unit_occupancy, 4),
            "bucket_batches": {
                str(b): c for b, c in sorted(
                    e.stats.bucket_batches.items())},
            "compiled_programs": e.stats.compiled_programs,
            "compile_s": round(e.stats.compile_seconds, 3),
            # cold-vs-warm spin-up attribution (evam_tpu/aot/): rungs
            # warmed from the persistent executable cache and what
            # those loads cost — a cache-hit shard shows hits ==
            # compiled_programs and compile_s ≈ 0
            "aot": {"hits": e.stats.aot_hits,
                    "load_s": round(e.stats.aot_load_seconds, 3)},
            "oversize_splits": e.stats.oversize_splits,
            # per-batch host clock means (ringbuf.STAGES order)
            "stage_ms": e.stats.stage_ms_per_batch(),
            # supervision lifecycle (engine/supervisor.py);
            # unsupervised raw engines report a static running
            "state": getattr(e, "state", "running"),
            "restarts": getattr(e, "restarts", 0),
            "last_stall_ts": getattr(e, "last_stall_ts", None),
            # submit-queue visibility (sched satellite): the
            # backlog that used to be invisible until the
            # stall watchdog tripped
            "queue_depth": e.queue_depth(),
            "queue_age_s": round(e.queue_age_s(), 3),
            # per-class depths when the QoS layer is on
            "sched_queues": e.class_depths(),
            # fleet placement (evam_tpu/fleet/): which chip this row
            # is, and the engine key it aggregates under — admission
            # sums capacity per group (Σ shards) instead of treating
            # every shard as an independent bottleneck
            "shard": shard,
            "device": device,
            "group": group,
        }

    def stats(self) -> dict[str, dict]:
        with self._lock:
            engines = dict(self._engines)
        default_dev = (str(self.plan.mesh.devices.flat[0])
                       if self.plan is not None else None)
        out: dict[str, dict] = {}
        for k, e in engines.items():
            if hasattr(e, "shard_rows"):  # FleetEngine (duck-typed: no cycle)
                for label, dev, sub in e.shard_rows():
                    out[f"{k}@{label}"] = self._stat_row(
                        sub, shard=label, device=dev, group=k)
            else:
                out[k] = self._stat_row(
                    e, shard=None, device=default_dev, group=k)
        return out

    def stage_summary(self) -> dict[str, float]:
        """Batch-weighted mean per-batch host-stage cost across ALL
        engines (ms) — the /healthz attribution block: where a
        batch's wall time goes (slot-write vs h2d issue/wait vs launch
        vs readback residual) without scraping /metrics quantiles.
        Keys are fixed
        (ringbuf.STAGES) from boot so the health payload keeps a
        stable shape; per-engine detail lives on /engines."""
        from evam_tpu.engine.ringbuf import STAGES

        with self._lock:
            engines = list(self._engines.values())
        batches = sum(e.stats.batches for e in engines)
        return {
            s: (round(
                1e3 * sum(e.stats.stage_seconds.get(s, 0.0)
                          for e in engines) / batches, 3)
                if batches else 0.0)
            for s in STAGES
        }

    def queue_summary(self) -> dict[str, float]:
        """Aggregate submit-queue backlog for /healthz (fixed keys —
        golden contract): total undispatched items and the oldest
        item's age across every engine. Refreshes the per-engine
        gauges on the way so a scrape sees live values even between
        dispatches (the whole point: backlog must be visible BEFORE
        the stall watchdog fires)."""
        with self._lock:
            engines = dict(self._engines)
        depth = 0
        oldest = 0.0
        for k, e in engines.items():
            d = e.queue_depth()
            age = e.queue_age_s()
            depth += d
            oldest = max(oldest, age)
            metrics.set("evam_engine_queue_depth", d, {"engine": k})
            metrics.set("evam_engine_queue_age_s", age, {"engine": k})
        return {"depth": depth, "oldest_age_s": round(oldest, 3)}

    def class_queue_depths(self) -> dict[str, int]:
        """Summed per-class queued depth across engines (zeros when
        the QoS layer is off — the /scheduler payload keeps a stable
        shape either way)."""
        out = {c: 0 for c in PRIORITIES}
        with self._lock:
            engines = list(self._engines.values())
        for e in engines:
            for c, n in e.class_depths().items():
                out[c] = out.get(c, 0) + n
        return out

    def shed_totals(self) -> dict[str, int]:
        """Summed per-class shed counts across engines. Monotonic
        across supervisor rebuilds: SupervisedEngine.shed_counts folds
        in the counts absorbed from quarantined predecessors
        (supervisor._absorb_counters), so this matches the
        evam_sched_shed_total{class} series instead of silently
        resetting when an engine is rebuilt."""
        out = {c: 0 for c in PRIORITIES}
        with self._lock:
            engines = list(self._engines.values())
        for e in engines:
            for c, n in e.shed_counts().items():
                out[c] = out.get(c, 0) + n
        return out

    def readiness(self) -> dict[str, int]:
        """Engine warm state for /healthz (serve-time preload,
        round-1 VERDICT item 7): ``warming`` > 0 means a first POST
        would still hit a compile in the hot path."""
        with self._lock:
            engines = list(self._engines.values())
        # without background warmup the event never fires — engines
        # compile on first batch and are "as ready as they get"
        warmed = (
            sum(1 for e in engines if e.warmed.is_set())
            if self.warmup else len(engines)
        )
        states = [getattr(e, "state", "running") for e in engines]
        batches = sum(e.stats.batches for e in engines)
        return {
            "engines": len(engines),
            "warmed": warmed,
            "warming": len(engines) - warmed,
            # occupancy export (engine/ragged.py satellite): the
            # batch-weighted item fill and the pad-tax-honest unit
            # fill across every engine — the fleet-level "are we
            # paying for empty rows" number, scalar so the health
            # payload keeps a fixed shape (per-bucket batch counts
            # live on /engines, per-engine gauges on /metrics)
            "occupancy": round(
                sum(e.stats.occupancy_sum for e in engines) / batches
                if batches else 0.0, 4),
            "unit_occupancy": round(
                (sum(e.stats.units for e in engines)
                 / max(1, sum(e.stats.unit_slots for e in engines)))
                if batches else 0.0, 4),
            # compile-cache bill across engines (bucket consolidation
            # drops it; /engines itemizes per engine)
            "compiled_programs": sum(
                e.stats.compiled_programs for e in engines),
            # a wedged backend (stall watchdog fired) is a liveness
            # failure, not a warmup phase — monitoring must see it.
            # Supervised engines leave this bucket the moment the
            # supervisor quarantines them (state flips to restarting/
            # degraded), so the three counts are disjoint.
            "stalled": sum(
                1 for e, s in zip(engines, states)
                if s == "running" and e.stalled.is_set()
            ),
            # supervision (engine/supervisor.py): restarting is a
            # transient 503 (rebuild in progress), degraded a terminal
            # one (restart budget exhausted — process restart needed)
            "restarting": sum(1 for s in states if s == "restarting"),
            "degraded": sum(1 for s in states if s == "degraded"),
            "restarts": sum(getattr(e, "restarts", 0) for e in engines),
        }

    def fleet_summary(self) -> dict:
        """The /scheduler fleet operating point (fixed keys — route
        golden): placement counts per chip, live/degraded shard
        counts, and the cumulative rebalance total. EVAM_FLEET=off
        reports the same shape with zeros so dashboards and the bench
        serve line don't branch on mode."""
        with self._lock:
            engines = list(self._engines.values())
        out = {
            "mode": "sharded" if self.fleet_active else "off",
            "shards": 0,
            "degraded_shards": 0,
            "rebalances": 0,
            "streams": {},
            "max_shards": 0,
            "scale_ups": 0,
            "scale_downs": 0,
        }
        for e in engines:
            if not hasattr(e, "shard_rows"):  # FleetEngine only
                continue
            s = e.fleet_summary()
            # every engine kind shards over the same chips: shard
            # counts report the widest view, placement counts sum
            # (a stream pins once per engine kind it traverses)
            out["shards"] = max(out["shards"], s["shards"])
            out["degraded_shards"] = max(
                out["degraded_shards"], s["degraded_shards"])
            out["rebalances"] += s["rebalances"]
            out["max_shards"] = max(out["max_shards"],
                                    s.get("max_shards", 0))
            out["scale_ups"] += s.get("scale_ups", 0)
            out["scale_downs"] += s.get("scale_downs", 0)
            for label, n in s["streams"].items():
                out["streams"][label] = out["streams"].get(label, 0) + n
        # autoscaling policy ceiling: the structural bound above is
        # the mesh (len(plans)); the operator's EVAM_FLEET_MAX_SHARDS
        # clamps it, and 0 — the default — disables the eighth law
        # (the controller treats max_shards 0 as "never scale")
        if self.fleet_active and self.fleet_max_shards > 0:
            cap = self.fleet_max_shards
            if out["max_shards"]:
                cap = min(cap, out["max_shards"])
            out["max_shards"] = cap
        else:
            out["max_shards"] = 0
        return out

    def retune(self, op) -> None:
        """Push the controller's operating point to every cached engine
        (evam_tpu/control/). Only structural knobs travel this path —
        scalar setpoints are pulled per dispatch via
        ``control.state.current_op``. SupervisedEngine delegates to its
        live BatchEngine; FleetEngine broadcasts to shards + mesh."""
        with self._lock:
            engines = list(self._engines.values())
        for e in engines:
            try:
                e.retune(op)
            except Exception:  # noqa: BLE001 — engine mid-teardown
                log.debug("retune skipped for a stopping engine",
                          exc_info=True)

    def stop(self) -> None:
        with self._lock:
            engines = list(self._engines.values())
            self._engines.clear()
        for e in engines:
            e.stop()
