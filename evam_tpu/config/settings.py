"""Service settings: file < env < pipeline default < request override.

(Env beats the config file — operators override a deployed file with
container env vars, matching the reference's compose-driven env
surface.)

Covers the reference's three config tiers (SURVEY.md §5.6):
  (a) env vars — RUN_MODE (reference run.sh:26), DETECTION_DEVICE /
      CLASSIFICATION_DEVICE (docker-compose.yml:58-59), ENABLE_RTSP /
      RTSP_PORT / ENABLE_WEBRTC / WEBRTC_SIGNALING_SERVER
      (docker-compose.yml:49-52), MODELS_DIR / PIPELINES_DIR
      (eii/docker-compose.yml:50-51), PY_LOG_LEVEL / DEV_MODE
      (evas/__main__.py:36-46), PROFILING_MODE
      (eii/docker-compose.yml:43);
  (b) a config file (the reference uses etcd via EII ConfigManager,
      evas/__main__.py:34 — here a local JSON file with an optional
      watcher, see evam_tpu/eii/configmgr.py);
  (c) per-pipeline JSON parameter defaults with per-request overrides
      (resolved in evam_tpu/graph/params.py).
"""

from __future__ import annotations

import json
import os
from pathlib import Path

from typing import Literal

from pydantic import BaseModel, Field


class TPUSettings(BaseModel):
    """TPU engine knobs — new surface, no reference equivalent."""

    mesh_shape: list[int] = Field(default_factory=lambda: [-1])
    mesh_axes: list[str] = Field(default_factory=lambda: ["data"])
    #: top batch bucket. 128 is the measured p99<100 ms operating
    #: point on the v5e (PROFILE.md); throughput-bound deployments set
    #: EVAM_MAX_BATCH=256-512 (127-142 streams/chip measured, higher
    #: p99) — dispatch overhead amortizes with batch, so undersizing
    #: this is the first thing to check when a chip underdelivers.
    max_batch: int = 128
    batch_deadline_ms: float = 8.0
    precision: str = "bfloat16"
    donate_buffers: bool = True
    compile_cache_dir: str = ""
    #: precompile every batch bucket in the background when an engine
    #: is created (kills mid-traffic compile spikes; off in tests)
    warmup: bool = True
    #: engine stall watchdog: one batch's device round-trip bound in
    #: seconds (0 disables); raise for very large models/compiles
    stall_timeout_s: float = 120.0
    #: engine supervision (engine/supervisor.py): quarantine a wedged
    #: engine and rebuild it in place instead of serving 503 until a
    #: process restart
    supervise: bool = True
    #: restart budget: at most this many rebuilds per engine within
    #: restart_window_s; exhausting it is terminal `degraded`
    max_restarts: int = 3
    restart_window_s: float = 300.0
    #: base of the exponential backoff between quarantine and rebuild
    restart_backoff_s: float = 0.5
    #: stall-watchdog multiplier for a bucket's FIRST batch (its
    #: round-trip contains trace + XLA compile); without it every
    #: cold start — including a supervisor rebuild's fresh jit —
    #: reads as a wedge
    first_batch_grace: float = 10.0
    #: device-transfer pipeline (engine/batcher.py): "pipelined"
    #: (default) overlaps the H2D upload of batch N+1 with batch N's
    #: launch on a dedicated launcher thread and issues D2H copies
    #: asynchronously at launch; "inline" is the serial pre-pipeline
    #: path, kept byte-identical for A/B (tools/bench_transfer.py).
    #: EVAM_SERIALIZE_COMPILE=1 forces inline regardless.
    transfer: Literal["pipelined", "inline"] = "pipelined"
    #: pipelined-transfer upload-queue depth: how many staged batches
    #: may sit between the dispatcher's h2d_issue and the launcher.
    #: 2 is the measured sweet spot at boot; the control plane
    #: (EVAM_TUNE=on) retunes it live from the h2d_wait/launch ratio.
    #: Setting it explicitly pins it against the controller.
    transfer_depth: int = 2
    #: ragged batching (engine/ragged.py): "packed" packs classify
    #: region sets into one fixed masked-compute device shape (row
    #: length/offset vectors, Ragged Paged Attention style) and
    #: consolidates adjacent batch buckets onto shared programs;
    #: "off" (default until a TPU accuracy window) keeps the dense
    #: bucketed path byte-identical for A/B (tools/bench_ragged.py).
    ragged: Literal["packed", "off"] = "off"
    #: packed unit rows budgeted per batch row (how many region slots
    #: a packed classify batch carries per frame ON AVERAGE; floored
    #: at the stage ROI budget so a lone full frame always fits)
    ragged_unit_budget: int = 4
    #: fleet serving mode (evam_tpu/fleet/): "sharded" serves every
    #: engine key as one per-chip shard per mesh device behind a
    #: consistent-hash stream placer (small buckets, no collectives)
    #: plus one mesh-sharded twin for batch-class big buckets, with
    #: fleet-wide Σ-shard admission capacity and drain-and-rebalance
    #: on shard degradation; "off" (default) keeps the single-chip
    #: path byte-identical for A/B (tools/bench_fleet.py), the same
    #: discipline as EVAM_TRANSFER / EVAM_GATE / EVAM_RAGGED.
    fleet: Literal["sharded", "off"] = "off"
    #: fleet only: restrict sharding to the first N mesh devices
    #: (0 = all) — the bench/canary knob for scaling curves
    fleet_shards: int = 0
    #: fleet only: per-shard bucket-ladder top (0 = max_batch / shard
    #: count) — a chip serving 1/N of the streams doesn't need the
    #: fleet-wide max_batch worth of compile bill and staging memory
    fleet_shard_max_batch: int = 0
    #: fleet autoscaling ceiling (the eighth control law): the fleet
    #: may grow up to this many shards (bounded by the mesh) when
    #: utilization stays over EVAM_TUNE_SCALE_UP_UTIL, and drains back
    #: when it stays under EVAM_TUNE_SCALE_DOWN_UTIL. 0 (default)
    #: keeps the law inert — the fleet stays at EVAM_FLEET_SHARDS.
    #: Note EVAM_FLEET_SHARDS names the BOOT size, not a pin.
    fleet_max_shards: int = 0


class SchedSettings(BaseModel):
    """QoS scheduling knobs (evam_tpu/sched/): admission control,
    priority classes, load shedding. ``EVAM_SCHED=off`` disables the
    whole layer — engines keep the legacy single-FIFO dispatch,
    byte-identical (A/B, like EVAM_BATCH_ASSEMBLY=legacy)."""

    enabled: bool = True
    #: projected-utilization ceiling for admission control; a start
    #: that would push demand/capacity past it is rejected 503 +
    #: Retry-After (classes get headroom-scaled ceilings — batch is
    #: turned away first, realtime last). 0 disables admission.
    admit_util: float = 0.85
    #: operator-declared serving capacity in frames/s; 0 = derive it
    #: from live EngineStats stage timings (a cold hub admits all)
    capacity_fps: float = 0.0
    #: assumed per-stream fps when a start request declares none
    default_fps: float = 30.0
    #: per-class batch-formation deadlines (ms): cameras keep a small
    #: latency floor, bulk traffic fills big buckets. Unless
    #: explicitly set, the standard class inherits the engine-level
    #: EVAM_BATCH_DEADLINE_MS (SchedConfig.from_settings) — turning
    #: the scheduler on must not repeal a tuned global deadline.
    deadline_ms_realtime: float = 4.0
    deadline_ms_standard: float = 8.0
    deadline_ms_batch: float = 25.0
    #: per-class staleness budgets (ms): frames older than this at
    #: dispatch are shed oldest-first (freshest-frame-wins) with
    #: their futures failed as ShedError. 0 = never shed that class.
    staleness_ms_realtime: float = 200.0
    staleness_ms_standard: float = 1000.0
    staleness_ms_batch: float = 5000.0


class TraceSettings(BaseModel):
    """Per-frame tracing knobs (obs/trace.py): trace ids minted at
    ingest, span trees through engine dispatch, a bounded in-process
    ring with tail-based sampling, and the quarantine flight
    recorder. ``EVAM_TRACE=off`` disables the whole layer —
    byte-identical A/B (tools/bench_trace.py), same discipline as
    EVAM_TRANSFER / EVAM_GATE."""

    enabled: bool = True
    #: healthy-frame retention: keep 1-in-N (error/shed/deadline-miss
    #: frames and the slow tail are ALWAYS retained regardless)
    sample_n: int = 16
    #: bounded ring capacity — retained frame traces and completed
    #: batch records each (the ring never grows past this)
    ring: int = 1024
    #: frames slower than this end-to-end are "the slow tail" and are
    #: always retained
    slow_ms: float = 250.0
    #: flight-recorder artifact directory; empty = <tmpdir>/evam_flight
    flight_dir: str = ""
    #: most-recent records of each kind written per flight dump
    flight_n: int = 256
    #: flight-recorder disk bound: keep at most this many
    #: flight-*.jsonl files in flight_dir (oldest rotated out after
    #: every dump; 0 = unbounded, the pre-cap behavior)
    flight_max_files: int = 64
    #: flight-recorder disk bound: total bytes across retained dumps
    #: (oldest rotated out first; 0 = unbounded)
    flight_max_bytes: int = 67108864


class CkptSettings(BaseModel):
    """Crash-consistent stream-state checkpoints (evam_tpu/state/):
    a versioned, CRC-guarded StreamCheckpoint of every stream's
    serving state (gate luma grid, coaster velocities, tracker
    identities, sched class, trace continuity) captured at the
    post-resolve and pre-rebalance barriers and restored before the
    first frame after a migration, rebuild, or restart.
    ``EVAM_CKPT=off`` (default until proven) disables the whole layer
    — byte-identical A/B, same discipline as EVAM_TRANSFER /
    EVAM_GATE / EVAM_TRACE."""

    enabled: bool = False
    #: post-resolve capture cadence: refresh a stream's checkpoint
    #: every N resolved frames (1 = every frame; the barrier capture
    #: is a dict build + CRC, no device work)
    interval: int = 30
    #: restore budget in seconds: a restore slower than this (stuck
    #: state volume, injected restore_ms fault) is abandoned for a
    #: loud cold start — a checkpoint must never wedge a stream
    restore_timeout_s: float = 2.0


class TuneSettings(BaseModel):
    """Self-tuning control plane knobs (evam_tpu/control/): a feedback
    controller on the watchdog cadence that retunes the registered
    serving knobs — batch-formation deadlines, batch cap, transfer
    upload-queue depth, gate thresholds, admission utilization /
    capacity, staleness budgets — from the live stage clock and queue
    gauges. ``EVAM_TUNE=off`` (default until a TPU window proves it)
    disables the whole layer — byte-identical A/B
    (tools/bench_tune.py), same discipline as EVAM_TRANSFER /
    EVAM_GATE / EVAM_TRACE. Every knob the controller manages stays
    pinnable via its existing env var: an explicitly-set key is
    clamped out of the control loop."""

    enabled: bool = False
    #: controller tick period in seconds (the hub watchdog cadence is
    #: stall_timeout_s/4; the controller runs its own clock so tests
    #: and benches can spin it fast)
    interval_s: float = 2.0
    #: bounded log of the last N control actions, served on /scheduler
    actions: int = 32
    #: anti-flap damping: a rule must agree for this many CONSECUTIVE
    #: ticks before its action is applied
    damping: int = 3
    #: per-knob cooldown in ticks after an applied action (hysteresis:
    #: a knob that just moved must re-earn its next move)
    cooldown: int = 2
    #: utilization above which the controller tightens (gate
    #: thresholds up, staleness budgets down, admission ceiling down)
    util_hi: float = 0.80
    #: utilization below which it relaxes back toward the static
    #: operating point (dead band between util_lo and util_hi)
    util_lo: float = 0.50
    #: eighth law (autoscaling, needs EVAM_FLEET_MAX_SHARDS > 0):
    #: fleet utilization sustained ABOVE this for `damping` ticks
    #: spawns one shard from the AOT cache — deliberately above
    #: util_hi so the in-shard laws (deadlines, gate, admission) get
    #: to absorb pressure before the fleet pays for a new chip
    scale_up_util: float = 0.90
    #: sustained utilization BELOW this drains one shard through
    #: scale_down() + checkpointed stream migration; deliberately
    #: below util_lo so grow/shrink never oscillate across one band
    scale_down_util: float = 0.30


class AotSettings(BaseModel):
    """Persistent AOT executable cache (evam_tpu/aot/): serialized
    compiled executables in a content-addressed, CRC-guarded,
    size-capped on-disk store shared by supervisor rebuilds, fleet
    shard spin-up and every warmup path. ``EVAM_AOT=off`` (default
    until proven) disables the whole layer — byte-identical A/B
    (tools/bench_aot.py), same discipline as EVAM_TRANSFER /
    EVAM_GATE / EVAM_TRACE / EVAM_CKPT."""

    enabled: bool = False
    #: cache directory; empty = <tmpdir>/evam_aot. Share it across
    #: processes/containers on one host — entries are atomic and
    #: content-addressed, concurrent writers converge.
    dir: str = ""
    #: size cap in bytes (LRU by mtime past it; the newest entry
    #: always survives). Default 1 GiB.
    max_bytes: int = 1073741824


class Settings(BaseModel):
    """Flat service settings resolved from env + optional config file."""

    run_mode: str = "EVA"  # EVA (REST) vs EII (msgbus) — reference run.sh:26-30
    rest_port: int = 8080  # reference docker-compose.yml:44
    detection_device: str = "tpu"  # reference default CPU, docker-compose.yml:58
    classification_device: str = "tpu"  # reference docker-compose.yml:59
    models_dir: str = "models"  # reference eii/docker-compose.yml:50
    pipelines_dir: str = "pipelines"  # reference eii/docker-compose.yml:51
    enable_rtsp: bool = False  # reference docker-compose.yml:49
    rtsp_port: int = 8554  # reference docker-compose.yml:45,50
    enable_webrtc: bool = False  # reference docker-compose.yml:51
    webrtc_signaling_server: str = ""  # reference docker-compose.yml:52
    #: "key" = keyframe-only VP8 (shared encoder, lowest latency);
    #: "delta" = per-viewer GOP delta encoding (~40x lower bitrate,
    #: gop/fps extra latency) — see publish/rtc/vp8.py
    webrtc_video_mode: Literal["key", "delta"] = "key"
    log_level: str = "INFO"  # PY_LOG_LEVEL, reference evas/__main__.py:42
    dev_mode: bool = True  # DEV_MODE, reference evas/__main__.py:36
    profiling_mode: bool = False  # reference eii/docker-compose.yml:43
    state_dir: str = ""  # stream-registry persistence (hardening, SURVEY §5.4)
    #: comma list of pipelines (name or name/version) or "all" to
    #: build+warm engines before the REST port opens (EVAM_PRELOAD)
    preload: str = ""
    #: >0 routes file/RTSP decode through a shared DecodePool of this
    #: many worker threads instead of per-stream inline decode —
    #: bounds total decode threads at 64-stream scale
    #: (media/pool.py; VERDICT r3 item 10). 0 = per-stream (default).
    decode_pool_workers: int = 0
    #: >0 routes rtsp:// sources through the async RtspDemux (one
    #: selector thread + this many JPEG-decode workers for ALL live
    #: streams — media/demux.py; VERDICT r4 item 3). 0 = per-stream
    #: blocking reader via cv2/FFmpeg (default; required for
    #: non-RFC-2435 camera codecs until RFC 6184 lands).
    rtsp_demux_workers: int = 0
    #: shutdown drain: per-instance join budget in seconds; stragglers
    #: past it are logged and counted (evam_shutdown_leaked_streams),
    #: never waited on indefinitely
    drain_timeout_s: float = 5.0
    tpu: TPUSettings = Field(default_factory=TPUSettings)
    sched: SchedSettings = Field(default_factory=SchedSettings)
    trace: TraceSettings = Field(default_factory=TraceSettings)
    tune: TuneSettings = Field(default_factory=TuneSettings)
    ckpt: CkptSettings = Field(default_factory=CkptSettings)
    aot: AotSettings = Field(default_factory=AotSettings)

    @classmethod
    def from_env(cls, config_file: str | os.PathLike | None = None) -> "Settings":
        data: dict = {}
        if config_file and Path(config_file).exists():
            data.update(json.loads(Path(config_file).read_text()))

        env = os.environ
        mapping = {
            "RUN_MODE": ("run_mode", str),
            "REST_PORT": ("rest_port", int),
            "DETECTION_DEVICE": ("detection_device", str),
            "CLASSIFICATION_DEVICE": ("classification_device", str),
            "MODELS_DIR": ("models_dir", str),
            "PIPELINES_DIR": ("pipelines_dir", str),
            "ENABLE_RTSP": ("enable_rtsp", _parse_bool),
            "RTSP_PORT": ("rtsp_port", int),
            "ENABLE_WEBRTC": ("enable_webrtc", _parse_bool),
            "WEBRTC_SIGNALING_SERVER": ("webrtc_signaling_server", str),
            "EVAM_WEBRTC_VIDEO_MODE": ("webrtc_video_mode", str),
            "PY_LOG_LEVEL": ("log_level", str),
            "DEV_MODE": ("dev_mode", _parse_bool),
            "PROFILING_MODE": ("profiling_mode", _parse_bool),
            "EVAM_STATE_DIR": ("state_dir", str),
            "EVAM_PRELOAD": ("preload", str),
            "EVAM_DECODE_POOL_WORKERS": ("decode_pool_workers", int),
            "EVAM_RTSP_DEMUX_WORKERS": ("rtsp_demux_workers", int),
            "EVAM_DRAIN_TIMEOUT_S": ("drain_timeout_s", float),
        }
        for var, (key, conv) in mapping.items():
            if var in env:
                data[key] = conv(env[var])

        tpu = data.setdefault("tpu", {})
        tpu_mapping = {
            "EVAM_MAX_BATCH": ("max_batch", int),
            "EVAM_BATCH_DEADLINE_MS": ("batch_deadline_ms", float),
            "EVAM_PRECISION": ("precision", str),
            "EVAM_COMPILE_CACHE_DIR": ("compile_cache_dir", str),
            "EVAM_WARMUP": ("warmup", _parse_bool),
            "EVAM_STALL_TIMEOUT_S": ("stall_timeout_s", float),
            "EVAM_ENGINE_SUPERVISE": ("supervise", _parse_bool),
            "EVAM_ENGINE_MAX_RESTARTS": ("max_restarts", int),
            "EVAM_ENGINE_RESTART_WINDOW_S": ("restart_window_s", float),
            "EVAM_ENGINE_RESTART_BACKOFF_S": ("restart_backoff_s", float),
            "EVAM_FIRST_BATCH_GRACE": ("first_batch_grace", float),
            "EVAM_TRANSFER": ("transfer", str),
            "EVAM_TRANSFER_DEPTH": ("transfer_depth", int),
            "EVAM_RAGGED": ("ragged", str),
            "EVAM_RAGGED_UNIT_BUDGET": ("ragged_unit_budget", int),
            "EVAM_FLEET": ("fleet", str),
            "EVAM_FLEET_SHARDS": ("fleet_shards", int),
            "EVAM_FLEET_SHARD_MAX_BATCH": ("fleet_shard_max_batch", int),
            "EVAM_FLEET_MAX_SHARDS": ("fleet_max_shards", int),
        }
        if isinstance(tpu, dict):
            for var, (key, conv) in tpu_mapping.items():
                if var in env:
                    tpu[key] = conv(env[var])

        sched = data.setdefault("sched", {})
        sched_mapping = {
            "EVAM_SCHED": ("enabled", _parse_bool),
            "EVAM_SCHED_ADMIT_UTIL": ("admit_util", float),
            "EVAM_SCHED_CAPACITY_FPS": ("capacity_fps", float),
            "EVAM_SCHED_DEFAULT_FPS": ("default_fps", float),
            "EVAM_SCHED_DEADLINE_MS_REALTIME": ("deadline_ms_realtime", float),
            "EVAM_SCHED_DEADLINE_MS_STANDARD": ("deadline_ms_standard", float),
            "EVAM_SCHED_DEADLINE_MS_BATCH": ("deadline_ms_batch", float),
            "EVAM_SCHED_STALENESS_MS_REALTIME": (
                "staleness_ms_realtime", float),
            "EVAM_SCHED_STALENESS_MS_STANDARD": (
                "staleness_ms_standard", float),
            "EVAM_SCHED_STALENESS_MS_BATCH": ("staleness_ms_batch", float),
        }
        if isinstance(sched, dict):
            for var, (key, conv) in sched_mapping.items():
                if var in env:
                    sched[key] = conv(env[var])

        trace = data.setdefault("trace", {})
        trace_mapping = {
            "EVAM_TRACE": ("enabled", _parse_bool),
            "EVAM_TRACE_SAMPLE_N": ("sample_n", int),
            "EVAM_TRACE_RING": ("ring", int),
            "EVAM_TRACE_SLOW_MS": ("slow_ms", float),
            "EVAM_TRACE_FLIGHT_DIR": ("flight_dir", str),
            "EVAM_TRACE_FLIGHT_N": ("flight_n", int),
            "EVAM_TRACE_FLIGHT_MAX_FILES": ("flight_max_files", int),
            "EVAM_TRACE_FLIGHT_MAX_BYTES": ("flight_max_bytes", int),
        }
        if isinstance(trace, dict):
            for var, (key, conv) in trace_mapping.items():
                if var in env:
                    trace[key] = conv(env[var])

        ckpt = data.setdefault("ckpt", {})
        ckpt_mapping = {
            "EVAM_CKPT": ("enabled", _parse_bool),
            "EVAM_CKPT_INTERVAL": ("interval", int),
            "EVAM_CKPT_RESTORE_TIMEOUT_S": ("restore_timeout_s", float),
        }
        if isinstance(ckpt, dict):
            for var, (key, conv) in ckpt_mapping.items():
                if var in env:
                    ckpt[key] = conv(env[var])

        tune = data.setdefault("tune", {})
        tune_mapping = {
            "EVAM_TUNE": ("enabled", _parse_bool),
            "EVAM_TUNE_INTERVAL_S": ("interval_s", float),
            "EVAM_TUNE_ACTIONS": ("actions", int),
            "EVAM_TUNE_DAMPING": ("damping", int),
            "EVAM_TUNE_COOLDOWN": ("cooldown", int),
            "EVAM_TUNE_UTIL_HI": ("util_hi", float),
            "EVAM_TUNE_UTIL_LO": ("util_lo", float),
            "EVAM_TUNE_SCALE_UP_UTIL": ("scale_up_util", float),
            "EVAM_TUNE_SCALE_DOWN_UTIL": ("scale_down_util", float),
        }
        if isinstance(tune, dict):
            for var, (key, conv) in tune_mapping.items():
                if var in env:
                    tune[key] = conv(env[var])

        aot = data.setdefault("aot", {})
        aot_mapping = {
            "EVAM_AOT": ("enabled", _parse_bool),
            "EVAM_AOT_DIR": ("dir", str),
            "EVAM_AOT_MAX_BYTES": ("max_bytes", int),
        }
        if isinstance(aot, dict):
            for var, (key, conv) in aot_mapping.items():
                if var in env:
                    aot[key] = conv(env[var])
        return cls.model_validate(data)


def _parse_bool(value: str) -> bool:
    return value.strip().lower() in ("1", "true", "yes", "on")


_settings: Settings | None = None


def get_settings() -> Settings:
    global _settings
    if _settings is None:
        _settings = Settings.from_env(os.environ.get("EVAM_CONFIG_FILE"))
    return _settings


def reset_settings() -> None:
    """Drop the cached settings (tests / hot reload)."""
    global _settings
    _settings = None
