"""``{env[VAR]}`` interpolation used by pipeline parameter defaults.

The reference interpolates environment variables into pipeline JSON
default values, e.g. ``"default": "{env[DETECTION_DEVICE]}"``
(reference pipelines/object_detection/person_vehicle_bike/pipeline.json:24).
"""

from __future__ import annotations

import os
import re
from typing import Any

_ENV_RE = re.compile(r"\{env\[([A-Za-z_][A-Za-z0-9_]*)\]\}")


def interpolate_env(value: str, env: dict[str, str] | None = None) -> str:
    """Substitute every ``{env[VAR]}`` occurrence in *value*.

    Unset variables resolve to the empty string (the reference's
    behavior is to rely on compose-provided defaults; empty lets the
    caller fall back to service settings).
    """
    environ = os.environ if env is None else env
    return _ENV_RE.sub(lambda m: environ.get(m.group(1), ""), value)


def interpolate_tree(tree: Any, env: dict[str, str] | None = None) -> Any:
    """Recursively interpolate env refs through dicts/lists/strings."""
    if isinstance(tree, str):
        return interpolate_env(tree, env)
    if isinstance(tree, dict):
        return {k: interpolate_tree(v, env) for k, v in tree.items()}
    if isinstance(tree, list):
        return [interpolate_tree(v, env) for v in tree]
    return tree
