from evam_tpu.config.settings import Settings, get_settings, reset_settings
from evam_tpu.config.interpolate import interpolate_env, interpolate_tree

__all__ = [
    "Settings",
    "get_settings",
    "reset_settings",
    "interpolate_env",
    "interpolate_tree",
]
