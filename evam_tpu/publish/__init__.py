"""Result destinations — the gvametapublish counterpart (reference
pipelines/*/pipeline.json templates end in gvametapublish; destination
types mqtt/file observed at charts/templates/NOTES.txt:15-19 and the
request schema ``destination.metadata.{type,host,topic}``)."""

from evam_tpu.publish.base import Destination, create_destination
from evam_tpu.publish.encode import encode_frame
from evam_tpu.publish.file_dest import FileDestination, StdoutDestination
from evam_tpu.publish.mqtt import MqttDestination
from evam_tpu.publish.zmq_dest import ZmqDestination

__all__ = [
    "Destination",
    "FileDestination",
    "MqttDestination",
    "StdoutDestination",
    "ZmqDestination",
    "create_destination",
    "encode_frame",
]
