"""ZeroMQ PUB destination with the EII MsgBus wire contract.

The reference's EII data plane is brokerless ZeroMQ pub/sub carrying
``(json-meta, frame-blob)`` message pairs (evas/publisher.py:246-250;
transports zmq_tcp / zmq_ipc at eii/config.json:17-19, 31-32). The
frame convention: multipart [topic, meta-json, blob?] so subscribers
filter server-side by topic prefix.

Failure discipline (same contract as publish/mqtt.py): a publisher
must never take down its stream. HWM overflow drops the message;
a broken socket is closed and rebuilt with bounded backoff, dropping
(and counting, ``evam_publish_dropped{dest="zmq"}``) everything that
arrives while disconnected.
"""

from __future__ import annotations

import json
import threading
import time

from evam_tpu.obs import get_logger
from evam_tpu.obs.metrics import metrics

log = get_logger("publish.zmq")


class ZmqDestination:
    #: the publishing stream thread increments, /streams snapshots
    #: read — guarded by ``_lock`` (lock-discipline pass).
    SHARED_UNDER = {"_dropped": "_lock"}

    def __init__(
        self,
        endpoint: str = "tcp://127.0.0.1:65114",
        topic: str = "evam_tpu",
        bind: bool = True,
        send_hwm: int = 1000,
        max_backoff_s: float = 10.0,
    ):
        self.topic = topic.encode()
        self.endpoint = endpoint
        self.bind = bind
        self.send_hwm = send_hwm
        self.max_backoff_s = max_backoff_s
        self._lock = threading.Lock()
        self._dropped = 0
        self._backoff = 0.5
        self._next_retry = 0.0
        self._sock = None
        # The FIRST connect still raises (→ a 400 at the REST layer,
        # e.g. two streams binding the same default endpoint): a
        # misconfigured destination must fail the start request, not
        # silently drop forever.
        self._connect()

    def _connect(self) -> None:
        import zmq

        self._ctx = zmq.Context.instance()
        sock = self._ctx.socket(zmq.PUB)
        # HWM gives the same backpressure knob as the reference's
        # zmq_recv_hwm (eii/config.json:37): overflow drops, the
        # engine never blocks on a slow consumer.
        sock.setsockopt(zmq.SNDHWM, self.send_hwm)
        sock.setsockopt(zmq.LINGER, 0)
        try:
            if self.bind:
                sock.bind(self.endpoint)
            else:
                sock.connect(self.endpoint)
        except zmq.ZMQError as exc:
            sock.close(0)
            raise ValueError(
                f"zmq destination endpoint {self.endpoint}: {exc}"
            ) from exc
        self._sock = sock
        log.info("zmq pub %s endpoint %s",
                 "bound" if self.bind else "connected", self.endpoint)

    def _ensure(self) -> bool:
        if self._sock is not None:
            return True
        if time.monotonic() < self._next_retry:
            return False
        try:
            self._connect()
            self._backoff = 0.5
            return True
        except ValueError as exc:
            self._next_retry = time.monotonic() + self._backoff
            self._backoff = min(self._backoff * 2, self.max_backoff_s)
            log.warning("zmq reconnect failed (%s); retry in %.1fs",
                        exc, self._backoff)
            return False

    def _drop(self) -> None:
        with self._lock:
            self._dropped += 1
        metrics.inc("evam_publish_dropped", labels={"dest": "zmq"})

    def publish(self, meta: dict, frame: bytes | None = None) -> None:
        if not self._ensure():
            self._drop()
            return
        parts = [self.topic, json.dumps(meta, separators=(",", ":")).encode()]
        if frame is not None:
            parts.append(frame)
        import zmq

        try:
            self._sock.send_multipart(parts, flags=zmq.NOBLOCK)
        except zmq.Again:
            self._drop()  # HWM reached: drop (slow-consumer backpressure)
        except zmq.ZMQError as exc:
            log.warning("zmq publish failed (%s); rebuilding socket", exc)
            self._sock.close(0)
            self._sock = None
            self._next_retry = time.monotonic() + self._backoff
            self._backoff = min(self._backoff * 2, self.max_backoff_s)
            self._drop()

    @property
    def dropped(self) -> int:
        return self._dropped

    def close(self) -> None:
        if self._sock is not None:
            self._sock.close(0)
            self._sock = None
