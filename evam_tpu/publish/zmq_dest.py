"""ZeroMQ PUB destination with the EII MsgBus wire contract.

The reference's EII data plane is brokerless ZeroMQ pub/sub carrying
``(json-meta, frame-blob)`` message pairs (evas/publisher.py:246-250;
transports zmq_tcp / zmq_ipc at eii/config.json:17-19, 31-32). The
frame convention: multipart [topic, meta-json, blob?] so subscribers
filter server-side by topic prefix.
"""

from __future__ import annotations

import json

from evam_tpu.obs import get_logger

log = get_logger("publish.zmq")


class ZmqDestination:
    def __init__(
        self,
        endpoint: str = "tcp://127.0.0.1:65114",
        topic: str = "evam_tpu",
        bind: bool = True,
        send_hwm: int = 1000,
    ):
        import zmq

        self.topic = topic.encode()
        self._ctx = zmq.Context.instance()
        self._sock = self._ctx.socket(zmq.PUB)
        # HWM gives the same backpressure knob as the reference's
        # zmq_recv_hwm (eii/config.json:37): overflow drops, the
        # engine never blocks on a slow consumer.
        self._sock.setsockopt(zmq.SNDHWM, send_hwm)
        self._sock.setsockopt(zmq.LINGER, 0)
        try:
            if bind:
                self._sock.bind(endpoint)
            else:
                self._sock.connect(endpoint)
        except zmq.ZMQError as exc:
            # Surfaces as a 400 at the REST layer (ValueError), e.g.
            # two streams binding the same default endpoint.
            self._sock.close(0)
            raise ValueError(
                f"zmq destination endpoint {endpoint}: {exc}"
            ) from exc
        log.info("zmq pub %s endpoint %s", "bound" if bind else "connected",
                 endpoint)

    def publish(self, meta: dict, frame: bytes | None = None) -> None:
        parts = [self.topic, json.dumps(meta, separators=(",", ":")).encode()]
        if frame is not None:
            parts.append(frame)
        import zmq

        try:
            self._sock.send_multipart(parts, flags=zmq.NOBLOCK)
        except zmq.Again:
            pass  # HWM reached: drop (slow-consumer backpressure)

    def close(self) -> None:
        self._sock.close(0)
