"""RTSP re-streaming server: RTP/MJPEG (RFC 2435) over TCP interleaved.

The reference re-streams annotated pipelines at
``rtsp://<host>:8554/<path>`` when ENABLE_RTSP=true (reference
docker-compose.yml:45,49-50; per-request path via
``destination.frame.{type:rtsp, path}``). The base image uses
GStreamer's C RTSP server; this is a from-scratch implementation:
RTSP handshake (OPTIONS/DESCRIBE/SETUP/PLAY/TEARDOWN), SDP with the
static JPEG payload type 26, and RFC 2435 JPEG packetization with
in-band quantization tables (Q=255), interleaved on the RTSP TCP
connection ('$' channel framing) so no UDP ports are needed.
Verified against ffprobe/OpenCV's FFmpeg RTSP client (tests).
"""

from __future__ import annotations

import socket
import struct
import threading
import time

import numpy as np

from evam_tpu.media.h264 import packetize_rfc6184
from evam_tpu.obs import get_logger

log = get_logger("publish.rtsp")

JPEG_PT = 26          # RTP/AVP static payload type for JPEG
RTP_CLOCK = 90_000
MAX_FRAG = 1400       # payload bytes per RTP packet


# ---------------------------------------------------------------- JPEG

def parse_jpeg(data: bytes):
    """Extract (width, height, qtables, scan_bytes) from a baseline
    JFIF buffer (the shape RFC 2435 needs: tables sent in-band,
    entropy-coded scan re-framed as RTP payloads)."""
    if data[:2] != b"\xff\xd8":
        raise ValueError("not a JPEG")
    i = 2
    qtables: list[bytes] = []
    width = height = 0
    while i < len(data):
        if data[i] != 0xFF:
            raise ValueError("bad marker")
        marker = data[i + 1]
        if marker == 0xD9:  # EOI
            break
        seg_len = struct.unpack(">H", data[i + 2 : i + 4])[0]
        seg = data[i + 4 : i + 2 + seg_len]
        if marker == 0xDB:  # DQT — may hold several 65-byte tables
            j = 0
            while j < len(seg):
                precision = seg[j] >> 4
                tbl_len = 64 * (2 if precision else 1)
                qtables.append(seg[j + 1 : j + 1 + tbl_len])
                j += 1 + tbl_len
        elif marker in (0xC0, 0xC1):  # SOF0/1 (baseline)
            height, width = struct.unpack(">HH", seg[1:5])
        elif marker == 0xDA:  # SOS — scan follows until EOI
            scan = data[i + 2 + seg_len : ]
            if scan.endswith(b"\xff\xd9"):
                scan = scan[:-2]
            return width, height, qtables, scan
        i += 2 + seg_len
    raise ValueError("no SOS segment")


def packetize_jpeg(jpeg: bytes, seq: int, timestamp: int, ssrc: int):
    """RFC 2435 packets for one frame. Returns (packets, next_seq)."""
    width, height, qtables, scan = parse_jpeg(jpeg)
    if width > FrameRelay.MAX_DIM or height > FrameRelay.MAX_DIM:
        raise ValueError(
            f"RFC 2435 caps dimensions at {FrameRelay.MAX_DIM}; got "
            f"{width}x{height} (downscale before push)"
        )
    qdata = b"".join(qtables)
    packets = []
    offset = 0
    first = True
    while offset < len(scan) or first:
        frag = scan[offset : offset + MAX_FRAG]
        last = offset + len(frag) >= len(scan)
        header = struct.pack(
            ">BBHII",
            0x80,
            (0x80 if last else 0) | JPEG_PT,
            seq & 0xFFFF,
            timestamp & 0xFFFFFFFF,
            ssrc,
        )
        # JPEG payload header: tspec=0, 24-bit offset, type 1 (4:2:0),
        # Q=255 (quantization tables in-band on the first fragment).
        jpeg_hdr = struct.pack(
            ">BBBBBB",
            0,
            (offset >> 16) & 0xFF, (offset >> 8) & 0xFF, offset & 0xFF,
            1,
            255,
        ) + bytes([width // 8 & 0xFF, height // 8 & 0xFF])
        body = header + jpeg_hdr
        if first:
            body += struct.pack(">BBH", 0, 0, len(qdata)) + qdata
            first = False
        body += frag
        packets.append(body)
        seq += 1
        offset += len(frag)
    return packets, seq


# --------------------------------------------------------------- relay

class FrameRelay:
    """Latest-frame mailbox for one mount: pipeline pushes encoded
    frames (JPEGs, or Annex-B H.264 access units for ``codec='h264'``
    mounts), client threads block for the next one (slow clients skip
    frames — live semantics, never backpressure into the pipeline)."""

    #: RFC 2435 encodes dimensions as blocks/8 in one byte → 2040 max.
    MAX_DIM = 2040

    def __init__(self, path: str, codec: str = "jpeg"):
        if codec not in ("jpeg", "h264"):
            raise ValueError(f"unsupported RTSP mount codec {codec!r}")
        self.path = path
        self.codec = codec
        self._cond = threading.Condition()
        self._jpeg: bytes | None = None
        self._gen = 0
        self._clients = 0

    def add_client(self) -> None:
        with self._cond:
            self._clients += 1

    def remove_client(self) -> None:
        with self._cond:
            self._clients = max(0, self._clients - 1)

    @property
    def has_clients(self) -> bool:
        """Producers check this to skip annotate/encode work when
        nobody is watching (64 streams x 1080p encode for zero viewers
        is real CPU)."""
        return self._clients > 0

    def push_jpeg(self, jpeg: bytes) -> None:
        with self._cond:
            self._jpeg = jpeg
            self._gen += 1
            self._cond.notify_all()

    def push_annexb(self, access_unit: bytes) -> None:
        """H.264 mounts: one self-contained Annex-B access unit
        (SPS+PPS+IDR for intra-only streams, e.g. media/h264.py
        output sliced per frame)."""
        self.push_jpeg(access_unit)   # same mailbox, codec-tagged mount

    def push_bgr(self, frame_bgr: np.ndarray, quality: int = 80) -> None:
        import cv2

        h, w = frame_bgr.shape[:2]
        # The RFC 2435 header carries dims as blocks-of-8: cap at
        # MAX_DIM and round to multiples of 8 so the advertised size
        # matches the JPEG MCU grid exactly.
        scale = min(1.0, self.MAX_DIM / max(h, w))
        dh = max(8, int(h * scale) & ~7)
        dw = max(8, int(w * scale) & ~7)
        if (dh, dw) != (h, w):
            frame_bgr = cv2.resize(frame_bgr, (dw, dh))
        ok, buf = cv2.imencode(
            ".jpg", frame_bgr, [cv2.IMWRITE_JPEG_QUALITY, quality]
        )
        if ok:
            self.push_jpeg(buf.tobytes())

    def next_frame(self, last_gen: int, timeout: float = 2.0):
        """Block until a frame newer than ``last_gen`` arrives.

        Returns ``(None, last_gen)`` on timeout so serving loops only
        send genuinely new frames — a stalled pipeline must not be
        re-sent as a fresh RTP frame every timeout period."""
        with self._cond:
            if self._cond.wait_for(lambda: self._gen != last_gen, timeout):
                return self._jpeg, self._gen
            return None, last_gen


class RtspServer:
    def __init__(self, port: int = 8554, host: str = "0.0.0.0"):
        self.host = host
        self.port = port
        self._mounts: dict[str, FrameRelay] = {}
        self._lock = threading.Lock()
        self._sock: socket.socket | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------- lifecycle

    def start(self) -> None:
        self._sock = socket.socket()
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((self.host, self.port))
        self.port = self._sock.getsockname()[1]  # resolve port 0
        self._sock.listen(8)
        self._sock.settimeout(0.5)
        self._thread = threading.Thread(
            target=self._accept_loop, name="rtsp-server", daemon=True
        )
        self._thread.start()
        log.info("rtsp server on %s:%d", self.host, self.port)

    def stop(self) -> None:
        self._stop.set()
        if self._sock is not None:
            self._sock.close()

    def mount(self, path: str, codec: str = "jpeg") -> FrameRelay:
        path = path.strip("/")
        with self._lock:
            if path not in self._mounts:
                self._mounts[path] = FrameRelay(path, codec=codec)
            relay = self._mounts[path]
            if relay.codec != codec:
                # pushing H.264 AUs into a JPEG mount (or vice versa)
                # would serve undecodable packets with no error
                raise ValueError(
                    f"mount {path!r} already exists with codec "
                    f"{relay.codec!r}, requested {codec!r}")
            return relay

    def unmount(self, path: str) -> None:
        with self._lock:
            self._mounts.pop(path.strip("/"), None)

    # --------------------------------------------------------- serving

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(
                target=self._serve_client, args=(conn, addr), daemon=True
            ).start()

    def _serve_client(self, conn: socket.socket, addr) -> None:
        conn.settimeout(10)
        session = f"{int(time.time()) & 0xFFFFFF:06x}"
        playing_path = None
        try:
            buf = b""
            while not self._stop.is_set():
                while b"\r\n\r\n" not in buf:
                    chunk = conn.recv(2048)
                    if not chunk:
                        return
                    buf += chunk
                head, _, buf = buf.partition(b"\r\n\r\n")
                lines = head.decode("latin-1").split("\r\n")
                method, url = lines[0].split(" ")[:2]
                headers = {
                    k.strip().lower(): v.strip()
                    for k, v, in (l.split(":", 1) for l in lines[1:] if ":" in l)
                }
                cseq = headers.get("cseq", "0")
                path = url.rstrip("/").split("/")[-1] if "/" in url else ""

                if method == "OPTIONS":
                    self._reply(conn, cseq, extra=(
                        "Public: OPTIONS, DESCRIBE, SETUP, PLAY, TEARDOWN"))
                elif method == "DESCRIBE":
                    relay = self._mounts.get(path)
                    if relay is None:
                        self._reply(conn, cseq, code="404 Not Found")
                        continue
                    if relay.codec == "h264":
                        media = (
                            "m=video 0 RTP/AVP 96\r\n"
                            "a=rtpmap:96 H264/90000\r\n"
                            "a=fmtp:96 packetization-mode=1\r\n"
                        )
                    else:
                        media = "m=video 0 RTP/AVP 26\r\n"
                    sdp = (
                        "v=0\r\n"
                        f"o=- 0 0 IN IP4 {self.host}\r\n"
                        "s=evam-tpu\r\n"
                        "t=0 0\r\n"
                        + media +
                        "c=IN IP4 0.0.0.0\r\n"
                        "a=control:streamid=0\r\n"
                    )
                    self._reply(conn, cseq, body=sdp,
                                extra="Content-Type: application/sdp")
                elif method == "SETUP":
                    self._reply(conn, cseq, extra=(
                        "Transport: RTP/AVP/TCP;unicast;interleaved=0-1\r\n"
                        f"Session: {session}"))
                elif method == "PLAY":
                    self._reply(conn, cseq, extra=f"Session: {session}")
                    playing_path = path or playing_path
                    self._stream(conn, playing_path)
                    return
                elif method == "TEARDOWN":
                    self._reply(conn, cseq, extra=f"Session: {session}")
                    return
                else:
                    self._reply(conn, cseq, code="405 Method Not Allowed")
        except (OSError, ValueError) as exc:
            log.debug("rtsp client %s: %s", addr, exc)
        finally:
            conn.close()

    @staticmethod
    def _reply(conn, cseq, code="200 OK", extra="", body=""):
        msg = f"RTSP/1.0 {code}\r\nCSeq: {cseq}\r\n"
        if extra:
            msg += extra + "\r\n"
        if body:
            msg += f"Content-Length: {len(body)}\r\n"
        msg += "\r\n" + body
        conn.sendall(msg.encode("latin-1"))

    def _stream(self, conn: socket.socket, path: str) -> None:
        relay = self._mounts.get(path)
        if relay is None:
            return
        seq = 0
        ssrc = 0x45564154  # "EVAT"
        gen = 0
        t0 = time.monotonic()
        relay.add_client()
        try:
            while not self._stop.is_set():
                jpeg, gen = relay.next_frame(gen)
                if jpeg is None:
                    continue
                ts = int((time.monotonic() - t0) * RTP_CLOCK)
                if relay.codec == "h264":
                    packets, seq = packetize_rfc6184(
                        jpeg, seq, ts, ssrc)
                else:
                    packets, seq = packetize_jpeg(jpeg, seq, ts, ssrc)
                try:
                    for pkt in packets:
                        # interleaved framing: '$', channel 0, length
                        conn.sendall(
                            b"$\x00" + struct.pack(">H", len(pkt)) + pkt)
                except OSError:
                    return
        finally:
            relay.remove_client()
