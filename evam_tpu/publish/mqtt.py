"""Minimal MQTT 3.1.1 publisher — no external client library.

The reference publishes results through a mosquitto sidecar
(mosquitto/mosquitto.conf:1-2, destination type mqtt at
charts/templates/NOTES.txt:15-19). paho-mqtt is not in this image, so
this is a from-scratch QoS-0 publisher speaking the MQTT 3.1.1 wire
protocol (OASIS spec): CONNECT/CONNACK, PUBLISH, PINGREQ keepalive,
DISCONNECT. Reconnects with backoff on broken pipes — the publisher
thread must never take down the stream (the reference leaves a
"attempt reconnect?" TODO at evas/publisher.py:253-255; here it's
implemented).
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import time

from evam_tpu.obs import get_logger
from evam_tpu.obs.metrics import metrics

log = get_logger("publish.mqtt")


def _encode_remaining_length(n: int) -> bytes:
    out = bytearray()
    while True:
        byte = n % 128
        n //= 128
        if n:
            byte |= 0x80
        out.append(byte)
        if not n:
            return bytes(out)


def _utf8(s: str) -> bytes:
    b = s.encode("utf-8")
    return struct.pack(">H", len(b)) + b


class MqttClient:
    """Blocking QoS-0 MQTT 3.1.1 client (publish-only)."""

    def __init__(
        self,
        host: str,
        port: int = 1883,
        client_id: str = "",
        keepalive: int = 60,
        timeout: float = 5.0,
    ):
        self.host = host
        self.port = port
        self.client_id = client_id or f"evam-tpu-{int(time.time()) & 0xFFFF}"
        self.keepalive = keepalive
        self.timeout = timeout
        self._sock: socket.socket | None = None
        self._lock = threading.Lock()
        self._last_send = 0.0

    # ------------------------------------------------------------ wire

    def connect(self) -> None:
        sock = socket.create_connection((self.host, self.port), self.timeout)
        sock.settimeout(self.timeout)
        var_header = (
            _utf8("MQTT")
            + bytes([0x04])          # protocol level 3.1.1
            + bytes([0x02])          # flags: clean session
            + struct.pack(">H", self.keepalive)
        )
        payload = _utf8(self.client_id)
        packet = (
            bytes([0x10])
            + _encode_remaining_length(len(var_header) + len(payload))
            + var_header
            + payload
        )
        sock.sendall(packet)
        ack = self._read_packet(sock)
        if not ack or ack[0] >> 4 != 2 or ack[-1] != 0:
            raise ConnectionError(f"CONNACK refused: {ack!r}")
        self._sock = sock
        self._last_send = time.monotonic()

    @staticmethod
    def _read_packet(sock: socket.socket) -> bytes:
        head = sock.recv(1)
        if not head:
            raise ConnectionError("broker closed connection")
        length = 0
        shift = 0
        while True:
            b = sock.recv(1)
            if not b:
                raise ConnectionError("short packet")
            length |= (b[0] & 0x7F) << shift
            if not b[0] & 0x80:
                break
            shift += 7
        body = b""
        while len(body) < length:
            chunk = sock.recv(length - len(body))
            if not chunk:
                raise ConnectionError("short packet body")
            body += chunk
        return head + body

    def publish(self, topic: str, payload: bytes) -> None:
        packet = (
            bytes([0x30])  # PUBLISH, QoS 0, no retain
            + _encode_remaining_length(2 + len(topic.encode()) + len(payload))
            + _utf8(topic)
            + payload
        )
        with self._lock:
            if self._sock is None:
                raise ConnectionError("not connected")
            self._sock.sendall(packet)
            self._last_send = time.monotonic()

    def ping_if_idle(self) -> None:
        with self._lock:
            if self._sock is None:
                return
            if time.monotonic() - self._last_send > self.keepalive / 2:
                self._sock.sendall(bytes([0xC0, 0x00]))  # PINGREQ
                self._last_send = time.monotonic()

    def disconnect(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.sendall(bytes([0xE0, 0x00]))
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None


class MqttDestination:
    """Destination publishing metadata JSON (and optional frame blob on
    ``<topic>/frames``) with automatic reconnect."""

    #: the publishing stream thread increments, /streams snapshots
    #: read — guarded by ``_lock`` (lock-discipline pass).
    SHARED_UNDER = {"_dropped": "_lock"}

    def __init__(
        self,
        host: str,
        port: int = 1883,
        topic: str = "evam_tpu",
        max_backoff: float = 10.0,
        lazy: bool = True,
    ):
        self.topic = topic
        self.max_backoff = max_backoff
        self._client = MqttClient(host, port)
        self._backoff = 0.5
        self._next_retry = 0.0
        self._lock = threading.Lock()
        self._dropped = 0
        if not lazy:
            self._client.connect()

    def _ensure(self) -> bool:
        if self._client._sock is not None:
            return True
        if time.monotonic() < self._next_retry:
            return False
        try:
            self._client.connect()
            self._backoff = 0.5
            log.info("mqtt connected to %s:%d", self._client.host,
                     self._client.port)
            return True
        except OSError as exc:
            self._next_retry = time.monotonic() + self._backoff
            self._backoff = min(self._backoff * 2, self.max_backoff)
            log.warning("mqtt connect failed (%s); retry in %.1fs",
                        exc, self._backoff)
            return False

    def _drop(self) -> None:
        # shared drop accounting across destination kinds (mqtt/zmq/
        # file): one metric an operator can alert on for ANY sink
        with self._lock:
            self._dropped += 1
        metrics.inc("evam_publish_dropped", labels={"dest": "mqtt"})

    def publish(self, meta: dict, frame: bytes | None = None) -> None:
        if not self._ensure():
            self._drop()
            return
        payload = json.dumps(meta, separators=(",", ":")).encode()
        try:
            self._client.publish(self.topic, payload)
            if frame is not None:
                self._client.publish(self.topic + "/frames", frame)
            self._client.ping_if_idle()
        except OSError as exc:
            log.warning("mqtt publish failed (%s); reconnecting", exc)
            self._client.disconnect()
            self._drop()

    @property
    def dropped(self) -> int:
        return self._dropped

    def close(self) -> None:
        self._client.disconnect()
