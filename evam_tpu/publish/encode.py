"""Frame encoding for publication.

The reference publisher optionally JPEG/PNG-encodes frames before the
message bus (cv2.imencode at evas/publisher.py:127-151, gated by
``encoding.type``/``encoding.level``); same semantics here, on host
CPU — encode is per-stream and embarrassingly parallel, the TPU stays
on inference.
"""

from __future__ import annotations

import numpy as np


def encode_frame(
    frame_bgr: np.ndarray,
    enc_type: str | None,
    level: int | None = None,
) -> bytes:
    """Encode BGR uint8 → bytes. enc_type: None/raw, jpeg, png.

    level: jpeg quality 0-100 (default 95) or png compression 0-9
    (default 3), mirroring the reference's validation ranges
    (evas/publisher.py:105-125).
    """
    if not enc_type or enc_type == "raw":
        return np.ascontiguousarray(frame_bgr).tobytes()
    import cv2

    if enc_type == "jpeg":
        q = 95 if level is None else int(level)
        if not 0 <= q <= 100:
            raise ValueError(f"jpeg quality {q} outside [0, 100]")
        ok, buf = cv2.imencode(".jpg", frame_bgr, [cv2.IMWRITE_JPEG_QUALITY, q])
    elif enc_type == "png":
        c = 3 if level is None else int(level)
        if not 0 <= c <= 9:
            raise ValueError(f"png compression {c} outside [0, 9]")
        ok, buf = cv2.imencode(".png", frame_bgr, [cv2.IMWRITE_PNG_COMPRESSION, c])
    else:
        raise ValueError(f"unsupported encoding type '{enc_type}'")
    if not ok:
        raise RuntimeError(f"{enc_type} encode failed")
    return bytes(buf.tobytes())
