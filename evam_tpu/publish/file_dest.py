"""File / stdout metadata destinations (gvametapublish method=file
counterpart — the reference's default file format is one JSON object
per line)."""

from __future__ import annotations

import json
import sys
import threading


class FileDestination:
    """JSON-lines (default) or JSON-array metadata file."""

    def __init__(self, path: str, fmt: str = "json-lines"):
        self.path = path
        self.fmt = fmt
        self._lock = threading.Lock()
        self._fh = open(path, "w", encoding="utf-8")
        self._first = True
        if fmt == "json":
            self._fh.write("[")

    def publish(self, meta: dict, frame: bytes | None = None) -> None:
        line = json.dumps(meta, separators=(",", ":"))
        with self._lock:
            if self.fmt == "json":
                if not self._first:
                    self._fh.write(",\n")
                self._first = False
                self._fh.write(line)
            else:
                self._fh.write(line + "\n")
            self._fh.flush()

    def close(self) -> None:
        with self._lock:
            if self.fmt == "json":
                self._fh.write("]\n")
            self._fh.close()


class StdoutDestination:
    """Print metadata lines (sample-verification flow: the reference
    docs verify pipelines by eyeballing published JSON,
    charts/README.md:112-119)."""

    def publish(self, meta: dict, frame: bytes | None = None) -> None:
        sys.stdout.write(json.dumps(meta, separators=(",", ":")) + "\n")
        sys.stdout.flush()

    def close(self) -> None:
        pass
