"""File / stdout metadata destinations (gvametapublish method=file
counterpart — the reference's default file format is one JSON object
per line).

Failure discipline (same contract as publish/mqtt.py and zmq_dest.py):
a publisher must never take down its stream. A write/open failure
(disk full, volume unmounted, permissions flipped) closes the handle,
drops the record — counted in ``evam_publish_dropped{dest="file"}`` —
and retries the open with bounded backoff; recovery re-opens in append
mode so already-written lines survive."""

from __future__ import annotations

import json
import sys
import threading
import time

from evam_tpu.analysis.annotations import locked_by
from evam_tpu.obs import get_logger
from evam_tpu.obs.metrics import metrics

log = get_logger("publish.file")


class FileDestination:
    """JSON-lines (default) or JSON-array metadata file."""

    #: the publishing stream thread increments, /streams snapshots
    #: read — guarded by ``_lock`` (lock-discipline pass).
    SHARED_UNDER = {"_dropped": "_lock"}

    def __init__(self, path: str, fmt: str = "json-lines",
                 retry_backoff_s: float = 0.5, max_backoff_s: float = 10.0):
        self.path = path
        self.fmt = fmt
        self.max_backoff_s = max_backoff_s
        self._lock = threading.Lock()
        # Lazy open: the file is created/truncated on the first
        # publish, not at construction, so a start request that fails
        # later in build_stages (unknown model, bad stage) can't
        # truncate an operator's existing output file. Parameter
        # errors are caught even earlier (resolve_parameters runs
        # before the destination is created).
        self._fh = None
        self._first = True
        self._closed = False
        self._opened_once = False
        self._dropped = 0
        self._backoff = retry_backoff_s
        self._base_backoff = retry_backoff_s
        self._next_retry = 0.0

    def _ensure_open(self):
        if self._fh is None:
            # "w" only on the very first open; a reconnect after a
            # write failure must append, not truncate what survived
            mode = "a" if self._opened_once else "w"
            self._fh = open(self.path, mode, encoding="utf-8")
            if self.fmt == "json" and not self._opened_once:
                self._fh.write("[")
            self._opened_once = True
        return self._fh

    @locked_by("_lock")
    def _drop(self, exc: OSError | None = None) -> None:
        self._dropped += 1
        metrics.inc("evam_publish_dropped", labels={"dest": "file"})
        if exc is not None:
            self._next_retry = time.monotonic() + self._backoff
            log.warning("file destination %s failed (%s); dropping and "
                        "retrying in %.1fs", self.path, exc, self._backoff)
            self._backoff = min(self._backoff * 2, self.max_backoff_s)

    def publish(self, meta: dict, frame: bytes | None = None) -> None:
        line = json.dumps(meta, separators=(",", ":"))
        with self._lock:
            if self._closed:
                # a late frame completing during teardown must not
                # re-open (and truncate) the finished output file
                return
            if self._fh is None and time.monotonic() < self._next_retry:
                self._drop()
                return
            try:
                fh = self._ensure_open()
                if self.fmt == "json":
                    if not self._first:
                        fh.write(",\n")
                    self._first = False
                    fh.write(line)
                else:
                    fh.write(line + "\n")
                fh.flush()
                self._backoff = self._base_backoff
            except OSError as exc:
                if self._fh is not None:
                    try:
                        self._fh.close()
                    except OSError:
                        pass
                    self._fh = None
                self._drop(exc)

    @property
    def dropped(self) -> int:
        return self._dropped

    def close(self) -> None:
        with self._lock:
            self._closed = True
            if self._fh is None:
                return
            try:
                if self.fmt == "json":
                    self._fh.write("]\n")
                self._fh.close()
            except OSError as exc:
                log.warning("file destination %s close failed: %s",
                            self.path, exc)
            self._fh = None


class StdoutDestination:
    """Print metadata lines (sample-verification flow: the reference
    docs verify pipelines by eyeballing published JSON,
    charts/README.md:112-119)."""

    def publish(self, meta: dict, frame: bytes | None = None) -> None:
        sys.stdout.write(json.dumps(meta, separators=(",", ":")) + "\n")
        sys.stdout.flush()

    def close(self) -> None:
        pass
