"""File / stdout metadata destinations (gvametapublish method=file
counterpart — the reference's default file format is one JSON object
per line)."""

from __future__ import annotations

import json
import sys
import threading


class FileDestination:
    """JSON-lines (default) or JSON-array metadata file."""

    def __init__(self, path: str, fmt: str = "json-lines"):
        self.path = path
        self.fmt = fmt
        self._lock = threading.Lock()
        # Lazy open: the file is created/truncated on the first
        # publish, not at construction, so a start request that fails
        # later in build_stages (unknown model, bad stage) can't
        # truncate an operator's existing output file. Parameter
        # errors are caught even earlier (resolve_parameters runs
        # before the destination is created).
        self._fh = None
        self._first = True
        self._closed = False

    def _ensure_open(self):
        if self._fh is None:
            self._fh = open(self.path, "w", encoding="utf-8")
            if self.fmt == "json":
                self._fh.write("[")
        return self._fh

    def publish(self, meta: dict, frame: bytes | None = None) -> None:
        line = json.dumps(meta, separators=(",", ":"))
        with self._lock:
            if self._closed:
                # a late frame completing during teardown must not
                # re-open (and truncate) the finished output file
                return
            fh = self._ensure_open()
            if self.fmt == "json":
                if not self._first:
                    fh.write(",\n")
                self._first = False
                fh.write(line)
            else:
                fh.write(line + "\n")
            fh.flush()

    def close(self) -> None:
        with self._lock:
            self._closed = True
            if self._fh is None:
                return
            if self.fmt == "json":
                self._fh.write("]\n")
            self._fh.close()
            self._fh = None


class StdoutDestination:
    """Print metadata lines (sample-verification flow: the reference
    docs verify pipelines by eyeballing published JSON,
    charts/README.md:112-119)."""

    def publish(self, meta: dict, frame: bytes | None = None) -> None:
        sys.stdout.write(json.dumps(meta, separators=(",", ":")) + "\n")
        sys.stdout.flush()

    def close(self) -> None:
        pass
