"""DTLS 1.2 + use_srtp via ctypes over the system OpenSSL 3.

The image ships ``libssl.so.3``/``libcrypto.so.3`` (no headers, no
pyOpenSSL), so the bindings are declared by hand: memory-BIO DTLS
endpoints (the WebRTC pattern — datagrams are shuttled between the
UDP socket and the BIO pair), the ``use_srtp`` extension negotiating
SRTP_AES128_CM_SHA1_80, and RFC 5764 §4.2 keying-material export
(client/server SRTP master keys + salts).

Certificates are generated at startup with the ``openssl`` CLI
(self-signed EC, like every browser's per-session WebRTC cert) and
fingerprinted for the SDP ``a=fingerprint`` line.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import hashlib
import os
import subprocess
import tempfile

SRTP_PROFILE = "SRTP_AES128_CM_SHA1_80"
EXPORT_LABEL = b"EXTRACTOR-dtls_srtp"
KEY_MATERIAL_LEN = 2 * (16 + 14)  # client+server key(16) + salt(14)

SSL_ERROR_WANT_READ = 2
SSL_ERROR_WANT_WRITE = 3
SSL_FILETYPE_PEM = 1
SSL_VERIFY_PEER = 0x01
SSL_VERIFY_FAIL_IF_NO_PEER_CERT = 0x02

#: verify callback that accepts any chain: WebRTC peers use
#: self-signed per-session certs, so chain verification is
#: meaningless — authentication is the SDP fingerprint pin, checked
#: post-handshake via peer_fingerprint()
_VERIFY_CB_TYPE = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_int, ctypes.c_void_p)
_accept_any_chain = _VERIFY_CB_TYPE(lambda _ok, _ctx: 1)


class _SrtpProtectionProfile(ctypes.Structure):
    _fields_ = [("name", ctypes.c_char_p), ("id", ctypes.c_ulong)]


def _load():
    ssl_path = ctypes.util.find_library("ssl") or "libssl.so.3"
    crypto_path = ctypes.util.find_library("crypto") or "libcrypto.so.3"
    crypto = ctypes.CDLL(crypto_path, mode=ctypes.RTLD_GLOBAL)
    ssl = ctypes.CDLL(ssl_path)

    P = ctypes.c_void_p
    sigs = {
        ssl: {
            "DTLS_method": ([], P),
            "SSL_CTX_new": ([P], P),
            "SSL_CTX_free": ([P], None),
            "SSL_CTX_use_certificate_file": ([P, ctypes.c_char_p,
                                              ctypes.c_int], ctypes.c_int),
            "SSL_CTX_use_PrivateKey_file": ([P, ctypes.c_char_p,
                                             ctypes.c_int], ctypes.c_int),
            "SSL_CTX_set_tlsext_use_srtp": ([P, ctypes.c_char_p],
                                            ctypes.c_int),
            "SSL_new": ([P], P),
            "SSL_free": ([P], None),
            "SSL_set_bio": ([P, P, P], None),
            "SSL_set_accept_state": ([P], None),
            "SSL_set_connect_state": ([P], None),
            "SSL_do_handshake": ([P], ctypes.c_int),
            "SSL_get_error": ([P, ctypes.c_int], ctypes.c_int),
            "SSL_is_init_finished": ([P], ctypes.c_int),
            "SSL_export_keying_material": (
                [P, ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
                 ctypes.c_size_t, ctypes.c_char_p, ctypes.c_size_t,
                 ctypes.c_int], ctypes.c_int),
            "SSL_get_selected_srtp_profile": (
                [P], ctypes.POINTER(_SrtpProtectionProfile)),
            "SSL_ctrl": ([P, ctypes.c_int, ctypes.c_long, P],
                         ctypes.c_long),
            "SSL_read": ([P, ctypes.c_char_p, ctypes.c_int],
                         ctypes.c_int),
            "SSL_write": ([P, ctypes.c_char_p, ctypes.c_int],
                          ctypes.c_int),
            "SSL_shutdown": ([P], ctypes.c_int),
            "SSL_CTX_set_verify": ([P, ctypes.c_int, P], None),
            "SSL_get1_peer_certificate": ([P], P),
        },
        crypto: {
            "i2d_X509": ([P, ctypes.POINTER(ctypes.c_void_p)],
                         ctypes.c_int),
            "X509_free": ([P], None),
            "BIO_new": ([P], P),
            "BIO_s_mem": ([], P),
            "BIO_read": ([P, ctypes.c_char_p, ctypes.c_int],
                         ctypes.c_int),
            "BIO_write": ([P, ctypes.c_char_p, ctypes.c_int],
                          ctypes.c_int),
            "BIO_ctrl_pending": ([P], ctypes.c_size_t),
            "ERR_get_error": ([], ctypes.c_ulong),
            "ERR_error_string_n": ([ctypes.c_ulong, ctypes.c_char_p,
                                    ctypes.c_size_t], None),
        },
    }
    #: OpenSSL 3 renamed SSL_get_peer_certificate (1.1) to
    #: SSL_get1_peer_certificate (same up-ref semantics) — accept both
    fallbacks = {
        "SSL_get1_peer_certificate": "SSL_get_peer_certificate",
    }
    for lib, table in sigs.items():
        for name, (argtypes, restype) in table.items():
            try:
                fn = getattr(lib, name)
            except AttributeError:
                alt = fallbacks.get(name)
                if alt is None:
                    raise
                fn = getattr(lib, alt)
                setattr(lib, name, fn)
            fn.argtypes = argtypes
            fn.restype = restype
    return ssl, crypto


_SSL = None
_CRYPTO = None


def _libs():
    global _SSL, _CRYPTO
    if _SSL is None:
        _SSL, _CRYPTO = _load()
    return _SSL, _CRYPTO


def generate_certificate(state_dir: str | None = None) -> tuple[str, str, str]:
    """Self-signed EC cert via the openssl CLI →
    (cert_path, key_path, sha256_fingerprint "AB:CD:…")."""
    d = state_dir or tempfile.mkdtemp(prefix="evam_rtc_")
    os.makedirs(d, exist_ok=True)
    cert, key = os.path.join(d, "cert.pem"), os.path.join(d, "key.pem")
    if not (os.path.exists(cert) and os.path.exists(key)):
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "ec", "-pkeyopt",
             "ec_paramgen_curve:prime256v1", "-keyout", key, "-out",
             cert, "-days", "30", "-nodes", "-subj", "/CN=evam-tpu"],
            check=True, capture_output=True,
        )
    der = subprocess.run(
        ["openssl", "x509", "-in", cert, "-outform", "DER"],
        check=True, capture_output=True,
    ).stdout
    digest = hashlib.sha256(der).hexdigest().upper()
    fp = ":".join(digest[i:i + 2] for i in range(0, len(digest), 2))
    return cert, key, fp


class DtlsEndpoint:
    """One memory-BIO DTLS endpoint (server or client role).

    Drive with ``put_datagram`` (network → rbio) and
    ``take_datagrams`` (wbio → network); ``handshake_step`` pumps the
    state machine. After completion, ``srtp_keys()`` returns the
    (local_key, local_salt, remote_key, remote_salt) for our sender
    direction per RFC 5764 §4.2 key layout.
    """

    def __init__(self, cert_path: str, key_path: str,
                 server: bool = True):
        ssl, crypto = _libs()
        self._ssl_lib, self._crypto = ssl, crypto
        self.server = server
        self.ctx = ssl.SSL_CTX_new(ssl.DTLS_method())
        if not self.ctx:
            raise RuntimeError("SSL_CTX_new failed")
        if ssl.SSL_CTX_use_certificate_file(
                self.ctx, cert_path.encode(), SSL_FILETYPE_PEM) != 1:
            raise RuntimeError(self._err("use_certificate"))
        if ssl.SSL_CTX_use_PrivateKey_file(
                self.ctx, key_path.encode(), SSL_FILETYPE_PEM) != 1:
            raise RuntimeError(self._err("use_privatekey"))
        # 0 = success for this call (inverted vs most OpenSSL APIs)
        if ssl.SSL_CTX_set_tlsext_use_srtp(
                self.ctx, SRTP_PROFILE.encode()) != 0:
            raise RuntimeError(self._err("set_tlsext_use_srtp"))
        # Require a peer certificate (both WebRTC roles present one);
        # any chain is accepted here — the caller pins the SDP
        # fingerprint against peer_fingerprint() after the handshake.
        ssl.SSL_CTX_set_verify(
            self.ctx,
            SSL_VERIFY_PEER | SSL_VERIFY_FAIL_IF_NO_PEER_CERT,
            _accept_any_chain,
        )
        self.conn = ssl.SSL_new(self.ctx)
        self.rbio = crypto.BIO_new(crypto.BIO_s_mem())
        self.wbio = crypto.BIO_new(crypto.BIO_s_mem())
        ssl.SSL_set_bio(self.conn, self.rbio, self.wbio)  # owns BIOs
        if server:
            ssl.SSL_set_accept_state(self.conn)
        else:
            ssl.SSL_set_connect_state(self.conn)

    def _err(self, where: str) -> str:
        buf = ctypes.create_string_buffer(256)
        code = self._crypto.ERR_get_error()
        self._crypto.ERR_error_string_n(code, buf, 256)
        return f"{where}: {buf.value.decode()}"

    # ------------------------------------------------------ datagrams

    def put_datagram(self, data: bytes) -> None:
        self._crypto.BIO_write(self.rbio, data, len(data))

    def take_datagrams(self) -> list[bytes]:
        out = []
        while True:
            pending = self._crypto.BIO_ctrl_pending(self.wbio)
            if not pending:
                break
            buf = ctypes.create_string_buffer(int(pending))
            n = self._crypto.BIO_read(self.wbio, buf, int(pending))
            if n <= 0:
                break
            out.append(buf.raw[:n])
        return out

    # ------------------------------------------------------ handshake

    def handshake_step(self) -> bool:
        """Advance the handshake; True once complete."""
        ssl = self._ssl_lib
        if ssl.SSL_is_init_finished(self.conn):
            return True
        rc = ssl.SSL_do_handshake(self.conn)
        if rc == 1:
            return True
        err = ssl.SSL_get_error(self.conn, rc)
        if err in (SSL_ERROR_WANT_READ, SSL_ERROR_WANT_WRITE):
            return False
        raise RuntimeError(self._err(f"handshake (SSL_get_error={err})"))

    def handle_timeout(self) -> None:
        """Retransmit a lost flight (call on a ~1 s stall).
        DTLSv1_handle_timeout is a macro: SSL_ctrl(ssl,
        DTLS_CTRL_HANDLE_TIMEOUT=74, 0, NULL)."""
        self._ssl_lib.SSL_ctrl(self.conn, 74, 0, None)

    @property
    def finished(self) -> bool:
        return bool(self._ssl_lib.SSL_is_init_finished(self.conn))

    # ----------------------------------------------------------- srtp

    def selected_srtp_profile(self) -> str | None:
        p = self._ssl_lib.SSL_get_selected_srtp_profile(self.conn)
        return p.contents.name.decode() if p else None

    def peer_fingerprint(self) -> str | None:
        """sha-256 fingerprint of the peer's certificate (DER),
        "AB:CD:…" — compare against the remote SDP's a=fingerprint
        (the ONLY peer authentication in WebRTC's DTLS)."""
        x509 = self._ssl_lib.SSL_get1_peer_certificate(self.conn)
        if not x509:
            return None
        try:
            n = self._crypto.i2d_X509(x509, None)
            if n <= 0:
                return None
            buf = ctypes.create_string_buffer(n)
            ptr = ctypes.c_void_p(ctypes.addressof(buf))
            self._crypto.i2d_X509(x509, ctypes.byref(ptr))
            digest = hashlib.sha256(buf.raw[:n]).hexdigest().upper()
            return ":".join(
                digest[i:i + 2] for i in range(0, len(digest), 2))
        finally:
            self._crypto.X509_free(x509)

    def export_key_material(self) -> bytes:
        buf = ctypes.create_string_buffer(KEY_MATERIAL_LEN)
        rc = self._ssl_lib.SSL_export_keying_material(
            self.conn, buf, KEY_MATERIAL_LEN,
            EXPORT_LABEL, len(EXPORT_LABEL), None, 0, 0)
        if rc != 1:
            raise RuntimeError(self._err("export_keying_material"))
        return buf.raw

    def srtp_keys(self) -> tuple[bytes, bytes, bytes, bytes]:
        """(local_key, local_salt, remote_key, remote_salt) — RFC 5764
        §4.2 layout: client_key | server_key | client_salt |
        server_salt; 'local' is our sending direction."""
        m = self.export_key_material()
        ck, sk = m[0:16], m[16:32]
        cs, ss = m[32:46], m[46:60]
        if self.server:
            return sk, ss, ck, cs
        return ck, cs, sk, ss

    def close(self) -> None:
        if getattr(self, "conn", None):
            self._ssl_lib.SSL_free(self.conn)
            self.conn = None
        if getattr(self, "ctx", None):
            self._ssl_lib.SSL_CTX_free(self.ctx)
            self.ctx = None

    def __del__(self):  # noqa: D105 — best-effort native cleanup
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass
