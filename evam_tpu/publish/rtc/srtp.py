"""SRTP protection — AES_CM_128_HMAC_SHA1_80 (RFC 3711).

The SRTP profile every browser offers first in DTLS-SRTP. Implements
the AES-CM key-derivation PRF (§4.3), the AES counter-mode packet
cipher (§4.1.1) and the truncated HMAC-SHA1 authentication tag
(§4.2), for the sender role (the service only publishes media).
Validated against the RFC 3711 appendix-B vectors
(tests/test_rtc.py::TestSrtpVectors).
"""

from __future__ import annotations

import hashlib
import hmac
import struct

from cryptography.hazmat.primitives.ciphers import (
    Cipher,
    algorithms,
    modes,
)

KEY_LEN = 16      # AES-128
SALT_LEN = 14     # 112-bit session salt
AUTH_KEY_LEN = 20
TAG_LEN = 10      # HMAC-SHA1 truncated to 80 bits

LABEL_RTP_ENCRYPTION = 0x00
LABEL_RTP_AUTH = 0x01
LABEL_RTP_SALT = 0x02


def _aes_ctr_keystream(key: bytes, iv16: bytes, n: int) -> bytes:
    """n bytes of AES-CM keystream: AES-CTR with the 128-bit counter
    starting at ``iv16`` (low 16 bits are the block counter)."""
    enc = Cipher(algorithms.AES(key), modes.CTR(iv16)).encryptor()
    return enc.update(b"\x00" * n)


def derive_keys(
    master_key: bytes, master_salt: bytes,
    index: int = 0, kdr: int = 0,
    labels: tuple[int, int, int] = (
        LABEL_RTP_ENCRYPTION, LABEL_RTP_AUTH, LABEL_RTP_SALT),
) -> tuple[bytes, bytes, bytes]:
    """RFC 3711 §4.3.1 key derivation → (cipher_key, auth_key, salt).

    ``x = (label || index DIV kdr) XOR master_salt``, then AES-CM
    keystream from ``x * 2^16`` under the master key. ``labels``
    selects the key family: (0,1,2) for SRTP (default), (3,4,5) for
    SRTCP (rtcp.SrtcpSender).
    """
    def prf(label: int, out_len: int) -> bytes:
        div = 0 if kdr == 0 else index // kdr
        key_id = (label << 48) | div  # 56-bit field
        x = int.from_bytes(master_salt, "big") ^ key_id
        iv = (x << 16).to_bytes(16, "big")
        return _aes_ctr_keystream(master_key, iv, out_len)

    enc_label, auth_label, salt_label = labels
    return (
        prf(enc_label, KEY_LEN),
        prf(auth_label, AUTH_KEY_LEN),
        prf(salt_label, SALT_LEN),
    )


def packet_iv(session_salt: bytes, ssrc: int, index: int) -> bytes:
    """§4.1.1: IV = (salt * 2^16) XOR (SSRC * 2^64) XOR (index * 2^16)."""
    v = (
        (int.from_bytes(session_salt, "big") << 16)
        ^ (ssrc << 64)
        ^ (index << 16)
    )
    return v.to_bytes(16, "big")


class SrtpSender:
    """Protect outgoing RTP packets for one SSRC.

    Index tracking is trivial for a sender: we emit monotonically
    increasing sequence numbers, so ROC increments exactly on wrap.
    """

    def __init__(self, master_key: bytes, master_salt: bytes):
        if len(master_key) != KEY_LEN or len(master_salt) != SALT_LEN:
            raise ValueError("AES_CM_128: 16-byte key + 14-byte salt")
        self.cipher_key, self.auth_key, self.salt = derive_keys(
            master_key, master_salt)
        self.roc = 0
        self._last_seq: int | None = None

    def protect(self, rtp: bytes) -> bytes:
        """RTP packet in → SRTP packet out (payload encrypted in
        place, 80-bit auth tag appended; header stays clear)."""
        if len(rtp) < 12:
            raise ValueError("short RTP packet")
        first, _pt, seq = struct.unpack("!BBH", rtp[:4])
        ssrc = struct.unpack("!I", rtp[8:12])[0]
        cc = first & 0x0F
        x_bit = first & 0x10
        payload_off = 12 + 4 * cc
        if x_bit:
            if len(rtp) < payload_off + 4:
                raise ValueError("truncated extension header")
            ext_words = struct.unpack(
                "!H", rtp[payload_off + 2:payload_off + 4])[0]
            payload_off += 4 + 4 * ext_words

        if self._last_seq is not None and seq < self._last_seq:
            self.roc = (self.roc + 1) & 0xFFFFFFFF
        self._last_seq = seq
        index = (self.roc << 16) | seq

        iv = packet_iv(self.salt, ssrc, index)
        keystream = _aes_ctr_keystream(
            self.cipher_key, iv, len(rtp) - payload_off)
        enc_payload = bytes(
            b ^ k for b, k in zip(rtp[payload_off:], keystream))
        protected = rtp[:payload_off] + enc_payload
        tag = hmac.new(
            self.auth_key,
            protected + struct.pack("!I", self.roc),
            hashlib.sha1,
        ).digest()[:TAG_LEN]
        return protected + tag
