"""Minimal RTCP for the sendonly media session (RFC 3550).

Browsers use the Sender Report's NTP↔RTP timestamp mapping for A/V
sync and stats, and the SDES CNAME to bind the SSRC to a source.
One compound packet (SR + SDES) every few seconds is enough for a
sendonly video session; it is SRTCP-protected by the caller with the
same SRTP context family (RFC 3711 §3.4) — here the sender encrypts
with its RTCP index and the E-bit, implemented in
``SrtcpSender``.

The receive direction carries the viewer's feedback — Receiver
Reports (RFC 3550 §6.4.2), transport-layer Generic NACK (RFC 4585
§6.2.1) and payload-specific PLI / FIR (RFC 4585 §6.3.1, RFC 5104
§4.3.1) — which drive the session's loss recovery: NACKed packets
are retransmitted from the send cache, PLI/FIR (or heavy RR loss)
forces a VP8 keyframe. ``SrtcpReceiver`` unprotects the inbound
compound, ``parse_feedback`` extracts the actionable bits. The
reference delegates all of this to webrtcbin's full stack
(reference docker-compose.yml:51-52)."""

from __future__ import annotations

import hashlib
import hmac
import struct
import time

from evam_tpu.publish.rtc import srtp

NTP_EPOCH_OFFSET = 2208988800  # 1900 → 1970


def ntp_now() -> tuple[int, int]:
    t = time.time() + NTP_EPOCH_OFFSET
    sec = int(t)
    frac = int((t - sec) * (1 << 32)) & 0xFFFFFFFF
    return sec & 0xFFFFFFFF, frac


def ntp_mid32() -> int:
    """The middle 32 bits of the NTP timestamp (low 16 of seconds,
    high 16 of fraction) — the LSR/DLSR unit of RFC 3550 §6.4.1."""
    sec, frac = ntp_now()
    return ((sec & 0xFFFF) << 16) | (frac >> 16)


def sender_report(ssrc: int, rtp_ts: int, packets: int,
                  octets: int, cname: str = "evam-tpu") -> bytes:
    """Compound SR + SDES(CNAME)."""
    ntp_s, ntp_f = ntp_now()
    sr = struct.pack(
        "!BBHIIIIII",
        0x80,            # V=2, no padding, RC=0
        200,             # PT=SR
        6,               # length in 32-bit words - 1
        ssrc & 0xFFFFFFFF,
        ntp_s, ntp_f,
        rtp_ts & 0xFFFFFFFF,
        packets & 0xFFFFFFFF,
        octets & 0xFFFFFFFF,
    )
    cname_b = cname.encode()
    item = bytes([1, len(cname_b)]) + cname_b  # CNAME item
    chunk = struct.pack("!I", ssrc & 0xFFFFFFFF) + item + b"\x00"
    pad = (4 - len(chunk) % 4) % 4
    chunk += b"\x00" * pad
    sdes = struct.pack(
        "!BBH", 0x81, 202, len(chunk) // 4) + chunk
    return sr + sdes


class SrtcpSender:
    """SRTCP protection (RFC 3711 §3.4) for outgoing compound RTCP.

    Same master secret as the RTP direction but the RTCP key-family
    labels (3/4/5); the 31-bit index + E-bit trail the ciphertext,
    then the 80-bit tag.
    """

    LABEL_RTCP_ENCRYPTION = 0x03
    LABEL_RTCP_AUTH = 0x04
    LABEL_RTCP_SALT = 0x05

    def __init__(self, master_key: bytes, master_salt: bytes):
        self.cipher_key, self.auth_key, self.salt = srtp.derive_keys(
            master_key, master_salt,
            labels=(self.LABEL_RTCP_ENCRYPTION, self.LABEL_RTCP_AUTH,
                    self.LABEL_RTCP_SALT),
        )
        self.index = 0

    def protect(self, rtcp: bytes) -> bytes:
        ssrc = struct.unpack("!I", rtcp[4:8])[0]
        index = self.index
        self.index = (self.index + 1) & 0x7FFFFFFF
        iv = srtp.packet_iv(self.salt, ssrc, index)
        ks = srtp._aes_ctr_keystream(
            self.cipher_key, iv, len(rtcp) - 8)
        enc = rtcp[:8] + bytes(
            b ^ k for b, k in zip(rtcp[8:], ks))
        trailer = struct.pack("!I", 0x80000000 | index)  # E-bit set
        tag = hmac.new(
            self.auth_key, enc + trailer, hashlib.sha1,
        ).digest()[:srtp.TAG_LEN]
        return enc + trailer + tag


class SrtcpReceiver:
    """SRTCP unprotection for inbound feedback (RFC 3711 §3.4).

    Constructed with the REMOTE side's master key/salt (the browser's
    DTLS client-write family when we are the DTLS server): verify the
    80-bit tag over ciphertext+index, then AES-CM decrypt from byte 8
    using the 31-bit index carried in the trailer.
    """

    #: replay window width (packets behind the highest-seen index
    #: still accepted exactly once) — RFC 3711 recommends >= 64
    REPLAY_WINDOW = 64

    def __init__(self, master_key: bytes, master_salt: bytes):
        self.cipher_key, self.auth_key, self.salt = srtp.derive_keys(
            master_key, master_salt,
            labels=(SrtcpSender.LABEL_RTCP_ENCRYPTION,
                    SrtcpSender.LABEL_RTCP_AUTH,
                    SrtcpSender.LABEL_RTCP_SALT),
        )
        self._highest_index = -1     # highest authenticated SRTCP index
        self._replay_mask = 0        # bit i = (highest - i) seen

    def _replay_check(self, index: int) -> None:
        """RFC 3711 §3.3.2 replay list over the 31-bit SRTCP index:
        a captured valid compound (e.g. one NACK re-triggering a
        512-packet retransmit burst) must not be accepted twice."""
        if index > self._highest_index:
            return
        delta = self._highest_index - index
        if delta >= self.REPLAY_WINDOW or (self._replay_mask >> delta) & 1:
            raise ValueError("SRTCP replay: index %d already seen" % index)

    def _replay_commit(self, index: int) -> None:
        if index > self._highest_index:
            shift = index - self._highest_index
            # cap the shift: a far jump (peer restart, index desync)
            # must not materialize a 2^31-bit intermediate
            if shift >= self.REPLAY_WINDOW:
                self._replay_mask = 1
            else:
                self._replay_mask = ((self._replay_mask << shift) | 1) \
                    & ((1 << self.REPLAY_WINDOW) - 1)
            self._highest_index = index
        else:
            self._replay_mask |= 1 << (self._highest_index - index)

    def unprotect(self, pkt: bytes) -> bytes:
        """SRTCP packet in → plaintext RTCP compound out.

        Raises ``ValueError`` on a bad tag, a malformed packet, or a
        replayed SRTCP index — callers drop the packet (never act on
        unauthenticated or replayed feedback: a forged or replayed
        NACK burst is a retransmission-amplifier).
        """
        if len(pkt) < 8 + 4 + srtp.TAG_LEN:
            raise ValueError("short SRTCP packet")
        tag = pkt[-srtp.TAG_LEN:]
        body = pkt[:-srtp.TAG_LEN]           # ciphertext + E|index
        calc = hmac.new(
            self.auth_key, body, hashlib.sha1).digest()[:srtp.TAG_LEN]
        if not hmac.compare_digest(tag, calc):
            raise ValueError("SRTCP auth tag mismatch")
        trailer = struct.unpack("!I", body[-4:])[0]
        e_bit, index = trailer >> 31, trailer & 0x7FFFFFFF
        self._replay_check(index)
        self._replay_commit(index)
        enc = body[:-4]
        if not e_bit:
            return enc                        # unencrypted RTCP
        ssrc = struct.unpack("!I", enc[4:8])[0]
        iv = srtp.packet_iv(self.salt, ssrc, index)
        ks = srtp._aes_ctr_keystream(
            self.cipher_key, iv, len(enc) - 8)
        return enc[:8] + bytes(b ^ k for b, k in zip(enc[8:], ks))


# ------------------------------------------------------------ feedback parse

PT_SR = 200
PT_RR = 201
PT_RTPFB = 205   # transport-layer feedback (FMT 1 = Generic NACK)
PT_PSFB = 206    # payload-specific feedback (FMT 1 = PLI, 4 = FIR)


def parse_feedback(compound: bytes, media_ssrc: int | None = None) -> dict:
    """Walk a plaintext RTCP compound and pull out what the sender
    acts on: ``{"nack": [seq…], "pli": bool, "fir": bool,
    "fraction_lost": float|None, "highest_seq": int|None,
    "jitter": int|None (RTP clock units), "lsr": int|None,
    "dlsr": int|None}`` (the last two in 1/65536 s, RFC 3550
    §6.4.1 — RTT inputs).

    NACK FCI entries are (PID, BLP) pairs (RFC 4585 §6.2.1): PID is a
    lost packet, each set bit i of BLP marks PID+i+1 lost too.

    ``media_ssrc`` (when given) drops feedback messages addressed to
    a different media source — an authenticated peer must not steer
    retransmission/keyframes for an SSRC it is not receiving.
    """
    out: dict = {"nack": [], "pli": False, "fir": False,
                 "fraction_lost": None, "highest_seq": None,
                 "jitter": None, "lsr": None, "dlsr": None}
    i = 0
    while i + 4 <= len(compound):
        first, pt, length_w = struct.unpack(
            "!BBH", compound[i:i + 4])
        if first >> 6 != 2:                  # bad version: stop walking
            break
        fmt = first & 0x1F                   # RC for SR/RR, FMT for FB
        end = i + 4 * (length_w + 1)
        body = compound[i + 8:end]           # after header + sender-ssrc
        want = None if media_ssrc is None else media_ssrc & 0xFFFFFFFF
        if pt == PT_RR and fmt >= 1 and len(body) >= 24:
            # walk all RC report blocks (24 bytes each) and use the
            # one ABOUT our source — a viewer receiving several
            # streams reports them all in one RR, in any order (the
            # loss path forces keyframes; see _handle_feedback)
            for j in range(0, min(fmt, len(body) // 24) * 24, 24):
                block_ssrc = struct.unpack("!I", body[j:j + 4])[0]
                if want is None or block_ssrc == want:
                    out["fraction_lost"] = body[j + 4] / 256.0
                    (out["highest_seq"], out["jitter"], out["lsr"],
                     out["dlsr"]) = struct.unpack(
                        "!IIII", body[j + 8:j + 24])
                    break
        elif pt in (PT_RTPFB, PT_PSFB) and len(body) >= 4:
            fb_media = struct.unpack("!I", body[:4])[0]
            if pt == PT_PSFB and fmt == 4:
                # FIR (RFC 5104 §4.3.1.1): the header media-SSRC
                # SHALL be 0 — the target SSRC rides in each 8-byte
                # FCI entry. Accept header==want for lenient senders.
                fci_ssrcs = [
                    struct.unpack("!I", body[4 + j:8 + j])[0]
                    for j in range(0, max(0, len(body) - 4 - 7), 8)
                ]
                if (want is None or fb_media == want
                        or want in fci_ssrcs):
                    out["fir"] = True
                i = end
                continue
            if want is not None and fb_media != want:
                i = end
                continue                     # feedback for another source
            if pt == PT_RTPFB and fmt == 1:
                fci = body[4:]
                for j in range(0, len(fci) - 3, 4):
                    pid, blp = struct.unpack("!HH", fci[j:j + 4])
                    out["nack"].append(pid)
                    for bit in range(16):
                        if blp & (1 << bit):
                            out["nack"].append((pid + bit + 1) & 0xFFFF)
            elif pt == PT_PSFB and fmt == 1:
                out["pli"] = True
        i = end
    return out


# ----------------------------------------------- feedback builders (viewer)

def receiver_report(sender_ssrc: int, media_ssrc: int,
                    fraction_lost: float, cumulative_lost: int,
                    highest_seq: int, jitter: int = 0,
                    lsr: int = 0, dlsr: int = 0) -> bytes:
    """RR with one report block — the packet a receiving peer sends;
    here it is the test viewer's way to exercise RR-driven recovery.
    ``jitter`` is in RTP clock units; ``lsr``/``dlsr`` echo the last
    SR per RFC 3550 §6.4.1 (the sender derives RTT from them)."""
    fl = min(255, max(0, int(fraction_lost * 256)))
    return struct.pack(
        "!BBHI I BBH IIII",
        0x81, PT_RR, 7, sender_ssrc & 0xFFFFFFFF,
        media_ssrc & 0xFFFFFFFF,
        fl, (cumulative_lost >> 16) & 0xFF, cumulative_lost & 0xFFFF,
        highest_seq & 0xFFFFFFFF,
        jitter & 0xFFFFFFFF, lsr & 0xFFFFFFFF, dlsr & 0xFFFFFFFF,
    )


def generic_nack(sender_ssrc: int, media_ssrc: int,
                 seqs: list[int]) -> bytes:
    """Generic NACK (RFC 4585 §6.2.1) covering ``seqs`` with packed
    (PID, BLP) FCI entries."""
    seqs = sorted(set(s & 0xFFFF for s in seqs))
    fci = b""
    while seqs:
        pid = seqs.pop(0)
        blp = 0
        rest = []
        for s in seqs:
            d = (s - pid) & 0xFFFF
            if 1 <= d <= 16:
                blp |= 1 << (d - 1)
            else:
                rest.append(s)
        seqs = rest
        fci += struct.pack("!HH", pid, blp)
    hdr = struct.pack(
        "!BBHII", 0x80 | 1, PT_RTPFB, 2 + len(fci) // 4,
        sender_ssrc & 0xFFFFFFFF, media_ssrc & 0xFFFFFFFF)
    return hdr + fci


def pli(sender_ssrc: int, media_ssrc: int) -> bytes:
    """Picture Loss Indication (RFC 4585 §6.3.1) — no FCI."""
    return struct.pack(
        "!BBHII", 0x80 | 1, PT_PSFB, 2,
        sender_ssrc & 0xFFFFFFFF, media_ssrc & 0xFFFFFFFF)
