"""Minimal RTCP for the sendonly media session (RFC 3550).

Browsers use the Sender Report's NTP↔RTP timestamp mapping for A/V
sync and stats, and the SDES CNAME to bind the SSRC to a source.
One compound packet (SR + SDES) every few seconds is enough for a
sendonly video session; it is SRTCP-protected by the caller with the
same SRTP context family (RFC 3711 §3.4) — here the sender encrypts
with its RTCP index and the E-bit, implemented in
``SrtcpSender``.
"""

from __future__ import annotations

import hashlib
import hmac
import struct
import time

from evam_tpu.publish.rtc import srtp

NTP_EPOCH_OFFSET = 2208988800  # 1900 → 1970


def ntp_now() -> tuple[int, int]:
    t = time.time() + NTP_EPOCH_OFFSET
    sec = int(t)
    frac = int((t - sec) * (1 << 32)) & 0xFFFFFFFF
    return sec & 0xFFFFFFFF, frac


def sender_report(ssrc: int, rtp_ts: int, packets: int,
                  octets: int, cname: str = "evam-tpu") -> bytes:
    """Compound SR + SDES(CNAME)."""
    ntp_s, ntp_f = ntp_now()
    sr = struct.pack(
        "!BBHIIIIII",
        0x80,            # V=2, no padding, RC=0
        200,             # PT=SR
        6,               # length in 32-bit words - 1
        ssrc & 0xFFFFFFFF,
        ntp_s, ntp_f,
        rtp_ts & 0xFFFFFFFF,
        packets & 0xFFFFFFFF,
        octets & 0xFFFFFFFF,
    )
    cname_b = cname.encode()
    item = bytes([1, len(cname_b)]) + cname_b  # CNAME item
    chunk = struct.pack("!I", ssrc & 0xFFFFFFFF) + item + b"\x00"
    pad = (4 - len(chunk) % 4) % 4
    chunk += b"\x00" * pad
    sdes = struct.pack(
        "!BBH", 0x81, 202, len(chunk) // 4) + chunk
    return sr + sdes


class SrtcpSender:
    """SRTCP protection (RFC 3711 §3.4) for outgoing compound RTCP.

    Same master secret as the RTP direction but the RTCP key-family
    labels (3/4/5); the 31-bit index + E-bit trail the ciphertext,
    then the 80-bit tag.
    """

    LABEL_RTCP_ENCRYPTION = 0x03
    LABEL_RTCP_AUTH = 0x04
    LABEL_RTCP_SALT = 0x05

    def __init__(self, master_key: bytes, master_salt: bytes):
        self.cipher_key, self.auth_key, self.salt = srtp.derive_keys(
            master_key, master_salt,
            labels=(self.LABEL_RTCP_ENCRYPTION, self.LABEL_RTCP_AUTH,
                    self.LABEL_RTCP_SALT),
        )
        self.index = 0

    def protect(self, rtcp: bytes) -> bytes:
        ssrc = struct.unpack("!I", rtcp[4:8])[0]
        index = self.index
        self.index = (self.index + 1) & 0x7FFFFFFF
        iv = srtp.packet_iv(self.salt, ssrc, index)
        ks = srtp._aes_ctr_keystream(
            self.cipher_key, iv, len(rtcp) - 8)
        enc = rtcp[:8] + bytes(
            b ^ k for b, k in zip(rtcp[8:], ks))
        trailer = struct.pack("!I", 0x80000000 | index)  # E-bit set
        tag = hmac.new(
            self.auth_key, enc + trailer, hashlib.sha1,
        ).digest()[:srtp.TAG_LEN]
        return enc + trailer + tag
