"""STUN (RFC 5389) message codec + ICE-lite responder role.

Just enough of STUN for WebRTC connectivity checks: BINDING
request/success-response with USERNAME, MESSAGE-INTEGRITY (HMAC-SHA1,
short-term credentials = the ICE password), FINGERPRINT, and
XOR-MAPPED-ADDRESS. The server side is ICE-lite (RFC 8445 §2.5): it
never initiates checks, it answers the browser's and watches for
USE-CANDIDATE to nominate the pair.

Validated against the RFC 5769 sample messages
(tests/test_rtc.py::TestStunVectors).
"""

from __future__ import annotations

import dataclasses
import hashlib
import hmac
import os
import socket
import struct
import zlib

MAGIC_COOKIE = 0x2112A442
HEADER_LEN = 20

BINDING_REQUEST = 0x0001
BINDING_SUCCESS = 0x0101
BINDING_ERROR = 0x0111

ATTR_MAPPED_ADDRESS = 0x0001
ATTR_USERNAME = 0x0006
ATTR_MESSAGE_INTEGRITY = 0x0008
ATTR_ERROR_CODE = 0x0009
ATTR_XOR_MAPPED_ADDRESS = 0x0020
ATTR_PRIORITY = 0x0024
ATTR_USE_CANDIDATE = 0x0025
ATTR_SOFTWARE = 0x8022
ATTR_FINGERPRINT = 0x8028
ATTR_ICE_CONTROLLED = 0x8029
ATTR_ICE_CONTROLLING = 0x802A

FINGERPRINT_XOR = 0x5354554E  # "STUN"


def _pad4(n: int) -> int:
    return (n + 3) & ~3


@dataclasses.dataclass
class StunMessage:
    msg_type: int
    transaction_id: bytes  # 12 bytes
    attributes: list[tuple[int, bytes]]

    @classmethod
    def parse(cls, data: bytes) -> "StunMessage":
        if len(data) < HEADER_LEN:
            raise ValueError("short STUN message")
        msg_type, length, cookie = struct.unpack("!HHI", data[:8])
        if cookie != MAGIC_COOKIE:
            raise ValueError("bad magic cookie")
        if msg_type & 0xC000:
            raise ValueError("not a STUN message (first bits set)")
        tid = data[8:20]
        if len(data) < HEADER_LEN + length:
            raise ValueError("truncated STUN message")
        attrs = []
        i = HEADER_LEN
        end = HEADER_LEN + length
        while i + 4 <= end:
            a_type, a_len = struct.unpack("!HH", data[i:i + 4])
            val = data[i + 4:i + 4 + a_len]
            if len(val) != a_len:
                raise ValueError("truncated attribute")
            attrs.append((a_type, val))
            i += 4 + _pad4(a_len)
        return cls(msg_type, tid, attrs)

    def get(self, a_type: int) -> bytes | None:
        for t, v in self.attributes:
            if t == a_type:
                return v
        return None

    # --------------------------------------------------------- build

    def _encode(self, attrs: list[tuple[int, bytes]]) -> bytes:
        body = b""
        for t, v in attrs:
            body += struct.pack("!HH", t, len(v)) + v
            body += b"\x00" * (_pad4(len(v)) - len(v))
        return (
            struct.pack("!HHI", self.msg_type, len(body), MAGIC_COOKIE)
            + self.transaction_id + body
        )

    def build(self, integrity_key: bytes | None = None,
              fingerprint: bool = True) -> bytes:
        """Serialize. RFC 5389 §15.4/.5: each trailer attribute is
        computed over the message that precedes it, with the header
        length field pre-adjusted to include the attribute itself
        (+24 for MESSAGE-INTEGRITY, +8 for FINGERPRINT)."""
        attrs = list(self.attributes)
        msg = self._encode(attrs)

        def adjusted(extra: int) -> bytes:
            return struct.pack(
                "!HH", self.msg_type, len(msg) - HEADER_LEN + extra
            ) + msg[4:HEADER_LEN] + msg[HEADER_LEN:]

        if integrity_key is not None:
            mac = hmac.new(
                integrity_key, adjusted(24), hashlib.sha1).digest()
            attrs.append((ATTR_MESSAGE_INTEGRITY, mac))
            msg = self._encode(attrs)
        if fingerprint:
            crc = (zlib.crc32(adjusted(8)) & 0xFFFFFFFF) ^ FINGERPRINT_XOR
            attrs.append((ATTR_FINGERPRINT, struct.pack("!I", crc)))
            msg = self._encode(attrs)
        return msg

    # ----------------------------------------------------- integrity

    def check_integrity(self, raw: bytes, key: bytes) -> bool:
        """Verify MESSAGE-INTEGRITY on a received message (RFC 5389
        §15.4: HMAC over the message up to the attribute, with the
        length field covering through it)."""
        i = HEADER_LEN
        length = struct.unpack("!H", raw[2:4])[0]
        end = HEADER_LEN + length
        while i + 4 <= end:
            a_type, a_len = struct.unpack("!HH", raw[i:i + 4])
            if a_type == ATTR_MESSAGE_INTEGRITY:
                mac = raw[i + 4:i + 24]
                adj = raw[:2] + struct.pack(
                    "!H", i + 24 - HEADER_LEN) + raw[4:HEADER_LEN]
                calc = hmac.new(
                    key, adj + raw[HEADER_LEN:i], hashlib.sha1).digest()
                return hmac.compare_digest(mac, calc)
            i += 4 + _pad4(a_len)
        return False


def check_fingerprint(raw: bytes) -> bool:
    """Verify the trailing FINGERPRINT attribute (RFC 5389 §15.5)."""
    length = struct.unpack("!H", raw[2:4])[0]
    i = HEADER_LEN
    end = HEADER_LEN + length
    while i + 4 <= end:
        a_type, a_len = struct.unpack("!HH", raw[i:i + 4])
        if a_type == ATTR_FINGERPRINT:
            want = struct.unpack("!I", raw[i + 4:i + 8])[0]
            adj = raw[:2] + struct.pack(
                "!H", i + 8 - HEADER_LEN) + raw[4:HEADER_LEN]
            crc = (zlib.crc32(adj + raw[HEADER_LEN:i]) & 0xFFFFFFFF) \
                ^ FINGERPRINT_XOR
            return crc == want
        i += 4 + _pad4(a_len)
    return False


def xor_mapped_address(addr: tuple[str, int],
                       transaction_id: bytes) -> bytes:
    """Encode an (ip, port) as XOR-MAPPED-ADDRESS (v4/v6)."""
    ip, port = addr
    xport = port ^ (MAGIC_COOKIE >> 16)
    try:
        packed = socket.inet_aton(ip)
        fam = 0x01
        xip = bytes(
            b ^ k for b, k in zip(packed, struct.pack("!I", MAGIC_COOKIE)))
    except OSError:
        packed = socket.inet_pton(socket.AF_INET6, ip)
        fam = 0x02
        key = struct.pack("!I", MAGIC_COOKIE) + transaction_id
        xip = bytes(b ^ k for b, k in zip(packed, key))
    return struct.pack("!BBH", 0, fam, xport) + xip


def is_stun(datagram: bytes) -> bool:
    """Demultiplex STUN from SRTP/DTLS on the shared media socket
    (RFC 7983): STUN starts 0x00-0x03 + magic cookie."""
    return (
        len(datagram) >= HEADER_LEN
        and datagram[0] < 4
        and struct.unpack("!I", datagram[4:8])[0] == MAGIC_COOKIE
    )


def is_dtls(datagram: bytes) -> bool:
    """RFC 7983: DTLS record content types live in [20, 63]."""
    return len(datagram) > 0 and 20 <= datagram[0] <= 63


class IceLiteResponder:
    """Answer ICE connectivity checks on the media socket.

    ``local_pwd`` authenticates incoming checks (the browser signs
    with OUR password); responses are signed with it too. Tracks the
    peer's source address once a valid check arrives (that is the
    candidate pair for an ice-lite host candidate) and whether
    USE-CANDIDATE nominated us.
    """

    def __init__(self, local_ufrag: str | None = None,
                 local_pwd: str | None = None):
        self.local_ufrag = local_ufrag or os.urandom(3).hex()
        # ice-pwd must be >= 22 chars (RFC 8445 §5.3)
        self.local_pwd = local_pwd or os.urandom(12).hex()
        self.remote_addr: tuple[str, int] | None = None
        self.nominated = False

    def handle(self, datagram: bytes,
               addr: tuple[str, int]) -> bytes | None:
        """Process one STUN datagram; returns the response to send
        (or None for non-requests/invalid)."""
        try:
            msg = StunMessage.parse(datagram)
        except ValueError:
            return None
        if msg.msg_type != BINDING_REQUEST:
            return None
        key = self.local_pwd.encode()
        # RFC 8445 §7.2.2: connectivity checks MUST carry
        # MESSAGE-INTEGRITY over our password — an unauthenticated
        # request must never repoint the media destination
        if not msg.check_integrity(datagram, key):
            return None  # absent or bad credentials: drop silently
        self.remote_addr = addr
        if msg.get(ATTR_USE_CANDIDATE) is not None:
            self.nominated = True
        resp = StunMessage(
            BINDING_SUCCESS, msg.transaction_id,
            [(ATTR_XOR_MAPPED_ADDRESS,
              xor_mapped_address(addr, msg.transaction_id))],
        )
        return resp.build(integrity_key=key)
