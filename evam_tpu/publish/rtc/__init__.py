"""From-scratch WebRTC media plane (round-2 VERDICT item 7).

The reference's docker-compose promises a WebRTC output destination
(reference docker-compose.yml:51-52) backed by GStreamer's webrtcbin.
This package is the TPU rebuild's equivalent, built the same way the
repo's MQTT/RTSP stacks were — from the RFCs, on what the image
actually provides:

* ``stun``  — RFC 5389 STUN + ICE-lite responder (RFC 8445): pure
  python, validated against the RFC 5769 test vectors.
* ``dtls``  — DTLS 1.2 with the use_srtp extension via ctypes over
  the system ``libssl.so.3`` (no headers needed); exports SRTP keying
  material per RFC 5764.
* ``srtp``  — SRTP AES_CM_128_HMAC_SHA1_80 protection (RFC 3711):
  AES-CM key derivation + CTR keystream + HMAC-SHA1-80 auth tags,
  validated against the RFC 3711 appendix-B vectors.
* ``vp8``   — VP8 frames via the image's FFmpeg/libvpx (per-frame
  WebM encode + EBML SimpleBlock extraction) and RFC 7741 RTP
  payloading.
* ``session`` — glue: UDP host candidate, ICE answer, DTLS
  handshake, SRTP-protected RTP sender, SDP offer/answer.
"""

from evam_tpu.publish.rtc.stun import StunMessage  # noqa: F401
