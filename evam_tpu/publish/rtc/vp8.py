"""VP8 frames + RFC 7741 RTP payloading.

The image has no libvpx headers, but its FFmpeg build encodes VP8
into WebM — so frames are encoded per-frame (keyframe-only, like the
reference's MJPEG preview paths) through ``cv2.VideoWriter`` and the
raw VP8 payload is lifted out of the WebM container with a minimal
EBML walk (Segment → Cluster → SimpleBlock). Keyframe-only costs
bitrate but removes all inter-frame encoder state, which is exactly
right for a many-viewers preview stream (every viewer can join at any
packet).

``packetize`` implements the RFC 7741 VP8 payload descriptor in its
minimal form (X=0, S set on the first fragment, PartID 0) over
standard RTP headers.
"""

from __future__ import annotations

import os
import struct
import tempfile

import numpy as np

RTP_HEADER_LEN = 12
DEFAULT_MTU = 1200  # typical WebRTC payload budget under 1500-byte UDP


# ------------------------------------------------------------------ EBML walk


def _read_vint(b: bytes, i: int, keep_marker: bool) -> tuple[int, int]:
    first = b[i]
    for n in range(8):
        if first & (0x80 >> n):
            if keep_marker:
                val = first
            else:
                val = first & (0x7F >> n)
            for k in range(n):
                val = (val << 8) | b[i + 1 + k]
            return val, i + n + 1
    raise ValueError(f"bad EBML varint at {i}")


_SEGMENT = 0x18538067
_CLUSTER = 0x1F43B675
_SIMPLEBLOCK = 0xA3


def simple_blocks(webm: bytes) -> list[bytes]:
    """Extract SimpleBlock payloads (track header stripped) from a
    WebM byte string."""
    out: list[bytes] = []

    def walk(lo: int, hi: int) -> None:
        i = lo
        while i < hi:
            eid, i = _read_vint(webm, i, keep_marker=True)
            size, i = _read_vint(webm, i, keep_marker=False)
            end = min(i + size, hi)
            if eid in (_SEGMENT, _CLUSTER):
                walk(i, end)
            elif eid == _SIMPLEBLOCK:
                # track number varint + int16 timecode + flags byte
                _, j = _read_vint(webm, i, keep_marker=False)
                out.append(webm[j + 3:end])
            i = end

    walk(0, len(webm))
    return out


def parse_vp8_header(payload: bytes) -> dict:
    """First bytes of a VP8 frame (RFC 6386 §9.1): frame tag +
    keyframe start code + dimensions."""
    tag = payload[0] | (payload[1] << 8) | (payload[2] << 16)
    info = {
        "keyframe": (tag & 1) == 0,
        "show_frame": bool((tag >> 4) & 1),
        "first_part_size": tag >> 5,
    }
    if info["keyframe"]:
        info["sync_ok"] = payload[3:6] == b"\x9d\x01\x2a"
        info["width"] = ((payload[7] << 8) | payload[6]) & 0x3FFF
        info["height"] = ((payload[9] << 8) | payload[8]) & 0x3FFF
    return info


# ----------------------------------------------------------------- encoder


class Vp8Encoder:
    """Per-frame VP8 via the image's FFmpeg (keyframe-only stream)."""

    def __init__(self, width: int, height: int):
        self.width = width
        self.height = height
        self._path = os.path.join(
            tempfile.gettempdir(), f"evam_vp8_{os.getpid()}_{id(self)}.webm")

    def encode(self, frame_bgr: np.ndarray) -> bytes:
        """BGR frame → one raw VP8 keyframe payload."""
        import cv2

        if frame_bgr.shape[1] != self.width or frame_bgr.shape[0] != self.height:
            frame_bgr = cv2.resize(frame_bgr, (self.width, self.height))
        wr = cv2.VideoWriter(
            self._path, cv2.VideoWriter_fourcc(*"VP80"), 30,
            (self.width, self.height))
        if not wr.isOpened():
            raise RuntimeError("VP8 encoder unavailable in this build")
        wr.write(frame_bgr)
        wr.release()
        with open(self._path, "rb") as f:
            blocks = simple_blocks(f.read())
        if not blocks:
            raise RuntimeError("no VP8 frame produced")
        return blocks[0]

    def close(self) -> None:
        try:
            os.unlink(self._path)
        except OSError:
            pass


class Vp8GopEncoder:
    """Delta-frame VP8 via GOP-batched encoding.

    The image's encoder (cv2.VideoWriter → FFmpeg/libvpx) buffers
    output until ``release()`` (measured: 256 KB AVIO buffer + libvpx
    lookahead — nothing reaches disk per-frame), so true streaming
    delta encode isn't reachable through it. Instead frames are
    collected into a small GOP and encoded in one writer pass,
    yielding one keyframe + (gop-1) genuine inter frames — ~40×
    smaller deltas measured at 320×180 — at the cost of ``gop/fps``
    seconds of added latency. The session paces the returned burst
    out one payload per frame tick, so the wire stays smooth.

    ``force_keyframe()`` (PLI / heavy RR loss / viewer join) discards
    the pending GOP — after picture loss the receiver can't use
    continuation deltas anyway — and encodes the next frame alone,
    which makes it an immediate keyframe.
    """

    def __init__(self, width: int, height: int, gop: int = 12):
        if gop < 1:
            raise ValueError("gop must be >= 1")
        self.width, self.height = width, height
        self.gop = gop
        self._buf: list[np.ndarray] = []
        self._force_key = False
        self._enc = Vp8Encoder(width, height)

    def force_keyframe(self) -> None:
        self._force_key = True

    def push(self, frame_bgr: np.ndarray) -> list[bytes]:
        """Add one frame; returns [] while the GOP fills, then the
        whole GOP's payloads (payload[0] is the keyframe)."""
        if self._force_key:
            self._force_key = False
            self._buf = [frame_bgr]      # 1-frame GOP ⇒ keyframe now
            return self._encode_buf()
        self._buf.append(frame_bgr)
        if len(self._buf) < self.gop:
            return []
        return self._encode_buf()

    def flush(self) -> list[bytes]:
        """Encode whatever is buffered (end-of-stream)."""
        return self._encode_buf() if self._buf else []

    def _encode_buf(self) -> list[bytes]:
        import cv2

        frames, self._buf = self._buf, []
        wr = cv2.VideoWriter(
            self._enc._path, cv2.VideoWriter_fourcc(*"VP80"), 30,
            (self.width, self.height))
        if not wr.isOpened():
            raise RuntimeError("VP8 encoder unavailable in this build")
        for f in frames:
            if f.shape[1] != self.width or f.shape[0] != self.height:
                f = cv2.resize(f, (self.width, self.height))
            wr.write(f)
        wr.release()
        with open(self._enc._path, "rb") as fh:
            blocks = simple_blocks(fh.read())
        if len(blocks) != len(frames):
            raise RuntimeError(
                f"encoder returned {len(blocks)} blocks "
                f"for {len(frames)} frames")
        return blocks

    def close(self) -> None:
        self._enc.close()


# -------------------------------------------------------------- packetizer


class Vp8Packetizer:
    """VP8 frame → RTP packets (RFC 7741 minimal descriptor)."""

    def __init__(self, ssrc: int, payload_type: int = 96,
                 mtu: int = DEFAULT_MTU):
        self.ssrc = ssrc
        self.payload_type = payload_type
        self.mtu = mtu
        self.seq = int.from_bytes(os.urandom(2), "big")

    def packetize(self, vp8_frame: bytes, timestamp: int) -> list[bytes]:
        packets = []
        budget = self.mtu - RTP_HEADER_LEN - 1  # 1-byte VP8 descriptor
        chunks = [vp8_frame[i:i + budget]
                  for i in range(0, len(vp8_frame), budget)]
        for ci, chunk in enumerate(chunks):
            marker = ci == len(chunks) - 1
            header = struct.pack(
                "!BBHII",
                0x80,                                   # V=2, no P/X/CC
                (0x80 if marker else 0) | self.payload_type,
                self.seq & 0xFFFF,
                timestamp & 0xFFFFFFFF,
                self.ssrc,
            )
            self.seq = (self.seq + 1) & 0xFFFF
            # VP8 payload descriptor: X=0 R=0 N=0 S(start) R=0 PID=0
            descriptor = 0x10 if ci == 0 else 0x00
            packets.append(header + bytes([descriptor]) + chunk)
        return packets


def depacketize(packets: list[bytes]) -> bytes:
    """Reassemble a VP8 frame from its RTP packets (test harness for
    the packetizer — the consumer role browsers play)."""
    chunks = []
    for i, pkt in enumerate(packets):
        descriptor = pkt[RTP_HEADER_LEN]
        if descriptor & 0x80:
            raise ValueError("extended descriptor unexpected (X=0 mode)")
        s_bit = bool(descriptor & 0x10)
        if (i == 0) != s_bit:
            raise ValueError("S bit must mark exactly the first packet")
        chunks.append(pkt[RTP_HEADER_LEN + 1:])
    marker = packets[-1][1] & 0x80
    if not marker:
        raise ValueError("last packet must carry the RTP marker")
    return b"".join(chunks)
