"""One WebRTC peer session: ICE-lite + DTLS-SRTP + VP8/RTP sender.

Wires the package's layers onto a single UDP socket (rtcp-mux,
BUNDLE): answers the browser's ICE connectivity checks, completes the
DTLS handshake in the passive role, derives SRTP send keys (RFC
5764), then encodes pipeline frames as VP8 keyframes and streams them
SRTP-protected to the nominated remote address. The session is the
media-plane counterpart of the reference's webrtcbin-based
destination (reference docker-compose.yml:51-52).
"""

from __future__ import annotations

import os
import re
import socket
import threading
import time
from collections import OrderedDict

from evam_tpu.obs import get_logger
from evam_tpu.publish.rtc import dtls, rtcp, srtp, stun, vp8

log = get_logger("publish.rtc")

PAYLOAD_TYPE = 96
CLOCK_RATE = 90000


def parse_remote_sdp(sdp: str) -> dict:
    """The few offer fields the answering side uses."""
    out: dict = {}
    for pat, key in [
        (r"^a=ice-ufrag:(\S+)", "ufrag"),
        (r"^a=ice-pwd:(\S+)", "pwd"),
        (r"^a=fingerprint:sha-256 (\S+)", "fingerprint"),
        (r"^a=mid:(\S+)", "mid"),
    ]:
        m = re.search(pat, sdp, re.M)
        if m and key not in out:
            out[key] = m.group(1)
    return out


def build_answer_sdp(ip: str, port: int, ufrag: str, pwd: str,
                     fingerprint: str, ssrc: int,
                     mid: str = "0") -> str:
    """Minimal browser-compatible answer: ice-lite, passive DTLS,
    sendonly VP8 with a host candidate."""
    sess = int.from_bytes(os.urandom(4), "big")
    return "\r\n".join([
        "v=0",
        f"o=- {sess} 2 IN IP4 127.0.0.1",
        "s=-",
        "t=0 0",
        "a=ice-lite",
        f"a=group:BUNDLE {mid}",
        "a=msid-semantic: WMS evam",
        f"m=video {port} UDP/TLS/RTP/SAVPF {PAYLOAD_TYPE}",
        f"c=IN IP4 {ip}",
        f"a=mid:{mid}",
        "a=sendonly",
        f"a=ice-ufrag:{ufrag}",
        f"a=ice-pwd:{pwd}",
        f"a=fingerprint:sha-256 {fingerprint}",
        "a=setup:passive",
        "a=rtcp-mux",
        f"a=rtpmap:{PAYLOAD_TYPE} VP8/{CLOCK_RATE}",
        # advertise loss-recovery feedback so viewers send NACK/PLI
        f"a=rtcp-fb:{PAYLOAD_TYPE} nack",
        f"a=rtcp-fb:{PAYLOAD_TYPE} nack pli",
        f"a=rtcp-fb:{PAYLOAD_TYPE} ccm fir",
        f"a=ssrc:{ssrc} cname:evam-tpu",
        f"a=ssrc:{ssrc} msid:evam video0",
        f"a=candidate:1 1 udp 2130706431 {ip} {port} typ host",
        "a=end-of-candidates",
        "",
    ])


class RtcSession:
    """Answering media session for one viewer."""

    def __init__(self, frame_source=None, width: int = 640,
                 height: int = 360,
                 bind_ip: str = "0.0.0.0", advertise_ip: str | None = None,
                 cert_dir: str | None = None, fps: float = 15.0,
                 on_dead=None, connect_timeout_s: float = 30.0,
                 payload_source=None, video_mode: str = "key",
                 gop: int = 12, loss_keyframe_threshold: float = 0.10):
        """``frame_source() -> np.ndarray | None`` supplies BGR frames
        (the publish relay's latest frame) which this session encodes
        itself; ``payload_source() -> bytes | None`` supplies
        ready-made VP8 payloads instead (SharedVp8Source: one encode
        per relay frame shared across N viewers — the keyframe-only
        stream is viewer-independent). Exactly one must be given.
        ``on_dead(session)`` fires once when the pump thread exits for
        any reason — owners use it to release relay clients and
        registry slots.

        ``video_mode`` picks the encoder: ``"key"`` (every frame a
        keyframe — shareable across viewers, lowest latency) or
        ``"delta"`` (GOP-batched inter frames via ``Vp8GopEncoder``
        — ~40× lower bitrate, ``gop/fps`` s extra latency; only valid
        with ``frame_source``). Both modes answer viewer feedback:
        NACKed packets are retransmitted from the send cache, and
        PLI/FIR or an RR with ``fraction_lost`` ≥
        ``loss_keyframe_threshold`` forces the next frame to be a
        keyframe (a no-op in ``"key"`` mode where every frame
        already is one)."""
        if (frame_source is None) == (payload_source is None):
            raise ValueError(
                "give exactly one of frame_source / payload_source")
        if video_mode not in ("key", "delta"):
            raise ValueError(f"unknown video_mode {video_mode!r}")
        if video_mode == "delta" and frame_source is None:
            raise ValueError(
                "delta mode needs a frame_source (per-viewer encoder "
                "state cannot ride a shared payload_source)")
        self.frame_source = frame_source
        self.payload_source = payload_source
        self.width, self.height = width, height
        self.fps = fps
        self.ssrc = int.from_bytes(os.urandom(4), "big") & 0x7FFFFFFF
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.bind((bind_ip, 0))
        self.sock.settimeout(0.2)
        self.port = self.sock.getsockname()[1]
        self.ip = advertise_ip or _default_ip()
        cert, key, self.fingerprint = dtls.generate_certificate(cert_dir)
        self.dtls = dtls.DtlsEndpoint(cert, key, server=True)
        self.ice = stun.IceLiteResponder()
        self.remote: dict = {}
        self.sender: srtp.SrtpSender | None = None
        self.connected = threading.Event()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.frames_sent = 0
        self.on_dead = on_dead
        self._dead_fired = False
        self._srtcp: rtcp.SrtcpSender | None = None
        self._rtp_packets = 0
        self._rtp_octets = 0
        self._last_sr = 0.0
        self.video_mode = video_mode
        self.gop = gop
        self.loss_keyframe_threshold = loss_keyframe_threshold
        #: seq → protected packet, for NACK retransmission. 512
        #: packets ≈ several seconds of preview video — beyond that a
        #: retransmit would arrive too late to matter anyway.
        self._sent_cache: "OrderedDict[int, bytes]" = OrderedDict()
        self._srtcp_rx: rtcp.SrtcpReceiver | None = None
        self._force_key = False
        self._last_loss_key = 0.0
        # feedback counters (observable in tests + /metrics)
        self.nacks_received = 0
        self.packets_retransmitted = 0
        self.plis_received = 0
        self.keyframes_forced = 0
        # ---- RR-driven rate adaptation (VERDICT r4 item 6): under
        # sustained reported loss the sender halves its frame rate
        # (down to 1/4) instead of hammering a congested path with
        # keyframes; clean reports recover it multiplicatively. The
        # browser-facing analogue of webrtcbin's congestion control
        # (reference docker-compose.yml:51-52), driven purely by RFC
        # 3550 receiver reports since the viewer owns the send rate.
        self.fps_scale = 1.0
        self.fps_scale_min = 0.25
        self.rate_adaptations = 0
        self._lossy_rrs = 0
        #: RTT from the RR's LSR/DLSR echo (RFC 3550 §6.4.1) and the
        #: receiver's interarrival jitter, both surfaced as session
        #: stats for monitoring (None until a compliant RR arrives)
        self.last_rtt_ms: float | None = None
        self.last_jitter_ms: float | None = None
        #: give up (and fire on_dead → relay release) if no viewer
        #: completes ICE+DTLS in this window — an unreachable host
        #: candidate must not pin encode cost forever
        self.connect_timeout_s = connect_timeout_s

    # ------------------------------------------------------ signaling

    def answer(self, offer_sdp: str) -> str:
        self.remote = parse_remote_sdp(offer_sdp)
        return build_answer_sdp(
            self.ip, self.port, self.ice.local_ufrag,
            self.ice.local_pwd, self.fingerprint, self.ssrc,
            mid=self.remote.get("mid", "0"),
        )

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name=f"rtc-{self.port}", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.sock.close()
        self.dtls.close()

    # ---------------------------------------------------------- pump

    def _run(self) -> None:
        try:
            self._pump()
        except Exception as exc:  # noqa: BLE001 — a dead session must
            # never take the signaler down, and must always fire
            # on_dead so the owner releases its relay client
            log.warning("rtc session udp:%d died: %s", self.port, exc)
        finally:
            self._fire_dead()

    def _fire_dead(self) -> None:
        if self._dead_fired:
            return
        self._dead_fired = True
        if self.on_dead is not None:
            try:
                self.on_dead(self)
            except Exception:  # noqa: BLE001
                pass

    def _pump(self) -> None:
        enc = None
        delta = None
        if self.payload_source is None:
            if self.video_mode == "delta":
                # GOP batch encode takes seconds — NEVER on the pump
                # thread (it would stall STUN/DTLS/NACK handling);
                # a dedicated encoder thread owns the Vp8GopEncoder
                delta = _DeltaEncoder(
                    self.width, self.height, self.gop, self._stop)
                delta.start()
            else:
                enc = vp8.Vp8Encoder(self.width, self.height)
        pk = vp8.Vp8Packetizer(self.ssrc, PAYLOAD_TYPE)
        last_dtls_progress = time.monotonic()
        next_frame_t = 0.0
        ts0 = int.from_bytes(os.urandom(4), "big") & 0xFFFFFF
        t_start = time.monotonic()
        try:
            while not self._stop.is_set():
                try:
                    data, addr = self.sock.recvfrom(4096)
                except socket.timeout:
                    data, addr = None, None
                except OSError:
                    break
                if data is not None:
                    if stun.is_stun(data):
                        resp = self.ice.handle(data, addr)
                        if resp is not None:
                            self.sock.sendto(resp, addr)
                    elif stun.is_dtls(data):
                        self.dtls.put_datagram(data)
                        last_dtls_progress = time.monotonic()
                    elif (len(data) >= 2 and 192 <= data[1] <= 223
                          and self._srtcp_rx is not None):
                        # rtcp-mux (RFC 5761): viewer feedback
                        self._handle_feedback(data)

                if self.ice.remote_addr is not None and not self.dtls.finished:
                    self.dtls.handshake_step()
                    for d in self.dtls.take_datagrams():
                        self.sock.sendto(d, self.ice.remote_addr)
                    if time.monotonic() - last_dtls_progress > 1.0:
                        self.dtls.handle_timeout()
                        last_dtls_progress = time.monotonic()

                if self.dtls.finished and self.sender is None:
                    # the SDP fingerprint is the peer's ONLY identity:
                    # a handshake from a cert that doesn't match the
                    # signaled offer is an impostor — tear down
                    want = (self.remote.get("fingerprint") or "").upper()
                    got = self.dtls.peer_fingerprint()
                    if not want or got != want:
                        raise RuntimeError(
                            f"DTLS peer fingerprint mismatch: "
                            f"offer={want[:20]}… peer="
                            f"{(got or 'none')[:20]}…")
                    key, salt, rk, rs = self.dtls.srtp_keys()
                    self.sender = srtp.SrtpSender(key, salt)
                    self._srtcp = rtcp.SrtcpSender(key, salt)
                    self._srtcp_rx = rtcp.SrtcpReceiver(rk, rs)
                    self.connected.set()
                    log.info("rtc: media up to %s (%s)",
                             self.ice.remote_addr,
                             self.dtls.selected_srtp_profile())

                if (not self.connected.is_set()
                        and time.monotonic() - t_start
                        > self.connect_timeout_s):
                    raise TimeoutError(
                        f"no viewer connected within "
                        f"{self.connect_timeout_s:.0f}s")

                now = time.monotonic()
                if (self.sender is not None
                        and self.ice.remote_addr is not None
                        and now >= next_frame_t):
                    next_frame_t = now + 1.0 / (self.fps * self.fps_scale)
                    payload = None
                    if delta is not None:
                        if self._force_key:
                            self._force_key = False
                            self.keyframes_forced += 1
                            delta.force_keyframe()
                        frame = self.frame_source()
                        if frame is not None:
                            delta.submit(frame)
                        payload = delta.next_payload()
                    elif enc is not None:
                        self._force_key = False  # every frame is one
                        frame = self.frame_source()
                        if frame is None:
                            continue
                        payload = enc.encode(frame)
                    else:
                        self._force_key = False
                        payload = self.payload_source()
                    if payload is None:
                        continue
                    ts = (ts0 + int((now - t_start) * CLOCK_RATE)) \
                        & 0xFFFFFFFF
                    for pkt in pk.packetize(payload, ts):
                        seq = int.from_bytes(pkt[2:4], "big")
                        protected = self.sender.protect(pkt)
                        self.sock.sendto(
                            protected, self.ice.remote_addr)
                        self._sent_cache[seq] = protected
                        while len(self._sent_cache) > 512:
                            self._sent_cache.popitem(last=False)
                        self._rtp_packets += 1
                        self._rtp_octets += len(pkt) - 12
                    self.frames_sent += 1
                    # compound SR+SDES every ~2 s (browser sync/stats)
                    if now - self._last_sr > 2.0:
                        self._last_sr = now
                        sr = rtcp.sender_report(
                            self.ssrc, ts, self._rtp_packets,
                            self._rtp_octets)
                        self.sock.sendto(
                            self._srtcp.protect(sr),
                            self.ice.remote_addr)
        finally:
            if enc is not None:
                enc.close()
            if delta is not None:
                delta.close()

    def _handle_feedback(self, data: bytes) -> None:
        """Unprotect + act on one inbound SRTCP compound. Forged or
        corrupt packets are dropped (unauthenticated feedback must
        never drive retransmission — amplification risk)."""
        try:
            plain = self._srtcp_rx.unprotect(data)
        except ValueError:
            return
        fb = rtcp.parse_feedback(plain, media_ssrc=self.ssrc)
        if fb["nack"]:
            self.nacks_received += 1
            for seq in fb["nack"]:
                pkt = self._sent_cache.get(seq & 0xFFFF)
                if pkt is not None and self.ice.remote_addr is not None:
                    # resend the identical protected packet: same SRTP
                    # index ⇒ same keystream, a plain dup on the wire
                    self.sock.sendto(pkt, self.ice.remote_addr)
                    self.packets_retransmitted += 1
        want_key = fb["pli"] or fb["fir"]
        if fb["pli"] or fb["fir"]:
            self.plis_received += 1
        lost = fb["fraction_lost"]
        if (not want_key and lost is not None
                and lost >= self.loss_keyframe_threshold):
            # heavy reported loss without an explicit PLI: refresh
            # the picture anyway, at most once per second
            now = time.monotonic()
            if now - self._last_loss_key > 1.0:
                self._last_loss_key = now
                want_key = True
        if want_key:
            self._force_key = True
        if fb["jitter"] is not None:
            self.last_jitter_ms = fb["jitter"] / 90.0   # 90 kHz clock
        if fb["lsr"]:
            # RTT = now_ntp_mid32 − LSR − DLSR (1/65536 s units)
            units = (rtcp.ntp_mid32() - fb["lsr"]
                     - (fb["dlsr"] or 0)) & 0xFFFFFFFF
            if units < 0x80000000:          # sane (non-wrapped) value
                self.last_rtt_ms = units * 1000.0 / 65536.0
        # ---- rate adaptation: two consecutive lossy RRs halve the
        # frame rate (AIMD-flavored: multiplicative decrease, gentle
        # multiplicative recovery on clean reports)
        if lost is not None:
            if lost >= self.loss_keyframe_threshold:
                self._lossy_rrs += 1
                if (self._lossy_rrs >= 2
                        and self.fps_scale > self.fps_scale_min):
                    self.fps_scale = max(
                        self.fps_scale_min, self.fps_scale * 0.5)
                    self.rate_adaptations += 1
                    self._lossy_rrs = 0
            else:
                self._lossy_rrs = 0
                if self.fps_scale < 1.0:
                    self.fps_scale = min(1.0, self.fps_scale * 1.25)


class _DeltaEncoder:
    """Dedicated encoder thread for delta-mode sessions.

    ``Vp8GopEncoder`` encodes a whole GOP per pass (seconds on small
    hosts); running it inline would freeze the session pump — no
    STUN/DTLS answers, no NACK retransmits — for the duration. The
    pump instead submits frames/force-keyframe commands to this
    thread and paces finished payloads out one per tick. Ordering is
    preserved because one thread owns both the command queue and the
    payload queue; a force command drains stale continuation deltas
    before the fresh keyframe lands.
    """

    def __init__(self, width: int, height: int, gop: int, stop_event):
        import queue as queue_mod
        import threading as threading_mod

        self.enc = vp8.Vp8GopEncoder(width, height, gop)
        self._cmds: "queue_mod.Queue" = queue_mod.Queue(maxsize=2 * gop)
        self._payloads: "queue_mod.Queue" = queue_mod.Queue()
        self._stop = stop_event
        #: close() must end the thread even when the pump died
        #: without the session-level stop event being set
        self._done = threading_mod.Event()
        self._thread = threading_mod.Thread(
            target=self._run, name="vp8-gop-enc", daemon=True)

    def start(self) -> None:
        self._thread.start()

    def submit(self, frame) -> None:
        try:
            self._cmds.put_nowait(("frame", frame))
        except Exception:  # noqa: BLE001 — encoder behind: skip frame
            pass

    def force_keyframe(self) -> None:
        try:
            self._cmds.put_nowait(("force", None))
        except Exception:  # noqa: BLE001
            pass

    def next_payload(self):
        try:
            return self._payloads.get_nowait()
        except Exception:  # noqa: BLE001
            return None

    def _run(self) -> None:
        import queue as queue_mod

        while not (self._stop.is_set() or self._done.is_set()):
            try:
                cmd, arg = self._cmds.get(timeout=0.2)
            except queue_mod.Empty:
                continue
            try:
                if cmd == "force":
                    # stale continuation deltas are useless to a
                    # receiver that just reported picture loss
                    while True:
                        try:
                            self._payloads.get_nowait()
                        except queue_mod.Empty:
                            break
                    self.enc.force_keyframe()
                else:
                    for p in self.enc.push(arg):
                        self._payloads.put(p)
            except Exception as exc:  # noqa: BLE001 — encoder failure
                log.warning("vp8 gop encoder error: %s", exc)

    def close(self) -> None:
        self._done.set()
        self._thread.join(timeout=5)
        self.enc.close()


class RelayBgrSource:
    """Generation-cursor JPEG→BGR decode over a ``FrameRelay``.

    THE relay-consumption protocol for media sessions — shared by
    ``SharedVp8Source`` (key mode) and the delta-mode per-viewer
    ``frame_source`` (publish/webrtc.py) so the timeout and
    stalled-pipeline resend rules can't diverge. ``frame()`` returns
    the latest decoded BGR frame (the previous one while the pipeline
    is stalled, None before the first frame); ``gen`` identifies it.
    """

    def __init__(self, relay, timeout: float = 0.5):
        self.relay = relay
        self.timeout = timeout
        self.gen = 0
        self._frame = None

    def frame(self):
        import cv2
        import numpy as np

        jpeg, gen = self.relay.next_frame(self.gen, timeout=self.timeout)
        if jpeg is not None and gen != self.gen:
            frame = cv2.imdecode(
                np.frombuffer(jpeg, np.uint8), cv2.IMREAD_COLOR)
            if frame is not None:
                self._frame, self.gen = frame, gen
        return self._frame


class SharedVp8Source:
    """One VP8 encode per relay frame, shared by every viewer session.

    The stream is keyframe-only (vp8.Vp8Encoder), so the payload is
    identical for all viewers; each session applies only its own RTP
    seq/timestamp and SRTP protection. N viewers cost one encode,
    not N (review finding r3)."""

    def __init__(self, relay, width: int = 640, height: int = 360):
        import threading as _t

        self.src = RelayBgrSource(relay)
        self.enc = vp8.Vp8Encoder(width, height)
        self._lock = _t.Lock()
        self._enc_gen = 0
        self._payload: bytes | None = None

    def payload(self) -> bytes | None:
        frame = self.src.frame()
        if frame is None:
            return self._payload  # stalled pipeline: resend last
        with self._lock:
            if self.src.gen != self._enc_gen:
                self._payload = self.enc.encode(frame)
                self._enc_gen = self.src.gen
        return self._payload

    def close(self) -> None:
        self.enc.close()


def _default_ip() -> str:
    """Best-effort local address for the SDP host candidate."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("10.255.255.255", 1))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()
