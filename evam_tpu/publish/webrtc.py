"""WebRTC destination: signaling client + real media plane.

The reference enables a WebRTC frame destination by pointing at an
external signaling server (``ENABLE_WEBRTC`` +
``WEBRTC_SIGNALING_SERVER`` ws endpoint, reference
docker-compose.yml:51-52). This client registers each stream there
and serves viewers two ways:

* **SDP offer/answer → real WebRTC media** (`publish/rtc/`): the peer
  sends ``{"type": "offer", "sdp": ...}``; we answer with an ice-lite
  + DTLS-passive SDP and stream SRTP-protected VP8 over UDP straight
  to the viewer (STUN/DTLS/SRTP/RTP from scratch on the system
  OpenSSL + FFmpeg-libvpx — see evam_tpu.publish.rtc).
* **ws-MJPEG fallback** for minimal viewers: ``{"type": "play"}`` →
  binary JPEG frames over the websocket itself.

Protocol (JSON text frames, binary for media):
  -> {"type": "register", "stream": <name>}
  <- {"type": "offer", "stream": <name>, "sdp": <offer>, "peer": id}
  -> {"type": "answer", "stream": <name>, "sdp": <answer>, "peer": id}
     (then SRTP media flows peer-to-peer over UDP)
  <- {"type": "play", "stream": <name>}    # MJPEG fallback
  -> binary JPEG frames until
  <- {"type": "stop", "stream": <name>}
"""

from __future__ import annotations

import asyncio
import json
import threading

from evam_tpu.obs import get_logger
from evam_tpu.publish.rtsp import FrameRelay

log = get_logger("publish.webrtc")


class WebRtcSignaler:
    def __init__(self, server_url: str, stream: str, relay: FrameRelay,
                 video_mode: str = "key"):
        """``video_mode``: "key" (shared keyframe-only encoder) or
        "delta" (per-viewer GOP delta sessions) — plumbed from
        ``Settings.webrtc_video_mode`` (EVAM_WEBRTC_VIDEO_MODE)."""
        self.server_url = server_url
        self.stream = stream
        self.relay = relay
        self.video_mode = video_mode
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        #: peer id -> live RtcSession (SDP-negotiated viewers);
        #: guarded by _sessions_lock (ws thread vs pump on_dead)
        self._sessions: dict = {}
        self._sessions_lock = threading.Lock()
        #: lazily-created shared VP8 encoder (SharedVp8Source)
        self._vp8 = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name=f"webrtc-{self.stream}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        for peer in list(self._sessions):
            self._drop_session(peer)
        if self._vp8 is not None:
            self._vp8.close()
            self._vp8 = None

    def _drop_session(self, peer: str) -> None:
        """Stop + forget one media session, releasing its relay client
        exactly once (idempotent: callable from 'bye', from the
        session's on_dead, and from stop())."""
        with self._sessions_lock:
            sess = self._sessions.pop(peer, None)
            if sess is None:
                return
            self.relay.remove_client()
        try:
            sess.stop()
        except Exception:  # noqa: BLE001 — teardown best-effort
            pass

    def _rtc_answer(self, offer_sdp: str, peer: str) -> str | None:
        """Create a media session for one viewer; returns answer SDP."""
        try:
            from evam_tpu.publish.rtc.session import (
                RelayBgrSource,
                RtcSession,
                SharedVp8Source,
            )
        except Exception as exc:  # noqa: BLE001 — no OpenSSL/cv2 VP8
            log.warning("webrtc media plane unavailable: %s", exc)
            return None
        # renegotiation: a fresh offer for a peer replaces (and stops)
        # its previous session, keeping the relay client count balanced
        self._drop_session(peer)
        try:
            if self.video_mode == "delta":
                # per-viewer GOP encoder (delta frames need private
                # encoder state); ~40× lower bitrate per viewer at
                # gop/fps extra latency
                sess = RtcSession(
                    frame_source=RelayBgrSource(self.relay).frame,
                    video_mode="delta",
                    on_dead=lambda s, _p=peer: self._on_session_dead(
                        _p, s),
                )
            else:
                if self._vp8 is None:
                    # one encoder for every viewer of this stream (the
                    # keyframe-only payload is viewer-independent)
                    self._vp8 = SharedVp8Source(self.relay)
                sess = RtcSession(
                    payload_source=self._vp8.payload,
                    on_dead=lambda s, _p=peer: self._on_session_dead(
                        _p, s),
                )
            answer = sess.answer(offer_sdp)
            with self._sessions_lock:
                self.relay.add_client()  # producers keep encoding
                self._sessions[peer] = sess
            sess.start()
            log.info("webrtc: media session for peer %s on udp:%d",
                     peer, sess.port)
            return answer
        except Exception as exc:  # noqa: BLE001 — answer failure ≠ crash
            log.warning("webrtc: offer handling failed: %s", exc)
            return None

    def _on_session_dead(self, peer: str, sess) -> None:
        """A session's pump thread exited (error or stop): release the
        relay client unless a renegotiation already replaced it.
        Check-and-pop under the lock so a concurrent 'bye' can't make
        the relay count go down twice for one session."""
        with self._sessions_lock:
            if self._sessions.get(peer) is not sess:
                return
            self._sessions.pop(peer, None)
            self.relay.remove_client()

    def _run(self) -> None:
        asyncio.run(self._main())

    async def _main(self) -> None:
        import websockets

        backoff = 1.0
        while not self._stop.is_set():
            try:
                async with websockets.connect(self.server_url) as ws:
                    backoff = 1.0
                    await ws.send(json.dumps(
                        {"type": "register", "stream": self.stream}))
                    log.info("webrtc: registered %s at %s",
                             self.stream, self.server_url)
                    playing = False
                    gen = 0
                    try:
                        while not self._stop.is_set():
                            if playing:
                                jpeg, gen = await asyncio.to_thread(
                                    self.relay.next_frame, gen, 0.5)
                                if jpeg is not None:
                                    await ws.send(jpeg)
                                msg = await self._poll(ws)
                            else:
                                msg = await self._poll(ws, timeout=0.5)
                            if msg is None:
                                continue
                            data = json.loads(msg)
                            if data.get("stream") not in (None, self.stream):
                                continue
                            if data.get("type") == "offer":
                                peer = str(data.get("peer", "0"))
                                answer = self._rtc_answer(
                                    data.get("sdp", ""), peer)
                                if answer is not None:
                                    await ws.send(json.dumps({
                                        "type": "answer",
                                        "stream": self.stream,
                                        "peer": peer,
                                        "sdp": answer,
                                    }))
                            elif data.get("type") == "bye":
                                self._drop_session(
                                    str(data.get("peer", "0")))
                            elif data.get("type") == "play" and not playing:
                                playing = True
                                self.relay.add_client()
                            elif data.get("type") == "stop" and playing:
                                playing = False
                                self.relay.remove_client()
                    finally:
                        if playing:
                            self.relay.remove_client()
            except Exception as exc:  # noqa: BLE001 — reconnect loop
                if self._stop.is_set():
                    return
                log.warning("webrtc signaling (%s); retry in %.0fs",
                            exc, backoff)
                if self._stop.wait(backoff):
                    return
                backoff = min(backoff * 2, 30.0)

    @staticmethod
    async def _poll(ws, timeout: float = 0.001):
        try:
            return await asyncio.wait_for(ws.recv(), timeout)
        except asyncio.TimeoutError:
            return None
