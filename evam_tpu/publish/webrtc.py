"""WebRTC signaling destination.

The reference enables a WebRTC frame destination by pointing at an
external signaling server (``ENABLE_WEBRTC`` +
``WEBRTC_SIGNALING_SERVER`` ws endpoint, reference
docker-compose.yml:51-52); media negotiation/transport live in that
external stack, the service's job is to announce streams and feed
frames. This client does the same over websockets: it registers each
stream with the signaling server and, when asked to play, pushes
JPEG frames as binary messages (the in-image stack has no DTLS/SRTP,
so the frame channel is ws-binary MJPEG — the signaling contract and
lifecycle match, the media encapsulation is documented here).

Protocol (JSON text frames, binary for media):
  -> {"type": "register", "stream": <name>}
  <- {"type": "play", "stream": <name>}
  -> binary JPEG frames until
  <- {"type": "stop", "stream": <name>}
"""

from __future__ import annotations

import asyncio
import json
import threading

from evam_tpu.obs import get_logger
from evam_tpu.publish.rtsp import FrameRelay

log = get_logger("publish.webrtc")


class WebRtcSignaler:
    def __init__(self, server_url: str, stream: str, relay: FrameRelay):
        self.server_url = server_url
        self.stream = stream
        self.relay = relay
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name=f"webrtc-{self.stream}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        asyncio.run(self._main())

    async def _main(self) -> None:
        import websockets

        backoff = 1.0
        while not self._stop.is_set():
            try:
                async with websockets.connect(self.server_url) as ws:
                    backoff = 1.0
                    await ws.send(json.dumps(
                        {"type": "register", "stream": self.stream}))
                    log.info("webrtc: registered %s at %s",
                             self.stream, self.server_url)
                    playing = False
                    gen = 0
                    try:
                        while not self._stop.is_set():
                            if playing:
                                jpeg, gen = await asyncio.to_thread(
                                    self.relay.next_frame, gen, 0.5)
                                if jpeg is not None:
                                    await ws.send(jpeg)
                                msg = await self._poll(ws)
                            else:
                                msg = await self._poll(ws, timeout=0.5)
                            if msg is None:
                                continue
                            data = json.loads(msg)
                            if data.get("stream") not in (None, self.stream):
                                continue
                            if data.get("type") == "play" and not playing:
                                playing = True
                                self.relay.add_client()
                            elif data.get("type") == "stop" and playing:
                                playing = False
                                self.relay.remove_client()
                    finally:
                        if playing:
                            self.relay.remove_client()
            except Exception as exc:  # noqa: BLE001 — reconnect loop
                if self._stop.is_set():
                    return
                log.warning("webrtc signaling (%s); retry in %.0fs",
                            exc, backoff)
                if self._stop.wait(backoff):
                    return
                backoff = min(backoff * 2, 30.0)

    @staticmethod
    async def _poll(ws, timeout: float = 0.001):
        try:
            return await asyncio.wait_for(ws.recv(), timeout)
        except asyncio.TimeoutError:
            return None
