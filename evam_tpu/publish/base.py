"""Destination protocol + factory.

The reference request body selects the metadata destination
(``destination: {metadata: {type: mqtt, host: ..., topic: ...}}``,
charts/templates/NOTES.txt:15-19; file type via gvametapublish
file-path in EVA samples). A destination receives the §6-schema
metadata dict per frame, and optionally the encoded frame bytes
(EII-mode ``(json, blob)`` framing, evas/publisher.py:246-250).
"""

from __future__ import annotations

from typing import Protocol, runtime_checkable


@runtime_checkable
class Destination(Protocol):
    def publish(self, meta: dict, frame: bytes | None = None) -> None: ...
    def close(self) -> None: ...


class NullDestination:
    """Swallows results (appsink-without-consumer equivalent)."""

    def publish(self, meta: dict, frame: bytes | None = None) -> None:
        pass

    def close(self) -> None:
        pass


def create_destination(cfg: dict | None) -> Destination:
    """Resolve a request ``destination.metadata`` object.

    Types: mqtt (host, topic, port), file (path, format), zmq
    (endpoint, topic), stdout, null. Unknown types raise ValueError —
    surfaced as a 400 by the REST layer like the reference's bad
    destination errors.
    """
    if not cfg:
        return NullDestination()
    dtype = cfg.get("type", "null")
    if dtype == "mqtt":
        from evam_tpu.publish.mqtt import MqttDestination

        host = cfg.get("host", "localhost:1883")
        port = int(cfg.get("port", 0))
        if ":" in str(host) and not port:
            host, _, p = str(host).partition(":")
            port = int(p)
        return MqttDestination(
            host=host, port=port or 1883, topic=cfg.get("topic", "evam_tpu"),
        )
    if dtype == "file":
        from evam_tpu.publish.file_dest import FileDestination

        return FileDestination(
            path=cfg.get("path", "/tmp/results.jsonl"),
            fmt=cfg.get("format", "json-lines"),
        )
    if dtype == "zmq":
        from evam_tpu.publish.zmq_dest import ZmqDestination

        return ZmqDestination(
            endpoint=cfg.get("endpoint", "tcp://127.0.0.1:65114"),
            topic=cfg.get("topic", "evam_tpu"),
        )
    if dtype == "stdout":
        from evam_tpu.publish.file_dest import StdoutDestination

        return StdoutDestination()
    if dtype in ("null", "appsink", "application"):
        return NullDestination()
    raise ValueError(f"unsupported destination type '{dtype}'")
