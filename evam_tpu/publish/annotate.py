"""Frame annotation: draw detection overlays for re-streaming.

The reference's RTSP re-stream serves the *annotated* stream (watermarked
frames from the pipeline, reference docker-compose.yml:49-50); this is
the host-side box/label painter used before JPEG encode.
"""

from __future__ import annotations

import numpy as np

from evam_tpu.stages.context import FrameContext

_BOX = (64, 220, 64)
_TEXT = (255, 255, 255)


def annotate_frame(ctx: FrameContext) -> np.ndarray:
    """BGR copy of ctx.frame with rects + labels painted."""
    import cv2

    frame = ctx.frame.copy()
    h, w = frame.shape[:2]
    for r in ctx.regions:
        x, y, bw, bh = r.rect(w, h)
        cv2.rectangle(frame, (x, y), (x + bw, y + bh), _BOX, 2)
        label = r.label
        if r.object_id is not None:
            label = f"{label} #{r.object_id}"
        attrs = [t.label for t in r.tensors if not t.is_detection and t.label]
        if attrs:
            label += " " + "/".join(attrs[:2])
        cv2.putText(frame, f"{label} {r.confidence:.2f}",
                    (x, max(12, y - 4)), cv2.FONT_HERSHEY_SIMPLEX,
                    0.45, _TEXT, 1, cv2.LINE_AA)
    return frame
