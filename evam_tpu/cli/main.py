"""evam-tpu command line: serve / fetch-models / bench / list.

The single CLI replacing the reference's RUN_MODE shell dispatch
(reference run.sh:26-30): ``serve`` starts the REST (EVA-equivalent)
or msgbus (EII-equivalent) frontend per settings; ``fetch-models``
is the model_downloader counterpart (reference
tools/model_downloader/model_downloader.sh:24-32).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from evam_tpu.config import get_settings
from evam_tpu.obs import configure_logging, get_logger

log = get_logger("cli")


def cmd_list(args) -> int:
    from evam_tpu.graph import PipelineLoader
    from evam_tpu.models import ModelRegistry

    settings = get_settings()
    loader = PipelineLoader(settings.pipelines_dir)
    print(json.dumps(
        {
            "pipelines": [f"{n}/{v}" for n, v in loader.names()],
            "models": ModelRegistry(settings.models_dir).keys(),
        },
        indent=2,
    ))
    return 0


def cmd_fetch_models(args) -> int:
    modes = [m for m, on in [("--download", args.download),
                             ("--from-ir", bool(args.from_ir)),
                             ("--synthesize-omz", bool(args.synthesize_omz))]
             if on]
    if len(modes) > 1:
        print(f"fetch-models: {' and '.join(modes)} are mutually "
              "exclusive", file=sys.stderr)
        return 2
    if args.download:
        from evam_tpu.models import download as dl

        try:
            report = dl.download_models(
                model_list=args.model_list, output=args.output,
                base_url=args.base_url or dl.DEFAULT_BASE_URL,
                proc_base_url=args.proc_base_url or dl.DEFAULT_PROC_BASE_URL,
                force=args.force,
            )
        except dl.DownloadError as exc:
            print(f"fetch-models --download: {exc}", file=sys.stderr)
            return 1
        print(f"installed={report.installed} skipped={report.skipped} "
              f"failed={report.failed}")
        return 0 if report.ok else 1
    if args.synthesize_omz:
        from evam_tpu.models.fetch import synthesize_omz

        return synthesize_omz(
            args.output, alias=args.synthesize_omz, version=args.version,
            precision=args.precision, input_size=args.size,
            topology=args.topology,
        )
    if args.from_ir:
        from evam_tpu.models.fetch import import_ir_dir

        return import_ir_dir(
            args.from_ir, args.output,
            alias=args.alias, version=args.version, precision=args.precision,
        )
    from evam_tpu.models.fetch import fetch_models

    return fetch_models(
        model_list=args.model_list, output=args.output, force=args.force
    )


def cmd_serve(args) -> int:
    settings = get_settings()
    mode = (args.mode or settings.run_mode).upper()
    if mode == "EII":
        from evam_tpu.eii.manager import run_eii_service

        return run_eii_service(settings)
    from evam_tpu.server.app import run_server

    return run_server(settings)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="evam-tpu")
    sub = p.add_subparsers(dest="command", required=True)

    s = sub.add_parser("serve", help="start the serving frontend")
    s.add_argument("--mode", choices=["EVA", "EII", "eva", "eii"], default=None)
    s.set_defaults(fn=cmd_serve)

    f = sub.add_parser("fetch-models", help="materialize the model directory")
    f.add_argument("--model-list", default="models_list/models.list.yml")
    f.add_argument("--output", default="models")
    f.add_argument("--force", action="store_true")
    f.add_argument("--download", action="store_true",
                   help="fetch OpenVINO IR artifacts + model-procs over "
                        "the network (reference model_downloader "
                        "counterpart); validates the model list with "
                        "jsonschema, import-checks every IR before "
                        "declaring it installed")
    f.add_argument("--base-url", default=None,
                   help="--download: IR artifact root "
                        "({base}/{model}/{precision}/{model}.xml)")
    f.add_argument("--proc-base-url", default=None,
                   help="--download: model-proc root "
                        "({base}/{model}.json)")
    f.add_argument("--from-ir", default=None, metavar="DIR",
                   help="install OpenVINO IR .xml/.bin (file or tree) "
                        "into the serving layout instead of zoo export")
    f.add_argument("--alias", default=None,
                   help="serving alias for --from-ir (default: xml stem)")
    f.add_argument("--synthesize-omz", default=None, metavar="ALIAS",
                   help="materialize an OMZ-topology-shaped MobileNet-SSD "
                        "IR under ALIAS (offline stand-in for the OMZ "
                        "download; see models/ir_build.py)")
    f.add_argument("--size", type=int, default=None,
                   help="input resolution for --synthesize-omz "
                        "(default: 512 for ssd, 72 for attributes)")
    f.add_argument("--topology",
                   choices=["ssd", "attributes", "manifest"],
                   default="ssd",
                   help="--synthesize-omz topology: MobileNet-SSD "
                        "detector, multi-head attributes classifier, "
                        "or 'manifest' = IR-backed stand-ins for ALL "
                        "8 reference-manifest models (ALIAS ignored)")
    f.add_argument("--version", default="1")
    f.add_argument("--precision", default="FP32")
    f.set_defaults(fn=cmd_fetch_models)

    ls = sub.add_parser("list", help="list pipelines and models")
    ls.set_defaults(fn=cmd_list)
    return p


def main(argv: list[str] | None = None) -> int:
    configure_logging()
    # Fake-TPU backend (SURVEY.md §4): EVAM_PLATFORM=cpu runs the full
    # serving path without TPU hardware (the image's .axon_site hook
    # rewrites JAX_PLATFORMS at import, so a config update is needed).
    platform = os.environ.get("EVAM_PLATFORM")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
