"""Fault injection (SURVEY.md §5.3 — the reference has none; its
recovery story is container restart policy). Enabled only via the
``EVAM_FAULT_INJECT`` env var, e.g.:

    EVAM_FAULT_INJECT="drop=0.01,stall=0.001,stall_ms=200,corrupt=0.005"

The runner consults this per frame; injected faults exercise the
per-frame error isolation, reconnect/backoff, and supervision paths
under test and soak load.
"""

from __future__ import annotations

import os
import random
import time

import numpy as np

from evam_tpu.obs import get_logger
from evam_tpu.obs.metrics import metrics

log = get_logger("obs.faults")


_KNOWN_KEYS = {"drop", "stall", "stall_ms", "corrupt", "error"}


class FaultInjector:
    def __init__(self, spec: str = "", seed: int | None = None):
        cfg = {}
        for part in (spec or "").split(","):
            if not part.strip():
                continue
            k, _, v = part.partition("=")
            k = k.strip()
            try:
                value = float(v)
            except ValueError:
                log.warning("EVAM_FAULT_INJECT: ignoring malformed entry %r",
                            part)
                continue
            if k not in _KNOWN_KEYS:
                log.warning("EVAM_FAULT_INJECT: unknown key %r (known: %s)",
                            k, sorted(_KNOWN_KEYS))
                continue
            cfg[k] = value
        self.drop_p = cfg.get("drop", 0.0)
        self.stall_p = cfg.get("stall", 0.0)
        self.stall_ms = cfg.get("stall_ms", 100.0)
        self.corrupt_p = cfg.get("corrupt", 0.0)
        self.error_p = cfg.get("error", 0.0)
        self._rng = random.Random(seed)

    @property
    def active(self) -> bool:
        return any(
            p > 0 for p in (self.drop_p, self.stall_p, self.corrupt_p,
                            self.error_p)
        )

    def apply(self, frame: np.ndarray | None):
        """Returns the (possibly corrupted) frame, or None to drop.
        May sleep (stall) or raise (error). Drop applies only to video
        frames (audio events carry frame=None and can't be dropped
        here), so the drop metric counts real drops only."""
        if (
            self.drop_p
            and frame is not None
            and self._rng.random() < self.drop_p
        ):
            metrics.inc("evam_faults_injected", labels={"kind": "drop"})
            return None
        if self.stall_p and self._rng.random() < self.stall_p:
            metrics.inc("evam_faults_injected", labels={"kind": "stall"})
            time.sleep(self.stall_ms / 1e3)
        if self.error_p and self._rng.random() < self.error_p:
            metrics.inc("evam_faults_injected", labels={"kind": "error"})
            raise RuntimeError("injected fault (EVAM_FAULT_INJECT error)")
        if (
            self.corrupt_p
            and frame is not None
            and self._rng.random() < self.corrupt_p
        ):
            metrics.inc("evam_faults_injected", labels={"kind": "corrupt"})
            frame = frame.copy()
            h = frame.shape[0]
            frame[self._rng.randrange(h)] = self._rng.randrange(256)
        return frame


_cache: tuple[str, FaultInjector | None] | None = None


def from_env() -> FaultInjector | None:
    """Injector for the current EVAM_FAULT_INJECT value, parsed (and
    its ACTIVE warning logged) once per distinct spec — runners are
    created per stream and per reconnect attempt."""
    global _cache
    spec = os.environ.get("EVAM_FAULT_INJECT", "")
    if _cache is not None and _cache[0] == spec:
        return _cache[1]
    inj = FaultInjector(spec)
    result = inj if inj.active else None
    if result is not None:
        log.warning("fault injection ACTIVE: %s", spec)
    _cache = (spec, result)
    return result
