"""Fault injection (SURVEY.md §5.3 — the reference has none; its
recovery story is container restart policy). Enabled only via the
``EVAM_FAULT_INJECT`` env var, e.g.:

    EVAM_FAULT_INJECT="drop=0.01,stall=0.001,stall_ms=200,corrupt=0.005"

Known keys (all probabilities are per-consult, 0..1):

* ``drop``     — probability a video frame is dropped before the chain
                 (audio events carry frame=None and are never dropped).
* ``stall``    — probability the stream thread sleeps ``stall_ms``
                 before processing a frame (simulates decode jitter).
* ``stall_ms`` — duration of an injected stall (default 100).
* ``corrupt``  — probability one frame row is overwritten with noise.
* ``error``    — probability a RuntimeError is raised for the frame
                 (exercises per-frame error isolation in the runner).
* ``wedge``    — probability ONE engine batch dispatch blocks inside
                 the jitted-step call for ``wedge_s`` seconds — the
                 hung-device-call failure mode (BENCH_r03–r05). Long
                 enough wedges trip the stall watchdog and drive the
                 EngineSupervisor's quarantine → rebuild path.
* ``wedge_s``  — duration of an injected wedge (seconds, default 30).
* ``wedge_n``  — maximum number of wedge events to inject (default
                 unlimited); ``wedge=1,wedge_n=1`` wedges exactly the
                 first dispatched batch — the deterministic chaos-test
                 shape.
* ``shard_loss``   — probability a fleet shard is retired mid-dispatch
                 (evam_tpu/fleet/engine.py consults per submit): the
                 chip-loss drill without waiting out a wedge→watchdog
                 cycle. Streams migrate per the rebalance path.
* ``shard_loss_n`` — maximum shard-loss events (default unlimited);
                 ``shard_loss=1,shard_loss_n=1`` kills exactly the
                 next dispatched-to shard — deterministic.
* ``ckpt_corrupt`` — probability a captured StreamCheckpoint
                 (evam_tpu/state/) is stored with a flipped CRC: the
                 restore side must degrade to a LOUD cold start
                 (evam_ckpt_restore_failures_total{reason="crc"}),
                 never a wedge.
* ``double_fault`` — probability a migration-barrier capture itself
                 fails (the second failure during a migration): the
                 stream cold-starts on the destination.
* ``restore_ms``   — injected checkpoint-restore stall in ms; past
                 EVAM_CKPT_RESTORE_TIMEOUT_S the restore is abandoned
                 for a cold start (reason="timeout").

``EVAM_FAULT_SEED`` (integer) seeds the injector's RNG so chaos runs
are reproducible; unset means a fresh nondeterministic seed per
process.

The runner consults this per frame and the BatchEngine per batch
dispatch; injected faults exercise the per-frame error isolation,
reconnect/backoff, and engine-supervision paths under test and soak
load.
"""

from __future__ import annotations

import os
import random
import threading
import time

import numpy as np

from evam_tpu.obs import get_logger
from evam_tpu.obs.metrics import metrics

log = get_logger("obs.faults")


#: The fault-injection environment surface, exported programmatically:
#: ``evam_tpu.analysis`` (knob-plumbing pass) and the compose/helm doc
#: surfaces derive the chaos keys from here instead of re-listing them.
ENV_KEYS: tuple[str, ...] = ("EVAM_FAULT_INJECT", "EVAM_FAULT_SEED")

#: Spec keys accepted inside EVAM_FAULT_INJECT, in doc order (see the
#: module docstring) — the single source for "keys: drop, stall, …"
#: lists in deploy configs.
SPEC_KEYS: tuple[str, ...] = ("drop", "stall", "stall_ms", "corrupt",
                              "error", "wedge", "wedge_s", "wedge_n",
                              "shard_loss", "shard_loss_n",
                              "ckpt_corrupt", "double_fault",
                              "restore_ms")

_KNOWN_KEYS = set(SPEC_KEYS)


class FaultInjector:
    def __init__(self, spec: str = "", seed: int | None = None):
        cfg = {}
        for part in (spec or "").split(","):
            if not part.strip():
                continue
            k, _, v = part.partition("=")
            k = k.strip()
            try:
                value = float(v)
            except ValueError:
                log.warning("EVAM_FAULT_INJECT: ignoring malformed entry %r",
                            part)
                continue
            if k not in _KNOWN_KEYS:
                log.warning("EVAM_FAULT_INJECT: unknown key %r (known: %s)",
                            k, sorted(_KNOWN_KEYS))
                continue
            cfg[k] = value
        self.drop_p = cfg.get("drop", 0.0)
        self.stall_p = cfg.get("stall", 0.0)
        self.stall_ms = cfg.get("stall_ms", 100.0)
        self.corrupt_p = cfg.get("corrupt", 0.0)
        self.error_p = cfg.get("error", 0.0)
        self.wedge_p = cfg.get("wedge", 0.0)
        self.wedge_s = cfg.get("wedge_s", 30.0)
        #: remaining wedge events; < 0 means unlimited
        self._wedge_left = int(cfg.get("wedge_n", -1))
        self.shard_loss_p = cfg.get("shard_loss", 0.0)
        #: remaining shard-loss events; < 0 means unlimited
        self._shard_loss_left = int(cfg.get("shard_loss_n", -1))
        self.ckpt_corrupt_p = cfg.get("ckpt_corrupt", 0.0)
        self.double_fault_p = cfg.get("double_fault", 0.0)
        self.restore_ms = cfg.get("restore_ms", 0.0)
        self._rng = random.Random(seed)
        # one injector is shared by every stream thread AND every
        # engine dispatcher (from_env cache) — the wedge countdown
        # must decrement exactly once per event
        self._lock = threading.Lock()

    @property
    def active(self) -> bool:
        return any(
            p > 0 for p in (self.drop_p, self.stall_p, self.corrupt_p,
                            self.error_p, self.wedge_p,
                            self.shard_loss_p, self.ckpt_corrupt_p,
                            self.double_fault_p, self.restore_ms)
        )

    def apply(self, frame: np.ndarray | None):
        """Returns the (possibly corrupted) frame, or None to drop.
        May sleep (stall) or raise (error). Drop applies only to video
        frames (audio events carry frame=None and can't be dropped
        here), so the drop metric counts real drops only."""
        if (
            self.drop_p
            and frame is not None
            and self._rng.random() < self.drop_p
        ):
            metrics.inc("evam_faults_injected", labels={"kind": "drop"})
            return None
        if self.stall_p and self._rng.random() < self.stall_p:
            metrics.inc("evam_faults_injected", labels={"kind": "stall"})
            time.sleep(self.stall_ms / 1e3)
        if self.error_p and self._rng.random() < self.error_p:
            metrics.inc("evam_faults_injected", labels={"kind": "error"})
            raise RuntimeError("injected fault (EVAM_FAULT_INJECT error)")
        if (
            self.corrupt_p
            and frame is not None
            and self._rng.random() < self.corrupt_p
        ):
            metrics.inc("evam_faults_injected", labels={"kind": "corrupt"})
            frame = frame.copy()
            h = frame.shape[0]
            frame[self._rng.randrange(h)] = self._rng.randrange(256)
        return frame

    def maybe_wedge(self, name: str = "") -> None:
        """Engine-side consult (BatchEngine._run): with probability
        ``wedge`` block the calling dispatcher thread for ``wedge_s``
        seconds — indistinguishable, from the watchdog's and
        supervisor's point of view, from a hung backend RPC."""
        if not self.wedge_p:
            return
        with self._lock:
            if self._wedge_left == 0:
                return
            if self._rng.random() >= self.wedge_p:
                return
            if self._wedge_left > 0:
                self._wedge_left -= 1
        metrics.inc("evam_faults_injected", labels={"kind": "wedge"})
        log.error("injected wedge: stalling engine %s for %.1fs "
                  "(EVAM_FAULT_INJECT)", name or "?", self.wedge_s)
        time.sleep(self.wedge_s)

    def maybe_shard_loss(self, name: str = "") -> bool:
        """Fleet-side consult (FleetEngine.submit, per dispatch): True
        means "this shard just died" — the caller retires it and the
        rebalance path migrates its streams. The deterministic shape
        ``shard_loss=1,shard_loss_n=1`` kills exactly one shard."""
        if not self.shard_loss_p:
            return False
        with self._lock:
            if self._shard_loss_left == 0:
                return False
            if self._rng.random() >= self.shard_loss_p:
                return False
            if self._shard_loss_left > 0:
                self._shard_loss_left -= 1
        metrics.inc("evam_faults_injected",
                    labels={"kind": "shard_loss"})
        log.error("injected shard loss: retiring shard %s mid-dispatch "
                  "(EVAM_FAULT_INJECT)", name or "?")
        return True

    def maybe_ckpt_corrupt(self) -> bool:
        """Checkpoint-capture consult: True = store the blob with a
        flipped CRC so the restore side must take the loud-cold-start
        rung (never a wedge)."""
        if not self.ckpt_corrupt_p:
            return False
        with self._lock:
            hit = self._rng.random() < self.ckpt_corrupt_p
        if hit:
            metrics.inc("evam_faults_injected",
                        labels={"kind": "ckpt_corrupt"})
            log.error("injected checkpoint corruption "
                      "(EVAM_FAULT_INJECT ckpt_corrupt)")
        return hit

    def maybe_double_fault(self) -> bool:
        """Migration-capture consult: True = the capture itself fails
        (the second failure during a migration) — the stream
        cold-starts on the destination shard."""
        if not self.double_fault_p:
            return False
        with self._lock:
            hit = self._rng.random() < self.double_fault_p
        if hit:
            metrics.inc("evam_faults_injected",
                        labels={"kind": "double_fault"})
        return hit

    def maybe_restore_stall(self) -> None:
        """Checkpoint-restore consult: sleep ``restore_ms`` so the
        restore-timeout degradation rung is drillable."""
        if self.restore_ms <= 0:
            return
        metrics.inc("evam_faults_injected",
                    labels={"kind": "restore_stall"})
        time.sleep(self.restore_ms / 1e3)


_cache: tuple[tuple[str, str], FaultInjector | None] | None = None
#: process-wide memo for the hot path: a 1-tuple holding the resolved
#: injector (or None). ``current()`` reads it without touching the
#: environment — BatchEngine consults per BATCH, and two getenv calls
#: plus a tuple compare per batch is real dispatcher-thread work at
#: the serving rate. Cleared by ``reset_cache()`` (the explicit
#: reconfiguration hook) and refreshed by any ``from_env()`` call.
_resolved: tuple[FaultInjector | None] | None = None


def current() -> FaultInjector | None:
    """Hot-path accessor: the memoized injector, no env reads.

    Resolution happens once — the first call after import or after
    ``reset_cache()`` pays the env read + parse (via ``from_env``);
    every later call is one global load. Code that changes
    ``EVAM_FAULT_INJECT``/``EVAM_FAULT_SEED`` at runtime
    (tests/test_chaos.py, tools/chaos_soak.py) must call
    ``reset_cache()`` for engines to observe the new spec."""
    if _resolved is not None:
        return _resolved[0]
    return from_env()


def from_env() -> FaultInjector | None:
    """Injector for the current EVAM_FAULT_INJECT value, parsed (and
    its ACTIVE warning logged) once per distinct (spec, seed) — runners
    are created per stream and per reconnect attempt, and the engines
    consult per batch (through the memoized ``current()``); they all
    share one injector so wedge_n and the seeded RNG stream are
    global."""
    global _cache, _resolved
    spec = os.environ.get("EVAM_FAULT_INJECT", "")
    seed_str = os.environ.get("EVAM_FAULT_SEED", "")
    if _cache is not None and _cache[0] == (spec, seed_str):
        _resolved = (_cache[1],)
        return _cache[1]
    seed: int | None = None
    if seed_str:
        try:
            seed = int(seed_str)
        except ValueError:
            log.warning("EVAM_FAULT_SEED %r is not an integer; ignoring",
                        seed_str)
    inj = FaultInjector(spec, seed=seed)
    result = inj if inj.active else None
    if result is not None:
        log.warning("fault injection ACTIVE: %s%s", spec,
                    f" (seed={seed})" if seed is not None else "")
    _cache = ((spec, seed_str), result)
    _resolved = (result,)
    return result


def reset_cache() -> None:
    """Drop the cached injector (tests: a fresh spec must re-parse, a
    reused spec must restart its wedge_n countdown, and the engines'
    memoized ``current()`` view must re-resolve)."""
    global _cache, _resolved
    _cache = None
    _resolved = None
